"""Decomposition profile: where does an embedding batch's time go?

Separates the three layers the e2e number (bench.py) mixes:
  1. device-only encode: steady-state jit call on resident inputs,
     block_until_ready (compute + dispatch, no host pipeline);
  2. dispatch+transfer overhead: same call on fresh host numpy inputs,
     forced per call (what a sync drain pays per batch);
  3. batch-1 latency per bucket (the p50 set->vector floor).

Prints ONE JSON line:
  {"metric": "encode_device_ms_per_batch", "value": N, "unit": "ms", ...}
with per-shape breakdowns in detail.  Appends to bench_results.jsonl.

Run strictly alone: the tunneled TPU admits one client
(.claude/skills/verify/SKILL.md).  BENCH_CPU=1 for a host-CPU run.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SHAPES = os.environ.get("PROFILE_SHAPES",
                        "512x16,512x32,512x64,8x1024,1x16,1x64")
# 8x1024 exercises the flash-attention bucket (>= flash_min_seq=512)
REPS = int(os.environ.get("PROFILE_REPS", "10"))


def main() -> int:
    import numpy as np

    import jax

    if os.environ.get("BENCH_CPU") == "1":
        from libsplinter_tpu.utils.jaxplatform import force_cpu
        force_cpu()
    from libsplinter_tpu.utils.jaxplatform import enable_compile_cache
    enable_compile_cache()

    from libsplinter_tpu.models import EmbeddingModel, EncoderConfig

    backend = jax.default_backend()
    print(f"backend={backend}", file=sys.stderr, flush=True)

    cfg = EncoderConfig(out_dim=768, max_len=2048)
    shapes = [tuple(int(x) for x in s.split("x"))
              for s in SHAPES.split(",")]
    buckets = tuple(sorted({b for _, b in shapes}))
    model = EmbeddingModel(cfg, buckets=buckets)

    detail: dict = {"backend": backend, "reps": REPS}
    rows = []
    for bsz, bucket in shapes:
        ids_h = np.random.default_rng(0).integers(
            0, cfg.vocab_size, (bsz, bucket)).astype(np.int32)
        lens_h = np.full((bsz,), bucket, np.int32)

        model.encode_ids(ids_h, lens_h)          # compile

        # 1. device-resident steady state
        ids_d, lens_d = jax.device_put(ids_h), jax.device_put(lens_h)
        fn = model._fn
        fn(model.params, ids_d, lens_d).block_until_ready()
        t0 = time.perf_counter()
        for _ in range(REPS):
            out = fn(model.params, ids_d, lens_d)
        out.block_until_ready()
        dev_ms = (time.perf_counter() - t0) / REPS * 1e3

        # 2. host->device each call, forced each call (sync drain cost)
        t0 = time.perf_counter()
        for _ in range(REPS):
            model.encode_ids(ids_h, lens_h)
        e2e_ms = (time.perf_counter() - t0) / REPS * 1e3

        # 3. pipelined: dispatch all, force at the end (async drain cost)
        t0 = time.perf_counter()
        pends = [model.encode_ids_async(ids_h, lens_h)
                 for _ in range(REPS)]
        for p in pends:
            p.materialize()
        pipe_ms = (time.perf_counter() - t0) / REPS * 1e3

        r = {"batch": bsz, "bucket": bucket,
             "device_ms": round(dev_ms, 2),
             "sync_ms": round(e2e_ms, 2),
             "pipelined_ms": round(pipe_ms, 2),
             "device_emb_s": round(bsz / dev_ms * 1e3, 0),
             "pipelined_emb_s": round(bsz / pipe_ms * 1e3, 0)}
        rows.append(r)
        print(json.dumps(r), file=sys.stderr, flush=True)

    detail["shapes"] = rows
    big = max(rows, key=lambda r: r["batch"])
    rec = {"metric": "encode_device_ms_per_batch",
           "value": big["device_ms"], "unit": "ms",
           "vs_baseline": 0.0, "detail": detail}
    print(json.dumps(rec), flush=True)
    try:
        rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%S%z")
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "bench_results.jsonl"), "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
