"""Decomposition profile: where does an embedding batch's time go?

Thin standalone wrapper over bench_series.phase_profile (the single
implementation every tunnel client runs, VERDICT r3 #1): steady-state
device ms, sync-dispatch ms, and async-pipelined ms per (batch,
bucket) shape.  Prints ONE JSON line and appends to
bench_results.jsonl.

Run strictly alone: the tunneled TPU admits one client.  BENCH_CPU=1
for a host-CPU run.  Env: PROFILE_SHAPES, PROFILE_REPS.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from bench_series import shim_main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(shim_main("profile"))
