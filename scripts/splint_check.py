"""CI gate for splint, the repo-native static-analysis suite
(`make lint-check`; wired into `make check`).

Runs every cataloged rule over `libsplinter_tpu/` + `scripts/` and
exits non-zero on any unsuppressed, unbaselined finding — report
format `file:line · RULE_ID · message`, same as `spt lint`.

Loads `libsplinter_tpu/analysis` by path WITHOUT importing the
package (whose __init__ needs the built native .so): the gate is
stdlib-only and runs before any build step.
"""
from __future__ import annotations

import importlib.util
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_splint():
    spec = importlib.util.spec_from_file_location(
        "_splint_load", os.path.join(
            REPO, "libsplinter_tpu", "analysis", "_load.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.load()


def main() -> int:
    splint = load_splint()
    rep = splint.scan(REPO)
    print(rep.render())
    for f, sup in rep.suppressed:
        print(f"  suppressed: {f.render()}  [reason={sup.reason}]")
    if not rep.clean:
        print("splint_check: FAIL — fix the findings above, add a "
              "justified inline suppression, or (outside the engine "
              "layer) baseline them (spt lint --write-baseline)",
              file=sys.stderr)
        return 1
    print("splint_check: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
