"""CI gate: the pipeline lane must actually collapse per-stage client
round trips (`make pipeline-check`).

Runs the SAME rag-churn chain two ways against one in-process stack
(stub encoder/generator — this measures orchestration, not model
math): the client-side scenario (one submit+poll round trip per
ingest -> embed -> top-k -> complete hop) and the stored-script
scenario (ONE pipeline-lane request, the chain server-side).  The
scripted p50 must land >= 30% below the client-side p50 — the
ROADMAP item-3 target and the ISSUE 12 acceptance bar.  Both runs
also enforce the standing zero-admitted-loss invariant.
"""
from __future__ import annotations

import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from libsplinter_tpu import Store  # noqa: E402
from libsplinter_tpu.cli.loadgen import (LoadGenerator,  # noqa: E402
                                         TenantSpec)
from libsplinter_tpu.engine.completer import Completer  # noqa: E402
from libsplinter_tpu.engine.embedder import Embedder  # noqa: E402
from libsplinter_tpu.engine.pipeliner import Pipeliner  # noqa: E402
from libsplinter_tpu.engine.searcher import Searcher  # noqa: E402

REQUIRED_DROP = 0.30


def main() -> int:
    name = f"/spt-plcheck-{os.getpid()}"
    st = Store.create(name, nslots=512, max_val=1024, vec_dim=32)

    def enc(texts):
        out = np.zeros((len(texts), st.vec_dim), np.float32)
        for i, t in enumerate(texts):
            out[i, hash(t) % st.vec_dim] = 1.0
        return out

    emb = Embedder(st, encoder_fn=enc, max_ctx=64)
    sr = Searcher(st)
    comp = Completer(st, generate_fn=lambda p: iter([b"answer"]),
                     template="none")
    pl = Pipeliner(st)
    daemons = (emb, sr, comp, pl)
    for d in daemons:
        d.attach()
    ths = [threading.Thread(target=d.run,
                            kwargs=dict(idle_timeout_ms=10,
                                        stop_after=180.0),
                            daemon=True) for d in daemons]
    for t in ths:
        t.start()
    time.sleep(0.2)

    def p50_of(scenario: str) -> float:
        gen = LoadGenerator(st, [TenantSpec(1, 10.0, deadline_ms=8000)],
                            duration_s=3.0, corpus=8, seed=11,
                            scenario=scenario)
        rep = gen.run()
        assert rep["lost"] == 0, f"{scenario}: lost={rep['lost']}"
        assert rep["ok"] >= max(1, rep["issued"] - 1), \
            f"{scenario}: {rep}"
        lane = "rag" if scenario == "rag-churn" else "script"
        # exact median from the raw samples — the report's
        # log-bucketed p50 quantizes to ~19%-wide buckets, too coarse
        # for a 30% A/B gate
        return float(np.median(gen.raw_ms[(1, lane)]))

    try:
        # client first, script second: any store warmup bias favors
        # the CLIENT side, so a pass is conservative
        client_p50 = p50_of("rag-churn")
        script_p50 = p50_of("rag-churn-script")
    finally:
        for d in daemons:
            d.stop()
        for t in ths:
            t.join(timeout=15)
        st.close()
        Store.unlink(name)

    drop = 1.0 - script_p50 / client_p50
    print(f"rag-churn p50: client-chained {client_p50:.1f} ms, "
          f"stored-script {script_p50:.1f} ms "
          f"({drop:.0%} drop; gate >= {REQUIRED_DROP:.0%})")
    if drop < REQUIRED_DROP:
        print("FAIL: the pipeline lane did not beat client-side "
              "chaining by the required margin")
        return 1
    print("PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
