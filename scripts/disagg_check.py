#!/usr/bin/env python
"""CI gate: decode isolation under a prefill burst, in-process.

Runs the disaggregated pair — PrefillLane + DecodeLane on one shared
store (ISSUE 18) — and drives `spt loadgen`'s prefill-burst scenario
through a 1x -> 10x -> 1x prompt-heavy rate step while a steady
decode-floor tenant streams underneath.  Asserts the tentpole's
serving contract at smoke scale:

  - the decode floor's inter-chunk p99 during the 10x prefill burst
    stays within 1.2x of the prefill-idle baseline (plus a small
    absolute slack so a 1-core CI box's scheduler jitter cannot flake
    the ratio on a ~5 ms baseline);
  - ZERO admitted-request loss (loadgen's `lost` classification is
    the drain-protocol contract, same as scale_step_check);
  - the handoff plane actually ran: prefill handed off wire pages and
    decode adopted them (handoff_refill == 0 — the store is sized so
    real page export/import is what gets measured, not the re-prefill
    fallback).

The baseline run and the burst run share one warm lane pair, so
compile time never lands inside a measured gap.

Run: JAX_PLATFORMS=cpu python scripts/disagg_check.py
(make disagg-check wires it into make check.)
"""
from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax.numpy as jnp  # noqa: E402

from libsplinter_tpu import Store  # noqa: E402
from libsplinter_tpu.cli.loadgen import (LoadGenerator,  # noqa: E402
                                         TenantSpec)
from libsplinter_tpu.engine import protocol as P  # noqa: E402
from libsplinter_tpu.engine.disagg import (DecodeLane,  # noqa: E402
                                           PrefillLane)
from libsplinter_tpu.models.decoder import (CompletionModel,  # noqa: E402
                                            DecoderConfig)

STORE = f"/spt-disagg-check-{os.getpid()}"
RATE = 2.0                          # 1x offered rate per class (req/s)
IDLE_PROFILE = [(1.0, 4.0)]
BURST_PROFILE = [(1.0, 2.0), (10.0, 6.0), (1.0, 2.0)]
RATIO = 1.2                         # the ISSUE 18 acceptance bound
SLACK_MS = 50.0                     # absolute floor for tiny baselines


def _floor_p99(report: dict, phase: int) -> float | None:
    for row in report.get("prefill_burst", []):
        if row.get("phase") == phase:
            return row.get("decode-floor", {}).get("interchunk_p99_ms")
    return None


def main() -> int:
    Store.unlink(STORE)
    # max_val 16384 > page_wire_bytes(tiny f32, page=8) = 4096: the
    # gate exercises the REAL wire export/import, never the fallback
    store = Store.create(STORE, nslots=1024, max_val=16384, vec_dim=8)
    model = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                            buckets=(32,), temp=0.0, seed=1,
                            suffix_buckets=(8,))
    kw = dict(model=model, max_new_tokens=10, flush_tokens=2,
              template="none", batch_cap=4, page_size=8)
    lanes = [PrefillLane(store, **kw), DecodeLane(store, **kw)]
    ths: list[threading.Thread] = []
    try:
        for d in lanes:
            d.attach()
        ths = [threading.Thread(
            target=d.run_continuous,
            kwargs=dict(idle_timeout_ms=10, stop_after=300.0),
            daemon=True) for d in lanes]
        for th in ths:
            th.start()

        # warm the pair end-to-end (prefill bucket + decode chunk
        # compiles) before anything is measured
        for i in range(3):
            key = f"__warm/{i}"
            store.set(key, f"warm {i} up")
            store.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
            store.bump(key)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if all(store.labels(f"__warm/{i}") & P.LBL_READY
                   for i in range(3)):
                break
            time.sleep(0.05)
        else:
            print("FAIL: warmup requests never completed")
            return 1

        def run(profile, seed):
            gen = LoadGenerator(
                store, [TenantSpec(tenant=1, rate=RATE,
                                   deadline_ms=60_000)],
                scenario="prefill-burst", rate_profile=profile,
                corpus=16, seed=seed, drain_s=45.0)
            return gen.run()

        idle_rep = run(IDLE_PROFILE, seed=11)
        burst_rep = run(BURST_PROFILE, seed=12)

        p99_idle = _floor_p99(idle_rep, 0)
        p99_burst = _floor_p99(burst_rep, 1)
        pf, dl = lanes[0]._lane_stats, lanes[1]._lane_stats
        lost = idle_rep["lost"] + burst_rep["lost"]

        print(f"disagg_check: idle floor inter-chunk p99 = "
              f"{p99_idle} ms; burst (10x prefill) = {p99_burst} ms; "
              f"lost={lost}")
        print(f"  prefill: handoffs={pf.get('handoffs')} "
              f"failed={pf.get('handoff_failed')} "
              f"wire_mb={pf.get('handoff_wire_mb')}")
        print(f"  decode: adopted={dl.get('adopted')} "
              f"readopted={dl.get('readopted')} "
              f"refill={dl.get('handoff_refill')} "
              f"backpressure={dl.get('adopt_backpressure')}")

        fails = []
        if p99_idle is None or p99_burst is None:
            fails.append("missing inter-chunk quantiles (floor tenant "
                         "streamed no multi-chunk completions)")
        else:
            bound = max(RATIO * p99_idle, p99_idle + SLACK_MS)
            if p99_burst > bound:
                fails.append(
                    f"decode p99 degraded under prefill burst: "
                    f"{p99_burst:.1f} ms > bound {bound:.1f} ms "
                    f"(idle {p99_idle:.1f} ms)")
        if lost:
            fails.append(f"{lost} admitted requests LOST "
                         "(zero-loss contract)")
        if not pf.get("handoffs"):
            fails.append("prefill lane recorded zero handoffs")
        if not dl.get("adopted"):
            fails.append("decode lane adopted zero rows")
        if dl.get("handoff_refill"):
            fails.append(f"{dl['handoff_refill']} adoptions fell back "
                         "to re-prefill (wire path not exercised)")
        if fails:
            print("disagg_check: FAIL — " + "; ".join(fails))
            return 1
        print("disagg_check: PASS — decode floor held its inter-chunk "
              "p99 through a 10x prefill burst with zero admitted "
              "loss")
        return 0
    finally:
        for d in lanes:
            d.stop()
        for th in ths:
            th.join(timeout=30)
        store.close()
        Store.unlink(STORE)


if __name__ == "__main__":
    raise SystemExit(main())
