"""CPU fast gate for the dispatch-floor work (`make dispatch-check`).

BENCH_r05 attributed ~94% of the p50 set->vector to the per-call
runtime dispatch (null_dispatch_ms ~63 ms); PR 7's resident ring runs
K batches per dispatch so the floor amortizes to ~floor/K.  This gate
asserts the amortization actually holds on this backend:

  - resident per-drain host overhead shrinks MONOTONICALLY with depth
    (15% noise headroom per step, best-of-ROUNDS to dampen scheduler
    jitter);
  - depth-8 amortized cost is at least 2x below depth 1 (the bench
    phase's acceptance bar is 4x on the measurement backend; the CI
    gate keeps generous slack for loaded shared runners).

The K-overlap rows are measured and printed for attribution but not
gated: on CPU each dispatch's HOST cost dominates the round trip, so
overlap amortizes little here — its win is the tunneled-runtime RTT,
which only the TPU bench row (phase `dispatch`) can show.
"""
from __future__ import annotations

import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROUNDS = int(os.environ.get("DISPATCH_CHECK_ROUNDS", "3"))
DEPTHS = (1, 2, 4, 8)


def main() -> int:
    from bench_series import dispatch_depth_rows

    best: dict[int, dict] = {}
    for _ in range(ROUNDS):
        for row in dispatch_depth_rows(DEPTHS, reps=20):
            d = row["depth"]
            if (d not in best or row["resident_ms_per_drain"]
                    < best[d]["resident_ms_per_drain"]):
                best[d] = row
    rows = [best[d] for d in DEPTHS]
    print(json.dumps(rows, indent=1))

    res = [r["resident_ms_per_drain"] for r in rows]
    ok = True
    for i in range(1, len(res)):
        if res[i] > res[i - 1] * 1.15:
            print(f"FAIL: resident per-drain cost rose "
                  f"{res[i - 1]:.4f} -> {res[i]:.4f} ms at depth "
                  f"{DEPTHS[i]} (must shrink monotonically)")
            ok = False
    if res[-1] > res[0] / 2:
        print(f"FAIL: depth-{DEPTHS[-1]} amortized cost "
              f"{res[-1]:.4f} ms not >=2x below depth-1 {res[0]:.4f} ms")
        ok = False
    if ok:
        print(f"OK: resident per-drain {res[0]:.4f} ms @1 -> "
              f"{res[-1]:.4f} ms @{DEPTHS[-1]} "
              f"({res[0] / max(res[-1], 1e-9):.1f}x amortization)")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
