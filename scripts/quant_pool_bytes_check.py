#!/usr/bin/env python
"""quant-check's byte gate: the quantized paged pools must actually
be small — MEASURED from the placed device buffers, not computed from
shapes — for the same page count:

    int8  ==  1/2 of bf16  ==  1/4 of f32   (within 10%)
    int4  ==  1/4 of bf16  ==  1/8 of f32  ==  1/2 of int8

The tolerance absorbs the per-page scale arrays ((n_blocks, KH) f32
per layer per side — the only overhead the quantized layouts add; at
serving page sizes they are <1% of int8's values and <2% of int4's).
A regression here means a pool silently stored floats or unpacked
codes (a dtype/packing threading bug) or the scales ballooned —
either way the "cache bytes are tokens/sec" claim of the quantized
decode lane is void, so CI fails loudly.

Run: JAX_PLATFORMS=cpu python scripts/quant_pool_bytes_check.py
(wired into `make quant-check` and `make check`).
"""
from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402

from libsplinter_tpu.models.decoder import (DecoderConfig,  # noqa: E402
                                            PagedKVCache)


def main() -> int:
    cfg = DecoderConfig.tiny(max_len=256)
    mb: dict[str, float] = {}
    for kvd in ("f32", "bf16", "int8", "int4"):
        cache = PagedKVCache(cfg, 4, page=32, pool_pages=32,
                             kv_dtype=kvd)
        mb[kvd] = cache.device_mb()
        assert cache.kv_dtype == kvd
        assert cache.packed == (kvd == "int4")
    r_bf16 = mb["int8"] / mb["bf16"]
    r_f32 = mb["int8"] / mb["f32"]
    r4_bf16 = mb["int4"] / mb["bf16"]
    r4_f32 = mb["int4"] / mb["f32"]
    r4_i8 = mb["int4"] / mb["int8"]
    print(f"paged pool bytes (measured from placed buffers, "
          f"{cfg.layers} layers x 33 blocks x page 32):")
    for kvd, v in mb.items():
        print(f"  {kvd:>5}: {v:8.3f} MB")
    print(f"  int8/bf16 = {r_bf16:.3f} (want 0.5 +- 10%)")
    print(f"  int8/f32  = {r_f32:.3f} (want 0.25 +- 10%)")
    print(f"  int4/bf16 = {r4_bf16:.3f} (want 0.25 +- 10%)")
    print(f"  int4/f32  = {r4_f32:.3f} (want 0.125 +- 10%)")
    print(f"  int4/int8 = {r4_i8:.3f} (want 0.5 +- 10%)")
    ok = abs(r_bf16 - 0.5) < 0.05 and abs(r_f32 - 0.25) < 0.025
    if not ok:
        print("FAIL: the int8 pool does not halve bf16 / quarter f32 "
              "— storage dtype threading is broken")
        return 1
    ok4 = (abs(r4_bf16 - 0.25) < 0.025 and abs(r4_f32 - 0.125) < 0.0125
           and abs(r4_i8 - 0.5) < 0.05)
    if not ok4:
        print("FAIL: the int4 pool does not quarter bf16 / eighth "
              "f32 / halve int8 — nibble packing is not reaching the "
              "placed buffers")
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
