"""CI gate: the radix prefix cache must actually collapse hot-prefix
admission latency (`make prefix-check`).

Serves the SAME long prompt repeatedly through one in-process
continuous-batching completer (real tiny decoder, CPU) two ways:
with the prefix cache DISABLED (every admission pays the full dense
bucket prefill — the cold path) and ENABLED (the first admission
warms the tree, every later one maps the shared pages and replays at
most a page-tail — a host-side table write plus one decode chunk).
The hot admission-to-first-token p50 must land >= 5x below the cold
p50 — the CPU-stack floor of the ISSUE 14 / ROADMAP item 2 target
(the >= 10x headline is the TPU ledger row, where the dense prefill
the hot path skips is far more expensive relative to a table write).

Both runs also assert byte-identical greedy output, so the speedup
can never be bought with a correctness regression.
"""
from __future__ import annotations

import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from libsplinter_tpu import Store  # noqa: E402
from libsplinter_tpu.engine import protocol as P  # noqa: E402
from libsplinter_tpu.engine.completer import Completer  # noqa: E402
from libsplinter_tpu.models.decoder import (CompletionModel,  # noqa: E402
                                            DecoderConfig)

REQUIRED_SPEEDUP = 5.0
PAGE = 32
# 33 pages of prompt (+ BOS): long enough that the cold dense bucket
# prefill dwarfs scheduling noise, short enough that CPU CI stays
# fast.  chars = pages*PAGE - 1 because the byte tokenizer prepends
# BOS — the repeated prompt must land exactly on a page boundary so
# the hot path is the pure map + replay (zero prefill) form.
PROMPT = ("retrieval context: " * 70)[: 33 * PAGE - 1]
TRIALS = 6


def first_token_ms(st, comp_key: str, prompt: str) -> float:
    """Submit one completion and clock submit -> first streamed byte
    (the completer claims the slot by overwriting it with the
    rendered prompt, so 'first token' is value growth past it)."""
    st.set(comp_key, prompt)
    rendered_len = len(prompt.encode())
    t0 = time.perf_counter()
    st.label_or(comp_key, P.LBL_INFER_REQ | P.LBL_WAITING)
    st.bump(comp_key)
    deadline = t0 + 60.0
    while time.perf_counter() < deadline:
        try:
            if st.value_len(comp_key) > rendered_len:
                return (time.perf_counter() - t0) * 1e3
        except KeyError:
            pass
        time.sleep(0.0002)
    raise SystemExit(f"request {comp_key} never streamed a token")


def run_lane(tag: str, enable_cache: bool) -> tuple[list[float], list[bytes]]:
    name = f"/spt-pfxchk-{tag}-{os.getpid()}"
    Store.unlink(name)
    st = Store.create(name, nslots=256, max_val=8192, vec_dim=8)
    lat: list[float] = []
    outs: list[bytes] = []
    try:
        cfg = DecoderConfig.tiny(max_len=2048)
        model = CompletionModel(cfg, buckets=(1088,), temp=0.0,
                                seed=1, suffix_buckets=(16,))
        # a tight pool matters on CPU: buffer donation is a no-op
        # there, so every dispatch COPIES the pools — an oversized
        # pool taxes the hot path (one chunk) far more than the cold
        # one (one big prefill), understating the real win
        comp = Completer(st, model=model, max_new_tokens=6,
                         flush_tokens=1, template="none", batch_cap=4,
                         page_size=PAGE, pool_pages=110,
                         inflight_depth=1,
                         prefix_cache=enable_cache)
        comp.attach()
        comp.warmup_paged()           # no compiles inside the clock
        th = threading.Thread(
            target=comp.run_continuous,
            kwargs=dict(idle_timeout_ms=5, stop_after=180.0),
            daemon=True)
        th.start()
        time.sleep(0.1)
        # one unmeasured warmer: with the cache on it seeds the tree,
        # with it off it equalizes any store/lane warmup bias
        first_token_ms(st, f"{tag}/warm", PROMPT)
        for i in range(TRIALS):
            key = f"{tag}/{i}"
            lat.append(first_token_ms(st, key, PROMPT))
            deadline = time.time() + 30
            while time.time() < deadline and \
                    not st.labels(key) & P.LBL_READY:
                time.sleep(0.001)
            assert st.labels(key) & P.LBL_READY, f"{key} never READY"
            outs.append(st.get(key).rstrip(b"\0"))
        if enable_cache:
            s = comp.prefix_cache.stats
            assert s.hits >= TRIALS, \
                f"hot run missed the cache: {s}"
        comp.stop()
        th.join(timeout=20)
    finally:
        st.close()
        Store.unlink(name)
    return lat, outs


def main() -> int:
    cold, cold_out = run_lane("cold", enable_cache=False)
    hot, hot_out = run_lane("hot", enable_cache=True)
    assert cold_out == hot_out, (
        "prefix-shared output diverged from the cache-disabled path:\n"
        f"  cold: {cold_out[0]!r}\n  hot:  {hot_out[0]!r}")
    cold_p50 = float(np.median(cold))
    hot_p50 = float(np.median(hot))
    speedup = cold_p50 / hot_p50 if hot_p50 > 0 else float("inf")
    print(f"admission-to-first-token p50: cache-disabled "
          f"{cold_p50:.2f} ms, hot prefix {hot_p50:.2f} ms "
          f"({speedup:.1f}x; gate >= {REQUIRED_SPEEDUP:g}x)")
    if speedup < REQUIRED_SPEEDUP:
        print("FAIL: the prefix cache did not beat the cold path by "
              "the required margin")
        return 1
    print("PASS")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
