#!/usr/bin/env python
"""CI gate: a supervised kill-and-restart comes back WARM.

Runs one tiered completer lane (`--kv-tier-pages` + a persistent
`--kv-tier-persist` segment, ISSUE 19) under `spt supervise`, drives
`spt loadgen` through it, SIGKILLs the lane MID-LOAD, and asserts the
warm-restart contract at smoke scale:

  - zero admitted-request loss through the kill (the respawned lane
    reclaims every stranded claim — loadgen's `lost` classification);
  - the respawn attaches WARM: the persistent radix index restores
    (heartbeat tier_restored > 0, no typed tier_restore_reason) and
    the hot prompts served before the kill come back via DRAM/file
    readmission (tier_readmits > 0, prefix_hits > 0) — not re-prefill;
  - greedy bytes for those prompts are identical across the restart;
  - post-restart first-token p50 stays within 2x of the pre-restart
    baseline (plus a small absolute slack so a 1-core CI box's
    scheduler jitter cannot flake a ~5 ms baseline).  Both measured
    windows run against a warmed lane — compile time never lands
    inside a measured TTFT.

Run: JAX_PLATFORMS=cpu python scripts/warm_restart_check.py
(make warm-check wires it into make check.)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

STORE = f"/spt-warm-check-{os.getpid()}"
RATIO = 2.0                         # the ISSUE 19 acceptance bound
SLACK_MS = 50.0                     # absolute floor for tiny baselines
WARM_PROMPTS = [f"the warm set prompt number {i} stays hot"
                for i in range(3)]


def child(store_name: str, persist_name: str) -> int:
    """The supervised lane: a tiny tiered completer with the
    persistent warm layer armed (what `spt supervise --tier-pages N
    --tier-persist` fans out at production scale)."""
    import jax.numpy as jnp

    from libsplinter_tpu import Store
    from libsplinter_tpu.engine.completer import Completer
    from libsplinter_tpu.models.decoder import (CompletionModel,
                                                DecoderConfig)
    st = Store.open(store_name)
    model = CompletionModel(DecoderConfig.tiny(dtype=jnp.float32),
                            buckets=(32,), temp=0.0, seed=1,
                            suffix_buckets=(8,))
    comp = Completer(st, model=model, max_new_tokens=10,
                     flush_tokens=2, template="none", batch_cap=4,
                     page_size=8, kv_tier_pages=64,
                     kv_tier_persist=persist_name)
    comp.attach()
    comp.run_continuous(idle_timeout_ms=10, stop_after=900.0)
    return 0


def _ttft_p50(report: dict) -> float | None:
    for row in report.get("prefill_burst", []):
        sect = row.get("prefill-burst") or {}
        if "ttft_p50_ms" in sect:
            return sect["ttft_p50_ms"]
    return None


def main() -> int:
    from libsplinter_tpu import Store
    from libsplinter_tpu.cli.loadgen import LoadGenerator, TenantSpec
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.kv_tier import TierPersist
    from libsplinter_tpu.engine.supervisor import Supervisor

    persist = f"{STORE}-kvtier"
    Store.unlink(STORE)
    TierPersist.unlink(persist)
    # max_val 16384: same sizing as disagg_check — roomy values, the
    # tier's own persistence lives in its own segment
    store = Store.create(STORE, nslots=1024, max_val=16384, vec_dim=8)

    def spawn(lane):
        return subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             STORE, persist])

    sup = Supervisor(STORE, lanes=("completer",), spawn_fn=spawn,
                     store=store, backoff_base_ms=100,
                     backoff_max_ms=2000, breaker_threshold=8,
                     breaker_window_s=120, startup_grace_s=300)
    sup_t = threading.Thread(target=sup.run,
                             kwargs={"poll_interval_s": 0.1,
                                     "stop_after": 900.0})
    sup_t.start()

    def submit(key, prompt):
        store.set(key, prompt)
        store.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
        store.bump(key)

    def await_ready(keys, timeout):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(store.labels(k) & P.LBL_READY for k in keys):
                return True
            time.sleep(0.05)
        return False

    def run_loadgen(seed):
        gen = LoadGenerator(
            store, [TenantSpec(tenant=1, rate=2.0,
                               deadline_ms=120_000)],
            scenario="prefill-burst", rate_profile=[(1.0, 8.0)],
            corpus=16, seed=seed, drain_s=90.0)
        return gen.run()

    try:
        # warm the lane AND plant the hot set the restart must revive
        warm_keys = [f"__warm/{i}" for i in range(len(WARM_PROMPTS))]
        for k, p in zip(warm_keys, WARM_PROMPTS):
            submit(k, p)
        if not await_ready(warm_keys, 240):
            print("FAIL: warmup requests never completed")
            return 1
        pre_bytes = [store.get(k).rstrip(b"\0") for k in warm_keys]

        rep_pre = run_loadgen(seed=31)
        # let one more dirty-gated checkpoint beat land (5s cadence)
        # so the snapshot covers the loadgen window's inserts too
        time.sleep(6.0)

        # SIGKILL mid-load: a third loadgen window is in flight when
        # the lane dies — the respawn must reclaim every claim
        holder: dict = {}
        kt = threading.Thread(
            target=lambda: holder.update(rep=run_loadgen(seed=32)))
        kt.start()
        time.sleep(2.0)
        lane = sup.lanes["completer"]
        gen_before = lane.generation
        proc = lane.proc
        if proc is None:
            print("FAIL: no live lane process to kill")
            return 1
        proc.kill()                  # no checkpoint, no cleanup
        kt.join()
        rep_kill = holder["rep"]

        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if lane.generation > gen_before and lane.pid:
                break
            time.sleep(0.1)
        else:
            print("FAIL: supervisor never respawned the lane")
            return 1

        # the SAME prompts through the respawned lane: must come back
        # byte-identical via the restored index + readmission (and
        # re-warm the new process so measured TTFT excludes compiles)
        rewarm_keys = [f"__rewarm/{i}"
                       for i in range(len(WARM_PROMPTS))]
        for k, p in zip(rewarm_keys, WARM_PROMPTS):
            submit(k, p)
        if not await_ready(rewarm_keys, 240):
            print("FAIL: post-restart requests never completed")
            return 1
        post_bytes = [store.get(k).rstrip(b"\0") for k in rewarm_keys]

        rep_post = run_loadgen(seed=33)
        snap = json.loads(
            store.get(P.KEY_COMPLETE_STATS).rstrip(b"\0"))

        p50_pre = _ttft_p50(rep_pre)
        p50_post = _ttft_p50(rep_post)
        lost = (rep_pre["lost"] + rep_kill["lost"]
                + rep_post["lost"])
        print(f"warm_check: ttft p50 pre={p50_pre} ms "
              f"post={p50_post} ms; lost={lost}; "
              f"restarts={lane.restarts}")
        print(f"  tier: restored={snap.get('tier_restored')} "
              f"readmits={snap.get('tier_readmits')} "
              f"pages={snap.get('tier_pages')} "
              f"reason={snap.get('tier_restore_reason', '')!r} "
              f"prefix_hits={snap.get('prefix_hits')}")

        fails = []
        if lost:
            fails.append(f"{lost} admitted requests LOST "
                         "(zero-loss contract)")
        if lane.restarts < 1:
            fails.append("the lane never restarted (kill not seen)")
        if post_bytes != pre_bytes:
            fails.append("hot-prompt bytes changed across the "
                         "restart (greedy must be identical)")
        if not snap.get("tier_restored"):
            fails.append("respawn attached COLD (tier_restored == 0 "
                         "— persistent index not restored)")
        if snap.get("tier_restore_reason"):
            fails.append("typed cold fallback: tier_restore_reason="
                         f"{snap['tier_restore_reason']!r}")
        if not snap.get("tier_readmits"):
            fails.append("no readmissions: the warm set was "
                         "re-prefilled, not readmitted")
        if not snap.get("prefix_hits"):
            fails.append("radix hit rate did not recover post-"
                         "restart (prefix_hits == 0)")
        if p50_pre is None or p50_post is None:
            fails.append("missing TTFT quantiles in a loadgen window")
        else:
            bound = max(RATIO * p50_pre, p50_pre + SLACK_MS)
            if p50_post > bound:
                fails.append(
                    f"post-restart first-token p50 degraded: "
                    f"{p50_post:.1f} ms > bound {bound:.1f} ms "
                    f"(pre {p50_pre:.1f} ms)")
        if fails:
            print("warm_check: FAIL — " + "; ".join(fails))
            return 1
        print("warm_check: PASS — supervised kill-and-restart came "
              "back warm (index restored, hot set readmitted, bytes "
              "identical, first-token p50 within bound, zero loss)")
        return 0
    finally:
        sup.stop()
        sup_t.join(timeout=30)
        sup.shutdown()
        store.close()
        Store.unlink(STORE)
        TierPersist.unlink(persist)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        raise SystemExit(child(sys.argv[2], sys.argv[3]))
    raise SystemExit(main())
