"""Regenerate the pinned end-to-end checkpoint golden fixture.

Produces (committed under tests/fixtures/):
  - golden_encoder.gguf  — tiny nomic-geometry encoder, fixed-seed
    weights, with a REAL trained HF WordPiece vocab embedded as
    tokenizer.ggml metadata (tokenizer.ggml.model="bert");
  - golden_expected.json — for a fixed set of input texts: the exact
    token ids and the exact (out_dim,) embedding vectors the cold
    load→tokenize→encode chain must reproduce.

The e2e test (tests/test_golden_e2e.py) opens the .gguf with NO
side-channel configuration — config, tokenizer, and weights all come
from the file — and must reproduce both ids and vectors exactly
(VERDICT r2 #5; reference analog: executing a published checkpoint,
splinference.cpp:423-447).

Determinism: the HF `tokenizers` WordPiece trainer is NOT run-to-run
deterministic (hash-order tie-breaking), so the trained vocab is itself
a pinned artifact — tests/fixtures/golden_vocab.txt, trained ONCE by
the HF Rust trainer and committed; this script retrains only if that
file is missing.  With the vocab pinned, regeneration is fully
deterministic (weights from a fixed PRNG seed, float32 on the CPU
backend) and must be a no-op diff unless the model/tokenizer code
changed — in which case the diff IS the signal that the golden must be
re-pinned deliberately.

Usage:  python scripts/make_golden_fixture.py
"""
from __future__ import annotations

import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from libsplinter_tpu.utils.jaxplatform import force_cpu  # noqa: E402

force_cpu()

import numpy as np  # noqa: E402

# SPTPU_GOLDEN_OUT overrides the output dir (the determinism test
# regenerates into a tempdir and byte-compares)
FIXDIR = os.environ.get("SPTPU_GOLDEN_OUT") or \
    os.path.join(ROOT, "tests", "fixtures")

CORPUS = [
    "the seqlock store commits vectors epoch gated",
    "a signal pulse wakes the embedding daemon",
    "tpu meshes shard the arena row wise over ici",
    "bloom labels route keys to interest groups",
    "the completion daemon streams chunked tokens",
    "matryoshka truncation keeps the leading dimensions",
    "ring attention rotates key value blocks around the pod",
    "pallas kernels fuse similarity and top k",
] * 4

TEXTS = [
    "the daemon commits epoch gated vectors",
    "pallas kernels shard the arena",
    "a wake pulse routes bloom labels",
    "unseen wordforms backoff to subword pieces",
]

VOCAB_SIZE = 384
SEED = 7
OUT_DIM = 32


VOCAB_PIN = os.path.join(ROOT, "tests", "fixtures", "golden_vocab.txt")


def pinned_vocab() -> list[str]:
    """The committed vocab if present; otherwise train and pin it."""
    if os.path.exists(VOCAB_PIN):
        with open(VOCAB_PIN, encoding="utf-8") as f:
            return [ln.rstrip("\n") for ln in f]
    vocab = train_vocab()
    os.makedirs(os.path.dirname(VOCAB_PIN), exist_ok=True)
    with open(VOCAB_PIN, "w", encoding="utf-8") as f:
        f.write("\n".join(vocab) + "\n")
    print(f"trained and pinned new vocab -> {VOCAB_PIN}")
    return vocab


def train_vocab() -> list[str]:
    from tokenizers import Tokenizer, models, normalizers, pre_tokenizers
    from tokenizers.trainers import WordPieceTrainer

    tok = Tokenizer(models.WordPiece(unk_token="[UNK]"))
    tok.normalizer = normalizers.Sequence(
        [normalizers.NFD(), normalizers.Lowercase(),
         normalizers.StripAccents()])
    tok.pre_tokenizer = pre_tokenizers.Whitespace()
    trainer = WordPieceTrainer(
        vocab_size=VOCAB_SIZE, show_progress=False,
        special_tokens=["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"])
    tok.train_from_iterator(CORPUS, trainer)
    vocab = tok.get_vocab()
    return [t for t, _ in sorted(vocab.items(), key=lambda kv: kv[1])]


def main() -> int:
    import jax

    from libsplinter_tpu.models.encoder import (EmbeddingModel,
                                                EncoderConfig)
    from libsplinter_tpu.models.gguf_writer import export_encoder_gguf

    os.makedirs(FIXDIR, exist_ok=True)
    vocab = pinned_vocab()
    print(f"WordPiece vocab: {len(vocab)} tokens")

    cfg = EncoderConfig.tiny(vocab_size=len(vocab), out_dim=OUT_DIM,
                             dtype=jax.numpy.float32)
    model = EmbeddingModel(cfg, seed=SEED, buckets=(32,))
    gguf_path = os.path.join(FIXDIR, "golden_encoder.gguf")
    export_encoder_gguf(model.params, cfg, gguf_path,
                        tokenizer_vocab=vocab)
    print(f"wrote {gguf_path} ({os.path.getsize(gguf_path)} bytes)")

    # -- compute the expected outputs through the COLD-LOAD path ----------
    from libsplinter_tpu.models.gguf import (GgufFile,
                                             encoder_config_from_gguf,
                                             load_tokenizer)

    with GgufFile(gguf_path) as gf:
        cold_cfg = encoder_config_from_gguf(
            gf, out_dim=OUT_DIM, dtype=jax.numpy.float32)
        tok = load_tokenizer(gf)
    cold = EmbeddingModel(cold_cfg, weights=gguf_path, buckets=(32,))

    expected = {"texts": [], "config": {
        "vocab_size": cold_cfg.vocab_size, "hidden": cold_cfg.hidden,
        "layers": cold_cfg.layers, "out_dim": OUT_DIM, "seed": SEED}}
    for text in TEXTS:
        ids = tok.encode(text)
        arr = np.full((1, 32), tok.pad_id, np.int32)
        arr[0, : len(ids)] = ids
        vec = cold.encode_ids(arr, np.array([len(ids)], np.int32))[0]
        expected["texts"].append({
            "text": text,
            "token_ids": [int(i) for i in ids],
            "vector": [float(f"{v:.8e}") for v in np.asarray(vec)],
        })
        print(f"  {text!r}: {len(ids)} ids, |v|="
              f"{np.linalg.norm(vec):.4f}")

    out = os.path.join(FIXDIR, "golden_expected.json")
    with open(out, "w") as f:
        json.dump(expected, f, indent=1)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
