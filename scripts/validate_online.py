"""Online checkpoint validation: real Nomic GGUF vs HF reference.

This build sandbox has ZERO egress, so the end-to-end checkpoint story
is regression-locked offline by the pinned golden fixture
(tests/test_golden_e2e.py).  In a network-enabled environment, run this
script to validate the same chain against the REAL published
checkpoint — it cross-checks this framework's GGUF loader + tokenizer +
encoder against the HuggingFace implementation token-for-token and
vector-for-vector (reference analog: splinference.cpp:423-447 executing
nomic-embed-text through llama.cpp).

One command:

    python scripts/validate_online.py \
        [--gguf nomic-ai/nomic-embed-text-v1.5-GGUF] \
        [--hf nomic-ai/nomic-embed-text-v1.5]

What it does:
  1. downloads the f32 GGUF via huggingface_hub (or uses --gguf-path);
  2. cold-loads it: encoder_config_from_gguf + load_tokenizer +
     EmbeddingModel(weights=...);
  3. tokenizes the probe texts with BOTH our WordPiece and HF's
     AutoTokenizer; asserts identical ids;
  4. encodes with both (ours on jax, HF's on torch cpu), mean-pools,
     L2-normalizes, truncates to --dim (matryoshka);
  5. asserts cosine(ours, hf) > 0.999 per text and prints a table.

Exit 0 = full parity; non-zero = the first mismatching stage, printed.
"""
from __future__ import annotations

import argparse
import sys

PROBES = [
    "search_query: what is a seqlock?",
    "search_document: The quick brown fox jumps over the lazy dog.",
    "Multi-reader single-writer stores favor wait-free reads.",
    "TPUs execute matmuls on a 128x128 systolic array.",
]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--gguf", default="nomic-ai/nomic-embed-text-v1.5-GGUF")
    ap.add_argument("--gguf-file", default="nomic-embed-text-v1.5.f32.gguf")
    ap.add_argument("--gguf-path", help="already-downloaded .gguf")
    ap.add_argument("--hf", default="nomic-ai/nomic-embed-text-v1.5")
    ap.add_argument("--dim", type=int, default=768)
    args = ap.parse_args()

    import os
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from libsplinter_tpu.utils.jaxplatform import force_cpu
    force_cpu()

    path = args.gguf_path
    if path is None:
        try:
            from huggingface_hub import hf_hub_download
        except ImportError:
            print("huggingface_hub not installed and no --gguf-path; "
                  "this environment has no download path", file=sys.stderr)
            return 2
        try:
            path = hf_hub_download(args.gguf, args.gguf_file)
        except Exception as e:
            print(f"download failed ({e}); zero-egress environment? "
                  "use --gguf-path", file=sys.stderr)
            return 2

    import numpy as np

    from libsplinter_tpu.models.encoder import EmbeddingModel
    from libsplinter_tpu.models.gguf import (GgufFile,
                                             encoder_config_from_gguf,
                                             load_tokenizer)

    with GgufFile(path) as gf:
        cfg = encoder_config_from_gguf(gf, out_dim=args.dim)
        tok = load_tokenizer(gf)
    model = EmbeddingModel(cfg, weights=path)
    print(f"loaded {path}: {cfg.layers}x{cfg.hidden} vocab={cfg.vocab_size}")

    from transformers import AutoModel, AutoTokenizer
    hf_tok = AutoTokenizer.from_pretrained(args.hf)
    hf_model = AutoModel.from_pretrained(args.hf, trust_remote_code=True)
    hf_model.eval()

    import torch

    worst = 1.0
    for text in PROBES:
        ours = tok.encode(text)
        theirs = hf_tok(text)["input_ids"]
        if ours != theirs:
            print(f"TOKENIZER MISMATCH on {text!r}:\n  ours   {ours}\n"
                  f"  theirs {theirs}")
            return 1
        n = len(ours)
        bucket = model.bucket_for(n)
        ids = np.full((1, bucket), tok.pad_id, np.int32)
        ids[0, :n] = ours
        v_ours = np.asarray(model.encode_ids(
            ids, np.array([n], np.int32))[0])
        with torch.no_grad():
            out = hf_model(**{k: torch.tensor(v).unsqueeze(0)
                              for k, v in hf_tok(text).items()})
        emb = out.last_hidden_state[0, :n].mean(0)
        emb = emb[: args.dim]
        v_hf = (emb / emb.norm()).numpy()
        cos = float(v_ours @ v_hf)
        worst = min(worst, cos)
        print(f"  cos={cos:.6f}  {text[:50]!r}")
    if worst < 0.999:
        print(f"FAIL: worst cosine {worst:.6f} < 0.999")
        return 1
    print(f"PARITY OK (worst cosine {worst:.6f})")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
