"""Generate docs/api/ — the per-function API reference for the sptpu.h
C ABI (VERDICT r4 #9; reference ships ~60 per-function pages,
/root/reference/docs/api/index.md).

The header's comments ARE the documentation source; this script turns
them into browsable markdown so they cannot drift apart:
`tests/test_api_docs.py` regenerates into a temp dir and fails when the
committed pages differ.

Since PR 11 it also renders the splint-registry-derived tables: the
label-bit map (into the bloom-labels appendix, from
`engine/protocol.py` via `libsplinter_tpu/analysis/registry.py`) and
the fault-point catalog + splint rule catalog (into the marked
regions of `docs/operations.md`).  Those tables are DERIVED, never
hand-edited — splint rule SPL106 and the doc-sync tests fail on
drift.

Usage: python scripts/gen_api_docs.py [outdir]   (default docs/api;
the default run also refreshes docs/operations.md's marked regions)
"""
from __future__ import annotations

import importlib.util
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
HEADER = os.path.join(REPO, "native", "include", "sptpu.h")
OPERATIONS_MD = os.path.join(REPO, "docs", "operations.md")


def load_splint():
    """Load libsplinter_tpu/analysis as a standalone package, WITHOUT
    importing libsplinter_tpu itself (whose __init__ needs the built
    native .so) — the analysis layer is stdlib-only by contract.
    The package-loading trick lives in analysis/_load.py (shared with
    scripts/splint_check.py and tests/test_splint.py)."""
    spec = importlib.util.spec_from_file_location(
        "_splint_load", os.path.join(
            REPO, "libsplinter_tpu", "analysis", "_load.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.load()

_SECTION_RE = re.compile(r"^/\* -{3,}\s*(.+?)\s*-*\s*(?:\*/)?\s*$")
_PROTO_START = re.compile(
    r"^(?:const\s+)?(?:unsigned\s+)?[A-Za-z_][A-Za-z0-9_]*\s*\**\s*"
    r"(spt_[A-Za-z0-9_]+)\s*\(")
_DEFINE_RE = re.compile(r"^#define\s+(SPT_[A-Za-z0-9_]+)")


def _clean_comment(lines: list[str]) -> str:
    """Strip comment markers, preserve paragraph flow."""
    out = []
    for ln in lines:
        ln = ln.strip()
        ln = re.sub(r"^/\*+", "", ln)
        ln = re.sub(r"\*+/$", "", ln)
        ln = re.sub(r"^\*\s?", "", ln)
        out.append(ln.rstrip())
    text = "\n".join(out).strip("\n")
    # collapse runs of blank lines
    text = re.sub(r"\n{3,}", "\n\n", text)
    return text.strip()


def _slug(title: str) -> str:
    s = title.lower()
    s = re.sub(r"\(.*?\)", "", s)          # drop parentheticals
    s = re.sub(r"[^a-z0-9]+", "-", s).strip("-")
    return s


class Section:
    def __init__(self, title: str):
        self.title = title
        self.slug = _slug(title)
        self.intro = ""
        self.funcs: list[tuple[str, str, str]] = []   # (name, sig, doc)
        self.defines: list[tuple[str, str, str]] = []  # (name, line, doc)
        self.types: list[tuple[str, str, str]] = []   # (name, body, doc)


# Hand-maintained appendices merged into generated pages (slug -> md).
# The header documents the C ABI; these document Python-layer surfaces
# that extend a section — kept HERE so the docs stay regenerable and
# tests/test_api_docs.py's sync check covers them too.
_APPENDICES = {
    "bloom-labels": """
## Label-bit map (`libsplinter_tpu/engine/protocol.py`)

The Python engine's bloom-label word, one row per constant — bit
positions, masks, and meanings extracted STATICALLY from
`engine/protocol.py` by the splint registry
(`libsplinter_tpu/analysis/registry.py`), so this table cannot drift
from the code: splint rule SPL101 fails any bit collision, SPL106
fails a stale table, and `make lint-check` gates both.

__SPLINT_LABEL_TABLE__

Bits 48-51 form the tenant-id *field* (`TENANT_MASK`); every other
row is a single-purpose flag.  Raw use of any of these bit values
outside `protocol.py` is splint violation SPL102 — always spell them
via the `protocol.LBL_*` / `BIT_*` constants.

## Paged KV cache + ragged paged attention (`models/decoder.py`, `ops/paged_attention.py`)

The completion lane behind `LBL_INFER_REQ` serves continuous batching
(`spt … --continuous`, `completer.run_continuous`) over a
**block-paged KV pool** instead of the dense per-batch cache:

### `PagedKVCache` (`libsplinter_tpu/models/decoder.py`)

| surface | contents |
|---|---|
| `k_pools` / `v_pools` | per layer `(n_blocks, kv_heads, page, head_dim)` global page pool |
| `tables` | host `(batch, pages_per_row)` int32 block table — entry `(b, p)` holds row b's tokens `[p*page, (p+1)*page)` |
| `lengths` | host `(batch,)` int32 per-row token counts (row b attends `j < lengths[b]`) |
| `ensure(row, tokens)` / `free_row(row)` | page-granular alloc (all-or-nothing; False = backpressure) and per-page refcount release (a page frees only at refcount zero) |
| `refcounts` / `map_shared(row, bids)` | cross-request prefix sharing (PR 14): tables from different rows point at the same full pages; `map_shared` is the refcount-bump table write that replaces a whole prefix prefill |
| `available_pages` | free-list pages + zero-ref prefix-cache pages reclaimable on demand — what admission backpressure gates on |
| `free_pages` / `used_pages` / `live_tokens()` | the pool gauges the completer heartbeat publishes (`sptpu_completer_pages_{free,used}`) |

Block 0 is the reserved **trash block**: unallocated table entries
point at it, so dead rows' appends land harmlessly and gathers of
unused pages read garbage the length mask excludes.  Cache HBM scales
with LIVE TOKENS, not `batch x max_len` — which is why `--batch-cap`
defaults to 32 (was 8) and `--pool-pages` caps the budget (default:
batch full windows).

### `paged_attention` (`libsplinter_tpu/ops/paged_attention.py`)

Pallas decode kernel, grid `(B, kv_heads, pages_per_row)`: the block
table rides scalar prefetch (`PrefetchScalarGridSpec`) so each
program's index map gathers exactly its page; a flash-style online
softmax carried across the page axis computes every row's attention
over its OWN ragged length — no shared `pos`, no window-mask padding,
pages wholly past a row's length skipped.  `interpret=True` runs it on
CPU for parity tests; non-TPU backends serve through the identical
jnp gathered-page math.  Prefill stays on the dense bucket programs
(`causal_flash_attention` for long chunks) and scatters into pages via
one commit program per bucket (`CompletionModel.paged_prefill_row`).

### Scheduler contract (`completer.run_continuous`)

Every admission is a join: the prompt prefills into freshly allocated
pages at any time (no join budget, no oversized-joiner deferral — a
joiner longer than a neighbour's remaining window is fine), finished
rows return pages immediately, and admission reserves the row's worst
case (`prompt + max_new` rounded up to a decode-chunk boundary —
decode appends whole `flush_tokens` chunks — capped at the window) so
decode can never
strand on an exhausted pool — a request the pool cannot cover stays
WAITING and `join_backpressure` counts it.  Stage spans publish under
`CONT_INFER_STAGES` (join / sample / decode / flush) and
client-stamped requests land in the flight recorder (`spt trace
tail`).  `make decode-check` gates the tier.

### Pod-sharded paged serving (`parallel/serve.py`, PR 8)

`ShardedCompletionModel` serves the SAME paged surface tensor-
parallel (`paged_supported` is True): each layer's pool shards on its
kv-head axis over the mesh's `tp` axis
(`parallel/mesh.kv_pool_sharding`; `PagedKVCache(..., sharding=)`
creates the zeros directly into the sharding), block tables / lengths
/ alloc / free stay host-side and replicated, and the ragged
paged-attention + flash-prefill kernels run under `shard_map`
(`paged_attention(..., mesh=)` /
`causal_flash_attention(..., mesh=)`) — each device executes the
same program over its local KH/tp heads, no collective inside the
kernel.  The commit/chunk programs pin `out_shardings` to the pool
sharding so warmup covers the whole serve-time signature (a
join/finish/join cycle never compiles).  `spt … --continuous --tp N`
is the deployment surface; `make pod-check` gates token-exact parity
(sharded-paged == single-chip-paged == serial) on the 8-device CPU
mesh.

### Quantized pool + self-drafting speculation (PR 9)

`PagedKVCache(..., kv_dtype="int8")` (daemon flag `--kv-dtype int8`)
stores the pools as int8 values plus per-page per-kv-head f32 scales
(`k_scales`/`v_scales`, `(n_blocks, kv_heads)` per layer — separate
buffers, layout leaving room for int4-packed values): the prefill
commit scatter quantizes whole pages, decode appends rescale-on-
append (monotone page scales), and `paged_attention(...,
k_scales=, v_scales=)` dequantizes IN REGISTER inside the page loop
— the scales ride scalar prefetch with the block tables.  Cache HBM
per token: 1/2 of bf16, 1/4 of f32 (`device_mb()` measures placed
buffers; heartbeat `pool_mb` + `kv_dtype`, `make quant-check` gates
the parity + byte tiers).  Under `tp` the scales shard with their kv
heads (`parallel/mesh.kv_scale_sharding`).

The kernel also accepts a MULTI-QUERY stack — `q` shaped
`(B, S, H, D)`: token t attends `j < lengths + t` (causal across the
stack).  That is the speculative verifier:
`SpeculativeCompletionModel` (with `self_draft_model(target, k)` — a
draft that is a truncated VIEW of the target's own first k layers,
`--draft-layers k`) implements the full paged surface
(`paged_supported` True): the draft proposes gamma tokens via paged
decode steps, the target scores all gamma+1 positions in ONE
multi-query paged dispatch, acceptance/resample run on device, and a
host FIFO adapts ragged per-row acceptance to the daemon's fixed
chunk cadence.  Draft/verify counters ride the heartbeat
(`sptpu_completer_spec_{draft,accepted,verified}_tokens`); the PR-5
demotion floor still guards the lane (the swap lands at the next
idle point of `run_continuous`).

### Multi-tenant requests: tenant labels + deadline stamps (PR 10)

The request label word carries a **tenant id in bits 48-51**
(`protocol.TENANT_MASK`; `stamp_tenant`/`read_tenant`; ids 1-15, 0 =
untagged) — daemons read every candidate's labels anyway, so tenant
discovery is free, one tenant's waiting rows enumerate with a bloom
prefilter, and the field survives the WAITING→SERVICING→READY
trifecta for post-hoc attribution.  **`LBL_DEADLINE` (bit 52)** flags
an absolute wall-clock deadline in the `__dl_<idx>` companion key
(`stamp_deadline`/`read_deadline` — epoch-gated and self-invalidating
like trace stamps; search requests may carry `{"deadline": ts}` in
their request JSON instead).

Every drain runs the shared admission policy (`engine/qos.py`:
stride-scheduled weighted fairness, persistent across drains) BEFORE
rendering anything: expired deadlines fail fast with a typed
`{"err": "deadline_expired"}` record, saturation orders admission by
tenant weight, and backlog past the queue high-water mark is shed
with `{"err": "overloaded", "retry_after_ms": N}` — backpressure,
never a wedge, and past the mark a typed answer, never silence.
Client side, `engine/client.py::call_with_retries` (under
`submit_search` / `submit_completion`) honors the hint with jittered
backoff inside the caller's deadline.  Runbook:
`docs/operations.md` §Multi-tenant QoS.
""",
    "embedding-vector-lane": """
## Search daemon (`libsplinter_tpu/engine/searcher.py`)

The query-coalescing counterpart of the embedding daemon: scoring
moves server-side so N concurrent clients cost ceil(N / QB) fused
top-k dispatches over the daemon's device-resident lane, not N
private round trips.

### Request contract (one slot per request)

| surface | contents |
|---|---|
| value | JSON `{"k": int, "bloom": int?}` — result count + optional label prefilter |
| vector lane | the query vector in the SAME slot (the embed daemon puts it there in the classic CLI flow, or write it with `spt_vec_set`) |
| labels | `LBL_SEARCH_REQ` (bit 57) + optionally `LBL_WAITING`, then bump |

The daemon drains every pending request per wake
(signal group 4), groups by bloom mask, coalesces each group into
QB-bucketed batches {8, 32, 256} against pre-compiled programs of the
**fused streaming top-k kernel** (`ops/similarity.topk_program`:
block-local select + merge in VMEM, O(k*Q) off-chip, k <=
`FUSED_K_MAX` = 128), and commits per-request results to the
slot-indexed companion key `__sr_<idx>`:

```json
{"s": [scores...], "i": [slot indices...], "keys": [resolved keys...],
 "fetched": K, "n": valid_candidates}
```

sorted by similarity desc, system keys (`__` prefix — scratch rows,
heartbeats, other requests' slots) already dropped.  The commit is
epoch-gated: a slot rewritten mid-service is retried, never answered
stale.  Clients poll their own request key and read the companion
once `LBL_SEARCH_REQ` clears (`engine.searcher.submit_search` wraps
the dance; `daemon_live` probes the `__searcher_stats` heartbeat).

The CLI `search` command dispatches to a live daemon automatically
(`--local` opts out) and falls back to client-side scoring on
timeout.  Stage quantiles publish under the `SEARCH_STAGES` names
(wake / drain / score / select / commit) in the heartbeat, `spt
metrics`, and `spt trace tail` — see the diagnostics appendix.
""",
    "diagnostics": """
## Observability surface (`libsplinter_tpu/obs/`)

The Python layer above the C ABI: log-bucketed latency histograms,
per-request flight recording, and a Prometheus text exposition.  The
reference's only runtime telemetry is the `__debug` append channel;
this is the structured counterpart the TPU port adds.

### Env vars

| var | effect |
|---|---|
| `SPTPU_TRACE=1` | enable span histograms + flight recording in the daemons (off: the hot path pays one dict lookup) |
| `SPTPU_TRACE_SLOW_MS=<ms>` | explicit slow-log promotion threshold; unset → 5× the recorder's live e2e p50 (arms after 20 samples) |
| `SPTPU_JAX_PROFILE=<dir>` | additionally capture jax.profiler device timelines per drain |

### Trace-id convention (`engine/protocol.py`)

A client that wants one request's wake→commit journey reconstructed
stamps it **next to the request label** — after `set` + `label_or`,
ideally before the `bump` (a daemon racing the stamp then can't
service the row stampless):

```python
tid = protocol.stamp_trace(store, key)   # returns the trace id
```

The stamp is `"<trace_id>:<wall_ts>:<slot_epoch>"` in the
slot-indexed companion key `__tr_<idx>` (`trace_stamp_key`), plus
`LBL_TRACED` (bit 58) on the request key itself — the daemons'
candidate filters already read every row's label word, so untraced
rows never pay a stamp lookup.  The embedded epoch makes stamps
self-invalidating: a daemon finding a stamp whose epoch doesn't
match the request it gathered consumes it as stale instead of
attributing it (and its seconds-old wall clock) to the wrong
request.  Ids are `(pid << 24) | counter`: unique across concurrent
clients without coordination, originating pid recoverable as
`id >> 24`.  The
servicing daemon consumes the stamp (clears key + label), appends the
request's stage events to its flight recorder under the pinned stage
names (`PIPELINE_STAGES` for the embedder: drain / tokenize /
dispatch / device_wait / commit; `INFER_STAGES` for the completer:
render / generate / commit; `SEARCH_STAGES` for the search daemon:
wake / drain / score / select / commit), and publishes its ring to
`__embedder_trace` / `__completer_trace` / `__searcher_trace`
alongside the heartbeat.

```
$ SPTPU_TRACE=1 ... ; spt trace tail 4
[embedder] id=0x6804000001 pid=26628 key='k' wall=1493.817ms \\
  drain=0.269ms tokenize=0.053ms dispatch=0.087ms \\
  device_wait=0.052ms commit=0.363ms
```

### Heartbeat sections (`publish_heartbeat`)

With tracing on, `__embedder_stats` / `__completer_stats` gain:

- `spans` — per span name `{n, total_ms, max_ms}` (the legacy
  aggregate shape, kept for old consumers);
- `quantiles` — histogram-sourced `{n, total_ms, max_ms, p50_ms,
  p90_ms, p95_ms, p99_ms}` keyed by the pinned stage names (prefix
  stripped) — what `bench.py`'s stage table and `spt metrics`
  consume;
- `recorder` — `{recorded, dropped, slow_promoted,
  slow_threshold_ms}`;
- `slow_log` — promoted slow requests, each
  `{id, key, wall_ms, ts, slow_threshold_ms,
  events: [[stage, ms], ...]}` (bounded deque; survives ring wrap).

Oversized heartbeats degrade section by section (largest first,
`truncated: true`): the slow log goes before the quantiles, and the
scalar counters always land.

### Prometheus exposition

`spt metrics` renders exposition-format text: store header gauges
(`sptpu_store_used_slots`, `sptpu_store_parse_failures`, ...),
heartbeat scalars (`sptpu_embedder_*` / `sptpu_completer_*`),
heartbeat ages, per-stage quantile summaries
(`sptpu_stage_ms{daemon=...,stage=...,quantile=...}`), recorder
counters, and StagedLane chunk accounting when a lane is staged.
In-process, `Tracer.render_prom()` serializes the live histograms as
native prometheus histograms (cumulative `le` buckets, edges in ms)
plus any counter groups passed in.  `make obs-check` pins the enabled
record path's overhead < 3% vs disabled.

### Cross-lane span records (`libsplinter_tpu/obs/spans.py`)

Since PR 13 the trace stamp is a full TRACE CONTEXT —
`"<trace_id>:<wall_ts>:<slot_epoch>:<parent_span>:<span_id>"` (legacy
3-field stamps parse as `parent=0, span=trace_id`) — and every lane
commits one **span record** per stamped request into a shared
bounded ring in the store:

| key | contents |
|---|---|
| `__span_<i>` | committed span records; slot claimed by atomically incrementing the `__span_head` BIGUINT, so the ring is multi-writer safe and bounded by construction (`span_ring_size` = nslots/8 clamped to [16, 128]) |
| `__sp_<idx>` | pending-span STAGING row (staged lanes: the pipeliner) — crash recovery: a restarted lane recovers the chain identity, the original queue-enter clock, and the attempt count, so the committed span shows the restart gap.  Orphans (slot epoch moved, TTL) are swept on the heartbeat cadence and by `shed_orphan_stamp`'s discard path |

Each record carries the trace id, span id + parent (the tree edges),
lane, key, tenant, status (`ok` / typed error), the queue-enter /
admit / commit wall clocks, and the **queue-wait vs service-time
split** — with per-stage ms under the pinned `*_STAGES` names when
`SPTPU_TRACE=1`.  Record commits BUFFER in the lane and flush on the
heartbeat cadence, keeping the wake path inside the obs budget
(`make trace-check` gates it).  Propagation: every client verb
(`submit_embed` / `submit_search` / `submit_completion` /
`submit_script`) takes `trace=` (True = new root, a trace id = a hop
of that trace, `(trace_id, parent_span)` = explicit placement), and
the pipeline lane stamps every verb a script dispatches with the
script's own span as parent — ONE trace id spans a whole chain in
both forms.  `spt trace show <id>` renders the assembled tree;
`spt trace export` emits Chrome/Perfetto trace-event JSON.

### Device-time & compile attribution (`libsplinter_tpu/obs/devtime.py`)

Every jitted hot program registers with the process-global `DEVTIME`
registry under a stable `lane.program` name (`embedder.encode`,
`completer.paged_chunk`, `searcher.topk`, ...; splint SPL205 fails an
unregistered one).  Registration wraps the program with two probes,
both piggybacking on work the lane already does — **zero new host
syncs** (SPL201 stays the law; `SPTPU_DEVTIME=0` is the kill
switch, and warmup dispatches never open device windows):

- **the compile ledger** — a jit cache-size growth across a call is a
  compile event: `{program, lane, shapes_key, duration_ms,
  generation, cause: warmup|runtime}`, buffered in-process and
  flushed on the heartbeat cadence into the `__compile_<i>` store
  ring (span-ring slot-claim discipline).  `spt trace export` renders
  the events as instants on their own Perfetto track; the post-warmup
  **no-recompile gate** (`scripts/compile_gate_check.py`, `make
  compile-check`) asserts the runtime-cause count stays ZERO across a
  serve drill and names the guilty program + shapes key when it
  doesn't (`SPTPU_SEED_RECOMPILE=1` seeds the drill for the gate's
  own failure test).
- **device windows** — dispatch→collect wall time per named program,
  closed at the lane's EXISTING collect point (`PendingChunk.block`,
  `materialize_host`, the top-k `device_get`).  Spans gain
  `device_ms` and `dispatch_queue` (= `service_ms - device_ms`)
  beside the queue/service split — "slow because device" vs "slow
  because the lane sat on it" is now readable per request — and each
  lane heartbeat gains a `devtime` section (per program `{n,
  compiles, runtime_compiles, p50_ms, p99_ms}`, rendered as
  `sptpu_<lane>_devtime_*{program=...}`).  The bench ledger rows
  carry `compile_events` + `device_ms_share`.

HBM watermarks ride the completer heartbeat beside the live gauges:
`pool_mb_peak` (measured placed-buffer MB high-water) and
`pages_used_peak` (page-occupancy high-water, sampled at
chunk-collect edges so a between-heartbeats spike still shows).

**Tail-based retention**: a request or drain that exceeds the slow
threshold keeps its full `*_STAGES` breakdown even when the client
never stamped a trace id — the lane allocates a trace id at commit
time (`tail: true` on the span), so every slow-log entry resolves
through `spt trace show`.
""",
    "system-keys-user-flags": """
## Supervision heartbeat keys (`libsplinter_tpu/engine/supervisor.py`)

The daemon heartbeats (`__embedder_stats` / `__completer_stats` /
`__searcher_stats`) carry two supervision fields beyond their
counters:

- `pid` — the publishing process.  Liveness probes
  (`protocol.heartbeat_live`, the CLI's `daemon_live`) kill-0 it, so
  a crashed daemon reads dead the instant it dies instead of after
  `max_age_s` of heartbeat decay.
- `generation` — monotonic per-lane start counter (BIGUINT companion
  key `__<heartbeat>_gen`, bumped by `protocol.bump_generation` at
  attach).  Two snapshots with different generations bracket a
  restart even when the OS recycled the pid.

`__supervisor_stats` is the supervisor's own heartbeat
(`spt supervise`): per-lane process state consumed by
`protocol.lane_down` and rendered by `spt metrics`
(`sptpu_supervisor_lane_*`):

| field | meaning |
|---|---|
| `state` | `starting` / `running` / `backoff` / `down` (breaker open) |
| `pid`, `generation` | current child process, spawn count |
| `restarts` | respawns after a crash or hung-heartbeat kill |
| `consecutive_crashes` | backoff ladder position (0 = healthy) |
| `backoff_ms` | the live jittered backoff |
| `breaker_opens`, `hung_kills`, `last_exit` | breaker + exit history |

A lane whose `state` is `down` is skipped by dispatching clients
(`daemon_live` returns False without probing the lane heartbeat) —
a crash-looping lane costs a client zero timeout.  With `SPTPU_FAULT`
armed, heartbeats additionally carry a `faults` section (per-site
hit/fired accounting).  Runbook: `docs/operations.md`.

### Pod-sharded completer keys (PR 8)

A completer serving through `ShardedCompletionModel`
(`--tp N --continuous`) extends `__completer_stats` with:

- `tp` — the tensor-parallel mesh degree
  (`sptpu_completer_tp` in `spt metrics`);
- `pages_shard` — per-tp-shard paged-pool view
  `{"0": {"free": n, "used": m, "shard_mb": x}, ...}`, rendered as
  `sptpu_completer_pages_{free,used}` and
  `sptpu_completer_pool_shard_mb` with a `shard` label.  The pool
  shards on its KV-HEAD axis, so the PAGE counts are host-global
  (every shard backs every page at 1/tp of its bytes); `shard_mb` is
  MEASURED from the placed device buffers per tp position — a broken
  placement collapses the key set (a replicated pool covers the full
  kv-head range → one key) or inflates the MB, so the dashboard
  shows real placement state, not an assumed-uniform number.

### Dispatch-overlap gauges (`libsplinter_tpu/engine/resident.py`)

Every lane heartbeat also carries the PR-7 overlap-window gauges —
the embedder's ring gauges ride a `dispatch` sub-section (dropped
first when a tiny store's `max_val` bites, like every optional
section) and `spt metrics` renders everything flat as
`sptpu_<lane>_<field>`:

| field | lanes | meaning |
|---|---|---|
| `inflight_depth` | all | configured K: un-awaited device dispatches the lane may hold (`--inflight-depth`) |
| `inflight_peak` | all | max un-awaited depth observed; pinned at `inflight_depth` = the overlap window saturates |
| `ring_depth` | embedder | configured resident-ring depth (`--ring-depth`; ≤1 = per-call dispatch) |
| `ring_occupancy` / `ring_occupancy_peak` | embedder | occupied slots of the last / fullest resident ring dispatch |
| `ring_dispatches` / `resident_iterations` | embedder | resident programs dispatched / batches serviced inside them — `resident_iterations ÷ ring_dispatches` is the live dispatch-floor amortization factor |
| `ring_faults` | embedder | ring dispatches degraded to the per-call programs |

The searcher's `lane` section additionally counts the StagedLane's
ring staging (`ring_dispatches` / `ring_chunks`: refresh scatter
chunks coalesced into resident dispatches).

### Multi-tenant QoS keys (`libsplinter_tpu/engine/qos.py`)

Every lane heartbeat gains the overload-survival counters
(`deadline_expired` / `shed` / `deferred`, flat `sptpu_<lane>_*`
gauges) plus two optional sections:

- `qos` — the live admission config: `admit_cap` (embedder/searcher;
  0 = unlimited), `queue_high_water` (-1 = shedding disabled),
  `retry_after_ms` (the hint shed responses carry).  Rendered flat as
  `sptpu_<lane>_qos_*`.
- `tenants` — the per-tenant ledger
  `{"<tenant>": {"admitted": n, "shed": n, "deadline_expired": n,
  "served_tokens": n}, ...}` (tenant ids 1-15 from the label word's
  bits 48-51; untagged traffic does not create a section).  Rendered
  as `sptpu_<lane>_tenant_<field>{tenant="..."}` — the incident view
  of WHO is being served and WHO is being shed.

The completer additionally publishes `bp_memo` — occupancy of the
epoch-keyed join-backpressure memo, bounded by the heartbeat-cadence
sweep (entries whose slot epoch moved or whose request label cleared
are evicted; a hard 4096 cap backstops pathological stores).

Deadline stamps ride `__dl_<idx>` companion keys (debug-labeled,
flagged by `LBL_DEADLINE` on the request key, format
`"<deadline_ts>:<slot_epoch>"` — the trace-stamp discipline: epoch
self-invalidating, consumed at service, orphans shed).  Runbook:
`docs/operations.md` §Multi-tenant QoS; harness: `spt loadgen`.

### Telemetry-history keys (`libsplinter_tpu/engine/telemetry.py`)

The telemetry sampler (supervisable lane `telemetry`, jax-free)
scrapes every lane heartbeat on its cadence into fixed-size
time-series rings stored IN the store — the signal plane the
elastic-lane scaling controller reads, rendered by `spt top` and
`spt metrics --history`:

- `__tele_<lane>` — one ring key per scraped lane:
  `{"v": 1, "lane": ..., "interval_s": ..., "n": samples,
  "gauges": {name: [[ts, value], ...]}}`, each gauge bounded to
  `--ring-len` samples (default 64; an oversized snapshot halves its
  history until it fits `max_val`).  Gauges: `queue_depth` (measured
  by label enumeration, never trusted from the heartbeat), `shed` /
  `deferred` / `deadline_expired`, the lane's progress counter,
  `pages_free` / `pool_mb` / `pool_mb_peak` / `pages_used_peak`
  (completer HBM watermarks), `compile_events` (the devtime plane's
  runtime-recompile count — a non-flat ring is the silent-recompile
  alarm), `p99_<stage>_ms` when tracing is on, and
  `tenant<id>_admitted` / `tenant<id>_served_tokens`.
- `__telemetry_stats` — the sampler's own heartbeat (samples,
  lanes_seen, points, shrinks, generation) — supervised exactly like
  the serving lanes, and because the rings live in the store a
  restarted sampler RESUMES them (gauged by the restart test in
  `make trace-check`).

Every lane heartbeat additionally carries a `spans_obs` section
(span-capture accounting: committed / recovered / dropped / pending —
obs/spans.py; size-droppable like every optional section), rendered
flat by `spt metrics` as `sptpu_<lane>_spans_*`.

### Compile-ledger keys (`libsplinter_tpu/obs/devtime.py`)

The device-time plane commits compile events into a bounded store
ring, claimed exactly like the span ring:

- `__compile_<i>` — committed compile-event records: `{"v": 1,
  "program": "lane.name", "lane": ..., "shapes_key": ...,
  "duration_ms": ..., "generation": G, "cause":
  "warmup"|"runtime", "ts": ..., "pid": ...}`.  Ring size =
  `span_ring_size` (nslots/8 in [16, 128]); events buffer in the
  lane and flush on the heartbeat cadence.
- `__compile_head` — the ring's atomically-incremented BIGUINT
  claim counter (multi-writer safe; replicas of an elastic lane
  share the one ring, their events distinguished by `pid` +
  `generation`).

`spt trace export` merges the ring into the Perfetto document as
instant events on a dedicated track; `collect_compile_events(store)`
is the programmatic reader; `scripts/compile_gate_check.py` is the
CI gate that fails on any post-warmup `cause: "runtime"` event.  The
`generation` field is synced from the lane's supervision generation
at attach, so a restart is visible as a generation bump in the ring
— warmup compiles of the NEW process never masquerade as serve-time
recompiles of the old one.

### Prefix-cache keys (`libsplinter_tpu/engine/prefix_cache.py`)

A continuous completer with prefix sharing live (the default; off via
`--no-prefix-cache`) extends `__completer_stats` with flat
`prefix_*` gauges, rendered by `spt metrics` as typed counters
(`sptpu_completer_prefix_*`) and ringed by the telemetry sampler
(`prefix_hits` / `prefix_shared_pages` sparkline in `spt top`):

| field | meaning |
|---|---|
| `prefix_hits` / `prefix_misses` | admissions that mapped ≥ 1 full shared page vs none |
| `prefix_hit_tokens` | prompt tokens served from shared pages instead of prefill |
| `prefix_shared_pages` / `prefix_evictable` | tree residency: total retained pages / the zero-ref subset reclaimable on demand (`available_pages = pages_free + prefix_evictable`) |
| `prefix_evictions` | LRU reclaims back to the free list |
| `prefix_cow_copies` | copy-on-write page copies (≈ one per fully-cached admission) |
| `prefix_bytes_saved` | KV bytes not re-prefilled/committed |

Per-tenant cache residency rides the `tenants` ledger section as
`prefix_pages` (quota pressure: `--prefix-quota T:PAGES,...`), and
traced admissions carry a `prefix_hit` stage span
(`CONT_INFER_STAGES`) so `spt trace show` attributes first-token
latency to the cache hit vs the suffix prefill.  Runbook:
`docs/operations.md` §Prefix cache.

### Elastic-lane keys (`libsplinter_tpu/engine/protocol.py`, `engine/autoscaler.py`)

Striped replica groups + the scaling controller keep their entire
control plane in the store (runbook: `docs/operations.md` §Elastic
lanes):

- `__stripe_<lane>` — the lane's stripe map: `{"v": 1, "epoch": E,
  "width": W, "owners": {"<replica>": [stripe, ...]},
  "closed": [...], "pending": {"<replica>": [...]}}`.  A request's
  stripe is its slot index mod `width`; replicas re-read the map at
  every drain (`protocol.StripeView`), so one epoch-bumped write
  re-stripes the lane with no orphaned requests.  `closed` stripes
  are claimed by NOBODY (a retiring replica's parked share during
  the deadline-bounded scale-down drain); `pending` lists the
  planned shares of spawning replicas — the incumbents keep serving
  those until the first-heartbeat promotion (the two-phase scale-up
  handoff), and being listed there is how a pending replica knows
  it is not retired.  No map = replica 0 owns everything (the
  classic single-process deployment).
- replica-suffixed heartbeats — replica N > 0 publishes
  `__<lane>_stats.rN` / `__<lane>_trace.rN`
  (`protocol.replica_stats_key`); readers discover them via
  `protocol.replica_heartbeat_keys` (`spt top` renders one row per
  replica + a lane aggregate; `spt metrics` exposes replica blocks
  as `sptpu_<lane>_rN_*`; splint SPL105 enforces the discovery).
  Each replica heartbeat carries `replica` + a `stripe` section
  (epoch / width / owned-stripe count).
- `__scale_policy` — supervisor-published bounds + controller knobs:
  `{"lanes": {lane: {"min": m, "max": M, "signal":
  "queue"|"pool"}}, "up_threshold": ..., "down_threshold": ...,
  "cooldown_s": ..., "interval_s": ...}` (`signal` selects each
  lane's pressure source: `queue` = queue depth per live replica —
  every lane's default; `pool` = fleet-worst paged-pool occupancy —
  the decode lane's memory-bound signal).
- `__scale_tgt_<lane>` — one desired-count key per lane: `{"r": N,
  "src": "auto"|"manual", "ts": ...}` (per-lane keys: no shared
  read-modify-write map for concurrent writers to race) — written
  by the autoscaler
  (`src=auto`) or `spt scale set` (`src=manual` = a hold the
  controller respects), applied by the supervisor's poll.
- `__autoscaler_stats` — the controller heartbeat: decision
  counters (ticks / scale_ups / scale_downs / holds), per-lane
  `{target, pressure, reason, up_streak, down_streak}`, and a
  bounded decision `history` (`spt scale status` renders it;
  `spt metrics` exposes `sptpu_autoscaler_lane_*`).
- `__supervisor_stats` lane sections gain `r` (active replicas),
  optional `scale_min`/`scale_max`, per-replica `replicas`
  subsections, and the supervisor totals gain `retired` +
  `scale_events`.

### Disaggregated-handoff keys (`libsplinter_tpu/engine/disagg.py`)

The prefill -> decode page handoff (runbook: `docs/operations.md`
§Disaggregated lanes) keeps its whole wire protocol in the store,
keyed by the request's SLOT INDEX so both sides and the supervisor's
reclaim agree on ownership without a directory:

- `__ho_<idx>` — the handoff record (debug-labeled JSON, `{"v": 1,
  "len": prompt_tokens, "ids": [...], "carry": first_sampled_token,
  "n_tok": 1, "remaining": ..., "disp_left": ..., "plen":
  slot_bytes_at_handoff, "t0": ..., "tenant": ..., "deadline": ...,
  "wire_pages": N, "quant": bool}`).  The record lands LAST — after
  the wire pages, before the `DECODE_READY` flip — so a record's
  existence IS the adoptability contract; `plen` is the truncation
  point crash recovery rolls a dead adopter's slot back to.
- `__ho_<idx>.p<j>` / `__ho_<idx>.s<j>` — the row's exported KV
  pages (and per-page int8 scales when `quant`), one key per page,
  written only when a page fits `max_val`; `wire_pages: 0` means the
  adopter re-prefills from `ids` instead (the `handoff_refill`
  counter).  All `__ho_` keys leave the store with the request —
  finish, typed reject, and both crash-recovery sweeps all clear
  them.
- `__prefill_stats` / `__decode_stats` — the lanes' heartbeats
  (replica-suffixed like every elastic lane).  Prefill: `handoffs`,
  `handoff_failed`, `handoff_wire_mb`, `prefill_wall_ema_ms` (the
  phase-aware QoS slack).  Decode: `adopted`, `readopted`,
  `adopt_backpressure`, `handoff_refill`, plus the pool gauges
  (`pages_free`/`pages_used`) the telemetry sampler turns into the
  `pool_occ` ring — the decode autoscaler's `pool` signal.
""",
}


def parse_header(path: str = HEADER):
    with open(path) as f:
        raw = f.read()
    lines = raw.splitlines()

    # the leading block comment is the ABI overview
    m = re.match(r"/\*(.*?)\*/", raw, re.S)
    preamble = _clean_comment(m.group(0).splitlines()) if m else ""

    sections: list[Section] = []
    cur = Section("core constants")   # pre-marker #defines land here
    sections.append(cur)
    pending: list[str] = []        # comment lines awaiting an owner
    i = 0
    n = len(lines)
    # skip the preamble comment
    while i < n and not lines[i].startswith("#ifndef"):
        i += 1
    while i < n:
        ln = lines[i]
        sm = _SECTION_RE.match(ln)
        if sm:
            cur = Section(sm.group(1))
            sections.append(cur)
            pending = []
            # a section marker may open a multi-line comment whose body
            # documents the whole section (e.g. the tokenizer block)
            if "*/" not in ln:
                # the marker opens a multi-line comment: its body is
                # the section's own introduction
                body = []
                i += 1
                while i < n and "*/" not in lines[i]:
                    body.append(lines[i])
                    i += 1
                if i < n:
                    body.append(lines[i])
                cur.intro = _clean_comment(body)
            i += 1
            continue
        stripped = ln.strip()
        if stripped.startswith("/*"):
            block = [ln]
            while "*/" not in lines[i] and i + 1 < n:
                i += 1
                block.append(lines[i])
            pending = block
            i += 1
            continue
        dm = _DEFINE_RE.match(stripped)
        if dm:
            # a define takes only its INLINE comment; a block comment
            # above it stays pending — in this header those blocks
            # document the function that follows the define (e.g. the
            # spt_vec_gather contract above SPT_GATHER_TORN)
            inline = re.search(r"/\*(.*?)\*/", stripped)
            doc = inline.group(1).strip() if inline else ""
            cur.defines.append(
                (dm.group(1), re.sub(r"\s*/\*.*?\*/", "", stripped), doc))
            i += 1
            continue
        if stripped.startswith("typedef enum") or \
                stripped.startswith("typedef struct {"):
            block = [stripped]
            while not re.search(r"}\s*\w+\s*;", block[-1]) and i + 1 < n:
                i += 1
                block.append(lines[i].strip())
            tm = re.search(r"}\s*(\w+)\s*;", block[-1])
            tname = tm.group(1) if tm else "?"
            doc = _clean_comment(pending) if pending else ""
            cur.types.append((tname, "\n".join(block), doc))
            pending = []
            i += 1
            continue
        pm = _PROTO_START.match(stripped)
        if pm:
            sig_lines = [stripped]

            def _unclosed(txt: str) -> bool:
                return txt.count("/*") > txt.count("*/")

            # collect until the statement's ';' lands OUTSIDE a comment
            # (trailing block comments can run past the prototype line)
            while i + 1 < n:
                joined = " ".join(sig_lines)
                bare = re.sub(r"/\*.*?\*/", "", joined, flags=re.S)
                if ";" in bare and not _unclosed(joined):
                    break
                i += 1
                sig_lines.append(lines[i].strip())
            sig = " ".join(sig_lines)
            inline = re.findall(r"/\*(.*?)\*/", sig, re.S)
            sig = re.sub(r"\s*/\*.*?\*/", "", sig, flags=re.S).rstrip()
            sig = re.sub(r"\s+", " ", sig)
            if ";" in sig:
                sig = sig[:sig.index(";") + 1]
            doc = _clean_comment(pending) if pending else ""
            if inline:
                extra = " ".join(
                    re.sub(r"\s+", " ", t.strip()) for t in inline)
                doc = (doc + "\n" + extra).strip()
            cur.funcs.append((pm.group(1), sig, doc))
            pending = []
            i += 1
            continue
        if stripped == "":
            pending = []           # a blank line orphans the comment
        i += 1
    return preamble, sections


def render(outdir: str) -> list[str]:
    preamble, sections = parse_header()
    os.makedirs(outdir, exist_ok=True)
    written = []

    idx = ["# sptpu.h — C ABI reference",
           "",
           "Generated from `native/include/sptpu.h` by "
           "`scripts/gen_api_docs.py`; do not edit by hand "
           "(`tests/test_api_docs.py` enforces sync).",
           "",
           "```",
           preamble,
           "```",
           "",
           "| Section | Functions |",
           "|---|---|"]
    for sec in sections:
        if not sec.funcs and not sec.defines and not sec.types:
            continue
        names = ", ".join(f"`{nm}`" for nm, _, _ in sec.funcs) or "—"
        idx.append(f"| [{sec.title}]({sec.slug}.md) | {names} |")
    idx.append("")

    for sec in sections:
        if not sec.funcs and not sec.defines and not sec.types:
            continue
        page = [f"# {sec.title}",
                "",
                f"Part of the [sptpu.h C ABI](index.md); declarations "
                f"in `native/include/sptpu.h`.",
                ""]
        if sec.intro:
            page.append(sec.intro)
            page.append("")
        if sec.defines:
            page.append("## Constants")
            page.append("")
            for name, line, doc in sec.defines:
                page.append(f"- `{line}`" + (f" — {doc.splitlines()[0]}"
                                             if doc else ""))
            page.append("")
        for name, body, doc in sec.types:
            page.append(f"## `{name}`")
            page.append("")
            page.append("```c")
            page.append(body)
            page.append("```")
            page.append("")
            if doc:
                page.append(doc)
                page.append("")
        for name, sig, doc in sec.funcs:
            page.append(f"## `{name}`")
            page.append("")
            page.append("```c")
            page.append(sig)
            page.append("```")
            page.append("")
            if doc:
                page.append(doc)
                page.append("")
        extra = _APPENDICES.get(sec.slug)
        if extra:
            if "__SPLINT_LABEL_TABLE__" in extra:
                splint = load_splint()
                extra = extra.replace(
                    "__SPLINT_LABEL_TABLE__",
                    splint.registry.render_label_table(
                        splint.extract_registry()))
            page.append(extra.strip())
            page.append("")
        path = os.path.join(outdir, f"{sec.slug}.md")
        with open(path, "w") as f:
            f.write("\n".join(page))
        written.append(path)

    with open(os.path.join(outdir, "index.md"), "w") as f:
        f.write("\n".join(idx))
    written.append(os.path.join(outdir, "index.md"))
    return written


def sync_operations(path: str = OPERATIONS_MD) -> None:
    """Refresh docs/operations.md's generated regions in place: the
    fault-point catalog (from the discovered `fault()` sites +
    FAULT_SITE_DOCS) and the splint rule catalog (from the rule
    registry).  Markers missing -> loud failure, never a silent
    stop."""
    splint = load_splint()
    R, core = splint.registry, sys.modules[splint.__name__ + ".core"]
    with open(path) as f:
        text = f.read()
    text = R.replace_marked_region(
        text, R.OPERATIONS_BEGIN, R.OPERATIONS_END,
        R.render_fault_table())
    text = R.replace_marked_region(
        text, core.RULES_BEGIN, core.RULES_END,
        core.render_rule_table())
    with open(path, "w") as f:
        f.write(text)


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else None
    files = render(out or os.path.join(REPO, "docs", "api"))
    print(f"wrote {len(files)} pages to {out or 'docs/api'}")
    if out is None:
        # the default run also refreshes the generated operations.md
        # regions; an explicit outdir (the doc-sync test's tmp dir)
        # must never touch the committed runbook
        sync_operations()
        print("refreshed docs/operations.md generated regions")
