"""Sustained MRMW-writers + live embedding daemon (BASELINE.md row
"32-writer signal-group -> batched TPU embed: sustained, no
corruption").

The reference's MRMW harness (splinter_chi_sao.c) sustains 32
disjoint-lane writers for a wall-clock duration and exits nonzero on
any torn read.  This bench adds the TPU-framework claim on top: a
CONCURRENT embedding daemon drains the same store via the dirty mask
the whole time, and every vector it commits must equal the fingerprint
of a version the key actually held — a torn or mixed gather would
match NO version.  tests/test_mrmw_embed.py is the CI-scaled version;
this is the sustained, ledgered one.

Threads, not processes: this sandbox's exec'd siblings lack coherent
MAP_SHARED views (.claude/skills/verify/SKILL.md); the seqlock
protocol under test is identical in one address space.

Env: MRMW_WRITERS (32), MRMW_DURATION_S (30), MRMW_KEYS_PER_LANE (4).
Appends a `mrmw_embed_sustained` record to bench_results.jsonl.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from libsplinter_tpu.utils.fingerprint import (  # noqa: E402
    DIM, fingerprint as _fingerprint, lane_text as _text)

N_WRITERS = int(os.environ.get("MRMW_WRITERS", "32"))
DURATION_S = float(os.environ.get("MRMW_DURATION_S", "30"))
KEYS_PER_LANE = int(os.environ.get("MRMW_KEYS_PER_LANE", "4"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def main() -> int:
    from libsplinter_tpu import Store, T_VARTEXT
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.embedder import Embedder

    name = f"/spt-mrmwbench-{os.getpid()}"
    Store.unlink(name)
    st = Store.create(name, nslots=max(512, N_WRITERS * KEYS_PER_LANE * 4),
                      max_val=256, vec_dim=DIM)
    stop = threading.Event()
    emb = None
    runner = None
    threads: list[threading.Thread] = []
    try:
        emb = Embedder(st, encoder_fn=lambda ts: np.stack(
            [_fingerprint(t) for t in ts]), max_ctx=64, batch_cap=64)
        emb.attach()

        writes = [0] * N_WRITERS
        max_ver = [0] * N_WRITERS

        def writer(lane: int):
            rng = np.random.default_rng(lane)
            ver = 0
            while not stop.is_set():
                for i in range(KEYS_PER_LANE):
                    k = f"lane{lane}/k{i}"
                    st.set(k, _text(lane, i, ver))
                    st.set_type(k, T_VARTEXT)
                    st.label_or(k, P.LBL_EMBED_REQ)
                    st.bump(k)
                    writes[lane] += 1
                max_ver[lane] = ver
                ver += 1
                time.sleep(float(rng.uniform(0.0005, 0.005)))

        runner = threading.Thread(
            target=emb.run,
            kwargs=dict(idle_timeout_ms=20, sweep_interval_s=0.5),
            daemon=True)
        runner.start()
        threads.extend(threading.Thread(target=writer, args=(w,),
                                        daemon=True)
                       for w in range(N_WRITERS))
        t0 = time.perf_counter()
        for t in threads:
            t.start()

        # mid-run integrity sampling: every committed vector must match
        # SOME version's fingerprint for its key (epoch-gated commits
        # make a superseded-text commit impossible; a torn gather would
        # match nothing)
        torn = 0
        checks = 0
        sampler_rng = np.random.default_rng(1234)
        deadline = t0 + DURATION_S
        while time.perf_counter() < deadline:
            # sleep FIRST: the continue paths must not busy-spin GIL
            # time away from the workload being measured
            time.sleep(0.002)
            lane = int(sampler_rng.integers(N_WRITERS))
            i = int(sampler_rng.integers(KEYS_PER_LANE))
            k = f"lane{lane}/k{i}"
            try:
                got = st.vec_get(k)
            except KeyError:
                continue
            if not np.any(got):
                continue              # not yet embedded
            cand = [_text(lane, i, v)
                    for v in range(max(max_ver[lane] - 2, 0),
                                   max_ver[lane] + 2)]
            if not any(np.array_equal(got, _fingerprint(t))
                       for t in cand):
                # wide re-check (sampling raced the version counter)
                if not any(np.array_equal(got, _fingerprint(
                        _text(lane, i, v)))
                        for v in range(max_ver[lane] + 2)):
                    torn += 1
            checks += 1
        stop.set()
        for t in threads:
            t.join(timeout=10)
        dt = time.perf_counter() - t0
        total_writes = sum(writes)

        # convergence: daemon must settle every key to its final text
        conv_deadline = time.time() + 60
        remaining = {f"lane{w}/k{i}": w
                     for w in range(N_WRITERS)
                     for i in range(KEYS_PER_LANE)}
        while time.time() < conv_deadline and remaining:
            for k, w in list(remaining.items()):
                if st.labels(k) & P.LBL_EMBED_REQ:
                    continue
                got = st.vec_get(k)
                want = _fingerprint(st.get(k).rstrip(b"\0").decode())
                if np.array_equal(got, want):
                    del remaining[k]
            if remaining:
                time.sleep(0.1)
        emb.stop()
        runner.join(timeout=5)

        rec = {
            "metric": "mrmw_embed_sustained",
            "value": round(total_writes / dt, 1),
            "unit": "writes/s (32 writers + live daemon)",
            "vs_baseline": 0.0,
            "detail": {
                "backend": "host+fake-encoder",
                "writers": N_WRITERS, "duration_s": round(dt, 1),
                "writes_per_sec": round(total_writes / dt, 1),
                "embeds_committed": emb.stats.embedded,
                "embeds_per_sec": round(emb.stats.embedded / dt, 1),
                "raced_retries": emb.stats.raced,
                "integrity_checks": checks,
                "torn_vectors": torn,
                "unconverged_keys": len(remaining),
            },
        }
        print(json.dumps(rec), flush=True)
        from bench_series import append_ledger
        append_ledger(rec)
        ok = torn == 0 and not remaining
        log(f"sustained {dt:.1f}s: {total_writes/dt:,.0f} writes/s, "
            f"{emb.stats.embedded/dt:,.0f} embeds/s, torn={torn}, "
            f"unconverged={len(remaining)} -> "
            f"{'OK' if ok else 'FAIL'}")
        return 0 if ok else 1
    finally:
        # stop every thread BEFORE closing the store: native reads on
        # a closed mapping are use-after-close (an exception mid-run
        # must not leave 33 threads racing the teardown)
        stop.set()
        if emb is not None:
            emb.stop()
        for t in threads:
            if t.ident is not None:   # never-started threads can't join
                t.join(timeout=10)
        if runner is not None:
            runner.join(timeout=10)
        alive = any(t.is_alive() for t in threads) or (
            runner is not None and runner.is_alive())
        if alive:
            log("[mrmw] WARNING: threads did not stop; leaking the "
                "store to avoid use-after-close")
        else:
            st.close()
            Store.unlink(name)


if __name__ == "__main__":
    raise SystemExit(main())
