#!/usr/bin/env python
"""CI gate: the elastic-lane rate-step response, in-process.

Drives the WHOLE control loop on one CPU — striped embedder replicas
(thread-backed children under the real Supervisor), the real
TelemetrySampler and AutoScaler, and `spt loadgen`'s open-loop
rate-profile harness — through a 1x -> 4x -> 1x offered-rate step,
and asserts ROADMAP item 4's target at smoke scale:

  - the replica set FOLLOWS the step: >= 2 replicas live during the
    4x phase, back to the 1-replica floor after the load drops;
  - ZERO admitted-request loss through scale-up AND scale-down
    (loadgen's `lost` classification counts claimed-but-never-
    completed requests — the drain protocol's contract);
  - the backlog clears: the run ends with (almost) nothing unserved.

The embedder children run a deliberately slow encoder (a fixed sleep
per batch) with a small admit cap, so one replica saturates below
the 4x offered rate — scaling is the only way the system tracks.

Run: JAX_PLATFORMS=cpu python scripts/scale_step_check.py
(make scale-check wires it into make check).
"""
from __future__ import annotations

import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from libsplinter_tpu import Store  # noqa: E402
from libsplinter_tpu.cli.loadgen import (LoadGenerator,  # noqa: E402
                                         TenantSpec)
from libsplinter_tpu.engine import protocol as P  # noqa: E402
from libsplinter_tpu.engine.autoscaler import AutoScaler  # noqa: E402
from libsplinter_tpu.engine.embedder import Embedder  # noqa: E402
from libsplinter_tpu.engine.supervisor import Supervisor  # noqa: E402
from libsplinter_tpu.engine.telemetry import (  # noqa: E402
    TelemetrySampler)

STORE = f"/spt-scale-check-{os.getpid()}"
RATE = 16.0                       # 1x offered rate (req/s)
PROFILE = [(1.0, 2.0), (4.0, 4.0), (1.0, 2.0)]
ENCODE_SLEEP_S = 0.15             # per encode batch: the capacity wall
ADMIT_CAP = 8                     # rows per drain (throughput ~53/s)


class _ThreadChild:
    """A 'process' the Supervisor can own that is really an Embedder
    thread — the in-process stand-in for `--replica N` children, so
    the gate runs the REAL supervisor scale/retire machinery without
    paying a jax import per replica."""

    def __init__(self, store_name: str, replica: int):
        st = Store.open(store_name)

        def enc(texts):
            time.sleep(ENCODE_SLEEP_S)
            return np.full((len(texts), st.vec_dim), 0.25 + replica,
                           np.float32)

        self._emb = Embedder(st, encoder_fn=enc, max_ctx=128,
                             admit_cap=ADMIT_CAP, replica=replica)
        self._emb.attach()
        self.pid = os.getpid()
        self._th = threading.Thread(
            target=self._emb.run,
            kwargs=dict(idle_timeout_ms=20), daemon=True)
        self._th.start()

    def poll(self):
        return None if self._th.is_alive() else 0

    def terminate(self):
        self._emb.stop()

    kill = terminate

    def wait(self, timeout=None):
        self._th.join(timeout)
        return 0


def main() -> int:
    Store.unlink(STORE)
    store = Store.create(STORE, nslots=512, max_val=4096, vec_dim=8)
    stop = threading.Event()
    r_history: list[int] = []
    try:
        sup = Supervisor(
            STORE, lanes=("embedder",), store=store,
            scale={"embedder": (1, 3)},
            scale_knobs={"up_threshold": 8.0, "down_threshold": 1.0,
                         "cooldown_s": 1.0, "interval_s": 0.25},
            drain_deadline_s=2.0,
            spawn_fn=lambda lane: _ThreadChild(STORE, lane.replica))
        tel = TelemetrySampler(store, interval_s=0.2)
        ctl = AutoScaler(store, interval_s=0.25, up_consecutive=2,
                         down_consecutive=8)

        def sup_loop():
            while not stop.is_set():
                try:
                    sup.poll_once()
                    r_history.append(
                        len(sup._active_ids("embedder")))
                except Exception:
                    pass
                time.sleep(0.1)

        def tel_loop():
            while not stop.is_set():
                try:
                    tel.sample_once()
                except Exception:
                    pass
                time.sleep(0.2)

        def ctl_loop():
            while not stop.is_set():
                try:
                    ctl.decide_once()
                    ctl.publish_stats()
                except Exception:
                    pass
                time.sleep(0.25)

        threads = [threading.Thread(target=f, daemon=True)
                   for f in (sup_loop, tel_loop, ctl_loop)]
        for th in threads:
            th.start()
        # wait for replica 0 to serve
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if P.heartbeat_live(store, P.KEY_EMBED_STATS,
                                max_age_s=5):
                break
            time.sleep(0.1)
        else:
            print("FAIL: replica 0 never published a heartbeat")
            return 1

        gen = LoadGenerator(store, [TenantSpec(tenant=1, rate=RATE)],
                            mix={"embed": 1.0}, arrivals="poisson",
                            seed=11, corpus=8, drain_s=4.0,
                            rate_profile=PROFILE)
        report = gen.run()

        # scale-down convergence: give the controller the idle run it
        # needs (down_consecutive * interval + cooldown + drain)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if r_history and r_history[-1] == 1:
                break
            time.sleep(0.2)
        peak_r = max(r_history) if r_history else 0
        final_r = r_history[-1] if r_history else 0
        stop.set()
        for th in threads:
            th.join(timeout=3)
        sup.shutdown()

        phases = report.get("rate_profile", [])
        print(f"scale_step_check: issued={report['issued']} "
              f"ok={report['ok']} lost={report['lost']} "
              f"shed={report['shed']} unserved={report['unserved']} "
              f"peak_r={peak_r} final_r={final_r}")
        for row in phases:
            print(f"  phase {row['phase']} ({row['mult']:g}x): "
                  f"issued={row['issued']} "
                  f"goodput={row['goodput_ratio']:.1%} "
                  f"p50={row.get('p50_ms', '—')}ms")
        ups = ctl.stats.scale_ups
        downs = ctl.stats.scale_downs
        print(f"  autoscaler: ups={ups} downs={downs} "
              f"ticks={ctl.stats.ticks}; supervisor "
              f"retired={sup.retired}")

        fails = []
        if report["lost"]:
            fails.append(f"{report['lost']} admitted requests LOST "
                         "(zero-loss contract)")
        if report["shed"]:
            fails.append(f"{report['shed']} shed (no high-water set "
                         "— nothing should shed)")
        if peak_r < 2:
            fails.append(f"replica set never scaled up (peak {peak_r}"
                         " — the 4x phase must exceed one replica)")
        if final_r != 1:
            fails.append(f"scale-down never converged (final r = "
                         f"{final_r})")
        if report["unserved"] > max(4, report["issued"] // 20):
            fails.append(f"{report['unserved']} unserved after the "
                         "drain window — the scaled set failed to "
                         "clear the backlog")
        if fails:
            print("scale_step_check: FAIL — " + "; ".join(fails))
            return 1
        print("scale_step_check: PASS — replica set tracked the "
              "1x->4x->1x step with zero admitted loss")
        return 0
    finally:
        stop.set()
        store.close()
        Store.unlink(STORE)


if __name__ == "__main__":
    raise SystemExit(main())
