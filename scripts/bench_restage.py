"""StagedLane restage cost at the 1M-row BASELINE target (VERDICT r3 #6).

Builds a real native store with N populated slots and a (N, 768) f32
vector lane (~2.9 GB at N=1M), then measures:

  - full_upload_s     first refresh: torn-safe lane copy + device_put
                      + the one-time full norm pass
  - refresh_clean_ms  refresh with ZERO dirty rows (the per-query cost
                      a search session pays: one bulk epoch diff)
  - refresh_k_ms      refresh after touching k rows (k = 128, 8192):
                      must scale with k (gather + scatter of k rows),
                      NOT with N — the O(dirty) property the engine's
                      incremental staging is built on
  - memory            lane bytes, store mapping bytes, process RSS

Appends a `staged_lane_restage` record to bench_results.jsonl.

Backend: host CPU by DEFAULT (the O(dirty) property is host-side
bookkeeping + transfer volume, so the CPU run is the scaling
evidence).  RESTAGE_TPU=1 runs on the chip instead — that path takes
the tunnel watcher's flock first, because the tunnel admits ONE
client and a second concurrent client wedges the claim (bench.py's
discipline).

MEMORY: nslots rounds N up to a power of two with 2x headroom, so
N=1M maps a 2^21 x 768 f32 lane = ~6.4 GB of shm; peak process
footprint is ~3x that (mmap lane + the torn-safe host copy + the
device buffer) — budget ~20 GB for the default run.

Env: RESTAGE_N (default 1,000,000), RESTAGE_DIM (768), RESTAGE_TPU=1.
"""
from __future__ import annotations

import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N = int(os.environ.get("RESTAGE_N", "1000000"))
DIM = int(os.environ.get("RESTAGE_DIM", "768"))


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def _take_tunnel_lock():
    """One tunnel client at a time: queue on the watcher's flock and
    hold it for our lifetime (same lock bench.py takes)."""
    import fcntl
    lk = open(os.environ.get("SPTPU_BENCH_LOCK",
                             "/tmp/tpu_bench_watch.lock"), "w")
    log("[restage] waiting for the tunnel lock ...")
    fcntl.flock(lk, fcntl.LOCK_EX)
    log("[restage] tunnel lock acquired")
    return lk


def main() -> int:
    import numpy as np

    _lock = None
    if os.environ.get("RESTAGE_TPU") == "1":
        _lock = _take_tunnel_lock()   # held until process exit
    else:
        from libsplinter_tpu.utils.jaxplatform import force_cpu
        force_cpu()
    from libsplinter_tpu.utils.jaxplatform import enable_compile_cache
    enable_compile_cache()
    import jax

    from libsplinter_tpu import Store
    from libsplinter_tpu.ops.staged_lane import StagedLane

    backend = jax.default_backend()
    name = f"/spt-restage-{os.getpid()}"
    Store.unlink(name)
    nslots = 1
    while nslots < N * 2:            # headroom against probe clustering
        nslots *= 2
    log(f"backend={backend}; creating store nslots={nslots} "
        f"dim={DIM} ({nslots * DIM * 4 / 1e9:.2f} GB lane) ...")
    st = Store.create(name, nslots=nslots, max_val=64, vec_dim=DIM)
    try:
        t0 = time.perf_counter()
        for i in range(N):
            st.set(f"v/{i}", "x")
        fill_keys_s = time.perf_counter() - t0
        # lane content: written directly through the mmap view (bulk
        # numpy assignment; epochs already even+stable from the sets)
        t0 = time.perf_counter()
        rng = np.random.default_rng(0)
        view = st.vectors
        chunk = 65536
        for lo in range(0, nslots, chunk):
            hi = min(lo + chunk, nslots)
            view[lo:hi] = rng.standard_normal(
                (hi - lo, DIM), dtype=np.float32)
        fill_lane_s = time.perf_counter() - t0
        log(f"populated {N} keys in {fill_keys_s:.1f}s, lane in "
            f"{fill_lane_s:.1f}s")

        lane = StagedLane(st)
        t0 = time.perf_counter()
        arr = lane.refresh()
        jax.block_until_ready(arr)
        full_upload_s = time.perf_counter() - t0
        log(f"full upload: {full_upload_s:.2f}s "
            f"({nslots * DIM * 4 / 1e6 / full_upload_s:,.0f} MB/s)")

        def timed_refresh() -> float:
            t0 = time.perf_counter()
            jax.block_until_ready(lane.refresh())
            return (time.perf_counter() - t0) * 1e3

        timed_refresh()                       # warm the scatter program
        clean_ms = min(timed_refresh() for _ in range(5))
        log(f"clean refresh (0 dirty): {clean_ms:.1f} ms")

        results = {}
        for k in (128, 8192):
            # round 1 compiles the scatter program for this pad
            # bucket; round 2 is the steady state a live session pays
            for round_i in (0, 1):
                staged_before = lane.rows_staged
                idx = rng.choice(N, size=k, replace=False)
                for i in idx:
                    st.set(f"v/{i}", "y")     # epoch bump -> dirty
                ms = timed_refresh()
                moved = lane.rows_staged - staged_before
                assert moved == k, (moved, k)
                results[k] = ms               # keep the warm round
            log(f"refresh after {k} dirty rows: {results[k]:.1f} ms "
                f"(warm; compile round excluded)")

        rss_gb = resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss / 1e6
        rec = {
            "metric": "staged_lane_restage",
            "value": round(results[8192], 1),
            "unit": "ms (8192 dirty of 1M)",
            "vs_baseline": 0.0,
            "detail": {
                "backend": backend, "n_keys": N, "nslots": nslots,
                "dim": DIM,
                "lane_gb": round(nslots * DIM * 4 / 1e9, 2),
                "full_upload_s": round(full_upload_s, 2),
                "refresh_clean_ms": round(clean_ms, 1),
                "refresh_128_dirty_ms": round(results[128], 1),
                "refresh_8192_dirty_ms": round(results[8192], 1),
                "max_rss_gb": round(rss_gb, 2),
            },
        }
        print(json.dumps(rec), flush=True)
        from bench_series import append_ledger
        append_ledger(rec)
    finally:
        st.close()
        Store.unlink(name)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
