"""StagedLane restage cost at scale (VERDICT r3 #6).

Thin standalone wrapper over bench_series.phase_restage (the single
implementation the unified tunnel series also runs): builds a real
native store with N populated slots and a (N, dim) f32 vector lane,
then measures full-upload vs O(dirty) refresh (clean / 128-dirty /
8192-dirty) and appends a `staged_lane_restage` record to
bench_results.jsonl.

Backend: host CPU by DEFAULT (the O(dirty) property is host-side
bookkeeping + transfer volume).  RESTAGE_TPU=1 runs on the chip
instead — that path takes the tunnel watcher's flock first, because
the tunnel admits ONE client (bench.py's discipline).

MEMORY at the 1M default: nslots rounds N up to a power of two with
2x headroom, so N=1M maps a 2^21 x 768 f32 lane = ~6.4 GB of shm;
the streaming upload (128 MB chunks + MADV_DONTNEED on staged slices)
peaks at ~1.3x the lane — budget ~9 GB (measured 8.46 GB; before the
round-5 diet the full-host-copy path needed ~25 GB).

Env: RESTAGE_N (default 1,000,000 cpu / 131,072 tpu), RESTAGE_DIM
(768), RESTAGE_TPU=1.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench_series import shim_main  # noqa: E402


def _take_tunnel_lock():
    """One tunnel client at a time: queue on the watcher's flock and
    hold it for our lifetime (same lock bench.py takes)."""
    import fcntl
    lk = open(os.environ.get("SPTPU_BENCH_LOCK",
                             "/tmp/tpu_bench_watch.lock"), "w")
    print("[restage] waiting for the tunnel lock ...", file=sys.stderr,
          flush=True)
    fcntl.flock(lk, fcntl.LOCK_EX)
    print("[restage] tunnel lock acquired", file=sys.stderr, flush=True)
    return lk


if __name__ == "__main__":
    if os.environ.get("RESTAGE_TPU") == "1":
        _LOCK = _take_tunnel_lock()   # held until process exit
    else:
        # unconditional: an inherited BENCH_CPU=0 must not send the
        # unlocked path to the single-client tunnel
        os.environ["BENCH_CPU"] = "1"
    raise SystemExit(shim_main("restage"))
