#!/bin/sh
# Sync the native C sources into the Rust -sys crate's vendored csrc/
# (reference parity: scripts/sync-rust-vendor.sh keeps libsplinter-sys'
# csrc/ copy of the core in lockstep with the top-level sources).
set -eu
ROOT="$(cd "$(dirname "$0")/.." && pwd)"
DST="$ROOT/bindings/rust/libsptpu-sys/csrc"
mkdir -p "$DST"
cp "$ROOT/native/src/store.c" \
   "$ROOT/native/src/wptok.c" \
   "$ROOT/native/src/coord.c" \
   "$ROOT/native/src/internal.h" \
   "$DST/"
cp "$ROOT/native/include/sptpu.h" "$DST/"
echo "synced native sources -> $DST"
