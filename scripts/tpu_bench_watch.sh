#!/bin/sh
# Opportunistic TPU measurement loop (VERDICT r2 #1b).
#
# The chip sits behind a single-client claim tunnel that can be
# unavailable for hours (a killed client wedges the claim server-side;
# recovery is a ~30 min server timeout).  This loop keeps exactly ONE
# patient client knocking: each cycle runs bench.py with a bounded
# window (its child blocks in PJRT client-init until the server answers
# UNAVAILABLE or grants the chip).  On the first real measurement it
# also runs the decode and search benches on the chip, then exits —
# every success lands in bench_results.jsonl (timestamped) so the
# round's evidence survives a flaky end-of-round window.
#
# Usage: nohup sh scripts/tpu_bench_watch.sh [deadline_epoch] &
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
DEADLINE="${1:-$(($(date +%s) + 30600))}"   # default: +8.5h

# Two locks with different lifetimes:
#   - instance lock (fd 8, held for our lifetime): one watcher process
#     total — a second launch exits instead of queueing duplicate
#     post-success bench series;
#   - cycle lock (fd 9, held per bench cycle): one tunnel CLIENT at a
#     time — released between cycles so a driver-invoked bench.py
#     (which queues on this lock) gets its turn.
INSTANCE=/tmp/tpu_bench_watch.instance
exec 8>"$INSTANCE"
if ! flock -n 8; then
    echo "[watch] another watcher instance is live; exiting" >&2
    exit 1
fi
LOCK=/tmp/tpu_bench_watch.lock
exec 9>"$LOCK"
OUT="/tmp/bench_cycle.$$.json"
LOG="/tmp/bench_cycle.$$.log"

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    # bounded blocking acquire: never start a cycle past the deadline
    # just because a long driver bench held the lock
    if ! flock -w "$((DEADLINE - $(date +%s)))" 9; then
        echo "[watch] deadline passed while waiting for the lock" >&2
        break
    fi
    echo "[watch] $(date -u +%H:%M:%S) bench cycle starting" >&2
    BENCH_FROM_WATCHER=1 \
    BENCH_SKIP_PROBE=1 BENCH_ATTEMPT_TIMEOUT=2700 BENCH_TIMEOUT=3000 \
        BENCH_BACKOFF=60 python bench.py > "$OUT" 2>>"$LOG"
    # success = a JSON line with a value and NO error field (a hard
    # crash leaves empty output, which must not count as success)
    if ! grep -q '"value"' "$OUT" || grep -q '"error"' "$OUT"; then
        echo "[watch] cycle failed; next cycle" >&2
        flock -u 9
        continue
    fi
    echo "[watch] EMBED BENCH LANDED: $(cat "$OUT")" >&2
    # chip is claimable: capture the whole series back to back while
    # we hold the window (each script is its own single client; they
    # run strictly sequentially).  Failures are logged, not fatal —
    # every success lands in bench_results.jsonl.
    echo "[watch] profile" >&2
    timeout 1200 python bench_profile.py          >> "$LOG" 2>&1
    echo "[watch] decode" >&2
    DECODE_TOKENS=256 timeout 1800 python bench_decode.py \
                                                  >> "$LOG" 2>&1
    echo "[watch] decode quantized" >&2
    DECODE_QUANT=1 DECODE_TOKENS=256 timeout 1800 python bench_decode.py \
                                                  >> "$LOG" 2>&1
    echo "[watch] search" >&2
    SEARCH_N=1000000 timeout 1800 python bench_search.py \
                                                  >> "$LOG" 2>&1
    echo "[watch] all benches done; results in bench_results.jsonl" >&2
    exit 0
done
echo "[watch] deadline reached without a successful claim" >&2
exit 1
