#!/bin/sh
# Opportunistic TPU measurement loop (VERDICT r2 #1b, r3 #1).
#
# The chip sits behind a single-client claim tunnel that can be
# unavailable for hours (a killed client wedges the claim server-side;
# recovery is a ~30 min server timeout).  This loop keeps exactly ONE
# patient client knocking: each cycle runs bench.py with a bounded
# window; its child blocks in PJRT client-init until the server answers
# UNAVAILABLE or grants the chip, and on a grant runs the ENTIRE series
# (embed/profile/kernels/search/decode — bench_series.py) inside that
# one claim, appending every record to bench_results.jsonl as it lands.
# On the first successful series the watcher exits — the evidence set
# is complete in one window.
#
# Usage: nohup sh scripts/tpu_bench_watch.sh [deadline_epoch] &
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
DEADLINE="${1:-$(($(date +%s) + 30600))}"   # default: +8.5h

# Two locks with different lifetimes:
#   - instance lock (fd 8, held for our lifetime): one watcher process
#     total — a second launch exits instead of queueing duplicates;
#   - cycle lock (fd 9, held per bench cycle): one tunnel CLIENT at a
#     time — released between cycles so a driver-invoked bench.py
#     (which queues on this lock) gets its turn.
INSTANCE=/tmp/tpu_bench_watch.instance
exec 8>"$INSTANCE"
if ! flock -n 8; then
    echo "[watch] another watcher instance is live; exiting" >&2
    exit 1
fi
LOCK="${SPTPU_BENCH_LOCK:-/tmp/tpu_bench_watch.lock}"
exec 9>"$LOCK"
OUT="/tmp/bench_cycle.$$.json"
LOG="/tmp/bench_cycle.$$.log"

while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    # bounded blocking acquire: never start a cycle past the deadline
    # just because a long driver bench held the lock
    if ! flock -w "$((DEADLINE - $(date +%s)))" 9; then
        echo "[watch] deadline passed while waiting for the lock" >&2
        break
    fi
    echo "[watch] $(date -u +%H:%M:%S) bench cycle starting" >&2
    # one patient child for nearly the whole cycle; once it claims the
    # chip it runs the full series and ledgers each phase itself
    BENCH_FROM_WATCHER=1 \
    BENCH_SKIP_PROBE=1 BENCH_ATTEMPT_TIMEOUT=3300 BENCH_TIMEOUT=3600 \
        BENCH_BACKOFF=60 python bench.py > "$OUT" 2>>"$LOG"
    # success = a JSON line with a value and NO error field (a hard
    # crash leaves empty output, which must not count as success)
    if ! grep -q '"value"' "$OUT" || grep -q '"error"' "$OUT"; then
        echo "[watch] cycle failed; next cycle" >&2
        flock -u 9
        continue
    fi
    if grep -q '"series_complete": false' "$OUT"; then
        # the headline landed but a later phase hung or was cut off —
        # keep knocking so the rest of the series gets its window
        echo "[watch] PARTIAL series (headline landed): $(cat "$OUT")" >&2
        flock -u 9
        continue
    fi
    echo "[watch] SERIES LANDED: $(cat "$OUT")" >&2
    echo "[watch] full record set in bench_results.jsonl" >&2
    exit 0
done
echo "[watch] deadline reached without a successful claim" >&2
exit 1
