#!/bin/sh
# Opportunistic TPU measurement loop (VERDICT r2 #1b, r3 #1, r4 #1b).
#
# The chip sits behind a single-client claim tunnel that can be
# unavailable for hours (a killed client wedges the claim server-side;
# recovery is a ~30 min server timeout).  Two-speed strategy:
#
#   PROBE cycles (tunnel state unknown/wedged): knock BRIEFLY with a
#   bounded window (child attempt <= 600 s, VERDICT r4 #1b), then stay
#   QUIET for WATCH_GAP seconds with the lock released and zero clients
#   in flight — giving the claim server the quiet interval its
#   wedge-recovery timeout needs (round 4's always-blocked knocking
#   plausibly starved that recovery).
#
#   BANK cycle (a probe just landed a FRESH measurement, i.e. the
#   tunnel is claimable RIGHT NOW): escalate immediately — no gap — to
#   one long window sized for the whole series (the 9 phases' floors
#   sum to ~1110 s plus compiles), so the full evidence set lands in
#   one claim while the tunnel is open.  If the bank cycle fails, drop
#   back to probing.
#
# Driver priority (VERDICT r4 #1b): a driver-invoked bench.py touches
# $LOCK.driver.<pid> on entry; while any live driver's flag exists this
# watcher never STARTS a cycle, so against probe cycles (<=600 s) a
# bounded driver window always gets the lock.  A driver arriving
# mid-BANK-cycle can still wait up to WATCH_BANK seconds — preempting
# a measuring child would kill a claim-holding client (the wedge
# trigger) and lose the series; the driver's ledger-promotion fallback
# reports the bank cycle's freshly ledgered headline in that case.
# A flag whose pid is dead (driver SIGKILLed, cleanup never ran) is
# stale and removed — it must not disable the watcher.
#
# On a granted claim the child runs the ENTIRE series (embed/profile/
# kernels/search/restage/decode — bench_series.py) inside that one
# claim, appending every record to bench_results.jsonl as it lands.
# On the first COMPLETE series the watcher exits.
#
# Usage: nohup sh scripts/tpu_bench_watch.sh [deadline_epoch] &
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
cd "$REPO"
DEADLINE="${1:-$(($(date +%s) + 30600))}"   # default: +8.5h
PROBE_S="${WATCH_CYCLE:-600}"               # short-knock window
BANK_S="${WATCH_BANK:-3600}"                # full-series window
GAP_S="${WATCH_GAP:-2100}"                  # quiet gap between probes

# Two locks with different lifetimes:
#   - instance lock (fd 8, held for our lifetime): one watcher process
#     total — a second launch exits instead of queueing duplicates;
#   - cycle lock (fd 9, held per bench cycle): one tunnel CLIENT at a
#     time — released between cycles so a driver-invoked bench.py
#     (which queues on this lock) gets its turn.
INSTANCE=/tmp/tpu_bench_watch.instance
exec 8>"$INSTANCE"
if ! flock -n 8; then
    echo "[watch] another watcher instance is live; exiting" >&2
    exit 1
fi
LOCK="${SPTPU_BENCH_LOCK:-/tmp/tpu_bench_watch.lock}"
exec 9>"$LOCK"
OUT="/tmp/bench_cycle.$$.json"
LOG="/tmp/bench_cycle.$$.log"

# 0 = no live driver flag; 1 = a live driver is waiting.  Stale flags
# (writer pid dead) are removed.  The pid is parsed from the FILENAME
# ($LOCK.driver.<pid>) so a just-created, still-empty file is never
# misread as stale.
driver_waiting() {
    _live=1
    for F in "$LOCK".driver.*; do
        [ -e "$F" ] || continue
        DPID="${F##*.driver.}"
        # liveness = that pid is still a bench.py process (plain
        # kill -0 would both trust a recycled pid forever and EPERM-
        # fail on a different-uid driver)
        if [ -n "$DPID" ] && \
           grep -aq "bench\.py" "/proc/$DPID/cmdline" 2>/dev/null; then
            _live=0
        else
            echo "[watch] stale driver flag $F (pid ${DPID:-?} gone); removing" >&2
            rm -f "$F"
        fi
    done
    return "$_live"
}

CYCLE_S="$PROBE_S"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    # a waiting driver owns the tunnel window; stay out of its way
    if driver_waiting; then
        echo "[watch] driver waiting; yielding" >&2
        sleep 15
        continue
    fi
    # bounded blocking acquire: never start a cycle past the deadline
    # just because a long driver bench held the lock
    if ! flock -w "$((DEADLINE - $(date +%s)))" 9; then
        echo "[watch] deadline passed while waiting for the lock" >&2
        break
    fi
    if driver_waiting; then           # driver arrived while we queued
        flock -u 9
        continue
    fi
    echo "[watch] $(date -u +%H:%M:%S) bench cycle starting (window ${CYCLE_S}s)" >&2
    # on a granted claim the child runs the full series and ledgers
    # each phase itself
    BENCH_FROM_WATCHER=1 \
    BENCH_SKIP_PROBE=1 \
    BENCH_ATTEMPT_TIMEOUT="$((CYCLE_S - 60))" BENCH_TIMEOUT="$CYCLE_S" \
        BENCH_BACKOFF=30 python bench.py > "$OUT" 2>>"$LOG"
    flock -u 9
    # quiet gap, never past the deadline (the instance lock is held for
    # our lifetime; lingering would lock out a next-round watcher)
    NAP="$((DEADLINE - $(date +%s)))"
    [ "$NAP" -gt "$GAP_S" ] && NAP="$GAP_S"
    # success = a JSON line with a value and NO error field (a hard
    # crash leaves empty output, which must not count as success)
    if ! grep -q '"value"' "$OUT" || grep -q '"error"' "$OUT"; then
        CYCLE_S="$PROBE_S"
        echo "[watch] cycle failed; quiet ${NAP}s (claim-server recovery)" >&2
        [ "$NAP" -gt 0 ] && sleep "$NAP"
        continue
    fi
    if grep -q '"series_complete": false' "$OUT"; then
        if grep -q '"headline_from_ledger"' "$OUT"; then
            # no fresh claim this cycle — the headline was promoted
            # from the ledger; treat as a failed probe (quiet, retry)
            CYCLE_S="$PROBE_S"
            echo "[watch] ledger-promoted partial (no fresh claim); quiet ${NAP}s" >&2
            [ "$NAP" -gt 0 ] && sleep "$NAP"
            continue
        fi
        # FRESH partial: the tunnel is claimable right now — escalate
        # immediately to a full-series window while it stays open
        echo "[watch] FRESH PARTIAL series: $(cat "$OUT")" >&2
        echo "[watch] escalating to a ${BANK_S}s full-series cycle" >&2
        CYCLE_S="$BANK_S"
        continue
    fi
    echo "[watch] SERIES LANDED: $(cat "$OUT")" >&2
    echo "[watch] full record set in bench_results.jsonl" >&2
    exit 0
done
echo "[watch] deadline reached without a successful claim" >&2
exit 1
