"""CPU fast gate for the multi-tenant QoS layer (`make qos-check`).

The serving stack's overload-survival claims (engine/qos.py) are only
claims until offered load actually exceeds capacity with the gate
watching.  This check drives a real Searcher — the cheapest daemon to
stand up, no model — through a saturated 10:1 two-tenant drill on CPU
and asserts the acceptance properties:

  - FAIRNESS: under sustained 10:1 offered-load skew at equal weights,
    both tenants make progress and the starved tenant's admitted share
    lands within 2x of its configured (equal) weight share;
  - WEIGHTED FAIRNESS: a 3:1 weight split lands the admitted ratio
    within 2x of 3:1;
  - SHEDDING: past the queue high-water mark overflow is failed with
    the typed {"err": "overloaded", "retry_after_ms": N} record —
    never silent unbounded queueing — and a drained lane admits fresh
    work again (shed-then-admit);
  - DEADLINE: an already-expired request is failed fast with a typed
    deadline_expired record instead of occupying a batch slot.

Runs in a few seconds; tier-1 keeps the full pytest matrix
(tests/test_qos.py), this is the standalone evidence `make check`
prints.
"""
from __future__ import annotations

import json
import os
import sys
import time
import uuid

os.environ.setdefault("JAX_PLATFORMS", "cpu")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _seed(store, n=8):
    import numpy as np
    rng = np.random.default_rng(0)
    for i in range(n):
        v = rng.standard_normal(store.vec_dim).astype(np.float32)
        store.set(f"doc{i}", f"doc {i}")
        store.vec_set(f"doc{i}", v / np.linalg.norm(v))


def _req(store, key, tenant, deadline=None):
    import numpy as np

    from libsplinter_tpu.engine import protocol as P
    params = {"k": 3}
    if deadline is not None:
        params["deadline"] = deadline
    store.set(key, json.dumps(params))
    qv = np.zeros(store.vec_dim, np.float32)
    qv[0] = 1.0
    store.vec_set(key, qv)
    if tenant:
        P.stamp_tenant(store, key, tenant)
    store.label_or(key, P.LBL_SEARCH_REQ | P.LBL_WAITING)
    store.bump(key)


def _result(store, key):
    from libsplinter_tpu.engine import protocol as P
    return json.loads(store.get(
        P.search_result_key(store.find_index(key))).rstrip(b"\0"))


def fairness_drill(weights, rounds=8, heavy=10, light=1,
                   admit_cap=4) -> tuple[int, int]:
    from libsplinter_tpu import Store
    from libsplinter_tpu.engine.searcher import Searcher

    name = f"/spt-qoscheck-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    st = Store.create(name, nslots=512, max_val=2048, vec_dim=32)
    try:
        _seed(st)
        sr = Searcher(st, admit_cap=admit_cap,
                      tenant_weights=weights)
        sr.attach()
        for r in range(rounds):
            for j in range(heavy):
                _req(st, f"h{r}-{j}", 1)
            for j in range(light):
                _req(st, f"l{r}-{j}", 2)
            sr.run_once()
        # drain the tail so "admitted" reflects steady-state shares,
        # not one final burst
        return (sr.tenants.get(1, "admitted"),
                sr.tenants.get(2, "admitted"))
    finally:
        st.close()
        Store.unlink(name)


def shed_and_deadline_drill() -> dict:
    from libsplinter_tpu import Store
    from libsplinter_tpu.engine import protocol as P
    from libsplinter_tpu.engine.searcher import Searcher

    name = f"/spt-qoscheck-{os.getpid()}-{uuid.uuid4().hex[:6]}"
    st = Store.create(name, nslots=512, max_val=2048, vec_dim=32)
    try:
        _seed(st)
        sr = Searcher(st, admit_cap=2, queue_high_water=1,
                      retry_after_ms=150)
        sr.attach()
        _req(st, "expired", 1, deadline=time.time() - 1.0)
        for i in range(6):
            _req(st, f"q{i}", 1)
        sr.run_once()
        shed = [i for i in range(6)
                if not st.labels(f"q{i}") & P.LBL_SEARCH_REQ
                and _result(st, f"q{i}").get("err") == "overloaded"]
        hints = {_result(st, f"q{i}").get("retry_after_ms")
                 for i in shed}
        # drain the deferred backlog, then fresh work must admit
        for _ in range(4):
            sr.run_once()
        _req(st, "fresh", 2)
        sr.run_once()
        return {
            "deadline_expired": sr.stats.deadline_expired,
            "expired_typed": _result(st, "expired").get("err"),
            "shed": len(shed),
            "retry_after_ms": sorted(hints),
            "fresh_admitted": "err" not in _result(st, "fresh"),
        }
    finally:
        st.close()
        Store.unlink(name)


def main() -> int:
    h_eq, l_eq = fairness_drill(None)
    # equal weights, 10:1 offered load: the light tenant's whole
    # offered load (8 rounds x 1) fits under half the admitted
    # capacity — it must ALL land, within 2x of the equal share
    print(f"fairness equal-weights: heavy={h_eq} light={l_eq}")
    if l_eq == 0 or h_eq == 0:
        print("FAIL: a tenant starved outright")
        return 1
    if l_eq < 8:
        print(f"FAIL: light tenant served {l_eq}/8 offered under "
              "equal weights")
        return 1

    h_w, l_w = fairness_drill({1: 3.0, 2: 1.0}, heavy=10, light=10,
                              admit_cap=4)
    ratio = h_w / max(l_w, 1)
    print(f"fairness 3:1 weights (both saturating): heavy={h_w} "
          f"light={l_w} ratio={ratio:.2f}")
    if not (1.5 <= ratio <= 6.0):
        print("FAIL: weighted share outside 2x of the 3:1 config")
        return 1

    shed = shed_and_deadline_drill()
    print(f"shed/deadline: {json.dumps(shed)}")
    if shed["deadline_expired"] != 1 \
            or shed["expired_typed"] != "deadline_expired":
        print("FAIL: expired request not fast-failed typed")
        return 1
    if shed["shed"] != 3 or shed["retry_after_ms"] != [150]:
        print("FAIL: high-water shed not typed overloaded + hint")
        return 1
    if not shed["fresh_admitted"]:
        print("FAIL: lane did not admit fresh work after draining")
        return 1
    print("qos-check OK: fairness within 2x of weights, typed "
          "shedding with retry_after_ms, deadline fast-fail")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
