"""compile-check: the post-warmup no-recompile gate.

Runs the pod-sharded paged serving drill (the PR 8 warmup-coverage
shape: dp=4 x tp=2 on the 8-device CPU mesh — join, decode, free,
re-join at a DIFFERENT prompt length, decode again) under the devtime
compile ledger (obs/devtime.py) and asserts ZERO runtime-cause
compile events: warmup must cover the whole serve-time signature, so
a serve-time jit cache growth is a silent-recompile regression — the
class SPL203 guards statically, gated here dynamically.

On failure the verdict names each guilty program and the shapes key
that missed warmup — the two facts the fix needs (which program, and
which signature to add to warmup).

`--seed-recompile` is the gate's own failure drill: it arms
`SPTPU_SEED_RECOMPILE=1` (models/decoder.py drops the paged-pool
`out_shardings` pin, resurrecting the PR 8 bug on purpose) and the
script exits 0 only if the gate CAUGHT it — a runtime-cause event
naming a completer program with a shapes key, surfaced both
in-process and through the `__compile_<i>` store ring.  A gate that
cannot fail is not a gate; `make compile-check` runs both directions.

Exit 0 and a JSON line on success (either direction); exit 1 with the
guilty programs when the gate's verdict is wrong.
"""
from __future__ import annotations

import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")
# 8 host devices BEFORE jax import — the dp=4 x tp=2 mesh drill
# (tests/chaos_child.py discipline)
_flags = os.environ.get("XLA_FLAGS", "")
_flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                _flags)
os.environ["XLA_FLAGS"] = (
    _flags + " --xla_force_host_platform_device_count=8").strip()

SEEDED = "--seed-recompile" in sys.argv[1:]
if SEEDED:
    os.environ["SPTPU_SEED_RECOMPILE"] = "1"

import numpy as np  # noqa: E402


def serve_drill():
    """Warmup, then the join/decode/free/re-join serve cycle — every
    dispatch a continuous-lane drain would issue, at two different
    prompt lengths so bucket selection is exercised."""
    import jax.numpy as jnp

    from libsplinter_tpu.models.decoder import DecoderConfig
    from libsplinter_tpu.parallel.mesh import make_mesh
    from libsplinter_tpu.parallel.serve import ShardedCompletionModel

    cfg = DecoderConfig.tiny(dtype=jnp.float32)
    mesh = make_mesh(dp=4, tp=2)
    m = ShardedCompletionModel(cfg, mesh, buckets=(16, 32),
                               temp=0.0, seed=1)
    cache = m.init_paged(4, page=16)
    m.warmup_paged(cache, chunk=4, max_prompt=30)

    lg = m.paged_prefill_row(cache, np.ones((7,), np.int32), 0)
    m.sample(lg)
    m.paged_decode_chunk(cache, np.array([1, 0, 0, 0], np.int32), 4)
    cache.free_row(0)
    lg = m.paged_prefill_row(cache, np.ones((20,), np.int32), 1)
    m.sample(lg)
    m.paged_decode_chunk(cache, np.array([0, 2, 0, 0], np.int32), 4)


def main() -> int:
    from libsplinter_tpu import Store
    from libsplinter_tpu.obs.devtime import (DEVTIME,
                                             collect_compile_events)

    if os.environ.get("SPTPU_DEVTIME") == "0":
        print("compile-check FAILED: SPTPU_DEVTIME=0 — the gate "
              "cannot see compiles with the ledger disabled",
              file=sys.stderr)
        return 1
    serve_drill()

    # the in-process verdict ...
    pending = DEVTIME.pending_events()
    runtime = [e for e in pending if e["cause"] == "runtime"]
    n_runtime = DEVTIME.compile_events()
    # ... and the cross-process one: flush through the store ring and
    # read it back the way `spt trace export` / an operator would
    name = f"/spt-compilegate-{os.getpid()}"
    Store.unlink(name)
    st = Store.create(name, nslots=256, max_val=1024, vec_dim=8)
    try:
        DEVTIME.flush(st)
        ringed = [e for e in collect_compile_events(st)
                  if e["cause"] == "runtime"]
    finally:
        st.close()
        Store.unlink(name)

    guilty = sorted({(e["program"], e["shapes_key"])
                     for e in runtime})
    rec = {"metric": "post_warmup_compile_events",
           "value": n_runtime,
           "seeded": SEEDED,
           "warmup_events": len(pending) - len(runtime),
           "guilty": [{"program": p, "shapes_key": k}
                      for p, k in guilty]}

    if not SEEDED:
        rec["ok"] = n_runtime == 0
        print(json.dumps(rec), flush=True)
        if n_runtime:
            for p, k in guilty:
                print(f"compile-check FAILED: {p} recompiled after "
                      f"warmup for shapes {k} — add the signature "
                      f"to warmup (or pin out_shardings)",
                      file=sys.stderr)
            return 1
        return 0

    # seeded self-test: the gate MUST have fired, naming a completer
    # program with a shapes key, visible through the ring too
    caught = (n_runtime > 0
              and any(p.startswith("completer.") and k
                      for p, k in guilty)
              and any(e["program"].startswith("completer.")
                      for e in ringed))
    rec["ok"] = caught
    print(json.dumps(rec), flush=True)
    if not caught:
        print(f"compile-check FAILED: seeded out_shardings drop was "
              f"NOT caught (runtime events={n_runtime}, "
              f"ring events={len(ringed)}) — the gate is blind",
              file=sys.stderr)
        return 1
    for p, k in guilty:
        print(f"compile-check seeded drill: caught {p} "
              f"recompiling for shapes {k} (as intended)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
