"""obs-check: the enabled record path must stay ~free.

Runs the embedder micro-bench (stub encoder, event-driven drains — the
shape of tests/test_embedder_pipeline.py's waves) twice in one
process: SPTPU_TRACE disabled, then enabled (histogram spans + stage
accumulation + flight-recorder stamps + the PR-13 span-ring commit
for the stamped request), and asserts the enabled path costs < 3%
extra wall time.  A second phase re-runs the ENABLED arm with the
telemetry sampler (engine/telemetry.py) scraping concurrently at a
production-like cadence and asserts the serving drain still fits the
same budget — the sampler lives off the wake path, and this is the
gate that keeps it there.

Methodology: interleaved arms (off, on, off, on, ...) compared at
their MINIMUM over many reps, best of up to 3 rounds.  The record
path's cost is deterministic; host noise (noisy neighbors on shared
infra, thermal, allocator state) is additive and can only INFLATE a
min-based overhead reading — it cannot make the enabled arm look
cheaper than it is — so "any round under budget" is a sound
upper-bound assertion while being robust to the multi-ms noise bursts
this box exhibits.  GC is disabled during timing so a collection
pause can't land in one arm.  A NULL CONTROL (the disabled samples
split even/odd — identical code, so their spread is pure noise)
guards the verdict: when the apparent overhead exceeds the budget but
the null spread rivals it, the box cannot resolve the budget and the
check reports inconclusive instead of failing CI on noise.  `make
obs-check` runs this plus `pytest -m obs`.

Exit 0 and a JSON line on success; exit 1 with the measured overhead
when the budget is blown.
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

from libsplinter_tpu import Store, T_VARTEXT  # noqa: E402
from libsplinter_tpu.engine import protocol as P  # noqa: E402
from libsplinter_tpu.engine.embedder import Embedder  # noqa: E402
from libsplinter_tpu.utils.trace import tracer  # noqa: E402

KEYS = int(os.environ.get("OBS_CHECK_KEYS", "128"))
REPS = int(os.environ.get("OBS_CHECK_REPS", "120"))
ROUNDS = int(os.environ.get("OBS_CHECK_ROUNDS", "3"))
BUDGET = float(os.environ.get("OBS_CHECK_BUDGET_PCT", "3.0"))


def encoder(texts):
    return np.zeros((len(texts), 8), np.float32)


def drain_once(st, emb, stamp: bool) -> float:
    for i in range(KEYS):
        key = f"k/{i}"
        st.set(key, f"obs check text number {i}")
        st.set_type(key, T_VARTEXT)
        st.label_or(key, P.LBL_EMBED_REQ)
        st.bump(key)
    if stamp:
        P.stamp_trace(st, "k/0")     # one traced request per wave
    t0 = time.perf_counter()
    n = emb.drain()
    dt = (time.perf_counter() - t0) * 1e3
    assert n == KEYS, (n, KEYS)
    return dt


def main() -> int:
    name = f"/spt-obscheck-{os.getpid()}"
    Store.unlink(name)
    st = Store.create(name, nslots=max(256, KEYS * 4), max_val=1024,
                      vec_dim=8)
    try:
        # daemon-default batch_cap: the per-batch record cost is
        # amortized exactly as production amortizes it
        emb = Embedder(st, encoder_fn=encoder, max_ctx=512)
        emb.attach()
        # alternate the arms drain by drain so host drift (thermal,
        # noisy neighbors, allocator state) hits both equally, then
        # compare best-of: min is the robust estimator of what each
        # code path itself costs
        import gc

        for arm in (False, True):    # warm both paths untimed
            tracer.enabled = arm
            drain_once(st, emb, arm)

        def round_() -> tuple[float, float, float]:
            """(min_off, min_on, null_pct): the null control splits
            the DISABLED samples into even/odd halves — two identical
            code paths — so their min-vs-min ratio measures the pure
            noise floor of this box right now."""
            offs, ons = [], []
            gc.collect()
            gc.disable()   # a GC pause landing in one arm would
            try:           # swamp the ~tens-of-us effect measured
                for _ in range(REPS):
                    tracer.enabled = False
                    offs.append(drain_once(st, emb, False))
                    tracer.enabled = True
                    ons.append(drain_once(st, emb, True))
            finally:
                gc.enable()
            tracer.reset()
            null = (abs(min(offs[0::2]) / min(offs[1::2]) - 1.0) * 100.0
                    if len(offs) >= 2 else 0.0)
            return min(offs), min(ons), null

        off, on, null_pct = round_()
        rounds_run = 1
        while on / off - 1.0 >= BUDGET / 100.0 \
                and rounds_run < ROUNDS:
            o, n, nl = round_()
            if n / o < on / off:
                off, on = o, n
            null_pct = max(null_pct, nl)   # worst observed noise
            rounds_run += 1

        # ---- phase 2: the telemetry sampler must stay off the wake
        # path.  Enabled-arm drains with a sampler thread scraping at
        # a production-like cadence vs without; min-based, so the
        # verdict reads the drains that show the sampler's STRUCTURAL
        # cost (store-lock contention on the wake path), not the rare
        # wall-clock collision with a 20 ms-spaced tick.
        import threading

        from libsplinter_tpu.engine.telemetry import TelemetrySampler

        sam = TelemetrySampler(st, interval_s=0.02)
        sam.attach()
        stop = threading.Event()

        def _scrape():
            while not stop.is_set():
                sam.sample_once()
                stop.wait(0.02)

        tracer.enabled = True
        gc.collect()
        gc.disable()
        try:
            base = [drain_once(st, emb, True)
                    for _ in range(max(REPS // 2, 20))]
            th = threading.Thread(target=_scrape, daemon=True)
            th.start()
            withs = [drain_once(st, emb, True)
                     for _ in range(max(REPS // 2, 20))]
            stop.set()
            th.join(timeout=5)
        finally:
            gc.enable()
        tracer.reset()
        sampler_pct = (min(withs) / min(base) - 1.0) * 100.0
        assert sam.stats.samples > 0, "sampler never ticked"

        # ---- phase 3: the devtime plane (PR 17) must fit the same
        # budget.  Two embedders over the same store — one whose
        # encoder is DEVTIME-registered (a dispatch mark opened and
        # closed per encode, the ledger cache-size probes, the lane
        # device-ms accumulator the drain's span commit pops) vs the
        # plain stub — interleaved and min-compared like phase 1.
        from libsplinter_tpu.obs.devtime import DEVTIME

        emb_dt = Embedder(st, encoder_fn=DEVTIME.register(
            "embedder.encode", encoder), max_ctx=512)
        emb_dt.attach()
        tracer.enabled = True
        drain_once(st, emb_dt, True)          # warm untimed
        gc.collect()
        gc.disable()
        try:
            plain, marked = [], []
            for _ in range(max(REPS // 2, 20)):
                plain.append(drain_once(st, emb, True))
                marked.append(drain_once(st, emb_dt, True))
        finally:
            gc.enable()
        tracer.reset()
        devtime_pct = (min(marked) / min(plain) - 1.0) * 100.0
        assert DEVTIME.compile_events() == 0, \
            "stub encoder cannot compile"
    finally:
        tracer.enabled = os.environ.get("SPTPU_TRACE") == "1"
        st.close()
        Store.unlink(name)
    overhead_pct = (on / off - 1.0) * 100.0
    # the verdict discounts the worst same-code noise spread seen:
    # the budget applies to (overhead - noise floor), so a quiet box
    # asserts the strict 3% while a noisy one cannot go red on bursts
    # it demonstrably produces with NO code difference.  A real
    # regression clears the floor by construction (its cost is
    # deterministic; noise is not).
    inconclusive = (overhead_pct >= BUDGET
                    and overhead_pct - null_pct < BUDGET)
    sampler_inconclusive = (sampler_pct >= BUDGET
                            and sampler_pct - null_pct < BUDGET)
    sampler_ok = sampler_pct < BUDGET or sampler_inconclusive
    devtime_inconclusive = (devtime_pct >= BUDGET
                            and devtime_pct - null_pct < BUDGET)
    devtime_ok = devtime_pct < BUDGET or devtime_inconclusive
    rec = {"metric": "obs_record_overhead_pct",
           "value": round(overhead_pct, 2),
           "budget_pct": BUDGET,
           "noise_floor_pct": round(null_pct, 2),
           "disabled_ms": round(off, 3), "enabled_ms": round(on, 3),
           "sampler_overhead_pct": round(sampler_pct, 2),
           "devtime_overhead_pct": round(devtime_pct, 2),
           "keys_per_drain": KEYS, "reps": REPS,
           "rounds_run": rounds_run,
           "ok": (overhead_pct < BUDGET or inconclusive)
           and sampler_ok and devtime_ok}
    if inconclusive or sampler_inconclusive or devtime_inconclusive:
        rec["inconclusive"] = True
    print(json.dumps(rec), flush=True)
    if inconclusive:
        print(f"obs-check INCONCLUSIVE: apparent overhead "
              f"{overhead_pct:.2f}% but same-code noise floor "
              f"{null_pct:.2f}% — box too noisy to resolve the "
              f"{BUDGET}% budget; not failing on noise",
              file=sys.stderr)
    if sampler_inconclusive:
        print(f"obs-check sampler arm INCONCLUSIVE: apparent "
              f"{sampler_pct:.2f}% vs noise floor {null_pct:.2f}%",
              file=sys.stderr)
    if devtime_inconclusive:
        print(f"obs-check devtime arm INCONCLUSIVE: apparent "
              f"{devtime_pct:.2f}% vs noise floor {null_pct:.2f}%",
              file=sys.stderr)
    if not rec["ok"]:
        if overhead_pct >= BUDGET and not inconclusive:
            print(f"obs-check FAILED: tracing overhead "
                  f"{overhead_pct:.2f}% >= {BUDGET}% budget "
                  f"(noise floor {null_pct:.2f}%)",
                  file=sys.stderr)
        if not sampler_ok:
            print(f"obs-check FAILED: concurrent telemetry sampler "
                  f"adds {sampler_pct:.2f}% >= {BUDGET}% to the "
                  f"serving drain (it must stay off the wake path)",
                  file=sys.stderr)
        if not devtime_ok:
            print(f"obs-check FAILED: the devtime mark/ledger path "
                  f"adds {devtime_pct:.2f}% >= {BUDGET}% to the "
                  f"serving drain (SPL201's zero-new-syncs bargain "
                  f"includes staying cheap)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
