/* spt_sidecar — terminal "side car" monitor for a splinter-tpu host.
 *
 * Capability parity with the reference's sidecar tool (sidecar.c: htop-style
 * CPU/mem/swap/iowait/loadavg/battery graphs from /proc + /sys, a tail -f
 * file mode OR a store-attach mode that label-watches the debug bloom bit on
 * signal group 63 and prints changed keys, number keys forking `.sidecar.N`
 * job scripts), re-designed for this store:
 *
 *   - store attach uses the native bloom-bit -> signal-group binding
 *     (spt_watch_label_register) plus the event bus when armed, instead of
 *     per-key watch registration over an enumeration;
 *   - an extra STORE panel renders header telemetry the reference lacks:
 *     used slots, global-epoch rate (ops/s observed from the monitor seat),
 *     parse failures, live shard bids and the current election sovereign;
 *   - changed-key detection is per-slot-epoch diffing over the index-based
 *     accessors, so a burst of writes between refreshes is never missed.
 *
 * Usage:
 *   spt_sidecar                  graphs only
 *   spt_sidecar spt:NAME         attach to store NAME (shm backend)
 *   spt_sidecar sptf:PATH        attach to file-backed store at PATH
 *   spt_sidecar /path/to/log     tail a text file into the chatter panel
 *
 * Keys: q quit, 1..9 fork ./.sidecar.N (a user job script), c clear chatter.
 */
#define _GNU_SOURCE
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>
#include <signal.h>
#include <time.h>
#include <errno.h>
#include <fcntl.h>
#include <termios.h>
#include <dirent.h>
#include <sys/ioctl.h>
#include <sys/stat.h>
#include <sys/wait.h>

#include "sptpu.h"

#define REFRESH_US      500000
#define HIST_MAX        512
#define CHATTER_MAX     12
#define CHATTER_WIDTH   500
#define DEBUG_GROUP     63u
#define DEBUG_BLOOM_BIT 59u   /* 0x0800000000000000 — reference debug label */

/* ---------------- sampled system state ---------------- */

typedef struct {
  unsigned long long user, nice, sys, idle, iowait, irq, softirq, steal;
} cpu_sample;

typedef struct {
  double cpu_pct, mem_pct, swap_pct, io_pct;
  double load1, load5, load15;
  int procs_running, procs_total;
  int battery_pct;   /* -1 when no battery exposed */
  int on_ac;
} sys_sample;

static int g_cols = 80, g_rows = 24, g_graphw = 60;
static volatile sig_atomic_t g_resized = 0, g_quit = 0;
static double g_hist_cpu[HIST_MAX], g_hist_mem[HIST_MAX];
static int g_hist_len = 0;

static char *g_chatter[CHATTER_MAX];
static int g_chatter_n = 0;

static struct termios g_tio_orig;

static void chatter_push(const char *line) {
  char *dup = strndup(line, CHATTER_WIDTH);
  if (!dup) return;
  if (g_chatter_n == CHATTER_MAX) {
    free(g_chatter[0]);
    memmove(g_chatter, g_chatter + 1, (CHATTER_MAX - 1) * sizeof(char *));
    g_chatter_n--;
  }
  g_chatter[g_chatter_n++] = dup;
}

static void chatter_clear(void) {
  for (int i = 0; i < g_chatter_n; i++) free(g_chatter[i]);
  g_chatter_n = 0;
}

/* ---------------- /proc + /sys sampling ---------------- */

static int read_cpu_sample(cpu_sample *s) {
  memset(s, 0, sizeof *s);
  FILE *f = fopen("/proc/stat", "r");
  if (!f) return -1;
  int n = fscanf(f, "cpu %llu %llu %llu %llu %llu %llu %llu %llu",
                 &s->user, &s->nice, &s->sys, &s->idle,
                 &s->iowait, &s->irq, &s->softirq, &s->steal);
  fclose(f);
  return n == 8 ? 0 : -1;
}

static void sample_cpu(cpu_sample *prev, sys_sample *out) {
  cpu_sample cur;
  if (read_cpu_sample(&cur) < 0) { out->cpu_pct = out->io_pct = 0; return; }
  unsigned long long pidle = prev->idle + prev->iowait;
  unsigned long long cidle = cur.idle + cur.iowait;
  unsigned long long pbusy = prev->user + prev->nice + prev->sys +
                             prev->irq + prev->softirq + prev->steal;
  unsigned long long cbusy = cur.user + cur.nice + cur.sys +
                             cur.irq + cur.softirq + cur.steal;
  unsigned long long dtot = (cidle + cbusy) - (pidle + pbusy);
  if (dtot) {
    out->cpu_pct = 100.0 * (double)(cbusy - pbusy) / (double)dtot;
    out->io_pct  = 100.0 * (double)(cur.iowait - prev->iowait) / (double)dtot;
  } else {
    out->cpu_pct = out->io_pct = 0.0;
  }
  *prev = cur;
}

static void sample_mem(sys_sample *out) {
  FILE *f = fopen("/proc/meminfo", "r");
  unsigned long total = 1, avail = 0, stotal = 0, sfree = 0, v;
  char key[64];
  if (!f) { out->mem_pct = out->swap_pct = 0; return; }
  while (fscanf(f, "%63s %lu kB\n", key, &v) == 2) {
    if (!strcmp(key, "MemTotal:")) total = v;
    else if (!strcmp(key, "MemAvailable:")) avail = v;
    else if (!strcmp(key, "SwapTotal:")) stotal = v;
    else if (!strcmp(key, "SwapFree:")) sfree = v;
  }
  fclose(f);
  out->mem_pct  = total ? 100.0 * (double)(total - avail) / (double)total : 0;
  out->swap_pct = stotal ? 100.0 * (double)(stotal - sfree) / (double)stotal : 0;
}

static void sample_load(sys_sample *out) {
  FILE *f = fopen("/proc/loadavg", "r");
  if (!f) return;
  if (fscanf(f, "%lf %lf %lf %d/%d", &out->load1, &out->load5, &out->load15,
             &out->procs_running, &out->procs_total) != 5) {
    out->load1 = out->load5 = out->load15 = 0;
  }
  fclose(f);
}

static int read_int_file(const char *path) {
  FILE *f = fopen(path, "r");
  int v = -1;
  if (f) { if (fscanf(f, "%d", &v) != 1) v = -1; fclose(f); }
  return v;
}

static void sample_power(sys_sample *out) {
  out->battery_pct = -1;
  out->on_ac = 0;
  DIR *d = opendir("/sys/class/power_supply");
  if (!d) return;
  struct dirent *e;
  char path[512];
  while ((e = readdir(d))) {
    if (e->d_name[0] == '.') continue;
    snprintf(path, sizeof path, "/sys/class/power_supply/%s/type", e->d_name);
    FILE *f = fopen(path, "r");
    char kind[32] = "";
    if (f) { if (!fgets(kind, sizeof kind, f)) kind[0] = 0; fclose(f); }
    if (!strncmp(kind, "Battery", 7)) {
      snprintf(path, sizeof path, "/sys/class/power_supply/%s/capacity",
               e->d_name);
      out->battery_pct = read_int_file(path);
    } else if (!strncmp(kind, "Mains", 5)) {
      snprintf(path, sizeof path, "/sys/class/power_supply/%s/online",
               e->d_name);
      out->on_ac = read_int_file(path) == 1;
    }
  }
  closedir(d);
}

/* ---------------- terminal handling ---------------- */

static void restore_term(void) {
  tcsetattr(STDIN_FILENO, TCSAFLUSH, &g_tio_orig);
  printf("\x1b[?25h\x1b[0m\n");  /* cursor back on */
  fflush(stdout);
}

static void raw_term(void) {
  tcgetattr(STDIN_FILENO, &g_tio_orig);
  atexit(restore_term);
  struct termios raw = g_tio_orig;
  raw.c_lflag &= (tcflag_t)~(ECHO | ICANON);
  raw.c_cc[VMIN] = 0;
  raw.c_cc[VTIME] = 0;
  tcsetattr(STDIN_FILENO, TCSAFLUSH, &raw);
  printf("\x1b[?25l");  /* hide cursor */
}

static void on_winch(int sig) { (void)sig; g_resized = 1; }
static void on_int(int sig)   { (void)sig; g_quit = 1; }

static void measure_term(void) {
  struct winsize ws;
  if (ioctl(STDOUT_FILENO, TIOCGWINSZ, &ws) == 0 && ws.ws_col > 0) {
    g_cols = ws.ws_col;
    g_rows = ws.ws_row;
  }
  g_graphw = g_cols - 14;
  if (g_graphw > HIST_MAX) g_graphw = HIST_MAX;
  if (g_graphw < 20) g_graphw = 20;
}

/* ---------------- rendering ---------------- */

static void push_hist(double *hist, double v) {
  /* hist is a rolling window of the most recent HIST_MAX samples */
  if (g_hist_len == HIST_MAX)
    memmove(hist, hist + 1, (HIST_MAX - 1) * sizeof(double));
  hist[g_hist_len == HIST_MAX ? HIST_MAX - 1 : g_hist_len] = v;
}

static void draw_bar(const char *tag, double pct, const char *color) {
  int fill = (int)(pct / 100.0 * g_graphw + 0.5);
  if (fill > g_graphw) fill = g_graphw;
  printf(" %-4s %s", tag, color);
  for (int i = 0; i < g_graphw; i++) putchar(i < fill ? '|' : ' ');
  printf("\x1b[0m %5.1f%%\x1b[K\n", pct);
}

static void draw_spark(const char *tag, const double *hist, const char *color) {
  static const char *lvl = " .:-=+*#%@";
  int n = g_hist_len < g_graphw ? g_hist_len : g_graphw;
  int start = g_hist_len - n;
  printf(" %-4s %s", tag, color);
  for (int i = 0; i < g_graphw - n; i++) putchar(' ');
  for (int i = 0; i < n; i++) {
    int l = (int)(hist[start + i] / 100.0 * 9.0 + 0.5);
    if (l < 0) l = 0;
    if (l > 9) l = 9;
    putchar(lvl[l]);
  }
  printf("\x1b[0m\x1b[K\n");
}

/* ---------------- store attachment ---------------- */

typedef struct {
  spt_store *st;
  uint64_t  *epochs;        /* last seen per-slot epoch */
  uint32_t  *idx_buf;       /* enumeration scratch, nslots entries */
  uint32_t   nslots;
  uint64_t   last_signal;
  uint64_t   last_global_epoch;
  double     ops_rate;      /* global-epoch delta per second */
  int        bus_ok;
} attach_t;

static int attach_store(attach_t *a, const char *name, uint32_t flags) {
  memset(a, 0, sizeof *a);
  a->st = spt_open(name, flags);
  if (!a->st) return -1;
  a->nslots = spt_nslots(a->st);
  a->epochs = calloc(a->nslots, sizeof(uint64_t));
  a->idx_buf = calloc(a->nslots, sizeof(uint32_t));
  if (!a->epochs || !a->idx_buf) {
    free(a->epochs);
    free(a->idx_buf);
    spt_close(a->st);
    a->st = NULL;
    return -1;
  }
  for (uint32_t i = 0; i < a->nslots; i++)
    a->epochs[i] = spt_epoch_at(a->st, i);
  spt_watch_label_register(a->st, DEBUG_BLOOM_BIT, DEBUG_GROUP);
  a->last_signal = spt_signal_count(a->st, DEBUG_GROUP);
  a->bus_ok = spt_bus_open(a->st) == 0;
  spt_header_view hv;
  if (spt_header_snapshot(a->st, &hv) == 0)
    a->last_global_epoch = hv.global_epoch;
  return 0;
}

/* Pull changed debug-labeled keys into the chatter panel. */
static void drain_debug(attach_t *a) {
  if (!a->st) return;
  uint64_t sig = spt_signal_count(a->st, DEBUG_GROUP);
  if (sig == a->last_signal) return;
  a->last_signal = sig;

  uint32_t *idx = a->idx_buf;
  int n = spt_enumerate(a->st, 1ull << DEBUG_BLOOM_BIT, idx, a->nslots);
  for (int i = 0; i < n; i++) {
    uint64_t e = spt_epoch_at(a->st, idx[i]);
    if (e == a->epochs[idx[i]]) continue;
    a->epochs[idx[i]] = e;
    char key[SPT_KEY_MAX] = "", val[CHATTER_WIDTH] = "";
    uint32_t len = 0;
    spt_key_at(a->st, idx[i], key);
    int rc = spt_get_at(a->st, idx[i], val, sizeof val - 1, &len);
    if (rc == -EMSGSIZE) {      /* value longer than the panel: truncate */
      len = sizeof val - 1;
      rc = 0;
    }
    if (rc == 0) val[len < sizeof val - 1 ? len : sizeof val - 1] = 0;
    char line[CHATTER_WIDTH + 160];
    snprintf(line, sizeof line, "(%llu) %s: %s",
             (unsigned long long)e, key, rc == 0 ? val : "(unreadable)");
    chatter_push(line);
  }
}

static void draw_store_panel(attach_t *a, double dt) {
  spt_header_view hv;
  if (!a->st || spt_header_snapshot(a->st, &hv) != 0) return;
  if (dt > 0) {
    double inst = (double)(hv.global_epoch - a->last_global_epoch) / dt;
    /* EWMA keeps the readout steady between refreshes */
    a->ops_rate = a->ops_rate * 0.7 + inst * 0.3;
  }
  a->last_global_epoch = hv.global_epoch;

  int sovereign = spt_shard_election(a->st);
  int live_bids = 0;
  for (int i = 0; i < SPT_MAX_BIDS; i++) {
    spt_bid_view bv;
    if (spt_bid_info(a->st, i, &bv) == 0 && bv.live) live_bids++;
  }
  printf(" \x1b[1mSTORE\x1b[0m slots %u/%u  epoch %llu  %.0f ops/s  "
         "parse-fail %llu  bids %d",
         hv.used_slots, hv.nslots, (unsigned long long)hv.global_epoch,
         a->ops_rate, (unsigned long long)hv.parse_failures, live_bids);
  if (sovereign >= 0) {
    spt_bid_view bv;
    if (spt_bid_info(a->st, sovereign, &bv) == 0)
      printf("  sovereign pid %lld", (long long)bv.pid);
  }
  printf("  bus %s\x1b[K\n", a->bus_ok ? "armed" : "poll");
}

/* ---------------- file tail ---------------- */

static FILE *g_tail_fp = NULL;

static int tail_open(const char *path) {
  g_tail_fp = fopen(path, "r");
  if (!g_tail_fp) return -1;
  setvbuf(g_tail_fp, NULL, _IONBF, 0);
  fseek(g_tail_fp, 0, SEEK_END);
  return 0;
}

static void tail_drain(void) {
  if (!g_tail_fp) return;
  char line[1024];
  while (fgets(line, sizeof line, g_tail_fp)) {
    line[strcspn(line, "\r\n")] = 0;
    chatter_push(line);
  }
  clearerr(g_tail_fp);
}

/* ---------------- job hotkeys ---------------- */

static void spawn_job(int n) {
  char path[64];
  snprintf(path, sizeof path, "./.sidecar.%d", n);
  if (access(path, X_OK) != 0) {
    char msg[96];
    snprintf(msg, sizeof msg, "[job %d] %s not executable", n, path);
    chatter_push(msg);
    return;
  }
  pid_t pid = fork();
  if (pid == 0) {
    int devnull = open("/dev/null", O_RDWR);
    if (devnull >= 0) {
      dup2(devnull, STDIN_FILENO);
      dup2(devnull, STDOUT_FILENO);
      dup2(devnull, STDERR_FILENO);
      if (devnull > 2) close(devnull);
    }
    execl(path, path, (char *)NULL);
    _exit(127);
  }
  char msg[96];
  snprintf(msg, sizeof msg, "[job %d] forked pid %d", n, (int)pid);
  chatter_push(msg);
}

/* ---------------- main ---------------- */

int main(int argc, char **argv) {
  attach_t at = {0};
  const char *title = "system";

  if (argc > 1) {
    if (!strncmp(argv[1], "spt:", 4)) {
      if (attach_store(&at, argv[1] + 4, SPT_BACKEND_SHM) < 0) {
        fprintf(stderr, "spt_sidecar: cannot open store %s: %s\n",
                argv[1] + 4, strerror(spt_last_error()));
        return 1;
      }
      title = argv[1];
    } else if (!strncmp(argv[1], "sptf:", 5)) {
      if (attach_store(&at, argv[1] + 5, SPT_BACKEND_FILE) < 0) {
        fprintf(stderr, "spt_sidecar: cannot open store file %s: %s\n",
                argv[1] + 5, strerror(spt_last_error()));
        return 1;
      }
      title = argv[1];
    } else {
      if (tail_open(argv[1]) < 0) {
        fprintf(stderr, "spt_sidecar: cannot tail %s\n", argv[1]);
        return 1;
      }
      title = argv[1];
    }
  }

  signal(SIGWINCH, on_winch);
  signal(SIGINT, on_int);
  signal(SIGTERM, on_int);
  signal(SIGCHLD, SIG_IGN);  /* auto-reap forked jobs */
  measure_term();
  raw_term();

  cpu_sample prev_cpu;
  read_cpu_sample(&prev_cpu);
  struct timespec prev_ts;
  clock_gettime(CLOCK_MONOTONIC, &prev_ts);

  printf("\x1b[2J");
  while (!g_quit) {
    if (g_resized) { measure_term(); g_resized = 0; printf("\x1b[2J"); }

    sys_sample s = {0};
    sample_cpu(&prev_cpu, &s);
    sample_mem(&s);
    sample_load(&s);
    sample_power(&s);
    push_hist(g_hist_cpu, s.cpu_pct);
    push_hist(g_hist_mem, s.mem_pct);
    if (g_hist_len < HIST_MAX) g_hist_len++;

    struct timespec now_ts;
    clock_gettime(CLOCK_MONOTONIC, &now_ts);
    double dt = (double)(now_ts.tv_sec - prev_ts.tv_sec) +
                (double)(now_ts.tv_nsec - prev_ts.tv_nsec) / 1e9;
    prev_ts = now_ts;

    drain_debug(&at);
    tail_drain();

    printf("\x1b[H");
    printf(" \x1b[1mspt_sidecar\x1b[0m — %s   load %.2f %.2f %.2f  "
           "procs %d/%d", title, s.load1, s.load5, s.load15,
           s.procs_running, s.procs_total);
    if (s.battery_pct >= 0)
      printf("  batt %d%%%s", s.battery_pct, s.on_ac ? "+" : "");
    printf("\x1b[K\n");

    draw_bar("cpu", s.cpu_pct, "\x1b[32m");
    draw_bar("mem", s.mem_pct, "\x1b[36m");
    draw_bar("swap", s.swap_pct, "\x1b[35m");
    draw_bar("io", s.io_pct, "\x1b[33m");
    draw_spark("cpu~", g_hist_cpu, "\x1b[32m");
    draw_spark("mem~", g_hist_mem, "\x1b[36m");
    draw_store_panel(&at, dt);

    printf(" \x1b[1mchatter\x1b[0m (q quit, c clear, 1-9 jobs)\x1b[K\n");
    int room = g_rows - 10 - (at.st ? 1 : 0);
    if (room > CHATTER_MAX) room = CHATTER_MAX;
    int first = g_chatter_n > room ? g_chatter_n - room : 0;
    for (int i = first; i < g_chatter_n; i++) {
      int w = g_cols - 3;
      printf("  %.*s\x1b[K\n", w > 0 ? w : 0, g_chatter[i]);
    }
    printf("\x1b[J");
    fflush(stdout);

    char ch;
    while (read(STDIN_FILENO, &ch, 1) == 1) {
      if (ch == 'q') g_quit = 1;
      else if (ch == 'c') chatter_clear();
      else if (ch >= '1' && ch <= '9') spawn_job(ch - '0');
    }
    usleep(REFRESH_US);
  }

  chatter_clear();
  if (g_tail_fp) fclose(g_tail_fp);
  if (at.st) {
    spt_watch_label_unregister(at.st, DEBUG_BLOOM_BIT, DEBUG_GROUP);
    spt_bus_close(at.st);
    spt_close(at.st);
  }
  free(at.epochs);
  free(at.idx_buf);
  return 0;
}
