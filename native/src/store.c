/* store.c — lifecycle, seqlock KV ops, typed slots, labels, tandem keys,
 * mop/purge, snapshots, recovery, and the embedding vector lane.
 *
 * Capability parity with the reference core (splinterhq/libsplinter
 * splinter.c:103-887, see SURVEY.md §2.1); fresh TPU-first design — see
 * sptpu.h header comment for the deliberate deviations.
 */
#include "internal.h"

#include <fcntl.h>
#include <stdio.h>
#include <stdlib.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <time.h>
#include <unistd.h>

static _Thread_local int spt_errno_tl;

static void set_err(int e) { spt_errno_tl = e; }
int spt_last_error(void) { return spt_errno_tl; }

/* ---------------------------------------------------------------- layout */

static uint64_t layout_size(uint32_t nslots, uint32_t max_val,
                            uint32_t vec_dim, uint64_t off[3]) {
  uint64_t o = SPT_HDR_BYTES;
  off[0] = o;                              /* slots */
  o += (uint64_t)nslots * SPT_SLOT_BYTES;
  o = (o + 63) & ~63ull;
  off[1] = o;                              /* values */
  o += (uint64_t)nslots * max_val;
  o = (o + 255) & ~255ull;
  off[2] = o;                              /* vectors */
  o += (uint64_t)nslots * vec_dim * sizeof(float);
  return (o + 4095) & ~4095ull;
}

static void wire(spt_store *st) {
  st->h = (spt_hdr *)st->base;
  st->slots = (spt_slot *)(st->base + st->h->slots_off);
  st->values = st->base + st->h->values_off;
  st->vectors = st->h->vec_dim
                    ? (float *)(st->base + st->h->vectors_off)
                    : NULL;
}

/* SPTPU_DEFAULT_UMASK: octal override applied around backing-object create
 * (parity with the reference's SPLINTER_DEFAULT_UMASK, splinter.c:113-146). */
static mode_t env_umask(int *active) {
  const char *s = getenv("SPTPU_DEFAULT_UMASK");
  *active = 0;
  if (!s || !*s) return 0;
  char *end = NULL;
  long v = strtol(s, &end, 8);
  if (end && *end == '\0' && v >= 0 && v <= 0777) {
    *active = 1;
    return (mode_t)v;
  }
  return 0;
}

static int open_backing(const char *name, uint32_t flags, int creating,
                        int *fd_out) {
  /* create is ALWAYS exclusive: truncating a live store out from under
   * its peers would SIGBUS them.  Callers that want replace semantics
   * unlink first. */
  int oflags = creating ? (O_RDWR | O_CREAT | O_EXCL) : O_RDWR;
  int um_active = 0;
  mode_t um = env_umask(&um_active);
  mode_t saved = 0;
  if (creating && um_active) saved = umask(um);
  /* 0666 so the process umask (or SPTPU_DEFAULT_UMASK) decides how widely
   * the store is shared */
  int fd;
  if (flags & SPT_BACKEND_FILE)
    fd = open(name, oflags | O_NOFOLLOW, 0666);
  else
    fd = shm_open(name, oflags, 0666);
  if (creating && um_active) umask(saved);
  if (fd < 0) return -errno;
  *fd_out = fd;
  return 0;
}

spt_store *spt_create(const char *name, uint32_t nslots, uint32_t max_val,
                      uint32_t vec_dim, uint32_t flags) {
  if (!name || !nslots || !max_val) { set_err(EINVAL); return NULL; }
  max_val = (max_val + 63) & ~63u;   /* mop slop granularity */
  uint64_t off[3];
  uint64_t sz = layout_size(nslots, max_val, vec_dim, off);

  int fd = -1, rc = open_backing(name, flags, 1, &fd);
  if (rc < 0) { set_err(-rc); return NULL; }
  if (ftruncate(fd, (off_t)sz) < 0) { set_err(errno); close(fd); return NULL; }

  uint8_t *base = mmap(NULL, sz, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { set_err(errno); close(fd); return NULL; }

  spt_store *st = calloc(1, sizeof *st);
  if (!st) { set_err(ENOMEM); munmap(base, sz); close(fd); return NULL; }
  st->base = base;
  st->map_size = sz;
  st->fd = fd;
  st->flags = flags;
  st->my_bus_fd = -1;
  snprintf(st->name, sizeof st->name, "%s", name);

  spt_hdr *h = (spt_hdr *)base;
  /* fresh mapping is zero-filled; fill geometry then publish magic last */
  h->version = SPT_FORMAT_VERSION;
  h->map_size = sz;
  h->nslots = nslots;
  h->max_val = max_val;
  h->vec_dim = vec_dim;
  h->slots_off = off[0];
  h->values_off = off[1];
  h->vectors_off = off[2];
  atomic_store(&h->mop_mode, SPT_MOP_HYBRID);
  atomic_store(&h->bus_fd, -1);
  atomic_thread_fence(memory_order_release);
  h->magic = SPT_MAGIC;
  wire(st);
  return st;
}

spt_store *spt_open(const char *name, uint32_t flags) {
  if (!name) { set_err(EINVAL); return NULL; }
  int fd = -1, rc = open_backing(name, flags, 0, &fd);
  if (rc < 0) { set_err(-rc); return NULL; }

  struct stat sb;
  if (fstat(fd, &sb) < 0 || (uint64_t)sb.st_size < SPT_HDR_BYTES) {
    set_err(EBADF); close(fd); return NULL;
  }
  uint8_t *base = mmap(NULL, (size_t)sb.st_size, PROT_READ | PROT_WRITE,
                       MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) { set_err(errno); close(fd); return NULL; }

  spt_hdr *h = (spt_hdr *)base;
  if (h->magic != SPT_MAGIC || h->version != SPT_FORMAT_VERSION ||
      h->map_size != (uint64_t)sb.st_size) {
    set_err(EPROTO);
    munmap(base, (size_t)sb.st_size);
    close(fd);
    return NULL;
  }
  spt_store *st = calloc(1, sizeof *st);
  if (!st) { set_err(ENOMEM); munmap(base, (size_t)sb.st_size); close(fd);
             return NULL; }
  st->base = base;
  st->map_size = h->map_size;
  st->fd = fd;
  st->flags = flags;
  st->my_bus_fd = -1;
  snprintf(st->name, sizeof st->name, "%s", name);
  wire(st);
  return st;
}

/* NUMA-bound open (parity with the reference's SPLINTER_NUMA_AFFINITY
 * variant, splinter.c:250-264): open the store, then mbind(MPOL_BIND) the
 * whole mapping to one node so the arena's pages — and the vector lane the
 * TPU runtime DMAs from — are allocated on the memory controller closest to
 * the accelerator's PCIe root.  Raw syscall: no libnuma dependency.  A
 * kernel without NUMA support returns -ENOSYS from the bind; the mapping
 * itself is still valid, so we surface the error and let the caller decide
 * (the Python tier treats it as advisory). */
#include <sys/syscall.h>
#ifndef SYS_mbind
#  if defined(__x86_64__)
#    define SYS_mbind 237
#  elif defined(__aarch64__)
#    define SYS_mbind 235
#  endif
#endif
#define SPT_MPOL_BIND 2
#define SPT_MPOL_MF_MOVE 2 /* migrate this process's existing pages too;
                              pages other processes pinned need
                              MPOL_MF_MOVE_ALL + CAP_SYS_NICE and stay put */

spt_store *spt_open_numa(const char *name, uint32_t flags, int node,
                         int *bind_rc) {
  spt_store *st = spt_open(name, flags);
  if (!st) return NULL;
  int rc = -ENOSYS;
#ifdef SYS_mbind
  if (node >= 0 && node < 1024) {
    unsigned long mask[1024 / (8 * sizeof(unsigned long))] = {0};
    mask[node / (8 * sizeof(unsigned long))] =
        1ul << (node % (8 * sizeof(unsigned long)));
    long r = syscall(SYS_mbind, st->base, st->map_size, SPT_MPOL_BIND,
                     mask, (unsigned long)(sizeof(mask) * 8 + 1),
                     (unsigned long)SPT_MPOL_MF_MOVE);
    rc = r < 0 ? -errno : 0;
  } else {
    rc = -EINVAL;
  }
#endif
  if (bind_rc) *bind_rc = rc;
  return st;
}

int spt_close(spt_store *st) {
  if (!st) return -EINVAL;
  spt_bus_close(st);
  munmap(st->base, st->map_size);
  close(st->fd);
  free(st);
  return 0;
}

int spt_unlink(const char *name, uint32_t flags) {
  if (!name) return -EINVAL;
  int rc = (flags & SPT_BACKEND_FILE) ? unlink(name) : shm_unlink(name);
  return rc < 0 ? -errno : 0;
}

uint32_t spt_nslots(const spt_store *st) { return st->h->nslots; }
uint32_t spt_max_val(const spt_store *st) { return st->h->max_val; }
uint32_t spt_vec_dim(const spt_store *st) { return st->h->vec_dim; }
void *spt_vec_lane(spt_store *st) { return st->vectors; }
void *spt_values_base(spt_store *st) { return st->values; }

/* ---------------------------------------------------------------- probing */

int spt__probe_find(spt_store *st, const char *key, uint64_t h) {
  uint32_t n = st->h->nslots;
  uint32_t start = (uint32_t)(h % n);
  for (uint32_t d = 0; d < n; d++) {
    uint32_t i = (start + d) % n;
    uint64_t sh = atomic_load_explicit(&st->slots[i].hash,
                                       memory_order_acquire);
    if (sh == 0) return -ENOENT;              /* never-used: end of chain */
    if (sh == h && strncmp(st->slots[i].key, key, SPT_KEY_MAX) == 0)
      return (int)i;
  }
  return -ENOENT;
}

int spt__probe_claim(spt_store *st, const char *key, uint64_t h,
                     int *existed) {
  uint32_t n = st->h->nslots;
  uint32_t start = (uint32_t)(h % n);
  int first_free = -1;
  for (uint32_t d = 0; d < n; d++) {
    uint32_t i = (start + d) % n;
    uint64_t sh = atomic_load_explicit(&st->slots[i].hash,
                                       memory_order_acquire);
    if (sh == 0) {
      *existed = 0;
      return first_free >= 0 ? first_free : (int)i;
    }
    if (sh == SPT_TOMBSTONE) {
      if (first_free < 0) first_free = (int)i;
      continue;
    }
    if (sh == h && strncmp(st->slots[i].key, key, SPT_KEY_MAX) == 0) {
      *existed = 1;
      return (int)i;
    }
  }
  *existed = 0;
  if (first_free >= 0) return first_free;
  return -ENOSPC;
}

/* ---------------------------------------------------------------- seqlock */

int spt__lock(spt_slot *s, uint64_t *e_out) {
  uint64_t e = atomic_load_explicit(&s->epoch, memory_order_acquire);
  if (e & 1) return -EAGAIN;                 /* writer active */
  if (!atomic_compare_exchange_strong_explicit(&s->epoch, &e, e + 1,
                                               memory_order_acq_rel,
                                               memory_order_acquire))
    return -EAGAIN;                          /* lost the race */
  *e_out = e;
  return 0;
}

void spt__unlock(spt_slot *s, uint64_t e_acquired) {
  atomic_store_explicit(&s->epoch, e_acquired + 2, memory_order_release);
}

/* Probe for an existing key, acquire its seqlock, and revalidate the
 * key->slot binding under the lock (the slot may have been unset or
 * reclaimed for a different key between probe and lock).  On success the
 * slot is locked and idx_out/e_out are set. */
static int lock_key(spt_store *st, const char *key, uint32_t *idx_out,
                    uint64_t *e_out) {
  uint64_t h = spt_hash_key(key);
  int idx = spt__probe_find(st, key, h);
  if (idx < 0) return idx;
  spt_slot *s = &st->slots[idx];
  uint64_t e;
  int rc = spt__lock(s, &e);
  if (rc < 0) return rc;
  uint64_t cur = atomic_load_explicit(&s->hash, memory_order_relaxed);
  if (cur <= SPT_TOMBSTONE) {
    spt__unlock(s, e);
    return -ENOENT;
  }
  if (!(cur == h && strncmp(s->key, key, SPT_KEY_MAX) == 0)) {
    spt__unlock(s, e);
    return -EAGAIN;           /* slot rebound to another key; retry */
  }
  *idx_out = (uint32_t)idx;
  *e_out = e;
  return 0;
}

/* mop scrub: zero the stale tail of the old value beyond the new length.
 * HYBRID rounds the zeroed span up to the 64B slop boundary; FULL always
 * zeroes the entire region. */
static void mop_scrub(spt_store *st, uint32_t idx, uint32_t old_len,
                      uint32_t new_len) {
  uint32_t mode = atomic_load_explicit(&st->h->mop_mode,
                                       memory_order_relaxed);
  uint8_t *v = slot_val(st, idx);
  if (mode == SPT_MOP_FULL) {
    memset(v, 0, st->h->max_val);
  } else if (mode == SPT_MOP_HYBRID && old_len > new_len) {
    uint32_t end = (old_len + 63u) & ~63u;
    if (end > st->h->max_val) end = st->h->max_val;
    memset(v + new_len, 0, end - new_len);
  }
}

/* ------------------------------------------------------------------- set */

int spt_set(spt_store *st, const char *key, const void *val, uint32_t len) {
  if (!st || !key || (!val && len)) return -EINVAL;
  if (strlen(key) >= SPT_KEY_MAX) return -ENAMETOOLONG;
  if (len > st->h->max_val) return -EMSGSIZE;

  uint64_t h = spt_hash_key(key);
  int existed = 0;
  int idx = spt__probe_claim(st, key, h, &existed);
  if (idx < 0) return idx;
  spt_slot *s = &st->slots[idx];

  uint64_t e;
  int rc = spt__lock(s, &e);
  if (rc < 0) return rc;

  /* the slot may have been claimed for a different key — or our key may
   * have been unset — between probe and lock; re-derive state under the
   * lock (a stale `existed` would publish a ghost slot with no key) */
  uint64_t cur = atomic_load_explicit(&s->hash, memory_order_relaxed);
  if (cur > SPT_TOMBSTONE &&
      !(cur == h && strncmp(s->key, key, SPT_KEY_MAX) == 0)) {
    spt__unlock(s, e);
    return -EAGAIN;
  }
  existed = cur > SPT_TOMBSTONE;

  uint32_t old_len = existed ? s->val_len : 0;
  if (!existed && st->vectors)
    memset(slot_vec(st, (uint32_t)idx), 0,
           (size_t)st->h->vec_dim * sizeof(float));
  mop_scrub(st, (uint32_t)idx, old_len, len);
  if (len) memcpy(slot_val(st, (uint32_t)idx), val, len);
  s->val_len = len;
  if (!existed) {
    atomic_store_explicit(&s->flags, SPT_T_VOID, memory_order_relaxed);
    atomic_store_explicit(&s->labels, 0, memory_order_relaxed);
    atomic_store_explicit(&s->watcher_mask, 0, memory_order_relaxed);
    s->ctime = (int64_t)spt_now();
    memset(s->key, 0, SPT_KEY_MAX);
    memcpy(s->key, key, strlen(key));
  }
  s->atime = (int64_t)spt_now();
  atomic_thread_fence(memory_order_release);
  atomic_store_explicit(&s->hash, h, memory_order_release); /* publication */
  spt__unlock(s, e);
  spt__fanout(st, (uint32_t)idx, s);
  return 0;
}

/* ------------------------------------------------------------------- get */

static int read_slot_val(spt_store *st, uint32_t idx, void *buf,
                         uint32_t cap, uint32_t *len_out) {
  spt_slot *s = &st->slots[idx];
  uint64_t e1 = atomic_load_explicit(&s->epoch, memory_order_acquire);
  if (e1 & 1) return -EAGAIN;
  uint64_t sh = atomic_load_explicit(&s->hash, memory_order_acquire);
  if (sh <= SPT_TOMBSTONE) return -ENOENT;
  uint32_t len = s->val_len;
  if (len > st->h->max_val) return -EAGAIN;  /* torn geometry read */
  if (buf) {
    uint32_t n = len < cap ? len : cap;
    memcpy(buf, slot_val(st, idx), n);
  }
  atomic_thread_fence(memory_order_acquire);
  uint64_t e2 = atomic_load_explicit(&s->epoch, memory_order_acquire);
  if (e1 != e2) return -EAGAIN;
  if (len_out) *len_out = len;
  if (buf && cap < len) return -EMSGSIZE;
  return 0;
}

int spt_get(spt_store *st, const char *key, void *buf, uint32_t cap,
            uint32_t *len_out) {
  if (!st || !key) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  return read_slot_val(st, (uint32_t)idx, buf, cap, len_out);
}

int spt_get_at(spt_store *st, uint32_t idx, void *buf, uint32_t cap,
               uint32_t *len_out) {
  if (!st || idx >= st->h->nslots) return -EINVAL;
  return read_slot_val(st, idx, buf, cap, len_out);
}

int spt_get_raw(spt_store *st, const char *key, const void **ptr,
                uint32_t *len_out, uint64_t *epoch_out) {
  if (!st || !key || !ptr) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  spt_slot *s = &st->slots[idx];
  uint64_t e = atomic_load_explicit(&s->epoch, memory_order_acquire);
  if (e & 1) return -EAGAIN;
  *ptr = slot_val(st, (uint32_t)idx);
  if (len_out) *len_out = s->val_len;
  if (epoch_out) *epoch_out = e;
  return idx;
}

/* ----------------------------------------------------------------- unset */

int spt_unset(spt_store *st, const char *key) {
  if (!st || !key) return -EINVAL;
  uint32_t idx;
  uint64_t e;
  int rc = lock_key(st, key, &idx, &e);
  if (rc < 0) return rc;
  spt_slot *s = &st->slots[idx];
  memset(slot_val(st, (uint32_t)idx), 0, st->h->max_val);
  if (st->vectors)
    memset(slot_vec(st, (uint32_t)idx), 0,
           (size_t)st->h->vec_dim * sizeof(float));
  memset(s->key, 0, SPT_KEY_MAX);
  s->val_len = 0;
  atomic_store_explicit(&s->flags, SPT_T_VOID, memory_order_relaxed);
  atomic_store_explicit(&s->labels, 0, memory_order_relaxed);
  atomic_store_explicit(&s->watcher_mask, 0, memory_order_relaxed);
  atomic_store_explicit(&s->hash, SPT_TOMBSTONE, memory_order_release);
  spt__unlock(s, e);
  atomic_fetch_add_explicit(&st->h->global_epoch, 1, memory_order_relaxed);
  return 0;
}

/* ---------------------------------------------------------------- append */

int spt_append(spt_store *st, const char *key, const void *val,
               uint32_t len) {
  if (!st || !key || (!val && len)) return -EINVAL;
  uint32_t idx;
  uint64_t e;
  int rc = lock_key(st, key, &idx, &e);
  if (rc == -ENOENT) return spt_set(st, key, val, len); /* append-new = set */
  if (rc < 0) return rc;
  spt_slot *s = &st->slots[idx];
  if ((uint64_t)s->val_len + len > st->h->max_val) {
    spt__unlock(s, e);
    return -EMSGSIZE;
  }
  memcpy(slot_val(st, (uint32_t)idx) + s->val_len, val, len);
  s->val_len += len;
  s->atime = (int64_t)spt_now();
  spt__unlock(s, e);
  spt__fanout(st, (uint32_t)idx, s);
  return 0;
}

/* ------------------------------------------------------------------ list */

int spt_list(spt_store *st, char *keys, uint32_t max_keys) {
  if (!st) return -EINVAL;
  uint32_t n = st->h->nslots, out = 0;
  for (uint32_t i = 0; i < n && (!keys || out < max_keys); i++) {
    uint64_t sh = atomic_load_explicit(&st->slots[i].hash,
                                       memory_order_acquire);
    if (sh <= SPT_TOMBSTONE) continue;
    if (keys) {
      memcpy(keys + (size_t)out * SPT_KEY_MAX, st->slots[i].key,
             SPT_KEY_MAX);
      keys[(size_t)out * SPT_KEY_MAX + SPT_KEY_MAX - 1] = '\0';
    }
    out++;
  }
  return (int)out;
}

/* ------------------------------------------------------------------ poll */

int spt_poll(spt_store *st, const char *key, int timeout_ms) {
  if (!st || !key) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  uint64_t e0 = atomic_load_explicit(&st->slots[idx].epoch,
                                     memory_order_acquire);
  uint64_t t_per_us = spt_ticks_per_us();
  uint64_t deadline = timeout_ms < 0
                          ? 0
                          : spt_now() + (uint64_t)timeout_ms * 1000 * t_per_us;
  struct timespec ts = {0, 1000000};  /* 1 ms */
  for (;;) {
    uint64_t e = atomic_load_explicit(&st->slots[idx].epoch,
                                      memory_order_acquire);
    if (e != e0) return 0;
    if (timeout_ms >= 0 && spt_now() >= deadline) return -ETIMEDOUT;
    if (st->my_bus_fd >= 0)
      spt_bus_wait(st, 1);
    else
      nanosleep(&ts, NULL);
  }
}

/* -------------------------------------------------------- index accessors */

int spt_find_index(spt_store *st, const char *key) {
  if (!st || !key) return -EINVAL;
  return spt__probe_find(st, key, spt_hash_key(key));
}

int spt_key_at(spt_store *st, uint32_t idx, char *key_out) {
  if (!st || idx >= st->h->nslots || !key_out) return -EINVAL;
  spt_slot *s = &st->slots[idx];
  for (int tries = 0; tries < 64; tries++) {
    uint64_t e1 = atomic_load_explicit(&s->epoch, memory_order_acquire);
    if (e1 & 1) continue;
    uint64_t sh = atomic_load_explicit(&s->hash, memory_order_acquire);
    if (sh <= SPT_TOMBSTONE) return -ENOENT;
    memcpy(key_out, s->key, SPT_KEY_MAX);
    atomic_thread_fence(memory_order_acquire);
    if (atomic_load_explicit(&s->epoch, memory_order_acquire) == e1) {
      key_out[SPT_KEY_MAX - 1] = '\0';
      return 0;
    }
  }
  return -EAGAIN;
}

uint64_t spt_epoch_at(spt_store *st, uint32_t idx) {
  if (!st || idx >= st->h->nslots) return 0;
  return atomic_load_explicit(&st->slots[idx].epoch, memory_order_acquire);
}

uint64_t spt_labels_at(spt_store *st, uint32_t idx) {
  if (!st || idx >= st->h->nslots) return 0;
  return atomic_load_explicit(&st->slots[idx].labels, memory_order_acquire);
}

uint32_t spt_flags_at(spt_store *st, uint32_t idx) {
  if (!st || idx >= st->h->nslots) return 0;
  return atomic_load_explicit(&st->slots[idx].flags, memory_order_acquire);
}

/* ------------------------------------------------------------- snapshots */

int spt_header_snapshot(spt_store *st, spt_header_view *out) {
  if (!st || !out) return -EINVAL;
  memset(out, 0, sizeof *out);
  out->magic = st->h->magic;
  out->version = st->h->version;
  out->nslots = st->h->nslots;
  out->max_val = st->h->max_val;
  out->vec_dim = st->h->vec_dim;
  out->mop_mode = atomic_load(&st->h->mop_mode);
  out->map_size = st->h->map_size;
  out->global_epoch = atomic_load(&st->h->global_epoch);
  out->core_flags = atomic_load(&st->h->core_flags);
  out->user_flags = atomic_load(&st->h->user_flags);
  out->parse_failures = atomic_load(&st->h->parse_failures);
  out->last_failure_epoch = atomic_load(&st->h->last_failure_epoch);
  out->bus_pid = atomic_load(&st->h->bus_pid);
  uint32_t used = 0;
  for (uint32_t i = 0; i < st->h->nslots; i++)
    if (atomic_load_explicit(&st->slots[i].hash, memory_order_relaxed) >
        SPT_TOMBSTONE)
      used++;
  out->used_slots = used;
  return 0;
}

static int slot_snapshot_idx(spt_store *st, uint32_t idx,
                             spt_slot_view *out) {
  spt_slot *s = &st->slots[idx];
  for (int tries = 0; tries < 1024; tries++) {
    uint64_t e1 = atomic_load_explicit(&s->epoch, memory_order_acquire);
    if (e1 & 1) continue;
    out->hash = atomic_load_explicit(&s->hash, memory_order_acquire);
    out->labels = atomic_load_explicit(&s->labels, memory_order_relaxed);
    out->watcher_mask =
        atomic_load_explicit(&s->watcher_mask, memory_order_relaxed);
    out->val_len = s->val_len;
    out->flags = atomic_load_explicit(&s->flags, memory_order_relaxed);
    out->ctime = s->ctime;
    out->atime = s->atime;
    memcpy(out->key, s->key, SPT_KEY_MAX);
    atomic_thread_fence(memory_order_acquire);
    uint64_t e2 = atomic_load_explicit(&s->epoch, memory_order_acquire);
    if (e1 == e2) {
      out->epoch = e1;
      out->index = (int32_t)idx;
      return 0;
    }
  }
  return -EAGAIN;
}

int spt_slot_snapshot(spt_store *st, const char *key, spt_slot_view *out) {
  if (!st || !key || !out) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  return slot_snapshot_idx(st, (uint32_t)idx, out);
}

int spt_slot_snapshot_at(spt_store *st, uint32_t idx, spt_slot_view *out) {
  if (!st || !out || idx >= st->h->nslots) return -EINVAL;
  return slot_snapshot_idx(st, idx, out);
}

/* ----------------------------------------------------------- typed slots */

int spt_set_type(spt_store *st, const char *key, uint32_t type_flag) {
  if (!st || !key || (type_flag & ~SPT_T_MASK)) return -EINVAL;
  uint32_t idx;
  uint64_t e;
  int rc = lock_key(st, key, &idx, &e);
  if (rc < 0) return rc;
  spt_slot *s = &st->slots[idx];
  if (type_flag == SPT_T_BIGUINT) {
    /* BIGUINT promotion: ASCII digits -> host-endian u64 in place */
    uint8_t *v = slot_val(st, (uint32_t)idx);
    uint64_t acc = 0;
    int ok = s->val_len > 0 && s->val_len < 21;
    for (uint32_t i = 0; ok && i < s->val_len; i++) {
      char c = (char)v[i];
      if (c == '\0') break;
      if (c < '0' || c > '9') { ok = 0; break; }
      acc = acc * 10 + (uint64_t)(c - '0');
    }
    if (!ok && s->val_len != 8) { spt__unlock(s, e); return -EPROTOTYPE; }
    if (ok) {
      memset(v, 0, s->val_len);
      memcpy(v, &acc, 8);
      s->val_len = 8;
    }
  }
  uint32_t f = atomic_load_explicit(&s->flags, memory_order_relaxed);
  atomic_store_explicit(&s->flags, (f & ~SPT_T_MASK) | type_flag,
                        memory_order_relaxed);
  spt__unlock(s, e);
  spt__fanout(st, (uint32_t)idx, s);
  return 0;
}

int spt_get_type(spt_store *st, const char *key, uint32_t *type_out) {
  if (!st || !key || !type_out) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  *type_out =
      atomic_load_explicit(&st->slots[idx].flags, memory_order_acquire) &
      SPT_T_MASK;
  return 0;
}

int spt_integer_op(spt_store *st, const char *key, spt_iop_t op,
                   uint64_t operand, uint64_t *result_out) {
  if (!st || !key) return -EINVAL;
  uint32_t idx;
  uint64_t e;
  int rc = lock_key(st, key, &idx, &e);
  if (rc < 0) return rc;
  spt_slot *s = &st->slots[idx];
  if ((atomic_load_explicit(&s->flags, memory_order_relaxed) & SPT_T_MASK) !=
          SPT_T_BIGUINT ||
      s->val_len != 8) {
    spt__unlock(s, e);
    return -EPROTOTYPE;
  }
  uint64_t v;
  memcpy(&v, slot_val(st, (uint32_t)idx), 8);
  switch (op) {
    case SPT_IOP_AND: v &= operand; break;
    case SPT_IOP_OR:  v |= operand; break;
    case SPT_IOP_XOR: v ^= operand; break;
    case SPT_IOP_NOT: v = ~v; break;
    case SPT_IOP_INC: v += 1; break;
    case SPT_IOP_DEC: v -= 1; break;
    case SPT_IOP_ADD: v += operand; break;
    case SPT_IOP_SUB: v -= operand; break;
    default: spt__unlock(s, e); return -EINVAL;
  }
  memcpy(slot_val(st, (uint32_t)idx), &v, 8);
  s->atime = (int64_t)spt_now();
  spt__unlock(s, e);
  spt__fanout(st, (uint32_t)idx, s);
  if (result_out) *result_out = v;
  return 0;
}

/* ------------------------------------------------------------ tandem keys */

static int tandem_name(char *buf, const char *base, uint32_t order) {
  int n = order == 0
              ? snprintf(buf, SPT_KEY_MAX, "%s", base)
              : snprintf(buf, SPT_KEY_MAX, "%s" SPT_ORDER_SEP "%u", base,
                         order);
  return (n < 0 || n >= SPT_KEY_MAX) ? -ENAMETOOLONG : 0;
}

int spt_tandem_set(spt_store *st, const char *base, uint32_t order,
                   const void *val, uint32_t len) {
  char k[SPT_KEY_MAX];
  int rc = tandem_name(k, base, order);
  if (rc < 0) return rc;
  rc = spt_set(st, k, val, len);
  if (rc == 0) spt_set_type(st, k, SPT_T_VARTEXT);
  return rc;
}

int spt_tandem_get(spt_store *st, const char *base, uint32_t order,
                   void *buf, uint32_t cap, uint32_t *len_out) {
  char k[SPT_KEY_MAX];
  int rc = tandem_name(k, base, order);
  if (rc < 0) return rc;
  return spt_get(st, k, buf, cap, len_out);
}

int spt_tandem_unset(spt_store *st, const char *base, uint32_t max_order) {
  char k[SPT_KEY_MAX];
  int removed = 0;
  for (uint32_t o = 0; o <= max_order; o++) {
    if (tandem_name(k, base, o) < 0) break;
    if (spt_unset(st, k) == 0) removed++;
  }
  return removed;
}

int spt_tandem_count(spt_store *st, const char *base) {
  char k[SPT_KEY_MAX];
  int n = 0;
  if (spt_find_index(st, base) >= 0) n = 1; else return 0;
  for (uint32_t o = 1;; o++) {
    if (tandem_name(k, base, o) < 0) break;
    if (spt_find_index(st, k) < 0) break;
    n++;
  }
  return n;
}

/* ---------------------------------------------------------- bloom labels */

int spt_label_or(spt_store *st, const char *key, uint64_t mask) {
  if (!st || !key) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  atomic_fetch_or_explicit(&st->slots[idx].labels, mask,
                           memory_order_acq_rel);
  return 0;
}

int spt_label_andnot(spt_store *st, const char *key, uint64_t mask) {
  if (!st || !key) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  atomic_fetch_and_explicit(&st->slots[idx].labels, ~mask,
                            memory_order_acq_rel);
  return 0;
}

int spt_get_labels(spt_store *st, const char *key, uint64_t *out) {
  if (!st || !key || !out) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  *out = atomic_load_explicit(&st->slots[idx].labels, memory_order_acquire);
  return 0;
}

int spt_enumerate(spt_store *st, uint64_t mask, uint32_t *idx_out,
                  uint32_t max_out) {
  if (!st) return -EINVAL;
  uint32_t n = st->h->nslots, out = 0;
  for (uint32_t i = 0; i < n; i++) {
    uint64_t sh = atomic_load_explicit(&st->slots[i].hash,
                                       memory_order_acquire);
    if (sh <= SPT_TOMBSTONE) continue;
    uint64_t l = atomic_load_explicit(&st->slots[i].labels,
                                      memory_order_acquire);
    if ((l & mask) == mask) {
      if (idx_out) {
        if (out >= max_out) break;
        idx_out[out] = i;
      }
      out++;
    }
  }
  return (int)out;
}

/* ------------------------------------------------------------ mop / purge */

int spt_set_mop(spt_store *st, uint32_t mode) {
  if (!st || mode > SPT_MOP_FULL) return -EINVAL;
  atomic_store(&st->h->mop_mode, mode);
  return 0;
}

uint32_t spt_get_mop(spt_store *st) { return atomic_load(&st->h->mop_mode); }

int spt_purge(spt_store *st) {
  if (!st) return -EINVAL;
  uint32_t n = st->h->nslots;
  int swept = 0;
  for (uint32_t i = 0; i < n; i++) {
    spt_slot *s = &st->slots[i];
    uint64_t sh = atomic_load_explicit(&s->hash, memory_order_acquire);
    uint64_t e;
    if (sh == SPT_TOMBSTONE) {
      /* compact: a tombstone whose chain-successor region is empty can
       * revert to truly-empty; conservatively just scrub its value */
      if (spt__lock(s, &e) == 0) {
        memset(slot_val(st, i), 0, st->h->max_val);
        spt__unlock(s, e);
        swept++;
      }
      continue;
    }
    if (sh == 0) continue;
    if (spt__lock(s, &e) == 0) {
      uint32_t len = s->val_len;
      if (len < st->h->max_val)
        memset(slot_val(st, i) + len, 0, st->h->max_val - len);
      spt__unlock(s, e);
      swept++;
    }
  }
  return swept;
}

/* -------------------------------------------------------------- recovery */

int spt_retrain(spt_store *st, const char *key) {
  if (!st || !key) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  spt_slot *s = &st->slots[idx];
  /* deliberately NOT CAS-guarded: this works on a slot stuck odd */
  atomic_store_explicit(&s->epoch, 3, memory_order_release);
  if (st->vectors)
    memset(slot_vec(st, (uint32_t)idx), 0,
           (size_t)st->h->vec_dim * sizeof(float));
  atomic_thread_fence(memory_order_release);
  atomic_store_explicit(&s->epoch, 4, memory_order_release);
  spt__fanout(st, (uint32_t)idx, s);
  return 0;
}

/* --------------------------------------------------- system keys & flags */

int spt_set_system(spt_store *st, const char *key) {
  if (!st || !key) return -EINVAL;
  if (spt__probe_find(st, key, spt_hash_key(key)) < 0) {
    int rc = spt_set(st, key, NULL, 0);
    if (rc < 0) return rc;
  }
  uint32_t idx;
  uint64_t e;
  int rc = lock_key(st, key, &idx, &e);
  if (rc < 0) return rc;
  spt_slot *s = &st->slots[idx];
  s->val_len = st->h->max_val;     /* scratchpad spans the full region */
  uint32_t f = atomic_load_explicit(&s->flags, memory_order_relaxed);
  atomic_store_explicit(&s->flags,
                        (f & ~SPT_T_MASK) | SPT_T_BINARY | SPT_F_SYSTEM,
                        memory_order_relaxed);
  spt__unlock(s, e);
  return 0;
}

int spt_slot_usr_set(spt_store *st, const char *key, uint8_t bits) {
  if (!st || !key) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  spt_slot *s = &st->slots[idx];
  uint32_t f = atomic_load_explicit(&s->flags, memory_order_acquire);
  for (;;) {
    uint32_t nf = (f & ~SPT_F_USER_MASK) | ((uint32_t)bits << SPT_F_USER_SHIFT);
    if (atomic_compare_exchange_weak_explicit(&s->flags, &f, nf,
                                              memory_order_acq_rel,
                                              memory_order_acquire))
      return 0;
  }
}

int spt_slot_usr_get(spt_store *st, const char *key, uint8_t *out) {
  if (!st || !key || !out) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  *out = (uint8_t)((atomic_load_explicit(&st->slots[idx].flags,
                                         memory_order_acquire) &
                    SPT_F_USER_MASK) >>
                   SPT_F_USER_SHIFT);
  return 0;
}

int spt_config_set_user(spt_store *st, uint32_t bits) {
  if (!st) return -EINVAL;
  atomic_store(&st->h->user_flags, bits & 0xFu);
  return 0;
}

uint32_t spt_config_get_user(spt_store *st) {
  return atomic_load(&st->h->user_flags) & 0xFu;
}

/* ------------------------------------------------------------ timestamps */

int spt_stamp(spt_store *st, const char *key, int which,
              uint64_t ticks_ago) {
  if (!st || !key || which < 0 || which > 2) return -EINVAL;
  int64_t t = (int64_t)(spt_now() - ticks_ago);
  uint32_t lidx;
  uint64_t e;
  int rc = lock_key(st, key, &lidx, &e);
  if (rc < 0) return rc;
  spt_slot *s = &st->slots[lidx];
  if (which == 0 || which == 2) s->ctime = t;
  if (which == 1 || which == 2) s->atime = t;
  spt__unlock(s, e);
  return 0;
}

/* ------------------------------------------------------------ vector lane */

int spt_vec_set_at(spt_store *st, uint32_t idx, const float *vec,
                   uint32_t dim) {
  if (!st || !vec || idx >= st->h->nslots) return -EINVAL;
  if (!st->vectors) return -ENOTSUP;
  if (dim != st->h->vec_dim) return -EMSGSIZE;
  spt_slot *s = &st->slots[idx];
  uint64_t e;
  int rc = spt__lock(s, &e);
  if (rc < 0) return rc;
  memcpy(slot_vec(st, idx), vec, (size_t)dim * sizeof(float));
  spt__unlock(s, e);
  spt__fanout(st, idx, s);
  return 0;
}

int spt_vec_set(spt_store *st, const char *key, const float *vec,
                uint32_t dim) {
  if (!st || !key || !vec) return -EINVAL;
  if (!st->vectors) return -ENOTSUP;
  if (dim != st->h->vec_dim) return -EMSGSIZE;
  uint32_t idx;
  uint64_t e;
  int rc = lock_key(st, key, &idx, &e);
  if (rc < 0) return rc;
  memcpy(slot_vec(st, idx), vec, (size_t)dim * sizeof(float));
  spt__unlock(&st->slots[idx], e);
  spt__fanout(st, idx, &st->slots[idx]);
  return 0;
}

int spt_vec_get_at(spt_store *st, uint32_t idx, float *out, uint32_t dim) {
  if (!st || !out || idx >= st->h->nslots) return -EINVAL;
  if (!st->vectors) return -ENOTSUP;
  if (dim != st->h->vec_dim) return -EMSGSIZE;
  spt_slot *s = &st->slots[idx];
  uint64_t e1 = atomic_load_explicit(&s->epoch, memory_order_acquire);
  if (e1 & 1) return -EAGAIN;
  memcpy(out, slot_vec(st, idx), (size_t)dim * sizeof(float));
  atomic_thread_fence(memory_order_acquire);
  if (atomic_load_explicit(&s->epoch, memory_order_acquire) != e1)
    return -EAGAIN;
  return 0;
}

int spt_vec_get(spt_store *st, const char *key, float *out, uint32_t dim) {
  if (!st || !key) return -EINVAL;
  int idx = spt__probe_find(st, key, spt_hash_key(key));
  if (idx < 0) return idx;
  return spt_vec_get_at(st, (uint32_t)idx, out, dim);
}

static int vec_is_zero(const float *v, uint32_t dim) {
  for (uint32_t i = 0; i < dim; i++)
    if (v[i] != 0.0f) return 0;
  return 1;
}

int spt_vec_commit_batch(spt_store *st, const uint32_t *rows,
                         const uint64_t *epochs, const float *vecs,
                         uint32_t n, uint32_t dim, int write_once,
                         int32_t *results) {
  if (!st || !rows || !epochs || !vecs) return -EINVAL;
  if (!st->vectors) return -ENOTSUP;
  if (dim != st->h->vec_dim) return -EMSGSIZE;
  int committed = 0;
  for (uint32_t i = 0; i < n; i++) {
    uint32_t idx = rows[i];
    int32_t r;
    if (idx >= st->h->nslots) {
      r = -EINVAL;
    } else {
      spt_slot *s = &st->slots[idx];
      uint64_t e;
      int rc = spt__lock(s, &e);
      if (rc < 0) {
        r = -ESTALE;          /* contended now => text may have changed */
      } else if (e != epochs[i]) {
        spt__unlock(s, e);
        r = -ESTALE;          /* the slot moved since the gather */
      } else if (write_once && !vec_is_zero(slot_vec(st, idx), dim)) {
        spt__unlock(s, e);
        r = -EEXIST;
      } else {
        memcpy(slot_vec(st, idx), vecs + (size_t)i * dim,
               (size_t)dim * sizeof(float));
        spt__unlock(s, e);
        spt__fanout(st, idx, s);
        r = 0;
        committed++;
      }
    }
    if (results) results[i] = r;
  }
  return committed;
}

int spt_epochs(spt_store *st, uint64_t *out) {
  if (!st || !out) return -EINVAL;
  uint32_t n = st->h->nslots;
  for (uint32_t i = 0; i < n; i++)
    out[i] = atomic_load_explicit(&st->slots[i].epoch, memory_order_acquire);
  return (int)n;
}

int spt_vec_gather(spt_store *st, const uint32_t *rows, uint32_t n,
                   float *out, uint64_t *epochs_out) {
  if (!st || !rows || !out || !epochs_out) return -EINVAL;
  if (!st->vectors) return -ENOTSUP;
  uint32_t dim = st->h->vec_dim;
  int stable = 0;
  for (uint32_t i = 0; i < n; i++) {
    uint32_t idx = rows[i];
    epochs_out[i] = SPT_GATHER_TORN;
    if (idx >= st->h->nslots) continue;
    spt_slot *s = &st->slots[idx];
    uint64_t e1 = atomic_load_explicit(&s->epoch, memory_order_acquire);
    if (e1 & 1) continue;                      /* writer active: torn */
    memcpy(out + (size_t)i * dim, slot_vec(st, idx),
           (size_t)dim * sizeof(float));
    atomic_thread_fence(memory_order_acquire);
    if (atomic_load_explicit(&s->epoch, memory_order_acquire) != e1)
      continue;                                /* raced: retry next pass */
    epochs_out[i] = e1;                        /* 0 = stable empty slot */
    stable++;
  }
  return stable;
}

/* ------------------------------------------------------------ diagnostics */

int spt_report_parse_failure(spt_store *st) {
  if (!st) return -EINVAL;
  atomic_fetch_add(&st->h->parse_failures, 1);
  atomic_store(&st->h->last_failure_epoch,
               atomic_load(&st->h->global_epoch));
  return 0;
}

/* Build identity: the Makefile passes -DSPT_BUILD_ID="git-describe/date"
 * (native/Makefile); a build outside make still links with a sentinel. */
#ifndef SPT_BUILD_ID
#define SPT_BUILD_ID "unstamped"
#endif
const char *spt_build_id(void) { return SPT_BUILD_ID; }
