/* TAP-style unit suite for the native core store.
 *
 * The behavioral spec tier (reference: splinter_test.c:85-533 — ~130
 * TEST() assertions; SURVEY.md §4).  Covers CRUD, seqlock epoch parity,
 * size queries, list, mop modes, snapshots, named types + BIGUINT
 * promotion, integer ops (incl. -EPROTOTYPE discipline), tandem keys,
 * bloom labels + enumeration, the signal arena, bump, append, purge
 * survival, system keys, user flags, timestamps, the vector lane with
 * epoch-gated batch commit, retrain (backward epoch), the full shard
 * election matrix (priority, expiry, claimed_at/pid tie-breaks, DONTNEED
 * bumper, rebid revival, -ENOSPC on the 33rd bid, sovereign /
 * non-sovereign madvise), and the event bus (init / dirty bits / wait).
 *
 * Like the reference's claim_ex determinism trick (splinter.h:1142-1152),
 * multi-process elections are tested by forging bids — no processes, no
 * sleeps.  The whole suite runs twice: shm backend, then file backend
 * (the reference builds every test binary twice instead,
 * CMakeLists.txt:269-277).
 */
#define _GNU_SOURCE
#include "sptpu.h"

#include <errno.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

static int n_run = 0, n_fail = 0;

#define TEST(cond, name) do {                                            \
    n_run++;                                                             \
    if (cond) printf("ok %d - %s\n", n_run, name);                       \
    else { n_fail++; printf("not ok %d - %s (%s:%d)\n", n_run, name,     \
                            __FILE__, __LINE__); }                       \
  } while (0)

static void suite(const char *name, uint32_t flags) {
  char buf[4096];
  uint32_t len = 0;

  spt_unlink(name, flags);
  spt_store *st = spt_create(name, 64, 256, 8, flags);
  TEST(st != NULL, "create");
  TEST(spt_nslots(st) == 64 && spt_max_val(st) == 256 &&
       spt_vec_dim(st) == 8, "geometry");

  /* exclusive create refuses an existing store */
  TEST(spt_create(name, 64, 256, 8, flags | SPT_CREATE_EXCL) == NULL,
       "create EXCL on existing store fails");

  /* ---- CRUD + seqlock epochs ---- */
  TEST(spt_set(st, "k1", "hello", 5) == 0, "set");
  TEST(spt_get(st, "k1", buf, sizeof buf, &len) == 0 && len == 5 &&
       memcmp(buf, "hello", 5) == 0, "get round trip");
  int idx = spt_find_index(st, "k1");
  TEST(idx >= 0, "find_index");
  uint64_t e = spt_epoch_at(st, (uint32_t)idx);
  TEST(e % 2 == 0 && e >= 2, "epoch even after publish");
  TEST(spt_set(st, "k1", "world", 5) == 0 &&
       spt_epoch_at(st, (uint32_t)idx) == e + 2, "rewrite bumps epoch by 2");
  TEST(spt_get(st, "k1", NULL, 0, &len) == 0 && len == 5, "size query");
  TEST(spt_get(st, "nope", buf, sizeof buf, &len) == -ENOENT,
       "get missing -ENOENT");
  TEST(spt_append(st, "k1", "!", 1) == 0, "append");
  TEST(spt_get(st, "k1", buf, sizeof buf, &len) == 0 && len == 6 &&
       buf[5] == '!', "append grew value");
  char big[512]; memset(big, 'x', sizeof big);
  TEST(spt_set(st, "k1", big, sizeof big) == -EMSGSIZE,
       "oversized set -EMSGSIZE");
  TEST(spt_append(st, "k1", big, 251) == -EMSGSIZE,
       "overflowing append -EMSGSIZE");

  /* zero-copy read protocol */
  const void *p; uint64_t re;
  TEST(spt_get_raw(st, "k1", &p, &len, &re) == idx && len == 6 &&
       re == spt_epoch_at(st, (uint32_t)idx), "get_raw epoch capture");

  /* ---- list ---- */
  spt_set(st, "k2", "v2", 2);
  char keys[64 * SPT_KEY_MAX];
  int n = spt_list(st, keys, 64);
  TEST(n == 2, "list count");

  /* ---- unset + tombstone probing ---- */
  TEST(spt_unset(st, "k2") == 0 && spt_find_index(st, "k2") == -ENOENT,
       "unset removes key");
  TEST(spt_unset(st, "k2") == -ENOENT, "double unset -ENOENT");
  TEST(spt_set(st, "k2", "back", 4) == 0, "slot reusable after unset");
  spt_unset(st, "k2");

  /* ---- types + BIGUINT promotion ---- */
  spt_set(st, "num", "41", 2);
  TEST(spt_set_type(st, "num", SPT_T_BIGUINT) == 0, "BIGUINT promotion");
  uint32_t ty;
  TEST(spt_get_type(st, "num", &ty) == 0 && ty == SPT_T_BIGUINT,
       "type readback");
  uint64_t r;
  TEST(spt_integer_op(st, "num", SPT_IOP_INC, 0, &r) == 0 && r == 42,
       "integer inc after promotion (ASCII 41 -> 42)");
  TEST(spt_integer_op(st, "num", SPT_IOP_ADD, 8, &r) == 0 && r == 50,
       "integer add");
  TEST(spt_integer_op(st, "num", SPT_IOP_SUB, 1, &r) == 0 && r == 49,
       "integer sub (borrow path)");
  TEST(spt_integer_op(st, "num", SPT_IOP_XOR, 0xFF, &r) == 0, "integer xor");
  spt_set(st, "txt", "abc", 3);
  TEST(spt_integer_op(st, "txt", SPT_IOP_INC, 0, &r) == -EPROTOTYPE,
       "integer op on non-BIGUINT -EPROTOTYPE");

  /* ---- tandem keys ---- */
  TEST(spt_tandem_set(st, "doc", 0, "p0", 2) == 0 &&
       spt_tandem_set(st, "doc", 1, "p1", 2) == 0 &&
       spt_tandem_set(st, "doc", 2, "p2", 2) == 0, "tandem set x3");
  TEST(spt_tandem_count(st, "doc") == 3, "tandem count");
  TEST(spt_tandem_get(st, "doc", 1, buf, sizeof buf, &len) == 0 &&
       memcmp(buf, "p1", 2) == 0, "tandem get order 1");
  TEST(spt_tandem_unset(st, "doc", 100) == 3 &&
       spt_tandem_count(st, "doc") == 0, "tandem unset removes the set");

  /* ---- bloom labels + enumeration ---- */
  spt_set(st, "lab", "x", 1);
  TEST(spt_label_or(st, "lab", 0x5) == 0, "label or");
  uint64_t lm;
  TEST(spt_get_labels(st, "lab", &lm) == 0 && lm == 0x5, "label readback");
  uint32_t hits[64];
  TEST(spt_enumerate(st, 0x4, hits, 64) == 1 &&
       hits[0] == (uint32_t)spt_find_index(st, "lab"),
       "enumerate by label mask");
  TEST(spt_label_andnot(st, "lab", 0x4) == 0 &&
       spt_enumerate(st, 0x4, hits, 64) == 0, "label clear");

  /* ---- signal arena + bump ---- */
  uint64_t c0 = spt_signal_count(st, 7);
  TEST(spt_watch_register(st, "lab", 7) == 0, "watch register");
  spt_set(st, "lab", "y", 1);
  TEST(spt_signal_count(st, 7) == c0 + 1, "write pulses watcher group");
  TEST(spt_bump(st, "lab") == 0 && spt_signal_count(st, 7) == c0 + 2,
       "bump pulses without writing");
  /* label-bound group: bloom bit 3 -> group 9 */
  TEST(spt_watch_label_register(st, 3, 9) == 0, "label watch register");
  spt_label_or(st, "lab", 1ull << 3);
  uint64_t c9 = spt_signal_count(st, 9);
  spt_set(st, "lab", "z", 1);
  TEST(spt_signal_count(st, 9) == c9 + 1, "label-bound group pulsed");
  TEST(spt_watch_label_unregister(st, 3, 9) == 0, "label watch unregister");
  TEST(spt_watch_unregister(st, "lab", 7) == 0, "watch unregister");
  uint64_t cnt;
  TEST(spt_signal_wait(st, 7, spt_signal_count(st, 7), 10, &cnt) ==
       -ETIMEDOUT, "signal_wait times out when quiet");

  /* ---- snapshots ---- */
  spt_header_view hv;
  TEST(spt_header_snapshot(st, &hv) == 0 && hv.magic == SPT_MAGIC &&
       hv.nslots == 64 && hv.used_slots >= 3, "header snapshot");
  spt_slot_view sv;
  TEST(spt_slot_snapshot(st, "lab", &sv) == 0 && sv.val_len == 1 &&
       strcmp(sv.key, "lab") == 0 && sv.epoch % 2 == 0, "slot snapshot");

  /* ---- timestamps ---- */
  TEST(spt_now() != 0 && spt_ticks_per_us() > 0, "tick counter");
  TEST(spt_stamp(st, "lab", 2, 0) == 0, "stamp ctime+atime");
  spt_slot_snapshot(st, "lab", &sv);
  TEST(sv.ctime > 0 && sv.atime > 0, "timestamps recorded");

  /* ---- mop modes + purge ---- */
  TEST(spt_get_mop(st) == SPT_MOP_HYBRID, "default mop hybrid");
  TEST(spt_set_mop(st, SPT_MOP_FULL) == 0 && spt_get_mop(st) == SPT_MOP_FULL,
       "mop full-boil");
  spt_set(st, "mop", "aaaaaaaa", 8);
  spt_set(st, "mop", "b", 1);          /* full-boil zeroes the stale tail */
  spt_get_raw(st, "mop", &p, &len, &re);
  TEST(len == 1 && ((const char *)p)[1] == 0 && ((const char *)p)[7] == 0,
       "full-boil scrubs stale tail");
  spt_set_mop(st, SPT_MOP_OFF);
  spt_set(st, "mop", "cccccccc", 8);
  spt_set(st, "mop", "d", 1);
  spt_get_raw(st, "mop", &p, &len, &re);
  TEST(((const char *)p)[3] == 'c', "mop off leaves stale tail");
  TEST(spt_purge(st) >= 1, "purge sweeps stale tails");
  spt_get_raw(st, "mop", &p, &len, &re);
  TEST(((const char *)p)[3] == 0, "purge scrubbed the tail");
  spt_set_mop(st, SPT_MOP_HYBRID);
  TEST(spt_get(st, "mop", buf, sizeof buf, &len) == 0 && len == 1 &&
       buf[0] == 'd', "value survives purge");

  /* ---- system keys + user flags ---- */
  TEST(spt_set_system(st, "__scratch") == 0, "system key");
  spt_slot_snapshot(st, "__scratch", &sv);
  TEST((sv.flags & SPT_F_SYSTEM) && (sv.flags & SPT_T_BINARY) &&
       sv.val_len == spt_max_val(st), "system scratchpad spans max_val");
  TEST(spt_slot_usr_set(st, "lab", 0xA5) == 0, "slot user flags set");
  uint8_t ub;
  TEST(spt_slot_usr_get(st, "lab", &ub) == 0 && ub == 0xA5,
       "slot user flags get");
  TEST(spt_config_set_user(st, 0x3) == 0 && spt_config_get_user(st) == 0x3,
       "store user flags");

  /* ---- vector lane ---- */
  float v[8] = {1, 2, 3, 4, 5, 6, 7, 8}, vo[8];
  TEST(spt_vec_set(st, "lab", v, 8) == 0 &&
       spt_vec_get(st, "lab", vo, 8) == 0 &&
       memcmp(v, vo, sizeof v) == 0, "vector round trip");
  idx = spt_find_index(st, "lab");
  uint64_t ve = spt_epoch_at(st, (uint32_t)idx);
  uint32_t rows[2] = {(uint32_t)idx, (uint32_t)idx};
  uint64_t eps[2] = {ve, ve - 2};            /* second is stale */
  float vecs[16] = {9, 9, 9, 9, 9, 9, 9, 9, 1, 1, 1, 1, 1, 1, 1, 1};
  int32_t res[2];
  TEST(spt_vec_commit_batch(st, rows, eps, vecs, 2, 8, 0, res) == 1 &&
       res[0] == 0 && res[1] == -ESTALE, "batch commit epoch gating");
  /* write-once gate: vector now non-zero, so write_once commit skips */
  ve = spt_epoch_at(st, (uint32_t)idx);
  TEST(spt_vec_commit_batch(st, rows, &ve, vecs, 1, 8, 1, res) == 0 &&
       res[0] == -EEXIST, "write-once gate -EEXIST");
  TEST(spt_vec_set(st, "nope", v, 8) == -ENOENT, "vec on missing -ENOENT");
  TEST(spt_vec_get(st, "lab", vo, 4) == -EMSGSIZE,
       "vec dim mismatch -EMSGSIZE");

  /* unset zeroes the vector */
  spt_set(st, "vz", "x", 1);
  spt_vec_set(st, "vz", v, 8);
  spt_unset(st, "vz");
  spt_set(st, "vz", "x", 1);
  spt_vec_get(st, "vz", vo, 8);
  int allz = 1; for (int i = 0; i < 8; i++) allz &= vo[i] == 0.0f;
  TEST(allz, "unset scrubs vector");

  /* ---- retrain (backward epoch) ---- */
  spt_set(st, "stuck", "v", 1);
  spt_vec_set(st, "stuck", v, 8);
  TEST(spt_retrain(st, "stuck") == 0, "retrain");
  idx = spt_find_index(st, "stuck");
  TEST(spt_epoch_at(st, (uint32_t)idx) == 4, "retrain publishes epoch 4");
  spt_vec_get(st, "stuck", vo, 8);
  allz = 1; for (int i = 0; i < 8; i++) allz &= vo[i] == 0.0f;
  TEST(allz, "retrain scrubs vector");
  TEST(spt_get(st, "stuck", buf, sizeof buf, &len) == 0 && buf[0] == 'v',
       "retrain keeps value");

  /* ---- shard election matrix (forged bids, deterministic) ----
   * claimed_at is ABSOLUTE microseconds (same clock as spt_now()/
   * spt_ticks_per_us()); forge bids relative to now so they are live. */
  uint64_t now_us = spt_now() / spt_ticks_per_us();
  int b1 = spt_shard_claim_ex(st, 0x100, 1111, SPT_ADV_WILLNEED, 40,
                              60000000, now_us - 3000);
  int b2 = spt_shard_claim_ex(st, 0x200, 2222, SPT_ADV_WILLNEED, 200,
                              60000000, now_us - 2000);
  TEST(b1 >= 0 && b2 >= 0 && b1 != b2, "claim_ex forged bids");
  TEST(spt_shard_election(st) == b2, "highest priority wins");
  /* tie on priority -> earliest claimed_at */
  int b3 = spt_shard_claim_ex(st, 0x300, 3333, SPT_ADV_WILLNEED, 200,
                              60000000, now_us - 3500);
  TEST(spt_shard_election(st) == b3, "tie -> earliest claimed_at");
  /* tie on both -> lowest pid */
  int b4 = spt_shard_claim_ex(st, 0x400, 44, SPT_ADV_WILLNEED, 200,
                              60000000, now_us - 3500);
  TEST(spt_shard_election(st) == b4, "tie -> lowest pid");
  /* DONTNEED bumper cannot win while live non-DONTNEED bids exist */
  int b5 = spt_shard_claim_ex(st, 0x500, 5, SPT_ADV_DONTNEED, 255,
                              60000000, now_us);
  TEST(spt_shard_election(st) == b4, "DONTNEED bumper cannot win");
  spt_shard_release(st, b1); spt_shard_release(st, b2);
  spt_shard_release(st, b3); spt_shard_release(st, b4);
  TEST(spt_shard_election(st) == b5, "bumper wins once alone");
  spt_shard_release(st, b5);
  /* duration 0 = born expired */
  int b6 = spt_shard_claim_ex(st, 0x600, 6, SPT_ADV_WILLNEED, 10, 0,
                              now_us);
  TEST(b6 >= 0 && spt_shard_election(st) == -ENOENT,
       "expired bid never elected");
  spt_bid_view bv;
  TEST(spt_bid_info(st, b6, &bv) == 0 && !bv.live, "bid_info live flag");
  spt_shard_release(st, b6);
  /* rebid refreshes claimed_at, reviving a bid expired BY TIME */
  b6 = spt_shard_claim_ex(st, 0x600, 6, SPT_ADV_WILLNEED, 10, 1000,
                          now_us - 5000000);     /* expired 5 s ago */
  TEST(spt_shard_election(st) == -ENOENT, "time-expired bid not elected");
  TEST(spt_shard_rebid(st, b6) == 0 && spt_shard_election(st) == b6,
       "rebid revives an expired bid");
  /* table capacity: fill to 32, 33rd refused */
  int held[SPT_MAX_BIDS], nheld = 0;
  for (int i = 0; i < SPT_MAX_BIDS; i++) {
    int b = spt_shard_claim_ex(st, 0x1000 + i, 100 + i, SPT_ADV_WILLNEED,
                               1, 60000000, 10);
    if (b >= 0) held[nheld++] = b;
  }
  TEST(nheld == SPT_MAX_BIDS - 1, "table fills to 32 bids");
  TEST(spt_shard_claim_ex(st, 0x9999, 9, SPT_ADV_WILLNEED, 1, 60000000,
                          10) == -ENOSPC, "33rd bid -ENOSPC");
  for (int i = 0; i < nheld; i++) spt_shard_release(st, held[i]);
  /* madvise: sovereign succeeds, non-sovereign defers */
  int lo = spt_shard_claim(st, 0x700, SPT_ADV_WILLNEED, 5, 60000000);
  int hi = spt_shard_claim_ex(st, 0x800, 1, SPT_ADV_WILLNEED, 250,
                              60000000, now_us);
  TEST(spt_madvise(st, lo, 0, 0, SPT_ADV_WILLNEED, 0) == -EAGAIN,
       "non-sovereign madvise defers -EAGAIN");
  TEST(spt_madvise(st, lo, 0, 0, SPT_ADV_WILLNEED, 20) == -ETIMEDOUT,
       "non-sovereign bounded wait -ETIMEDOUT");
  spt_shard_release(st, hi);
  TEST(spt_madvise(st, lo, 0, 0, SPT_ADV_WILLNEED, 0) == 0,
       "sovereign madvise issues");
  TEST(spt_madvise(st, b6, 0, 0, SPT_ADV_WILLNEED, 0) == -EPERM,
       "madvise without live bid -EPERM");
  spt_shard_release(st, lo);
  spt_shard_release(st, b6);

  /* ---- event bus ---- */
  TEST(spt_bus_init(st) == 0, "bus init (owner)");
  uint64_t dirty[SPT_DIRTY_WORDS];
  spt_bus_drain(st, dirty);                  /* clear backlog */
  spt_set(st, "k1", "bus", 3);
  TEST(spt_bus_wait(st, 200) == 0, "bus wakes on write");
  idx = spt_find_index(st, "k1");
  n = spt_bus_drain(st, dirty);
  TEST(n >= 1 &&
       (dirty[((uint32_t)idx % 1024) / 64] >>
        (((uint32_t)idx % 1024) % 64)) & 1, "dirty bit for written slot");
  n = spt_bus_peek(st, dirty);
  TEST(n == 0, "drain cleared the mask");
  TEST(spt_bus_wait(st, 10) == -ETIMEDOUT, "bus wait times out when idle");
  spt_bus_close(st);

  /* ---- diagnostics ---- */
  TEST(spt_report_parse_failure(st) == 0, "parse failure counter");
  spt_header_snapshot(st, &hv);
  TEST(hv.parse_failures == 1, "parse failure visible in header");

  /* ---- NUMA-bound open (advisory bind; mapping valid regardless) ---- */
  {
    int brc = 1;
    spt_store *sn = spt_open_numa(name, flags, 0, &brc);
    TEST(sn != NULL, "numa open maps the store");
    TEST(brc == 0 || brc == -ENOSYS || brc == -EPERM || brc == -EINVAL,
         "numa bind returns 0 or a sane advisory errno");
    uint32_t l2 = 0;
    TEST(spt_get(sn, "k1", buf, sizeof buf, &l2) == 0,
         "numa-opened handle reads data");
    spt_close(sn);
    int brc2 = 0;
    sn = spt_open_numa(name, flags, -1, &brc2);
    TEST(sn != NULL && brc2 == -EINVAL, "numa open rejects bad node");
    spt_close(sn);
  }

  /* ---- persistence across close/reopen ---- */
  spt_close(st);
  st = spt_open(name, flags);
  TEST(st != NULL, "reopen");
  TEST(spt_get(st, "k1", buf, sizeof buf, &len) == 0 && len == 3 &&
       memcmp(buf, "bus", 3) == 0, "data survives reopen");
  spt_vec_get(st, "lab", vo, 8);
  TEST(vo[0] == 9.0f, "vector survives reopen");
  spt_close(st);
  spt_unlink(name, flags);
  TEST(spt_open(name, flags) == NULL, "open after unlink fails");
}

int main(void) {
  char shm_name[64], file_name[128];
  snprintf(shm_name, sizeof shm_name, "/spt-unit-%d", (int)getpid());
  snprintf(file_name, sizeof file_name, "/tmp/spt-unit-%d.store",
           (int)getpid());

  printf("# backend: shm\n");
  suite(shm_name, SPT_BACKEND_SHM);
  printf("# backend: file (persistent)\n");
  suite(file_name, SPT_BACKEND_FILE);

  printf("1..%d\n", n_run);
  printf("# %d run, %d failed\n", n_run, n_fail);
  return n_fail ? 1 : 0;
}
