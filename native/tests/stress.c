/* stress.c — MRSW integrity stress: one writer thread hammers a hot key
 * set while N reader threads validate a structured payload on every read.
 * Any torn read (payload that doesn't parse back to ver|nonce|data) is an
 * integrity failure and a nonzero exit.
 *
 * Parity with the reference's splinter_stress harness (SURVEY.md §4):
 * same contract — readers count EAGAIN retries (expected under load) and
 * corruption (never acceptable); reports ops/sec.
 *
 * Usage: spt_stress [--readers N] [--keys K] [--duration-ms D]
 *                   [--slots S] [--val-size V] [--scrub MODE]
 */
#define _GNU_SOURCE
#include "sptpu.h"

#include <pthread.h>
#include <stdatomic.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

static _Atomic long g_writes, g_reads, g_eagain, g_miss, g_corrupt;
static _Atomic int g_stop;
static int g_nkeys = 2000;
static int g_valsz = 1024;
static spt_store *g_st;

static void key_name(char *buf, int i) {
  snprintf(buf, SPT_KEY_MAX, "stress-key-%d", i);
}

/* --raw: measure the STORE's ceiling, not the harness's — keys are
 * pre-rendered and the payload is constant, so the loop body is one
 * spt_set per iteration (hash + probe + seqlock + memcpy + fanout).
 * Readers skip payload validation in this mode (the payload carries no
 * per-write nonce to check). */
static int g_raw = 0;

static void *writer_raw(void *arg) {
  (void)arg;
  char *keys = malloc((size_t)g_nkeys * SPT_KEY_MAX);
  char *payload = malloc((size_t)g_valsz + 64);
  for (int i = 0; i < g_nkeys; i++)
    key_name(keys + (size_t)i * SPT_KEY_MAX, i);
  memset(payload, 'x', (size_t)g_valsz);
  long nonce = 0;
  while (!atomic_load_explicit(&g_stop, memory_order_relaxed)) {
    const char *key = keys + (size_t)(nonce % g_nkeys) * SPT_KEY_MAX;
    int rc = spt_set(g_st, key, payload, (uint32_t)g_valsz);
    if (rc == 0)
      atomic_fetch_add_explicit(&g_writes, 1, memory_order_relaxed);
    else if (rc == -11) /* EAGAIN */
      atomic_fetch_add_explicit(&g_eagain, 1, memory_order_relaxed);
    nonce++;
  }
  free(keys);
  free(payload);
  return NULL;
}

static void *writer(void *arg) {
  if (g_raw) return writer_raw(arg);
  char key[SPT_KEY_MAX];
  char *payload = malloc((size_t)g_valsz + 64);
  long nonce = 0;
  while (!atomic_load_explicit(&g_stop, memory_order_relaxed)) {
    int i = (int)(nonce % g_nkeys);
    key_name(key, i);
    int head = snprintf(payload, (size_t)g_valsz, "ver:%d|nonce:%ld|data:",
                        i, nonce);
    int fill = (int)(nonce % 64);
    for (int f = 0; f < fill && head + f < g_valsz - 1; f++)
      payload[head + f] = 'x';
    int len = head + (head + fill < g_valsz - 1 ? fill : 0);
    payload[len] = '\0';
    int rc = spt_set(g_st, key, payload, (uint32_t)len + 1);
    if (rc == 0)
      atomic_fetch_add_explicit(&g_writes, 1, memory_order_relaxed);
    else if (rc == -11) /* EAGAIN */
      atomic_fetch_add_explicit(&g_eagain, 1, memory_order_relaxed);
    nonce++;
  }
  free(payload);
  return NULL;
}

static int parse_payload(const char *buf, uint32_t len, int expect_key) {
  /* format: ver:<i>|nonce:<n>|data:x* — returns 1 if intact */
  int ver = -1;
  long nonce = -1;
  if (len < 8) return 0;
  if (sscanf(buf, "ver:%d|nonce:%ld|data:", &ver, &nonce) != 2) return 0;
  if (ver != expect_key || nonce < 0) return 0;
  const char *p = strstr(buf, "data:");
  if (!p) return 0;
  for (p += 5; *p; p++)
    if (*p != 'x') return 0;
  return 1;
}

static void *reader(void *arg) {
  (void)arg;
  char key[SPT_KEY_MAX];
  char *raw_keys = NULL;
  if (g_raw) {        /* pre-render keys: measure the store, not snprintf */
    raw_keys = malloc((size_t)g_nkeys * SPT_KEY_MAX);
    for (int i = 0; i < g_nkeys; i++)
      key_name(raw_keys + (size_t)i * SPT_KEY_MAX, i);
  }
  char *buf = malloc((size_t)g_valsz + 64);
  unsigned seed = (unsigned)(uintptr_t)&buf;
  while (!atomic_load_explicit(&g_stop, memory_order_relaxed)) {
    int i = (int)(rand_r(&seed) % g_nkeys);
    const char *k = key;
    if (raw_keys)
      k = raw_keys + (size_t)i * SPT_KEY_MAX;
    else
      key_name(key, i);
    uint32_t len = 0;
    int rc = spt_get(g_st, k, buf, (uint32_t)g_valsz + 64, &len);
    if (rc == 0) {
      atomic_fetch_add_explicit(&g_reads, 1, memory_order_relaxed);
      if (!g_raw && len > 0 && !parse_payload(buf, len, i)) {
        atomic_fetch_add_explicit(&g_corrupt, 1, memory_order_relaxed);
        fprintf(stderr, "CORRUPT key=%s len=%u buf=%.80s\n", k, len, buf);
      }
    } else if (rc == -11) {
      atomic_fetch_add_explicit(&g_eagain, 1, memory_order_relaxed);
    } else {
      atomic_fetch_add_explicit(&g_miss, 1, memory_order_relaxed);
    }
  }
  free(buf);
  free(raw_keys);
  return NULL;
}

/* --json: emit one machine-readable line for the bench ledger
 * (scripts/bench_store_ops / bench_series store_ops phase).  CPO
 * (cycles per op) is measured separately from the contended run: a
 * single-threaded spt_set loop over pre-rendered keys, timed with the
 * store's own tick clock (spt_now = rdtsc/cntvct), so the number is
 * the store's clean per-write cost — the same definition the
 * reference's published CPO uses — not a descheduling artifact of the
 * oversubscribed stress threads. */
static double measure_write_cpo(void) {
  enum { CPO_OPS = 200000 };
  char *keys = malloc((size_t)g_nkeys * SPT_KEY_MAX);
  char *payload = malloc((size_t)g_valsz + 64);
  for (int i = 0; i < g_nkeys; i++)
    key_name(keys + (size_t)i * SPT_KEY_MAX, i);
  memset(payload, 'x', (size_t)g_valsz);
  /* warm the slots so the timed loop measures steady-state updates */
  for (int i = 0; i < g_nkeys; i++)
    spt_set(g_st, keys + (size_t)i * SPT_KEY_MAX, payload,
            (uint32_t)g_valsz);
  uint64_t t0 = spt_now();
  for (long n = 0; n < CPO_OPS; n++)
    spt_set(g_st, keys + (size_t)(n % g_nkeys) * SPT_KEY_MAX, payload,
            (uint32_t)g_valsz);
  uint64_t dt = spt_now() - t0;
  free(keys);
  free(payload);
  return (double)dt / (double)CPO_OPS;
}

static int int_arg(int argc, char **argv, int *i) {
  if (*i + 1 >= argc) {
    fprintf(stderr, "%s needs a value\n", argv[*i]);
    exit(2);
  }
  return atoi(argv[++*i]);
}

int main(int argc, char **argv) {
  int readers = 7, duration_ms = 5000, slots = 50000, json_out = 0;
  uint32_t scrub = 1;
  for (int i = 1; i < argc; i++) {
    if (!strcmp(argv[i], "--readers")) readers = int_arg(argc, argv, &i);
    else if (!strcmp(argv[i], "--keys")) g_nkeys = int_arg(argc, argv, &i);
    else if (!strcmp(argv[i], "--duration-ms"))
      duration_ms = int_arg(argc, argv, &i);
    else if (!strcmp(argv[i], "--slots")) slots = int_arg(argc, argv, &i);
    else if (!strcmp(argv[i], "--val-size")) g_valsz = int_arg(argc, argv, &i);
    else if (!strcmp(argv[i], "--scrub"))
      scrub = (uint32_t)int_arg(argc, argv, &i);
    else if (!strcmp(argv[i], "--raw")) g_raw = 1;
    else if (!strcmp(argv[i], "--json")) json_out = 1;
  }
  char name[64];
  snprintf(name, sizeof name, "/spt-stress-%d", getpid());
  spt_unlink(name, 0);
  g_st = spt_create(name, (uint32_t)slots, (uint32_t)g_valsz + 64, 0, 0);
  if (!g_st) { perror("create"); return 2; }
  spt_set_mop(g_st, scrub);

  pthread_t wt, rt[64];
  pthread_create(&wt, NULL, writer, NULL);
  for (int i = 0; i < readers && i < 64; i++)
    pthread_create(&rt[i], NULL, reader, NULL);

  struct timespec ts = {duration_ms / 1000, (duration_ms % 1000) * 1000000L};
  nanosleep(&ts, NULL);
  atomic_store(&g_stop, 1);
  pthread_join(wt, NULL);
  for (int i = 0; i < readers && i < 64; i++) pthread_join(rt[i], NULL);

  long w = g_writes, r = g_reads, e = g_eagain, m = g_miss, c = g_corrupt;
  double secs = duration_ms / 1000.0;
  printf("MRSW: writers=1 readers=%d dur=%.1fs\n", readers, secs);
  printf("  writes=%ld (%.2fM/s)  reads=%ld (%.2fM/s)\n", w, w / secs / 1e6,
         r, r / secs / 1e6);
  printf("  total=%.2fM ops/s  eagain=%ld  miss=%ld  corrupt=%ld\n",
         (w + r) / secs / 1e6, e, m, c);
  if (json_out) {
    double cpo = measure_write_cpo();
    printf("{\"tool\": \"mrsw\", \"writers\": 1, \"readers\": %d, "
           "\"duration_s\": %.2f, \"writes\": %ld, \"reads\": %ld, "
           "\"ops_per_sec\": %.0f, \"write_cpo\": %.1f, "
           "\"ticks_per_us\": %llu, \"eagain\": %ld, \"miss\": %ld, "
           "\"corrupt\": %ld, \"raw\": %d}\n",
           readers, secs, w, r, (w + r) / secs, cpo,
           (unsigned long long)spt_ticks_per_us(), e, m, c, g_raw);
  }
  spt_close(g_st);
  spt_unlink(name, 0);
  if (c) { fprintf(stderr, "INTEGRITY FAILURE\n"); return 1; }
  printf("OK\n");
  return 0;
}
