/* chi_sao.c — MRMW disjoint-lane contention: up to 32 writer threads each
 * own a private key lane (write-write contention is zero by construction,
 * matching the store's 32-writer design ceiling), while reader threads
 * sample the whole keyspace and validate payload integrity.
 *
 * Parity with the reference's splinter_chi_sao harness (SURVEY.md §4).
 *
 * Usage: spt_chi_sao [--writers N] [--readers N] [--keys-per-lane K]
 *                    [--duration-ms D] [--slots S]
 */
#define _GNU_SOURCE
#include "sptpu.h"

#include <pthread.h>
#include <stdatomic.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>
#include <unistd.h>

static _Atomic long g_writes, g_reads, g_eagain, g_corrupt;
static _Atomic int g_stop;
static int g_keys_per_lane = 256;
static int g_writers = 4;
static int g_valsz = 512;
static spt_store *g_st;

static void key_name(char *buf, int lane, int i) {
  snprintf(buf, SPT_KEY_MAX, "lane%02d-key-%d", lane, i);
}

static void *writer(void *arg) {
  int lane = (int)(intptr_t)arg;
  char key[SPT_KEY_MAX];
  char payload[1024];
  long nonce = 0;
  while (!atomic_load_explicit(&g_stop, memory_order_relaxed)) {
    int i = (int)(nonce % g_keys_per_lane);
    key_name(key, lane, i);
    int len = snprintf(payload, sizeof payload,
                       "lane:%d|nonce:%ld|tail:%0*ld", lane, nonce,
                       (int)(nonce % 32) + 1, nonce);
    if (len >= g_valsz) len = g_valsz - 1;
    int rc = spt_set(g_st, key, payload, (uint32_t)len + 1);
    if (rc == 0)
      atomic_fetch_add_explicit(&g_writes, 1, memory_order_relaxed);
    else
      atomic_fetch_add_explicit(&g_eagain, 1, memory_order_relaxed);
    nonce++;
  }
  return NULL;
}

static void *reader(void *arg) {
  (void)arg;
  char key[SPT_KEY_MAX];
  char buf[1100];
  unsigned seed = (unsigned)(uintptr_t)&key;
  while (!atomic_load_explicit(&g_stop, memory_order_relaxed)) {
    int lane = (int)(rand_r(&seed) % g_writers);
    int i = (int)(rand_r(&seed) % g_keys_per_lane);
    key_name(key, lane, i);
    uint32_t len = 0;
    int rc = spt_get(g_st, key, buf, sizeof buf, &len);
    if (rc == 0 && len > 0) {
      atomic_fetch_add_explicit(&g_reads, 1, memory_order_relaxed);
      int got_lane = -1;
      long nonce = -1;
      if (sscanf(buf, "lane:%d|nonce:%ld|tail:", &got_lane, &nonce) != 2 ||
          got_lane != lane) {
        atomic_fetch_add_explicit(&g_corrupt, 1, memory_order_relaxed);
        fprintf(stderr, "CORRUPT key=%s buf=%.60s\n", key, buf);
      }
    } else if (rc == -11) {
      atomic_fetch_add_explicit(&g_eagain, 1, memory_order_relaxed);
    }
  }
  return NULL;
}

int main(int argc, char **argv) {
  int readers = 4, duration_ms = 5000, slots = 50000, json_out = 0;
  for (int i = 1; i < argc; i++) {
    int has_val = i + 1 < argc;
    if (!strcmp(argv[i], "--writers") && has_val) g_writers = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--readers") && has_val)
      readers = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--keys-per-lane") && has_val)
      g_keys_per_lane = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--duration-ms") && has_val)
      duration_ms = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--slots") && has_val) slots = atoi(argv[++i]);
    else if (!strcmp(argv[i], "--json")) json_out = 1;
  }
  if (g_writers > 32) g_writers = 32;  /* the 32-writer design ceiling */
  char name[64];
  snprintf(name, sizeof name, "/spt-chisao-%d", getpid());
  spt_unlink(name, 0);
  g_st = spt_create(name, (uint32_t)slots, (uint32_t)g_valsz + 64, 0, 0);
  if (!g_st) { perror("create"); return 2; }

  pthread_t wt[32], rt[64];
  for (int i = 0; i < g_writers; i++)
    pthread_create(&wt[i], NULL, writer, (void *)(intptr_t)i);
  for (int i = 0; i < readers && i < 64; i++)
    pthread_create(&rt[i], NULL, reader, NULL);

  struct timespec ts = {duration_ms / 1000, (duration_ms % 1000) * 1000000L};
  nanosleep(&ts, NULL);
  atomic_store(&g_stop, 1);
  for (int i = 0; i < g_writers; i++) pthread_join(wt[i], NULL);
  for (int i = 0; i < readers && i < 64; i++) pthread_join(rt[i], NULL);

  long w = g_writes, r = g_reads, e = g_eagain, c = g_corrupt;
  double secs = duration_ms / 1000.0;
  printf("MRMW: writers=%d readers=%d dur=%.1fs\n", g_writers, readers,
         secs);
  printf("  writes=%ld (%.2fM/s)  reads=%ld (%.2fM/s)  total=%.2fM ops/s\n",
         w, w / secs / 1e6, r, r / secs / 1e6, (w + r) / secs / 1e6);
  printf("  eagain=%ld  corrupt=%ld\n", e, c);
  if (json_out)
    printf("{\"tool\": \"mrmw\", \"writers\": %d, \"readers\": %d, "
           "\"duration_s\": %.2f, \"writes\": %ld, \"reads\": %ld, "
           "\"ops_per_sec\": %.0f, \"eagain\": %ld, \"corrupt\": %ld}\n",
           g_writers, readers, secs, w, r, (w + r) / secs, e, c);
  spt_close(g_st);
  spt_unlink(name, 0);
  if (c) { fprintf(stderr, "INTEGRITY FAILURE\n"); return 1; }
  printf("OK\n");
  return 0;
}
