"""Ring attention — sequence/context parallelism over an ICI ring.

The reference *rejects* long inputs outright (splinference.cpp:226-233
marks anything >= 0.9*n_ctx CONTEXT_EXCEEDED) or pre-chunks documents at
ingest time (splinter_cli_cmd_ingest.c:8-33).  The TPU build makes long
context a first-class capability instead: the sequence axis is sharded
over the mesh's `sp` axis and attention runs blockwise with an online
(flash-style) softmax while K/V shards rotate around the ring via
`lax.ppermute` — each device only ever holds O(S/n) keys, and the
rotation rides ICI neighbor links (no all-gather, no O(S) memory).

Design notes (TPU/XLA):
  - the per-step block matmuls are (S/n x D) x (D x S/n) einsums — large,
    static-shaped, bfloat16-friendly MXU work;
  - the step loop is a Python loop over the *static* axis size, so XLA
    sees a fixed unrolled schedule and can overlap the ppermute of step
    i+1 with the matmul of step i;
  - softmax statistics are carried in float32 regardless of input dtype;
  - reverse-mode autodiff works through ppermute (its transpose is the
    inverse rotation), so the same primitive serves training; each block
    step is wrapped in jax.checkpoint to keep backward memory at
    O(S/n) per device.

Must be called inside shard_map (or an equivalent axis context) where
`axis_name` is bound.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


NEG_INF = -1e9          # masked-score bias (finite: keeps softmax NaN-free)
ACC_MIN = -1e30         # initial running max


def _block_scores(q, k, scale):
    # q: (B, Sq, H, D)  k: (B, Sk, H, D)  ->  (B, H, Sq, Sk) in f32
    return jnp.einsum("bqhd,bkhd->bhqk", q, k,
                      preferred_element_type=jnp.float32) * scale


def _online_update(carry, q, k, v, bias):
    """One flash-attention accumulation step.

    carry = (o, m, l): o (B,Sq,H,D) f32 accumulator, m (B,H,Sq) running
    max, l (B,H,Sq) running denominator.  bias (B,H,Sq,Sk) additive.
    """
    o, m, l = carry
    s = _block_scores(q, k, 1.0) + bias          # scale folded into bias path
    m_blk = s.max(axis=-1)
    m_new = jnp.maximum(m, m_blk)
    alpha = jnp.exp(m - m_new)                   # rescale old accumulator
    p = jnp.exp(s - m_new[..., None])            # (B,H,Sq,Sk)
    l = l * alpha + p.sum(axis=-1)
    o_blk = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
    o = o * alpha.transpose(0, 2, 1)[..., None] + o_blk
    return o, m_new, l


def ring_attention(q, k, v, kv_mask, *, axis_name: str,
                   causal: bool = False, scale: float | None = None,
                   axis_size: int | None = None):
    """Blockwise ring attention over sequence shards.

    q, k, v:  (B, S_local, H, D) — this device's sequence chunk.
    kv_mask:  (B, S_local) bool — key/value validity (padding) for the
              LOCAL chunk; it rotates around the ring with k/v.
    causal:   apply a causal mask using global positions (chunk i holds
              positions [i*S_local, (i+1)*S_local)).
    Returns   (B, S_local, H, D) in q.dtype.
    """
    if axis_size is not None:
        n = axis_size
    else:
        from .mesh import axis_size as _axis_size
        n = _axis_size(axis_name)
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    qf = q.astype(jnp.float32) * scale
    my = lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    o = jnp.zeros((B, S, H, D), jnp.float32)
    m = jnp.full((B, H, S), ACC_MIN, jnp.float32)
    den = jnp.zeros((B, H, S), jnp.float32)

    step_fn = jax.checkpoint(_online_update)

    kr, vr, maskr = k, v, kv_mask
    for step in range(n):
        src = (my - step) % n                    # chunk index now held
        bias = jnp.where(maskr[:, None, None, :], 0.0, NEG_INF)
        if causal:
            q_pos = my * S + jnp.arange(S)
            kv_pos = src * S + jnp.arange(S)
            cmask = q_pos[:, None] >= kv_pos[None, :]
            bias = bias + jnp.where(cmask[None, None], 0.0, NEG_INF)
        o, m, den = step_fn((o, m, den), qf, kr, vr, bias)
        if step != n - 1:
            kr = lax.ppermute(kr, axis_name, perm)
            vr = lax.ppermute(vr, axis_name, perm)
            maskr = lax.ppermute(maskr, axis_name, perm)

    out = o / jnp.maximum(den, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def dense_reference(q, k, v, kv_mask, *, causal: bool = False,
                    scale: float | None = None):
    """Single-device dense attention with identical masking semantics —
    the correctness oracle for ring_attention tests."""
    B, S, H, D = q.shape
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    s = _block_scores(q, k, scale)
    bias = jnp.where(kv_mask[:, None, None, :], 0.0, NEG_INF)
    if causal:
        pos = jnp.arange(S)
        bias = bias + jnp.where(pos[:, None] >= pos[None, :],
                                0.0, NEG_INF)[None, None]
    p = jax.nn.softmax(s + bias, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return out.astype(q.dtype)


def ring_attention_sharded(mesh, q, k, v, kv_mask, *, axis: str = "sp",
                           causal: bool = False):
    """Convenience wrapper: shard q/k/v on the sequence axis over `axis`
    and run ring_attention under shard_map.  Batch rides `dp` when the
    mesh has one."""
    from jax.sharding import PartitionSpec as P

    from .mesh import shard_map

    batch_ax = "dp" if "dp" in mesh.axis_names else None
    qkv_spec = P(batch_ax, axis)
    fn = shard_map(
        functools.partial(ring_attention, axis_name=axis, causal=causal,
                          axis_size=mesh.shape[axis]),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, qkv_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return fn(q, k, v, kv_mask)
