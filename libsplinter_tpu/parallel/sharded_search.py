"""Pod-sharded similarity search: the arena's vector lane is sharded
row-wise across the mesh; each device computes local top-k with the
similarity kernel, then an all-gather over ICI merges the per-shard
candidates — exactly the scale-out path the reference deliberately lacks
(RDMA-hostile: splinter_stress.c:358-359; SURVEY.md §2.7 TPU mapping).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.similarity import FUSED_K_MAX, _fused_topk_fn, cosine_scores
from .mesh import shard_map


@functools.lru_cache(maxsize=64)
def _topk_program(mesh: Mesh, axis: str, local_n: int, d: int, nq: int,
                  k_local: int, k_final: int, use_pallas: bool,
                  mxu_bf16: bool = False, interpret: bool = False):
    """Compiled sharded top-k, cached per (mesh, shapes, k) so repeated
    queries from a live session don't re-trace/re-compile."""
    # pallas path: the local pass runs the STREAMING fused kernel —
    # each shard's (local_n, Q) score matrix never exists in HBM, and
    # only k_local candidate (score, index) pairs per shard feed the
    # ICI merge.  The jnp fallback (CPU tests) keeps the score-matrix
    # + lax.top_k shape, where XLA fuses it anyway.
    fused = (use_pallas or interpret) and k_local <= FUSED_K_MAX

    def local_then_merge(v_local, q, m_local):
        if fused:
            ls, li = _fused_topk_fn(k_local, 1024, mxu_bf16,
                                    interpret)(v_local, q, m_local,
                                               None)
            s, i = ls[0], li[0]
        else:
            # local fused scores + top-k on this shard
            scores = cosine_scores(v_local, q, m_local,
                                   use_pallas=use_pallas,
                                   mxu_bf16=mxu_bf16)
            s, i = jax.lax.top_k(scores[:, 0], k_local)
        # globalize indices by shard offset (fused-path filler rows,
        # index -1 at score NEG_INF, stay below every real candidate
        # in the merge and are dropped by callers' score filter)
        shard = jax.lax.axis_index(axis)
        gi = jnp.where(i >= 0, i + shard * local_n, -1)
        # all-gather candidates over ICI, merge, re-top-k
        all_s = jax.lax.all_gather(s, axis)      # (m, k_local)
        all_i = jax.lax.all_gather(gi, axis)     # (m, k_local)
        ms, mi = jax.lax.top_k(all_s.reshape(-1), k_final)
        return ms, all_i.reshape(-1)[mi]

    fn = shard_map(
        local_then_merge, mesh=mesh,
        in_specs=(P(axis, None), P(), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_topk(mesh: Mesh, vectors, query, k: int, mask=None,
                 axis: str = "dp", use_pallas: bool | None = None,
                 mxu_bf16: bool = False, interpret: bool = False
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Top-k over row-sharded vectors.

    vectors: (N, D) logically; physically sharded (N/m, D) per device on
    `axis`.  Returns (scores, GLOBAL indices) of the top k.
    """
    n, d = vectors.shape
    m = mesh.shape[axis]
    assert n % m == 0, "row count must divide the mesh axis"
    local_n = n // m
    # each shard can contribute at most local_n candidates; the merged
    # result still returns up to min(k, n) rows
    k_local = min(k, local_n)
    k_final = min(k, n)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"

    if mask is None:
        mask = jnp.ones((n,), jnp.float32)
    query = jnp.asarray(query, jnp.float32)
    if query.ndim == 1:
        query = query[None, :]
    fn = _topk_program(mesh, axis, local_n, d, query.shape[0],
                       k_local, k_final, bool(use_pallas),
                       bool(mxu_bf16), bool(interpret))
    s, i = fn(jnp.asarray(vectors, jnp.float32), query,
              jnp.asarray(mask, jnp.float32))
    return np.asarray(s), np.asarray(i)


def shard_vectors(mesh: Mesh, vectors, axis: str = "dp"):
    """Place a host (N, D) matrix row-sharded over the mesh axis."""
    return jax.device_put(
        vectors, NamedSharding(mesh, P(axis, None)))


class PodSearch:
    """End-to-end pod-sharded search over per-host store lanes.

    Every TPU-VM worker runs this SPMD-style with its OWN host-local
    store (SURVEY.md §2.7): each host's (nslots, dim) vector lane —
    zero-padded to the mesh tile — becomes this host's block of one
    global row-sharded device matrix.  Row addressing: global row g
    lives on host g // local_pad at local slot g % local_pad (every
    host's lane is padded to the SAME local_pad, validated at init).
    search() runs the fused local top-k + ICI all-gather merge on the
    mesh, then resolves winning global rows back to (host, key) with
    one DCN process_allgather of the owning hosts' key bytes — device
    data rides ICI, only control/keys ride DCN.

    Staging is epoch-diffed: a refresh with no store writes costs one
    scalar DCN allgather and touches no device data; updates scatter
    only the changed rows into the donated device matrix (same economy
    as ops.StagedLane).  The multi-process path is collectively
    incremental (VERDICT r2 #2): hosts allgather their dirty COUNTS,
    agree on a shared padded bucket, and every host runs ONE scatter
    program carrying its own changed rows (out-of-bounds sentinel rows
    from less-dirty hosts are dropped by the scatter) — O(max dirty)
    per refresh, never a full restage of every host's lane.

    Single-process (process_count == 1) degrades to sharding the one
    local lane across the local mesh axis — same code path the
    dryrun exercises on the virtual CPU mesh.
    """

    def __init__(self, store, mesh: Mesh | None = None, *,
                 axis: str = "dp"):
        from .mesh import make_mesh
        from .multihost import init_distributed, process_span

        init_distributed()
        self.store = store
        self.axis = axis
        self.mesh = mesh or make_mesh()
        self.pid, self.pcount = process_span()
        self.local_n = store.nslots
        m = self.mesh.shape[axis]
        if m % self.pcount:
            raise ValueError(
                f"mesh axis {axis}={m} not divisible by "
                f"{self.pcount} processes")
        per_host_shards = m // self.pcount
        # pad each host's block with zero rows to the shard tile; zero
        # vectors are never candidates (cosine_scores nonzero mask)
        self.local_pad = -(-self.local_n // per_host_shards) * \
            per_host_shards
        self.per_host_shards = per_host_shards
        self.tile = self.local_pad // per_host_shards
        self.global_n = self.local_pad * self.pcount
        if self.pcount > 1:
            # global-row arithmetic (host = g // local_pad, key resolve,
            # make_array_from_process_local_data's global shape) is only
            # sound if every worker has the same geometry — a mismatched
            # store would yield silently misattributed results.
            from jax.experimental import multihost_utils
            geo = np.asarray(multihost_utils.process_allgather(
                np.array([self.local_n, self.local_pad,
                          store.vec_dim], np.int64)))
            geo = geo.reshape(self.pcount, 3)
            if not (geo == geo[0]).all():
                raise ValueError(
                    "PodSearch requires identical store geometry on "
                    "every worker; got per-host (nslots, local_pad, "
                    f"vec_dim) = {geo.tolist()}")
        self._arr = None
        self._staged: np.ndarray | None = None   # epochs rows staged at
        # transfer accounting (tests + perf docs)
        self.full_stages = 0
        self.rows_staged = 0

    # -- staging -----------------------------------------------------------

    def _gather_local(self) -> np.ndarray:
        """Full torn-safe local lane, zero-padded to local_pad rows.
        Rows mid-write stage as zeros this pass (never candidates) and
        re-stage next refresh via their unchanged staged epoch."""
        rows = np.arange(self.local_n, dtype=np.uint32)
        vecs, eps = self.store.vec_gather(rows)
        torn = eps == self.store.GATHER_TORN
        vecs[torn] = 0.0
        staged = np.where(torn, np.uint64(1), eps)   # odd = restage
        if self.local_pad != self.local_n:
            vecs = np.pad(vecs,
                          ((0, self.local_pad - self.local_n), (0, 0)))
        return vecs, staged

    def _place(self, local: np.ndarray):
        sharding = NamedSharding(self.mesh, P(self.axis, None))
        if self.pcount == 1:
            return shard_vectors(self.mesh, local, self.axis)
        return jax.make_array_from_process_local_data(
            sharding, local, (self.global_n, local.shape[1]))

    def refresh(self):
        """Bring the sharded matrix up to date (epoch-diffed)."""
        if self._arr is None:
            local, self._staged = self._gather_local()
            self._arr = self._place(local)
            self.full_stages += 1
            return self._arr
        e = self.store.epochs()
        changed = np.nonzero(e != self._staged)[0]
        if self.pcount > 1:
            # collective O(dirty) update: rows are PACKED per device
            # shard, so the pod only needs to agree on the max dirty
            # count any single device sees — the scatter then ships
            # per_host_shards * bucket(max_per_device) rows per host,
            # ~per_host_shards x less than bucketing on per-host totals
            # when writes spread across shards.
            from jax.experimental import multihost_utils
            if changed.size:
                dev_counts = np.bincount(changed // self.tile,
                                         minlength=self.per_host_shards)
                local_max = int(dev_counts.max())
            else:
                local_max = 0
            counts = np.asarray(multihost_utils.process_allgather(
                np.array([local_max], np.int32))).ravel()
            maxc = int(counts.max())
            if maxc == 0:
                return self._arr
            bucket = _bucket(maxc)
            # past the point where the scatter ships as many rows as the
            # lane holds, a full restage is strictly cheaper (bulk
            # load).  Every host sees the same maxc, so the branch is
            # collectively consistent.
            if bucket * self.per_host_shards >= self.local_pad:
                local, self._staged = self._gather_local()
                self._arr = self._place(local)
                self.full_stages += 1
            else:
                self._collective_scatter(changed, bucket)
            return self._arr
        if changed.size:
            vecs, eps = self.store.vec_gather(
                changed.astype(np.uint32))
            ok = eps != self.store.GATHER_TORN
            rows = changed[ok]
            if rows.size:
                self._arr = _scatter_sharded(
                    self._arr, jnp.asarray(rows.astype(np.int32)),
                    jnp.asarray(vecs[ok]))
                self._staged[rows] = eps[ok]
                self.rows_staged += int(rows.size)
        return self._arr

    def _collective_scatter(self, changed: np.ndarray, bucket: int):
        """Multi-process incremental restage: scatter this host's changed
        rows (packed per device shard, padded to the pod-agreed per-device
        `bucket`) into the sharded matrix.

        Every worker executes the SAME program (SPMD discipline); devices
        with fewer dirty rows than the bucket pad with an out-of-bounds
        sentinel slot that the scatter drops.  Rows torn mid-gather stage
        as zeros with an odd staged epoch (never candidates, retried next
        refresh) — identical semantics to the full stage."""
        d = self.store.vec_dim
        rows = changed.astype(np.uint32)
        staged_eps = None
        if rows.size:
            vecs, eps = self.store.vec_gather(rows)
            torn = eps == self.store.GATHER_TORN
            vecs[torn] = 0.0
            staged_eps = np.where(torn, np.uint64(1), eps)
        else:
            vecs = np.zeros((0, d), np.float32)

        # per-device rows in shard-local coordinates, packed into the
        # leading columns; sentinel = tile (one past the end -> dropped
        # by mode='drop')
        lrows = np.full((self.per_host_shards, bucket), self.tile,
                        np.int32)
        lvals = np.zeros((self.per_host_shards, bucket, d), np.float32)
        if rows.size:
            dev = rows // self.tile
            off = rows % self.tile
            for dshard in range(self.per_host_shards):
                sel = dev == dshard
                k = int(sel.sum())
                if k:
                    lrows[dshard, :k] = off[sel]
                    lvals[dshard, :k] = vecs[sel]
        m = self.mesh.shape[self.axis]
        sh_r = NamedSharding(self.mesh, P(self.axis, None))
        sh_v = NamedSharding(self.mesh, P(self.axis, None, None))
        grows = jax.make_array_from_process_local_data(
            sh_r, lrows, (m, bucket))
        gvals = jax.make_array_from_process_local_data(
            sh_v, lvals, (m, bucket, d))
        self._arr = _pod_scatter_program(
            self.mesh, self.axis, bucket, self.tile, d)(
                self._arr, grows, gvals)
        # mark rows staged only AFTER the scatter executed: an exception
        # above must leave them dirty so the next refresh retries them
        # (the single-process path has the same ordering)
        if staged_eps is not None:
            self._staged[rows] = staged_eps
        self.rows_staged += int(rows.size)
        return self._arr

    # -- query -------------------------------------------------------------

    def search(self, query, k: int, *, mask=None, refresh: bool = True,
               use_pallas: bool | None = None,
               mxu_bf16: bool = False) -> list[dict]:
        """Global top-k.  Returns [{host, slot, key, similarity}, ...]
        sorted by similarity desc.  mask: optional per-host (nslots,)
        {0,1} candidate prefilter (bloom/regex/scratch exclusion),
        applied on this host's rows.  Must be called collectively (same
        query, same k on every worker) — standard SPMD discipline."""
        if refresh or self._arr is None:
            self.refresh()
        gmask = self._global_mask(mask)
        s, gi = sharded_topk(self.mesh, self._arr, query, k,
                             mask=gmask, axis=self.axis,
                             use_pallas=use_pallas, mxu_bf16=mxu_bf16)
        keep = s > -1e29
        s, gi = s[keep], gi[keep]
        keys = self._resolve_keys(gi)
        out = []
        for score, g, key in zip(s, gi, keys):
            out.append({"host": int(g) // self.local_pad,
                        "slot": int(g) % self.local_pad,
                        "key": key,
                        "similarity": float(score)})
        return out

    def _global_mask(self, local_mask):
        if local_mask is None:
            return None
        lm = np.zeros(self.local_pad, np.float32)
        lm[: self.local_n] = np.asarray(local_mask, np.float32)
        if self.pcount == 1:
            return jax.device_put(
                lm, NamedSharding(self.mesh, P(self.axis)))
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(self.axis)), lm,
            (self.global_n,))

    def _resolve_keys(self, global_rows: np.ndarray) -> list[str]:
        """Owner hosts contribute key bytes; one DCN allgather merges."""
        from .. import _native as N
        kmax = N.KEY_MAX
        mine = np.zeros((len(global_rows), kmax), np.uint8)
        for j, g in enumerate(np.asarray(global_rows)):
            host = int(g) // self.local_pad
            slot = int(g) % self.local_pad
            if host == self.pid and slot < self.local_n:
                key = self.store.key_at(slot) or ""
                raw = key.encode()[:kmax]
                mine[j, :len(raw)] = np.frombuffer(raw, np.uint8)
        if self.pcount > 1:
            from jax.experimental import multihost_utils
            allk = np.asarray(
                multihost_utils.process_allgather(mine))
            mine = allk.max(axis=0)    # owner's row is the only nonzero
        return [bytes(row[row != 0]).decode(errors="replace")
                for row in mine]


def _bucket(n: int) -> int:
    """Shared pad bucket: few distinct sizes -> few compiled programs."""
    b = 8
    while b < n:
        b *= 8
    return b


@functools.lru_cache(maxsize=64)
def _pod_scatter_program(mesh: Mesh, axis: str, bucket: int, tile: int,
                         d: int):
    """Compiled per-shard scatter for the multi-process incremental
    restage.  Each device owns a (tile, d) block and receives its own
    (bucket,) shard-local row ids + (bucket, d) values; sentinel rows
    (== tile, out of bounds) are dropped."""

    def upd(block, rows, vals):
        return block.at[rows[0]].set(vals[0], mode="drop")

    fn = shard_map(
        upd, mesh=mesh,
        in_specs=(P(axis, None), P(axis, None), P(axis, None, None)),
        out_specs=P(axis, None),
        check_vma=False,
    )
    return jax.jit(fn, donate_argnums=0)


@functools.lru_cache(maxsize=None)
def _scatter_fn():
    @functools.partial(jax.jit, donate_argnums=0)
    def scatter(arr, rows, vals):
        return arr.at[rows].set(vals)
    return scatter


def _scatter_sharded(arr, rows, vals):
    # pad the update to a few bucket sizes so the scatter compiles a
    # handful of times, not per distinct dirty count (cf. StagedLane)
    n = rows.shape[0]
    b = _bucket(n)
    if b != n:
        rows = jnp.concatenate(
            [rows, jnp.broadcast_to(rows[0], (b - n,))])
        vals = jnp.concatenate(
            [vals, jnp.broadcast_to(vals[0], (b - n, vals.shape[1]))])
    return _scatter_fn()(arr, rows, vals)
