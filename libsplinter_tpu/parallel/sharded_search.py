"""Pod-sharded similarity search: the arena's vector lane is sharded
row-wise across the mesh; each device computes local top-k with the
similarity kernel, then an all-gather over ICI merges the per-shard
candidates — exactly the scale-out path the reference deliberately lacks
(RDMA-hostile: splinter_stress.c:358-359; SURVEY.md §2.7 TPU mapping).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.similarity import cosine_scores
from .mesh import shard_map


def sharded_topk(mesh: Mesh, vectors, query, k: int, mask=None,
                 axis: str = "dp") -> tuple[np.ndarray, np.ndarray]:
    """Top-k over row-sharded vectors.

    vectors: (N, D) logically; physically sharded (N/m, D) per device on
    `axis`.  Returns (scores, GLOBAL indices) of the top k.
    """
    n, d = vectors.shape
    m = mesh.shape[axis]
    assert n % m == 0, "row count must divide the mesh axis"
    local_n = n // m
    # each shard can contribute at most local_n candidates; the merged
    # result still returns up to min(k, n) rows
    k_local = min(k, local_n)
    k_final = min(k, n)

    vspec = P(axis, None)
    qspec = P()
    mspec = P(axis)
    out_spec = P()

    def local_then_merge(v_local, q, m_local):
        # local fused scores + top-k on this shard
        scores = cosine_scores(v_local, q, m_local,
                               use_pallas=jax.default_backend() == "tpu")
        s, i = jax.lax.top_k(scores[:, 0], k_local)
        # globalize indices by shard offset
        shard = jax.lax.axis_index(axis)
        gi = i + shard * local_n
        # all-gather candidates over ICI, merge, re-top-k
        all_s = jax.lax.all_gather(s, axis)      # (m, k_local)
        all_i = jax.lax.all_gather(gi, axis)     # (m, k_local)
        ms, mi = jax.lax.top_k(all_s.reshape(-1), k_final)
        return ms, all_i.reshape(-1)[mi]

    fn = shard_map(
        local_then_merge, mesh=mesh,
        in_specs=(vspec, qspec, mspec),
        out_specs=(out_spec, out_spec),
        check_vma=False,
    )
    if mask is None:
        mask = jnp.ones((n,), jnp.float32)
    query = jnp.asarray(query, jnp.float32)
    if query.ndim == 1:
        query = query[None, :]
    s, i = jax.jit(fn)(jnp.asarray(vectors, jnp.float32), query,
                       jnp.asarray(mask, jnp.float32))
    return np.asarray(s), np.asarray(i)


def shard_vectors(mesh: Mesh, vectors, axis: str = "dp"):
    """Place a host (N, D) matrix row-sharded over the mesh axis."""
    return jax.device_put(
        vectors, NamedSharding(mesh, P(axis, None)))
