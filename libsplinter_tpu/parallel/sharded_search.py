"""Pod-sharded similarity search: the arena's vector lane is sharded
row-wise across the mesh; each device computes local top-k with the
similarity kernel, then an all-gather over ICI merges the per-shard
candidates — exactly the scale-out path the reference deliberately lacks
(RDMA-hostile: splinter_stress.c:358-359; SURVEY.md §2.7 TPU mapping).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.similarity import cosine_scores
from .mesh import shard_map


@functools.lru_cache(maxsize=64)
def _topk_program(mesh: Mesh, axis: str, local_n: int, d: int, nq: int,
                  k_local: int, k_final: int, use_pallas: bool):
    """Compiled sharded top-k, cached per (mesh, shapes, k) so repeated
    queries from a live session don't re-trace/re-compile."""

    def local_then_merge(v_local, q, m_local):
        # local fused scores + top-k on this shard
        scores = cosine_scores(v_local, q, m_local,
                               use_pallas=use_pallas)
        s, i = jax.lax.top_k(scores[:, 0], k_local)
        # globalize indices by shard offset
        shard = jax.lax.axis_index(axis)
        gi = i + shard * local_n
        # all-gather candidates over ICI, merge, re-top-k
        all_s = jax.lax.all_gather(s, axis)      # (m, k_local)
        all_i = jax.lax.all_gather(gi, axis)     # (m, k_local)
        ms, mi = jax.lax.top_k(all_s.reshape(-1), k_final)
        return ms, all_i.reshape(-1)[mi]

    fn = shard_map(
        local_then_merge, mesh=mesh,
        in_specs=(P(axis, None), P(), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(fn)


def sharded_topk(mesh: Mesh, vectors, query, k: int, mask=None,
                 axis: str = "dp", use_pallas: bool | None = None
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Top-k over row-sharded vectors.

    vectors: (N, D) logically; physically sharded (N/m, D) per device on
    `axis`.  Returns (scores, GLOBAL indices) of the top k.
    """
    n, d = vectors.shape
    m = mesh.shape[axis]
    assert n % m == 0, "row count must divide the mesh axis"
    local_n = n // m
    # each shard can contribute at most local_n candidates; the merged
    # result still returns up to min(k, n) rows
    k_local = min(k, local_n)
    k_final = min(k, n)
    if use_pallas is None:
        use_pallas = jax.default_backend() == "tpu"

    if mask is None:
        mask = jnp.ones((n,), jnp.float32)
    query = jnp.asarray(query, jnp.float32)
    if query.ndim == 1:
        query = query[None, :]
    fn = _topk_program(mesh, axis, local_n, d, query.shape[0],
                       k_local, k_final, bool(use_pallas))
    s, i = fn(jnp.asarray(vectors, jnp.float32), query,
              jnp.asarray(mask, jnp.float32))
    return np.asarray(s), np.asarray(i)


def shard_vectors(mesh: Mesh, vectors, axis: str = "dp"):
    """Place a host (N, D) matrix row-sharded over the mesh axis."""
    return jax.device_put(
        vectors, NamedSharding(mesh, P(axis, None)))


class PodSearch:
    """End-to-end pod-sharded search over per-host store lanes.

    Every TPU-VM worker runs this SPMD-style with its OWN host-local
    store (SURVEY.md §2.7): each host's (nslots, dim) vector lane —
    zero-padded to the mesh tile — becomes this host's block of one
    global row-sharded device matrix (multihost.local_rows convention:
    global row g lives on host g // local_pad at local slot
    g % local_pad).  search() runs the fused local top-k + ICI
    all-gather merge on the mesh, then resolves winning global rows
    back to (host, key) with one DCN process_allgather of the owning
    hosts' key bytes — device data rides ICI, only control/keys ride
    DCN.

    Staging is epoch-diffed: a refresh with no store writes touches
    nothing; single-process updates scatter only the changed rows into
    the donated device matrix (same economy as ops.StagedLane); in the
    multi-process case any host's change triggers a collective restage
    (every host must participate in array construction).

    Single-process (process_count == 1) degrades to sharding the one
    local lane across the local mesh axis — same code path the
    dryrun exercises on the virtual CPU mesh.
    """

    def __init__(self, store, mesh: Mesh | None = None, *,
                 axis: str = "dp"):
        from .mesh import make_mesh
        from .multihost import init_distributed, process_span

        init_distributed()
        self.store = store
        self.axis = axis
        self.mesh = mesh or make_mesh()
        self.pid, self.pcount = process_span()
        self.local_n = store.nslots
        m = self.mesh.shape[axis]
        if m % self.pcount:
            raise ValueError(
                f"mesh axis {axis}={m} not divisible by "
                f"{self.pcount} processes")
        per_host_shards = m // self.pcount
        # pad each host's block with zero rows to the shard tile; zero
        # vectors are never candidates (cosine_scores nonzero mask)
        self.local_pad = -(-self.local_n // per_host_shards) * \
            per_host_shards
        self.global_n = self.local_pad * self.pcount
        self._arr = None
        self._staged: np.ndarray | None = None   # epochs rows staged at
        # transfer accounting (tests + perf docs)
        self.full_stages = 0
        self.rows_staged = 0

    # -- staging -----------------------------------------------------------

    def _gather_local(self) -> np.ndarray:
        """Full torn-safe local lane, zero-padded to local_pad rows.
        Rows mid-write stage as zeros this pass (never candidates) and
        re-stage next refresh via their unchanged staged epoch."""
        rows = np.arange(self.local_n, dtype=np.uint32)
        vecs, eps = self.store.vec_gather(rows)
        torn = eps == self.store.GATHER_TORN
        vecs[torn] = 0.0
        staged = np.where(torn, np.uint64(1), eps)   # odd = restage
        if self.local_pad != self.local_n:
            vecs = np.pad(vecs,
                          ((0, self.local_pad - self.local_n), (0, 0)))
        return vecs, staged

    def _place(self, local: np.ndarray):
        sharding = NamedSharding(self.mesh, P(self.axis, None))
        if self.pcount == 1:
            return shard_vectors(self.mesh, local, self.axis)
        return jax.make_array_from_process_local_data(
            sharding, local, (self.global_n, local.shape[1]))

    def refresh(self):
        """Bring the sharded matrix up to date (epoch-diffed)."""
        if self._arr is None:
            local, self._staged = self._gather_local()
            self._arr = self._place(local)
            self.full_stages += 1
            return self._arr
        e = self.store.epochs()
        changed = np.nonzero(e != self._staged)[0]
        any_changed = changed.size > 0
        if self.pcount > 1:
            # collective decision: every host must agree to restage
            from jax.experimental import multihost_utils
            flags = np.asarray(multihost_utils.process_allgather(
                np.array([any_changed], np.int32)))
            if flags.max() > 0:
                local, self._staged = self._gather_local()
                self._arr = self._place(local)
                self.full_stages += 1
            return self._arr
        if any_changed:
            vecs, eps = self.store.vec_gather(
                changed.astype(np.uint32))
            ok = eps != self.store.GATHER_TORN
            rows = changed[ok]
            if rows.size:
                self._arr = _scatter_sharded(
                    self._arr, jnp.asarray(rows.astype(np.int32)),
                    jnp.asarray(vecs[ok]))
                self._staged[rows] = eps[ok]
                self.rows_staged += int(rows.size)
        return self._arr

    # -- query -------------------------------------------------------------

    def search(self, query, k: int, *, mask=None, refresh: bool = True,
               use_pallas: bool | None = None) -> list[dict]:
        """Global top-k.  Returns [{host, slot, key, similarity}, ...]
        sorted by similarity desc.  mask: optional per-host (nslots,)
        {0,1} candidate prefilter (bloom/regex/scratch exclusion),
        applied on this host's rows.  Must be called collectively (same
        query, same k on every worker) — standard SPMD discipline."""
        if refresh or self._arr is None:
            self.refresh()
        gmask = self._global_mask(mask)
        s, gi = sharded_topk(self.mesh, self._arr, query, k,
                             mask=gmask, axis=self.axis,
                             use_pallas=use_pallas)
        keep = s > -1e29
        s, gi = s[keep], gi[keep]
        keys = self._resolve_keys(gi)
        out = []
        for score, g, key in zip(s, gi, keys):
            out.append({"host": int(g) // self.local_pad,
                        "slot": int(g) % self.local_pad,
                        "key": key,
                        "similarity": float(score)})
        return out

    def _global_mask(self, local_mask):
        if local_mask is None:
            return None
        lm = np.zeros(self.local_pad, np.float32)
        lm[: self.local_n] = np.asarray(local_mask, np.float32)
        if self.pcount == 1:
            return jax.device_put(
                lm, NamedSharding(self.mesh, P(self.axis)))
        return jax.make_array_from_process_local_data(
            NamedSharding(self.mesh, P(self.axis)), lm,
            (self.global_n,))

    def _resolve_keys(self, global_rows: np.ndarray) -> list[str]:
        """Owner hosts contribute key bytes; one DCN allgather merges."""
        from .. import _native as N
        kmax = N.KEY_MAX
        mine = np.zeros((len(global_rows), kmax), np.uint8)
        for j, g in enumerate(np.asarray(global_rows)):
            host = int(g) // self.local_pad
            slot = int(g) % self.local_pad
            if host == self.pid and slot < self.local_n:
                key = self.store.key_at(slot) or ""
                raw = key.encode()[:kmax]
                mine[j, :len(raw)] = np.frombuffer(raw, np.uint8)
        if self.pcount > 1:
            from jax.experimental import multihost_utils
            allk = np.asarray(
                multihost_utils.process_allgather(mine))
            mine = allk.max(axis=0)    # owner's row is the only nonzero
        return [bytes(row[row != 0]).decode(errors="replace")
                for row in mine]


@functools.lru_cache(maxsize=None)
def _scatter_fn():
    @functools.partial(jax.jit, donate_argnums=0)
    def scatter(arr, rows, vals):
        return arr.at[rows].set(vals)
    return scatter


def _scatter_sharded(arr, rows, vals):
    # pad the update to a few bucket sizes so the scatter compiles a
    # handful of times, not per distinct dirty count (cf. StagedLane)
    n = rows.shape[0]
    b = 64
    while b < n:
        b *= 8
    if b != n:
        rows = jnp.concatenate(
            [rows, jnp.broadcast_to(rows[0], (b - n,))])
        vals = jnp.concatenate(
            [vals, jnp.broadcast_to(vals[0], (b - n, vals.shape[1]))])
    return _scatter_fn()(arr, rows, vals)
