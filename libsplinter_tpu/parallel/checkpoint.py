"""Trainer checkpoint / resume (orbax-backed).

The reference's checkpoint story is the persistent store mapping — the
store IS the checkpoint (SURVEY.md §5, splinter.c:157-168); it has no
trainer to checkpoint.  This framework trains the encoder
(parallel/train.py), so training state needs its own durable story:

  - save(state, path, step): atomic orbax write of the full TrainState
    pytree (params + optimizer state + step counter) keyed by step;
  - restore(path[, step]): back to a TrainState, optionally resharded
    onto a mesh (restore on a different topology than the save — the
    arrays are placed per the trainer's own param/opt specs);
  - latest_step(path): resume-from-newest without bookkeeping files.

Works for single-device and mesh-sharded states alike: orbax persists
the addressable shards and the restore path re-places them.
"""
from __future__ import annotations

import os

import jax
import numpy as np

from .train import TrainState


def _manager(path: str):
    import orbax.checkpoint as ocp

    return ocp.CheckpointManager(
        os.path.abspath(path),
        options=ocp.CheckpointManagerOptions(max_to_keep=3,
                                             create=True))


def save(state: TrainState, path: str, *, step: int | None = None) -> int:
    """Persist the TrainState under `path` keyed by `step` (defaults to
    state.step).  Returns the step saved.  Keeps the newest 3.
    Blocking: the manager is closed before returning (close() waits for
    the write), so the checkpoint is durable when this returns — hold a
    long-lived CheckpointManager yourself if you want async saves."""
    import orbax.checkpoint as ocp

    step = int(state.step) if step is None else int(step)
    mgr = _manager(path)
    mgr.save(step, args=ocp.args.StandardSave(state._asdict()))
    mgr.close()
    return step


def latest_step(path: str) -> int | None:
    """Newest saved step under `path`, or None if nothing is there."""
    if not os.path.isdir(path):
        return None
    mgr = _manager(path)
    step = mgr.latest_step()
    mgr.close()
    return step


def restore(path: str, like: TrainState, *,
            step: int | None = None) -> TrainState:
    """Load a TrainState.  step=None resumes the newest save.

    `like` is REQUIRED: a freshly-initialized TrainState from the
    trainer that will resume.  It supplies (a) the pytree STRUCTURE —
    optimizer states are optax NamedTuples that a structure-free
    restore would flatten into dicts — and (b) the target shardings,
    so a single-device save resumes directly onto a mesh-sharded
    trainer (or vice versa) with arrays placed where that trainer
    expects them."""
    import orbax.checkpoint as ocp

    mgr = _manager(path)
    step = mgr.latest_step() if step is None else step
    if step is None:
        mgr.close()
        raise FileNotFoundError(f"no checkpoint under {path}")

    def absify(x):
        sh = getattr(x, "sharding", None)
        if not isinstance(sh, jax.sharding.Sharding):
            sh = None
        return jax.ShapeDtypeStruct(np.shape(x),
                                    getattr(x, "dtype", np.float32),
                                    sharding=sh)

    tmpl = jax.tree.map(absify, like._asdict())
    out = mgr.restore(step, args=ocp.args.StandardRestore(tmpl))
    mgr.close()
    return TrainState(**out)


def save_params_npz(params, path: str) -> None:
    """Flat .npz export of a param tree (interchange/debugging; the
    orbax path above is the durable trainer format)."""
    flat = {}
    for kp, leaf in jax.tree_util.tree_leaves_with_path(params):
        key = "/".join(getattr(p, "key", str(p)) for p in kp)
        flat[key] = np.asarray(leaf)
    np.savez(path, **flat)
