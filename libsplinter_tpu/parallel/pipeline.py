"""Pipeline parallelism for the encoder: GPipe-style microbatching over
the mesh's `pp`-capable axis.

The reference has no model execution to pipeline (single-context
llama.cpp per daemon, SURVEY.md §2.7); this is the TPU-first path for
encoders whose layer stack exceeds one chip's HBM.  The design follows
the JAX SPMD recipe rather than a scheduler thread pool:

  - the transformer LAYER stack is the pipelined region: layer params
    stack into a leading (stages, layers_per_stage, ...) axis and shard
    P(axis) — each device physically holds only its stage's layers;
  - inside one shard_map, a lax.scan runs the GPipe schedule: at step t
    stage s processes microbatch (t - s); activations hop stage→stage
    with lax.ppermute (ICI neighbor traffic, no host involvement);
    warm-up/drain bubble steps compute garbage that is masked out of
    the output buffer;
  - embedding lookup and the pooling head replicate (they are a tiny
    fraction of FLOPs/bytes); the last stage's collected outputs are
    re-replicated with one psum;
  - everything is differentiable (ppermute/scan/where), so jax.grad
    through pipeline_encode yields pipeline-parallel training with no
    extra machinery.

Exact-parity contract: pipeline_encode(...) == Encoder.apply(...) for
any stage count and microbatch split — pinned by
tests/test_pipeline.py on the virtual CPU mesh.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.encoder import EncoderConfig, EncoderLayer, pool_normalize
from .mesh import shard_map


def stack_layer_params(params, cfg: EncoderConfig):
    """Stack layer_0..layer_{L-1} subtrees into leading-axis arrays."""
    p = params["params"] if "params" in params else params
    layers = [p[f"layer_{i}"] for i in range(cfg.layers)]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *layers)


def stage_params(params, cfg: EncoderConfig, mesh: Mesh,
                 axis: str = "pp"):
    """ONE-TIME setup for the pipeline: split the param tree into
    (outer, staged) and PLACE them —

      outer  = non-layer params (tok_emb, ln_emb) replicated;
      staged = layer params stacked to (stages, layers_per_stage, ...)
               and sharded P(axis), so each device physically holds
               only its own stage's layers.

    This is where the HBM win happens: pass the result to
    make_pipeline_encode_fn / pipeline_encode_staged and the full
    layer stack never materializes on any single chip.  (The
    convenience wrapper pipeline_encode() stages a replicated tree on
    every call — fine for tests and parity checks, NOT the
    big-model path.)"""
    stages = mesh.shape[axis]
    if cfg.layers % stages:
        raise ValueError(f"layers={cfg.layers} must divide into "
                         f"{stages} pipeline stages")
    per = cfg.layers // stages
    p = params["params"] if "params" in params else params
    outer = {k: v for k, v in p.items() if not k.startswith("layer_")}
    stacked = stack_layer_params(params, cfg)
    stacked = jax.tree.map(
        lambda a: a.reshape((stages, per) + a.shape[1:]), stacked)
    staged = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P(axis))),
        stacked)
    outer = jax.tree.map(
        lambda a: jax.device_put(a, NamedSharding(mesh, P())), outer)
    return outer, staged


def pipeline_encode(cfg: EncoderConfig, mesh: Mesh, params,
                    token_ids, attn_mask, *, microbatches: int,
                    axis: str = "pp"):
    """Convenience wrapper: stage a (replicated) param tree and run one
    pipelined forward.  token_ids: (B, S) int32; attn_mask: (B, S)
    bool.  Returns (B, out_dim) float32 — identical to Encoder.apply
    on the same params.  For repeated use (and for models that only
    fit BECAUSE of pipelining) call stage_params() once and use
    make_pipeline_encode_fn / pipeline_encode_staged instead."""
    outer, staged = stage_params(params, cfg, mesh, axis)
    return pipeline_encode_staged(cfg, mesh, outer, staged,
                                  token_ids, attn_mask,
                                  microbatches=microbatches, axis=axis)


def pipeline_encode_staged(cfg: EncoderConfig, mesh: Mesh, outer, staged,
                           token_ids, attn_mask, *, microbatches: int,
                           axis: str = "pp"):
    """Pipelined encoder forward over pre-staged params (stage_params).
    Differentiable w.r.t. (outer, staged)."""
    if cfg.variant != "nomic":
        raise ValueError("pipeline_encode supports the rotary 'nomic' "
                         "variant (bert adds a position table)")
    if cfg.ring_axis:
        raise ValueError(
            "pipeline_encode is mutually exclusive with ring_axis: the "
            "layers would treat the replicated sequence as sp-local "
            "chunks and silently mis-position/mis-pool — compose pp "
            "with dp/tp instead")
    stages = mesh.shape[axis]
    B, S = token_ids.shape
    M = microbatches
    if B % M:
        raise ValueError(f"batch {B} must divide into {M} microbatches")
    mb = B // M

    # replicated pre-stage: the SAME nn modules Encoder.__call__ runs,
    # applied over the outer params (no math duplicated to drift)
    x = nn.Embed(cfg.vocab_size, cfg.hidden, dtype=cfg.dtype) \
        .apply({"params": outer["tok_emb"]}, jnp.asarray(token_ids))
    x = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=cfg.dtype) \
        .apply({"params": outer["ln_emb"]}, x)

    x_mb = x.reshape(M, mb, S, cfg.hidden)
    m_mb = jnp.asarray(attn_mask, bool).reshape(M, mb, S)

    layer = EncoderLayer(cfg)

    def stage_fn(stage_params, xin, mask):
        def body(h, lp):
            return layer.apply({"params": lp}, h, mask), None
        out, _ = jax.lax.scan(body, xin, stage_params)
        return out

    def pipelined(stage_params, x_mb, m_mb):
        # stage_params arrives as (1, per, ...): this device's stage
        stage_params = jax.tree.map(lambda a: a[0], stage_params)
        s = jax.lax.axis_index(axis)
        n_steps = M + stages - 1
        zero = jnp.zeros((mb, S, cfg.hidden), cfg.dtype)
        out_buf = jnp.zeros((M, mb, S, jnp.shape(x_mb)[-1]), cfg.dtype)

        def step(carry, t):
            recv, out_buf = carry
            mb_idx = jnp.clip(t - s, 0, M - 1)   # my microbatch this step
            inp = jnp.where(s == 0, x_mb[mb_idx], recv)
            out = stage_fn(stage_params, inp, m_mb[mb_idx])
            # collect at the last stage (valid once the pipe is full)
            done_idx = jnp.clip(t - (stages - 1), 0, M - 1)
            upd = jax.lax.dynamic_update_index_in_dim(
                out_buf, out, done_idx, 0)
            take = jnp.logical_and(s == stages - 1, t >= stages - 1)
            out_buf = jnp.where(take, upd, out_buf)
            # hop stage s -> s+1 (no wraparound: stage 0 feeds fresh
            # microbatches; a device with no sender receives zeros)
            nxt = jax.lax.ppermute(
                out, axis, [(i, i + 1) for i in range(stages - 1)])
            return (nxt, out_buf), None

        (_, out_buf), _ = jax.lax.scan(
            step, (zero, out_buf), jnp.arange(n_steps))
        # pool BEFORE re-replicating: the end-of-pipe collective then
        # carries (M, mb, out_dim), not the S-times-larger activations.
        # The head is the shared pool_normalize (encoder.py) so the
        # tail cannot drift from Encoder.__call__; on non-last stages
        # out_buf is all zeros, so the pooled value is zeros too (no
        # NaN) and the where+psum discards it.
        pooled = pool_normalize(cfg, out_buf, m_mb)   # (M, mb, out)
        return jax.lax.psum(
            jnp.where(s == stages - 1, pooled, 0.0), axis)

    fn = shard_map(
        pipelined, mesh=mesh,
        in_specs=(P(axis), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    return fn(staged, x_mb, m_mb).reshape(B, cfg.out_dim)


def make_pipeline_encode_fn(cfg: EncoderConfig, mesh: Mesh, params, *,
                            microbatches: int, axis: str = "pp"):
    """Stage the params ONCE (each device keeps only its stage's
    layers; see stage_params) and return a jitted
    fn(token_ids, attn_mask) -> (B, out_dim)."""
    outer, staged = stage_params(params, cfg, mesh, axis)

    @jax.jit
    def fn(token_ids, attn_mask):
        return pipeline_encode_staged(
            cfg, mesh, outer, staged, token_ids, attn_mask,
            microbatches=microbatches, axis=axis)
    return fn
