"""Tensor-parallel completion serving: the decoder sharded over a mesh.

The reference's completion sidecar is single-context llama.cpp on one
CPU (splainference.cpp:414-448 — one model, one ctx, one seq); model
parallelism simply does not exist there (SURVEY.md §2.7).  On TPU a
completion model larger than one chip's HBM — or one that wants more
MXU per token — shards Megatron-style over the mesh's `tp` axis:

  - q/k/v and gate/up Dense kernels split their OUTPUT dim (heads /
    mlp lanes) across tp — column parallel;
  - out and down kernels split their INPUT dim — row parallel, so each
    transformer block needs exactly one psum pair, which XLA inserts
    from the shardings (GSPMD propagation; no hand-written
    collectives);
  - the KV cache shards on its kv_heads axis, so attention stays fully
    local per device (GQA's head-repeat also stays local because query
    heads shard consistently with kv heads);
  - the PAGED block pools (models/decoder.PagedKVCache) shard the same
    kv-head axis: every device holds every page at 1/tp of its bytes,
    so the host-side page scheduler (tables, lengths, alloc/free,
    admission backpressure) is byte-identical to the single-chip pool
    while cache HBM per chip divides by tp.  The ragged paged-decode
    and flash-prefill Pallas kernels run under shard_map (GSPMD cannot
    partition a Mosaic custom call) with query heads sharded
    consistently — one psum pair per block still comes from the
    row/column-parallel Dense shardings, nothing hand-written;
  - embeddings and the LM head stay replicated: logits come out
    replicated, so the in-graph sampler (and therefore the whole
    decode_chunk lax.scan) runs identically on every device with the
    same rng — no gather before sampling.

ShardedCompletionModel IS a CompletionModel: same prefill / decode_one /
decode_chunk / generate_tokens surface, same compiled-program caching,
AND the same paged continuous-batching surface (init_paged /
paged_prefill_row / paged_decode_chunk — paged_supported is True), so
the completion daemon (engine.completer run_continuous, the K-deep
in-flight window, the supervisor) drives it unchanged — scale-out is a
constructor swap.

Requires cfg.heads % tp == 0 and cfg.kv_heads % tp == 0.
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.decoder import CompletionModel, Decoder, init_cache
from .mesh import kv_pool_sharding, kv_scale_sharding, make_mesh


def decoder_param_pspec(path: tuple, leaf) -> P:
    """Megatron-style partition specs for Decoder / MoeDecoder params:
    attention + dense MLP shard on tp; stacked MoE expert tensors shard
    their expert axis on ep (models/moe.py); routers/norms/embeddings/
    lm head replicate."""
    names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
    joined = "/".join(str(n) for n in names)
    if leaf.ndim == 3 and joined.endswith("_experts"):
        return P("ep", None, None)            # expert parallel
    if leaf.ndim == 4 and joined.endswith("_experts_q"):
        return P("ep", None, None, None)      # int8 expert blocks
    if leaf.ndim == 3 and joined.endswith("_experts_scale"):
        return P("ep", None, None)
    # int8-resident projections (models/quant.py QuantDense): q is
    # (in_blocks, 32, out), scale is (in_blocks, out) — column-parallel
    # layers shard out, row-parallel layers shard the input blocks
    last2 = joined.rsplit("/", 2)[-2:]
    if len(last2) == 2 and last2[1] in ("q", "scale") \
            and last2[0] in ("q", "k", "v", "gate", "up", "out", "down"):
        colp = last2[0] in ("q", "k", "v", "gate", "up")
        if last2[1] == "q":                   # (nb, 32, out) int8
            return P(None, None, "tp") if colp else P("tp", None, None)
        return P(None, "tp") if colp else P("tp", None)   # (nb, out)
    # per-output-channel int8 projections (models/quant.py
    # ChannelQuantDense, --weights int8): wq is (in, out), wscale is
    # (out,).  The scale vector shards WITH the output columns it
    # scales on column-parallel layers; on row-parallel layers the
    # outputs are full-width partial sums, so wscale replicates —
    # scaling each partial sum before the psum is exact because the
    # multiply distributes over the sum.
    if len(last2) == 2 and last2[1] in ("wq", "wscale") \
            and last2[0] in ("q", "k", "v", "gate", "up", "out", "down"):
        colp = last2[0] in ("q", "k", "v", "gate", "up")
        if last2[1] == "wq":                  # (in, out) int8
            return P(None, "tp") if colp else P("tp", None)
        return P("tp") if colp else P()       # (out,) f32
    if leaf.ndim == 2:
        if "router" in joined:
            return P()                        # tiny: replicate
        if joined.endswith("kernel"):
            last = joined.rsplit("/", 2)[-2] if "/" in joined else ""
            if last in ("q", "k", "v", "gate", "up"):
                return P(None, "tp")          # column parallel
            if last in ("out", "down"):
                return P("tp", None)          # row parallel
    return P()                                # norms, embeddings, lm head


def shard_decoder_params(params, mesh: Mesh):
    """Place a Decoder param tree onto the mesh per decoder_param_pspec."""
    from .mesh import shard_params
    return shard_params(params, mesh, pspec_fn=decoder_param_pspec)


class ShardedCompletionModel(CompletionModel):
    """CompletionModel whose params + KV cache live sharded on a mesh.

    Everything above the placement is inherited: the same jitted
    programs run over sharded arrays and GSPMD inserts the block psums.
    The paged continuous-batching surface is inherited too — the pools
    it allocates are kv-head-sharded (_pool_sharding) and the default
    Decoder module threads the mesh into the shard_map'd attention
    kernels, so flash prefill is no longer demoted to the naive path
    and paged_supported stays True.
    """

    paged_supported = True

    def __init__(self, cfg, mesh: Mesh | None = None, **kw):
        self.mesh = mesh or make_mesh()
        tp = self.mesh.shape["tp"]
        if cfg.heads % tp or cfg.kv_heads % tp:
            raise ValueError(
                f"heads={cfg.heads}/kv_heads={cfg.kv_heads} must divide "
                f"the tp={tp} mesh axis")
        if kw.get("module") is None:
            # the default trunk, with the mesh threaded into the
            # attention kernels (CausalAttention.mesh): flash prefill
            # and ragged paged decode run under shard_map instead of
            # breaking the tp-sharded program on a Mosaic custom call
            kw["module"] = Decoder(cfg, mesh=self.mesh)
        elif getattr(kw["module"], "mesh", None) is None and tp > 1:
            # a custom module built WITHOUT the mesh cannot run the
            # Pallas kernels under GSPMD — leave the paged lane off
            # for this instance (the completion daemon then serves
            # dense, engine/completer._paged_ok); builders that want
            # the paged lane thread the mesh at module construction
            # (models/moe.MoeDecoder does).  The module's own closed-
            # over flash_min_seq is out of our reach (it was under the
            # pre-PR-8 cfg demotion too, which only replaced THIS
            # class's copy), so on TPU a long prefill chunk would
            # still hit the un-shard_map'd flash kernel inside the
            # tp-sharded program — warn loudly instead of failing in
            # the first long prompt's compile
            self.paged_supported = False
            mcfg = getattr(kw["module"], "cfg", None)
            if getattr(mcfg, "flash_min_seq", 0):
                import logging
                logging.getLogger("libsplinter_tpu.serve").warning(
                    "sharded serving with a meshless module whose "
                    "flash_min_seq=%d is nonzero: prefill chunks at/"
                    "above it route through a Pallas kernel GSPMD "
                    "cannot partition on TPU — build the module with "
                    "mesh= (or flash_min_seq=0) for tp>1",
                    mcfg.flash_min_seq)
        super().__init__(cfg, **kw)
        self.params = shard_decoder_params(self.params, self.mesh)

    def _fresh_cache(self, batch: int = 1):
        sh = NamedSharding(self.mesh, P(None, None, "tp", None))
        return [(jax.device_put(k, sh), jax.device_put(v, sh))
                for k, v in init_cache(self.cfg, batch)]

    # -- paged pool placement (the pod-sharded continuous lane) --------

    def _pool_sharding(self):
        """(n_blocks, KH, page, D) pools split on kv heads over tp —
        the sharding the shard_map'd ragged kernel expects."""
        return kv_pool_sharding(self.mesh)

    def _pool_scale_sharding(self):
        """int8 pools' (n_blocks, KH) per-page scales split on THEIR
        kv-head axis — scales shard with the heads they scale, so the
        quantized ragged kernel's per-device scalar-prefetch tables
        shrink by tp alongside the pools."""
        return kv_scale_sharding(self.mesh)

    def _paged_scratch(self, b: int):
        """Paged prefill's (1, bucket) dense scratch, sharded like the
        dense cache (kv heads on tp): the trunk runs the same sharded
        geometry as every other program and the per-bucket commit
        scatter into the sharded pool stays local per device.  The
        creation program comes from the SAME cached factory the pools
        use (decoder._pool_zeros) — one compile per (shape, sharding),
        never one per join."""
        from ..models.decoder import _pool_zeros
        cfg = self.cfg
        sh = NamedSharding(self.mesh, P(None, None, "tp", None))
        mk = _pool_zeros((1, b, cfg.kv_heads, cfg.head_dim),
                         cfg.dtype, sh)
        return [(mk(), mk()) for _ in range(cfg.layers)]
