"""Tensor-parallel completion serving: the decoder sharded over a mesh.

The reference's completion sidecar is single-context llama.cpp on one
CPU (splainference.cpp:414-448 — one model, one ctx, one seq); model
parallelism simply does not exist there (SURVEY.md §2.7).  On TPU a
completion model larger than one chip's HBM — or one that wants more
MXU per token — shards Megatron-style over the mesh's `tp` axis:

  - q/k/v and gate/up Dense kernels split their OUTPUT dim (heads /
    mlp lanes) across tp — column parallel;
  - out and down kernels split their INPUT dim — row parallel, so each
    transformer block needs exactly one psum pair, which XLA inserts
    from the shardings (GSPMD propagation; no hand-written
    collectives);
  - the KV cache shards on its kv_heads axis, so attention stays fully
    local per device (GQA's head-repeat also stays local because query
    heads shard consistently with kv heads);
  - embeddings and the LM head stay replicated: logits come out
    replicated, so the in-graph sampler (and therefore the whole
    decode_chunk lax.scan) runs identically on every device with the
    same rng — no gather before sampling.

ShardedCompletionModel IS a CompletionModel: same prefill / decode_one /
decode_chunk / generate_tokens surface, same compiled-program caching,
so the completion daemon (engine.completer) drives it unchanged —
scale-out is a constructor swap.

Requires cfg.heads % tp == 0 and cfg.kv_heads % tp == 0.
"""
from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.decoder import CompletionModel, init_cache
from .mesh import make_mesh


def decoder_param_pspec(path: tuple, leaf) -> P:
    """Megatron-style partition specs for Decoder / MoeDecoder params:
    attention + dense MLP shard on tp; stacked MoE expert tensors shard
    their expert axis on ep (models/moe.py); routers/norms/embeddings/
    lm head replicate."""
    names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
    joined = "/".join(str(n) for n in names)
    if leaf.ndim == 3 and joined.endswith("_experts"):
        return P("ep", None, None)            # expert parallel
    if leaf.ndim == 4 and joined.endswith("_experts_q"):
        return P("ep", None, None, None)      # int8 expert blocks
    if leaf.ndim == 3 and joined.endswith("_experts_scale"):
        return P("ep", None, None)
    # int8-resident projections (models/quant.py QuantDense): q is
    # (in_blocks, 32, out), scale is (in_blocks, out) — column-parallel
    # layers shard out, row-parallel layers shard the input blocks
    last2 = joined.rsplit("/", 2)[-2:]
    if len(last2) == 2 and last2[1] in ("q", "scale") \
            and last2[0] in ("q", "k", "v", "gate", "up", "out", "down"):
        colp = last2[0] in ("q", "k", "v", "gate", "up")
        if last2[1] == "q":                   # (nb, 32, out) int8
            return P(None, None, "tp") if colp else P("tp", None, None)
        return P(None, "tp") if colp else P("tp", None)   # (nb, out)
    if leaf.ndim == 2:
        if "router" in joined:
            return P()                        # tiny: replicate
        if joined.endswith("kernel"):
            last = joined.rsplit("/", 2)[-2] if "/" in joined else ""
            if last in ("q", "k", "v", "gate", "up"):
                return P(None, "tp")          # column parallel
            if last in ("out", "down"):
                return P("tp", None)          # row parallel
    return P()                                # norms, embeddings, lm head


def shard_decoder_params(params, mesh: Mesh):
    """Place a Decoder param tree onto the mesh per decoder_param_pspec."""
    from .mesh import shard_params
    return shard_params(params, mesh, pspec_fn=decoder_param_pspec)


class ShardedCompletionModel(CompletionModel):
    """CompletionModel whose params + KV cache live sharded on a mesh.

    Everything above the placement is inherited: the same jitted
    programs run over sharded arrays and GSPMD inserts the block psums.
    """

    # the paged pool is host-scheduled and unsharded; until the pools
    # get a tp placement (and the paged kernel a shard_map), sharded
    # serving stays on the dense batched path
    paged_supported = False

    def __init__(self, cfg, mesh: Mesh | None = None, **kw):
        import dataclasses

        self.mesh = mesh or make_mesh()
        tp = self.mesh.shape["tp"]
        if cfg.heads % tp or cfg.kv_heads % tp:
            raise ValueError(
                f"heads={cfg.heads}/kv_heads={cfg.kv_heads} must divide "
                f"the tp={tp} mesh axis")
        if cfg.flash_min_seq:
            # GSPMD cannot partition a Mosaic (Pallas) custom call, so
            # the flash prefill kernel would break (or force full
            # replication of) the tp-sharded program — sharded serving
            # prefills through the naive path; a shard_map'd kernel is
            # future work
            cfg = dataclasses.replace(cfg, flash_min_seq=0)
        super().__init__(cfg, **kw)
        self.params = shard_decoder_params(self.params, self.mesh)

    def _fresh_cache(self, batch: int = 1):
        sh = NamedSharding(self.mesh, P(None, None, "tp", None))
        return [(jax.device_put(k, sh), jax.device_put(v, sh))
                for k, v in init_cache(self.cfg, batch)]
