"""Distributed contrastive training step for the embedding encoder.

The reference has no training at all (SURVEY.md §2.7) — embedding models
arrive as GGUF files.  A TPU-native framework owns its weights, so this
module provides the canonical way embedding encoders are actually
produced: in-batch InfoNCE over text pairs, sharded dp×tp over a device
mesh.  Shardings are declared with jax.sharding; XLA inserts the psum /
all-gather collectives over ICI.
"""
from __future__ import annotations

import dataclasses
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from ..models import Encoder, EncoderConfig
from .mesh import (batch_sharding, param_shardings, replicated,
                   shard_map, shard_params)


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def info_nce_loss(za: jnp.ndarray, zb: jnp.ndarray,
                  temperature: float = 0.05) -> jnp.ndarray:
    """Symmetric in-batch InfoNCE: row i of za matches row i of zb."""
    logits = (za @ zb.T) / temperature
    labels = jnp.arange(za.shape[0])
    l_ab = optax.softmax_cross_entropy_with_integer_labels(logits, labels)
    l_ba = optax.softmax_cross_entropy_with_integer_labels(logits.T, labels)
    return (l_ab.mean() + l_ba.mean()) / 2.0


def make_train_step(cfg: EncoderConfig, optimizer=None,
                    temperature: float = 0.05):
    """Returns (init_fn, step_fn).  step_fn(state, batch) -> (state, loss).
    batch: dict(ids_a, mask_a, ids_b, mask_b)."""
    module = Encoder(cfg)
    optimizer = optimizer or optax.adamw(1e-4, weight_decay=0.01)

    def init_fn(rng, sample_ids, sample_mask):
        params = module.init(rng, sample_ids, sample_mask)
        return TrainState(params, optimizer.init(params),
                          jnp.zeros((), jnp.int32))

    def loss_fn(params, batch):
        za = module.apply(params, batch["ids_a"], batch["mask_a"])
        zb = module.apply(params, batch["ids_b"], batch["mask_b"])
        return info_nce_loss(za, zb, temperature)

    def step_fn(state: TrainState, batch) -> tuple[TrainState, jnp.ndarray]:
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return init_fn, step_fn


def make_sharded_train_step(cfg: EncoderConfig, mesh, optimizer=None,
                            temperature: float = 0.05):
    """jit the train step over the mesh with dp batch sharding and tp
    parameter sharding.  Returns (sharded_init, sharded_step)."""
    init_fn, step_fn = make_train_step(cfg, optimizer, temperature)
    bsh = batch_sharding(mesh)

    def sharded_init(rng, sample_ids, sample_mask):
        state = init_fn(rng, sample_ids, sample_mask)
        p_sh = param_shardings(state.params, mesh)
        params = shard_params(state.params, mesh)
        # optimizer state mirrors the param tree sharding where shaped
        # like params; scalars replicate
        def opt_place(x):
            return jax.device_put(x, replicated(mesh))
        opt_state = jax.tree_util.tree_map(opt_place, state.opt_state)
        state = TrainState(params, opt_state,
                           jax.device_put(state.step, replicated(mesh)))

        batch_shardings = {k: bsh for k in
                           ("ids_a", "mask_a", "ids_b", "mask_b")}
        opt_shardings = jax.tree_util.tree_map(
            lambda x: replicated(mesh), state.opt_state)
        state_shardings = TrainState(p_sh, opt_shardings, replicated(mesh))
        step = jax.jit(
            step_fn,
            in_shardings=(state_shardings, batch_shardings),
            out_shardings=(state_shardings, replicated(mesh)),
        )
        return state, step

    return sharded_init


def make_ring_train_step(cfg: EncoderConfig, mesh, optimizer=None,
                         temperature: float = 0.05):
    """Sequence-parallel (ring attention) training step under shard_map.

    cfg.ring_axis names the mesh sequence axis (conventionally "sp");
    batches arrive sharded (batch over dp) x (sequence over sp), each
    device runs the encoder on its O(S/n_sp) chunk with K/V rotating over
    ICI, and embeddings are all-gathered over dp for in-batch InfoNCE.

    Gradient correctness: the per-device losses are N identical replicas
    of the global loss (N = n_dp * n_sp), so the joint backward computes
    d(N*L)/dtheta spread across the devices' local parameter cotangents;
    psum over both axes then /N recovers the exact gradient (the same
    broadcast-transpose argument that makes replicated-parameter pmap
    training work).

    Returns (init_fn, step_fn); step_fn(state, batch) -> (state, loss)
    with batch dict(ids_a, mask_a, ids_b, mask_b) as GLOBAL arrays.
    """
    if not cfg.ring_axis or cfg.ring_axis not in mesh.axis_names:
        raise ValueError("cfg.ring_axis must name a mesh axis (e.g. 'sp')")
    axis = cfg.ring_axis
    n_total = mesh.shape["dp"] * mesh.shape[axis]
    module = Encoder(cfg)
    optimizer = optimizer or optax.adamw(1e-4, weight_decay=0.01)

    def init_fn(rng, sample_ids, sample_mask):
        # init with a dense twin: identical param tree, no axis context
        dense = Encoder(dataclasses.replace(cfg, ring_axis=None))
        params = dense.init(rng, sample_ids, sample_mask)
        return TrainState(params, optimizer.init(params),
                          jnp.zeros((), jnp.int32))

    def local_step(state, ids_a, mask_a, ids_b, mask_b):
        def loss_fn(params):
            za = module.apply(params, ids_a, mask_a)
            zb = module.apply(params, ids_b, mask_b)
            za_g = lax.all_gather(za, "dp", axis=0, tiled=True)
            zb_g = lax.all_gather(zb, "dp", axis=0, tiled=True)
            return info_nce_loss(za_g, zb_g, temperature)
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        grads = jax.tree_util.tree_map(
            lambda g: lax.psum(g, ("dp", axis)) / n_total, grads)
        updates, opt_state = optimizer.update(grads, state.opt_state,
                                              state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    batch_spec = P("dp", axis)
    step = jax.jit(shard_map(
        local_step, mesh=mesh,
        in_specs=(P(), batch_spec, batch_spec, batch_spec, batch_spec),
        out_specs=(P(), P()),
        check_vma=False,
    ))

    def step_fn(state: TrainState, batch) -> tuple[TrainState, jnp.ndarray]:
        return step(state, batch["ids_a"], batch["mask_a"],
                    batch["ids_b"], batch["mask_b"])

    return init_fn, step_fn
