"""Device mesh helpers for pod-scale execution.

The reference is single-machine by design ("Multi-machine replication —
use a real database", README.md:139-146); the scale-out path is net-new
here (SURVEY.md §2.7): shard the arena per host, run the encoder and the
similarity kernels over a jax.sharding.Mesh, and let XLA place
collectives on ICI.

Axes:
  dp — data parallel (batch)
  tp — tensor parallel (hidden/heads)
  sp — sequence parallel (long-context; ring attention rides this axis)
  ep — expert parallel (MoE expert dimension; models/moe.py)
  pp — pipeline parallel (layer stages; parallel/pipeline.py)
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map as _shard_map
except ImportError:                           # pragma: no cover
    from jax.experimental.shard_map import shard_map as _shard_map

import inspect

_HAS_VMA = "check_vma" in inspect.signature(_shard_map).parameters


def shard_map(f, *args, **kw):
    """Version-portable shard_map: newer jax renamed check_rep to
    check_vma.  Call sites in this tree use the NEW name; this shim
    translates for older jax so the parallel tier runs on both."""
    if "check_vma" in kw and not _HAS_VMA:
        kw["check_rep"] = kw.pop("check_vma")
    return _shard_map(f, *args, **kw)


def axis_size(name: str) -> int:
    """Static size of a bound mesh axis, portable across jax versions:
    jax.lax.axis_size is newer; on older jax, psum of the literal 1
    constant-folds to the axis size as a Python int."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(name)
    return jax.lax.psum(1, name)


def make_mesh(dp: int | None = None, tp: int = 1, sp: int = 1,
              ep: int = 1, pp: int = 1, devices=None) -> Mesh:
    """Build a (dp, tp, sp, ep, pp) mesh.  dp=None uses all remaining
    devices.  ep/pp default to 1, so existing (dp, tp, sp) call sites
    and partition specs are unaffected."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    rest = tp * sp * ep * pp
    if dp is None:
        if n % rest:
            raise ValueError(
                f"{n} devices not divisible by tp*sp*ep*pp={rest}")
        dp = n // rest
    if dp * rest != n:
        raise ValueError(f"dp*tp*sp*ep*pp={dp * rest} != #devices={n}")
    arr = np.asarray(devices).reshape(dp, tp, sp, ep, pp)
    return Mesh(arr, axis_names=("dp", "tp", "sp", "ep", "pp"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P("dp"))


def kv_pool_sharding(mesh: Mesh) -> NamedSharding:
    """Placement of a paged KV block pool (n_blocks, kv_heads, page,
    head_dim — or head_dim/2 uint8 for int4-PACKED pools, which shard
    identically because packing only narrows the unsharded last axis)
    for tensor-parallel decode: split on the KV-HEAD axis over tp, so
    every device holds every page at 1/tp of its bytes and the
    host-side page scheduler never changes (parallel/serve.py
    ShardedCompletionModel._pool_sharding; the shard_map'd ragged
    kernel in ops/paged_attention.py expects exactly this spec)."""
    return NamedSharding(mesh, P(None, "tp", None, None))


def kv_scale_sharding(mesh: Mesh) -> NamedSharding:
    """Placement of a quantized (int8 or int4-packed) paged pool's
    per-page per-kv-head scales (n_blocks, kv_heads): split on THEIR
    kv-head axis over tp — the scales shard with the heads they
    scale, so the shard_map'd quantized ragged kernel's
    scalar-prefetch tables shrink by tp alongside the pools
    (ops/paged_attention.py)."""
    return NamedSharding(mesh, P(None, "tp"))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def param_pspec(path: tuple, leaf) -> P:
    """Tensor-parallel partition spec for encoder parameters.

    Megatron-style within each block: qkv/gate/up Dense kernels shard
    their OUTPUT dim on tp (column parallel); out/down Dense kernels shard
    their INPUT dim on tp (row parallel) so the pair needs one
    psum per block, which XLA inserts from these shardings.  Embeddings
    shard the vocab axis; everything else is replicated.
    """
    names = [getattr(p, "key", getattr(p, "name", str(p))) for p in path]
    joined = "/".join(str(n) for n in names)
    colp = any(k in joined for k in ("qkv", "gate", "up"))
    rowp = any(k in joined for k in ("attn/out", "mlp/down"))
    if leaf.ndim == 2:
        if colp and joined.endswith("kernel"):
            return P(None, "tp")          # column parallel
        if rowp and joined.endswith("kernel"):
            return P("tp", None)          # row parallel
        # weights_int8 (quant.ChannelQuantDense): the int8 kernel
        # shards exactly like the float kernel it replaced
        if colp and joined.endswith("wq"):
            return P(None, "tp")
        if rowp and joined.endswith("wq"):
            return P("tp", None)
        if "tok_emb" in joined or "pos_emb" in joined:
            return P("tp", None)          # vocab-sharded embedding
    if leaf.ndim == 1 and joined.endswith("wscale"):
        # per-output-channel scales shard WITH the output columns on
        # column-parallel layers (scaling the local partial product
        # is exact — the multiply distributes over the later psum);
        # row-parallel outputs are full-width, so scales replicate
        return P("tp") if colp else P()
    return P()


def shard_params(params, mesh: Mesh, *, pspec_fn=None):
    """Place a param tree onto the mesh.  pspec_fn(path, leaf) -> P
    defaults to the encoder's param_pspec (serve.py passes the decoder
    rules)."""
    pspec_fn = pspec_fn or param_pspec
    def place(path, leaf):
        return jax.device_put(
            leaf, NamedSharding(mesh, pspec_fn(path, leaf)))
    return jax.tree_util.tree_map_with_path(place, params)


def param_shardings(params, mesh: Mesh):
    """The NamedSharding tree matching shard_params (for jit in_shardings)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: NamedSharding(mesh, param_pspec(path, leaf)),
        params)
