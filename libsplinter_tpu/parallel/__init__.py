from . import checkpoint
from .mesh import (batch_sharding, make_mesh, param_shardings, replicated,
                   shard_params)
from .ring_attention import (dense_reference, ring_attention,
                             ring_attention_sharded)
from .pipeline import (make_pipeline_encode_fn, pipeline_encode,
                       stack_layer_params)
from .serve import ShardedCompletionModel, shard_decoder_params
from .sharded_search import PodSearch, shard_vectors, sharded_topk
from .train import (TrainState, info_nce_loss, make_ring_train_step,
                    make_sharded_train_step, make_train_step)

__all__ = ["checkpoint", "make_mesh", "batch_sharding", "replicated", "shard_params",
           "param_shardings", "ShardedCompletionModel",
           "shard_decoder_params", "pipeline_encode",
           "make_pipeline_encode_fn", "stack_layer_params", "sharded_topk", "shard_vectors", "PodSearch",
           "TrainState", "info_nce_loss", "make_train_step",
           "make_sharded_train_step", "make_ring_train_step",
           "ring_attention", "ring_attention_sharded", "dense_reference"]
