from .mesh import (batch_sharding, make_mesh, param_shardings, replicated,
                   shard_params)
from .sharded_search import shard_vectors, sharded_topk
from .train import TrainState, info_nce_loss, make_sharded_train_step, \
    make_train_step

__all__ = ["make_mesh", "batch_sharding", "replicated", "shard_params",
           "param_shardings", "sharded_topk", "shard_vectors",
           "TrainState", "info_nce_loss", "make_train_step",
           "make_sharded_train_step"]
