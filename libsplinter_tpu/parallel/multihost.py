"""Multi-host (pod) bootstrap and host-local arena conventions.

The reference's communication backend is shared memory + atomics on ONE
machine (SURVEY.md §2.7; multi-node is explicitly out of scope there).
The pod story here follows the TPU shape instead:

  - every TPU-VM worker runs its own host-local store (same bus name),
    serving its local clients over shm exactly like the single-host case;
  - device compute spans hosts through ONE global mesh: jax.distributed
    wires the hosts, XLA places collectives on ICI/DCN;
  - cross-host data flow rides the device mesh (all_gather of per-shard
    top-k candidates, psum of stats) — the host stores never talk to each
    other directly, so there is no cross-host coherence protocol to get
    wrong; DCN carries only job control.

`init_distributed()` is idempotent and a no-op in single-process runs, so
daemons can call it unconditionally.
"""
from __future__ import annotations

import os

import jax

_initialized = False


def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> bool:
    """Wire this process into the pod's global device mesh.

    Arguments default from the standard env (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID — or their TPU-metadata fallbacks
    handled inside jax.distributed).  Returns True when a multi-process
    runtime was initialized, False for the single-process fast path.
    """
    global _initialized
    if _initialized:
        return jax.process_count() > 1
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    num = num_processes if num_processes is not None else \
        int(os.environ.get("JAX_NUM_PROCESSES", "0") or 0)
    pid = process_id if process_id is not None else \
        int(os.environ.get("JAX_PROCESS_ID", "-1") or -1)
    if coordinator is None and num <= 1:
        return False        # single host, nothing to wire
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num if num > 0 else None,
        process_id=pid if pid >= 0 else None,
    )
    _initialized = True
    return jax.process_count() > 1


def host_store_name(base: str) -> str:
    """Host-local bus name: identical on every worker by convention, so
    one deployment manifest serves the whole pod.  (Per-host isolation is
    automatic — /dev/shm is not shared across hosts.)"""
    return base


def process_span() -> tuple[int, int]:
    """(process_id, process_count) of this worker in the pod."""
    return jax.process_index(), jax.process_count()
