"""Client-side resilience — the one retry discipline every submit
path shares.

Before this module each call-site hand-rolled its own timeout loop:
`submit_search` polled with a half-deadline re-pulse, the CLI's
completion path blocked on READY, and neither knew what to do with a
lane that was down or a typed `overloaded` shed record.  The wrapper
here owns that policy once:

  - **fail fast on a down lane**: `protocol.lane_down` (the
    supervisor's circuit breaker) is consulted before every attempt,
    so a request against a crash-looping lane returns immediately
    instead of burning the full submit timeout;
  - **honor `retry_after_ms`**: a typed `overloaded` record (the
    daemons' high-water shed, engine/qos.py) is retried after the
    server's hint — jittered, so a thousand shed clients do not
    re-arrive as one synchronized thundering herd;
  - **jittered exponential backoff** floors the wait when the server
    gave no hint;
  - **give up at the caller's deadline**: the whole retry loop lives
    inside one `timeout_ms` budget; when the budget cannot cover
    another attempt the LAST result (typically the overloaded record)
    is returned so the caller sees WHY it failed, not just that it
    timed out.

`submit_completion` is the completer-lane client these semantics were
missing entirely: prompt in, READY-gated value out, typed error
records surfaced as dicts.  `searcher.submit_search` routes through
the same wrapper.
"""
from __future__ import annotations

import random
import time
from typing import Callable

from . import protocol as P

# retry pacing defaults: base doubles per attempt, jitter U(0.5, 1.5)
# — the supervisor's backoff discipline, client-side
BASE_BACKOFF_MS = 50.0
MAX_BACKOFF_MS = 2000.0


def call_with_retries(attempt: Callable[[float], object], *,
                      timeout_ms: float,
                      store=None, lane: str | None = None,
                      base_backoff_ms: float = BASE_BACKOFF_MS,
                      max_backoff_ms: float = MAX_BACKOFF_MS,
                      rng: random.Random | None = None):
    """Run `attempt(left_ms)` until it yields a non-retryable result
    or the deadline passes.

    `attempt` returns: a dict with {"err": "overloaded", ...} to be
    retried after the hint; any other value (including None = attempt
    timed out, and error dicts like deadline_expired) is terminal and
    returned as-is.  With `store`+`lane` given, a lane whose breaker
    is open short-circuits to None before the first attempt — the
    caller's local fallback runs instantly.
    """
    rng = rng or random
    deadline = time.monotonic() + timeout_ms / 1e3
    result = None
    k = 0
    while True:
        left_ms = (deadline - time.monotonic()) * 1e3
        if left_ms <= 0:
            return result
        if store is not None and lane is not None \
                and P.lane_down(store, lane):
            return result
        result = attempt(left_ms)
        rec = result if isinstance(result, dict) else None
        if rec is None or rec.get("err") != P.ERR_OVERLOADED:
            return result
        # shed: wait out the server's hint (floored by our own
        # backoff), jittered so retries decorrelate, capped by the
        # remaining budget — an unaffordable wait returns the typed
        # record so the caller knows it was shed, not silent
        hint = float(rec.get("retry_after_ms", 0) or 0)
        back = min(base_backoff_ms * (2 ** k), max_backoff_ms)
        wait_ms = max(hint, back) * (0.5 + rng.random())
        k += 1
        left_ms = (deadline - time.monotonic()) * 1e3
        if wait_ms >= left_ms:
            return result
        time.sleep(wait_ms / 1e3)


# sentinel: "not finished yet" for wait_with_repulse's check()
PENDING = object()


def wait_with_repulse(store, key: str, left_ms: float, check):
    """The shared bounded wait every submit path uses: poll `key`
    until `check()` returns something other than PENDING, re-bumping
    ONCE at half budget (the bump may have raced the daemon's
    signal_wait re-arm — the run-loop sweeps narrow but cannot close
    that window; one re-pulse costs a signal, silence costs the whole
    timeout), returning None when the budget runs out.  One
    definition, so a fix to the re-pulse race can never apply to one
    lane and miss another."""
    stop = time.monotonic() + left_ms / 1e3
    re_pulsed = False
    while True:
        res = check()
        if res is not PENDING:
            return res
        rem_ms = (stop - time.monotonic()) * 1e3
        if rem_ms <= 0:
            return None
        if not re_pulsed and rem_ms * 2 <= left_ms:
            try:
                store.bump(key)
            except (KeyError, OSError):
                pass
            re_pulsed = True
        store.poll(key, timeout_ms=int(min(rem_ms, 50)))


def _stamp_qos(store, key: str, tenant: int,
               deadline_ts: float | None, trace=None) -> None:
    """Tag a freshly-written request with its tenant, absolute
    deadline, and trace context (after set, before the bump — the
    stamp discipline).  `trace` follows protocol.stamp_trace_ctx:
    True = new root trace, an int trace id = a hop of that trace,
    (trace_id, parent_span) = explicit tree placement — one trace id
    then spans a whole client-chained pipeline across lanes."""
    if tenant:
        P.stamp_tenant(store, key, tenant)
    if deadline_ts is not None:
        P.stamp_deadline(store, key, deadline_ts)
    if trace:
        P.stamp_trace_ctx(store, key, trace)


def submit_completion(store, key: str, prompt: str | bytes, *,
                      timeout_ms: float = 10_000,
                      tenant: int = 0,
                      deadline_ms: float | None = None,
                      trace=None,
                      retry: bool = True):
    """The completer-lane client: write `prompt` to `key`, raise the
    INFER request, wait for READY.

    Returns the completed slot value (bytes: rendered prompt +
    streamed generation), a typed error dict ({"err": "overloaded",
    "retry_after_ms": ...} after exhausted retries, {"err":
    "deadline_expired"} for a deadline the daemon declined), or None
    on timeout / down lane.  `deadline_ms` (relative) stamps an
    absolute wall-clock deadline the daemon fast-fails behind;
    `tenant` tags the request for per-tenant admission.
    """
    deadline_ts = (time.time() + deadline_ms / 1e3
                   if deadline_ms is not None else None)

    def attempt(left_ms: float):
        store.set(key, prompt)
        # a retry (or a recycled key) may still carry READY from the
        # previous completion/shed — left set, the wait loop below
        # would return the raw prompt instantly as the "completion"
        store.label_clear(key, P.LBL_READY | P.LBL_SERVICING)
        _stamp_qos(store, key, tenant, deadline_ts, trace)
        store.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
        store.bump(key)

        def check():
            try:
                labels = store.labels(key)
            except KeyError:
                return None               # caller deleted it mid-wait
            if not labels & P.LBL_READY:
                return PENDING
            try:
                raw = store.get(key)
            except (KeyError, OSError):
                return None
            rec = P.parse_error_payload(raw)
            return rec if rec is not None else raw.rstrip(b"\0")

        return wait_with_repulse(store, key, left_ms, check)

    if not retry:
        return attempt(timeout_ms)
    return call_with_retries(attempt, timeout_ms=timeout_ms,
                             store=store, lane="completer")


def classify_embed_result(store, key: str, labels: int, *,
                          deadline_ts: float | None = None):
    """THE embed-lane result read — one definition `submit_embed` and
    the pipeline lane's verb polling share, so the subtle label-only
    protocol (the embedder has no value channel: success IS a
    committed vector, shed IS a cleared label with a zero vector)
    cannot drift between them.  Returns PENDING while the request is
    queued, True when the vector landed, else a typed error dict
    ({"err": "ctx_exceeded" | "deadline_expired" | "overloaded"})."""
    import numpy as np

    from .qos import DEFAULT_RETRY_AFTER_MS

    if labels & P.LBL_EMBED_REQ:
        return PENDING
    if labels & P.LBL_CTX_EXCEEDED:
        return {"err": "ctx_exceeded"}
    try:
        vec = store.vec_get(key)
        if vec is not None and np.abs(vec).max() > 0:
            return True
    except (KeyError, OSError):
        pass
    # label-only unblock with no vector: the embed lane's
    # shed/deadline signal (the heartbeat counters say which;
    # client-side the deadline disambiguates)
    if deadline_ts is not None and time.time() >= deadline_ts:
        return {"err": P.ERR_DEADLINE}
    return P.overloaded_record(DEFAULT_RETRY_AFTER_MS)


def submit_embed(store, key: str, text: str | bytes, *,
                 timeout_ms: float = 10_000,
                 tenant: int = 0,
                 deadline_ms: float | None = None,
                 trace=None,
                 retry: bool = True):
    """The embed-lane client that was missing (`submit_search` and
    `submit_completion` exist): write `text` to `key`, raise the
    EMBED request, wait for the daemon to clear it.

    The embedder has no value channel to spare (the slot holds the
    client's text), so its shed/expiry signal is the cleared label
    with NO vector committed — this helper reads that protocol and
    SYNTHESIZES the typed record the other lanes return explicitly:
    True when the vector landed, {"err": "overloaded"|
    "deadline_expired"|"ctx_exceeded"} when the daemon rejected it,
    None on timeout / down lane.  Tenant, deadline, and the shared
    retry wrapper behave exactly as in the sibling helpers."""
    deadline_ts = (time.time() + deadline_ms / 1e3
                   if deadline_ms is not None else None)

    def attempt(left_ms: float):
        store.set(key, text)
        # a reused key may still carry CTX_EXCEEDED from a previous
        # over-long text — left set, a successful re-embed would
        # still classify as rejected
        store.label_clear(key, P.LBL_CTX_EXCEEDED)
        _stamp_qos(store, key, tenant, deadline_ts, trace)
        store.label_or(key, P.LBL_EMBED_REQ | P.LBL_WAITING)
        store.bump(key)

        def check():
            try:
                labels = store.labels(key)
            except KeyError:
                return None               # caller deleted it mid-wait
            return classify_embed_result(store, key, labels,
                                         deadline_ts=deadline_ts)

        return wait_with_repulse(store, key, left_ms, check)

    if not retry:
        return attempt(timeout_ms)
    return call_with_retries(attempt, timeout_ms=timeout_ms,
                             store=store, lane="embedder")
