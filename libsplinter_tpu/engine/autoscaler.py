"""The scaling controller — QoS-driven replica counts for the
elastic lanes.

ROADMAP item 4's control loop: every interval it reads the telemetry
rings (engine/telemetry.py — queue depth measured from labels, shed /
deferred counters, stage p99s; PR 13 built them expressly as this
lane's input plane), computes per-lane pressure, and commands replica
counts through the supervisor by writing per-lane
`__scale_tgt_<lane>` target keys (the
supervisor applies them: spawn on scale-up, drain-protocol retire on
scale-down).  Deliberately jax-free and supervisable (`spt supervise
--scale lane=min:max` arms it automatically): its state of record is
the store — policy in `__scale_policy`, targets in per-lane
`__scale_tgt_<lane>` keys,
decisions in the `__autoscaler_stats` heartbeat — so a restarted
controller resumes from the live truth.

Hysteresis, because an open-loop arrival process is bursty and a
flapping replica set is worse than a slightly lazy one:

  - scale-UP is fast: `up_consecutive` (default 2) samples of queue
    pressure (queue depth / live replicas) at or above up_threshold —
    or a moving shed counter, the unambiguous overload signal — jump
    the target to ceil(queue / up_threshold), clamped to the bounds;
  - scale-DOWN is slow: `down_consecutive` (default 5) samples below
    down_threshold with shed flat step the target down by ONE;
  - a per-lane cooldown separates actions, so one burst cannot
    ratchet the set up and down inside a single drain cycle;
  - a `manual` target entry (`spt scale set`) is a
    hold: the controller leaves that lane alone until it is cleared
    back to auto.

Per-lane SIGNAL selection (the disaggregated lanes, PR 18): the
policy's per-lane `signal` field picks what pressure means.  `queue`
(the default) is the classic queue-depth-per-replica read above.
`pool` rates the lane by its paged-pool occupancy gauge instead —
the decode lane's backlog is KV residency of adopted rows, not queue
depth (a decode replica near pool exhaustion refuses adoption long
before any queue forms), so occupancy >= POOL_UP_THRESHOLD votes
scale-up one replica at a time and sustained occupancy below
POOL_DOWN_THRESHOLD votes scale-down.  This is how `spt supervise
--scale prefill=1:4 --scale decode=1:4` scales the two lanes
INDEPENDENTLY: a prefill burst moves queue pressure, not pool
occupancy, and vice versa.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import math
import time
from collections import deque

from ..store import Store
from ..utils.faults import fault
from . import protocol as P
from .telemetry import read_history

log = logging.getLogger("libsplinter_tpu.autoscaler")

DEFAULT_INTERVAL_S = 2.0
DEFAULT_UP_THRESHOLD = 8.0      # queue depth per replica
DEFAULT_DOWN_THRESHOLD = 1.0    # queue depth per replica
DEFAULT_UP_CONSECUTIVE = 2
DEFAULT_DOWN_CONSECUTIVE = 5
DEFAULT_COOLDOWN_S = 6.0
# the `pool` signal's hysteresis band (occupancy fractions, 0..1):
# adoption backpressure starts well before 1.0, so the up vote fires
# at 80% and the lane is only surrendered once sustained below 30%
POOL_UP_THRESHOLD = 0.80
POOL_DOWN_THRESHOLD = 0.30
# PR 20: occupancy attributable to pages the KV tier READMITTED in
# the last sampler interval is discounted from the pool signal — a
# warm restart or a prefix-hot burst readmits whole chains in one
# tick, and those pages are restored capital (droppable again at
# zero recompute cost), not live demand.  The cap bounds how much a
# pathological ring can suppress genuine saturation: the hysteresis
# band itself (streaks, thresholds, cooldown) is untouched.
READMIT_DISCOUNT_CAP = 0.5


@dataclasses.dataclass
class AutoScalerStats:
    ticks: int = 0               # decision cycles completed
    decisions: int = 0           # targets written (up + down)
    scale_ups: int = 0
    scale_downs: int = 0
    holds: int = 0               # lanes skipped on a manual hold
    no_data: int = 0             # lanes skipped for missing rings


@dataclasses.dataclass
class _LaneCtl:
    """Per-lane hysteresis state."""
    up_streak: int = 0
    down_streak: int = 0
    last_action_mono: float = -1e9
    last_shed: float | None = None
    # the newest ring sample already counted into the streaks: a
    # controller ticking FASTER than the sampler must not count one
    # telemetry point N times (that would collapse up_consecutive /
    # down_consecutive to a single sample and re-open the flap door)
    last_sample_ts: float | None = None
    target: int | None = None    # last target this controller wrote
    pressure: float = 0.0
    # the pool-signal discount applied this tick (0.0 on queue lanes
    # and quiet tiers) — published so `spt scale status` can show WHY
    # a readmit burst did not vote scale-up
    readmit_discount: float = 0.0
    reason: str = "init"


class AutoScaler:
    """Drive with run() (blocking loop) or decide_once() (one
    decision cycle — tests and --oneshot)."""

    def __init__(self, store: Store, *,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 up_threshold: float = DEFAULT_UP_THRESHOLD,
                 down_threshold: float = DEFAULT_DOWN_THRESHOLD,
                 up_consecutive: int = DEFAULT_UP_CONSECUTIVE,
                 down_consecutive: int = DEFAULT_DOWN_CONSECUTIVE,
                 cooldown_s: float = DEFAULT_COOLDOWN_S,
                 history_len: int = 32):
        self.store = store
        self.interval_s = max(0.05, interval_s)
        self.up_threshold = max(0.1, up_threshold)
        self.down_threshold = max(0.0, down_threshold)
        self.up_consecutive = max(1, up_consecutive)
        self.down_consecutive = max(1, down_consecutive)
        self.cooldown_s = max(0.0, cooldown_s)
        self.stats = AutoScalerStats()
        self.lanes: dict[str, _LaneCtl] = {}
        # lane -> scaling signal ("queue"|"pool"), from the policy
        self.signals: dict[str, str] = {}
        # decision history: [ts, lane, from_r, to_r, reason] rows the
        # heartbeat publishes (and `spt scale status` renders) — the
        # flap/stuck triage read
        self.history: deque = deque(maxlen=max(4, history_len))
        self.generation = 0
        self._running = False

    # -- wiring ------------------------------------------------------------

    def attach(self) -> None:
        self.generation = P.bump_generation(self.store,
                                            P.KEY_AUTOSCALER_STATS)

    # -- inputs ------------------------------------------------------------

    def _policy(self) -> dict[str, tuple[int, int]]:
        """The supervisor-published per-lane bounds.  Controller
        knobs in the policy override the constructor defaults, so
        `spt supervise --scale-*` flags reach a supervised child
        without argv plumbing."""
        rec = P.read_scale_policy(self.store)
        if rec is None:
            return {}
        for field, attr in (("up_threshold", "up_threshold"),
                            ("down_threshold", "down_threshold"),
                            ("cooldown_s", "cooldown_s"),
                            ("interval_s", "interval_s")):
            v = rec.get(field)
            if isinstance(v, (int, float)) and v > 0:
                setattr(self, attr, max(0.05, float(v))
                        if attr == "interval_s" else float(v))
        out: dict[str, tuple[int, int]] = {}
        lanes = rec.get("lanes")
        if not isinstance(lanes, dict):
            return out
        for lane, b in lanes.items():
            if not isinstance(b, dict):
                continue
            try:
                lo = max(1, int(b.get("min", 1)))
                hi = max(lo, int(b.get("max", lo)))
            except (TypeError, ValueError):
                continue
            out[lane] = (lo, hi)
            sig = b.get("signal")
            self.signals[lane] = (sig if sig in ("queue", "pool")
                                  else "queue")
        return out

    def _live_r(self, lane: str) -> int:
        """The lane's currently-active replica count, from the
        supervisor heartbeat (the applier's truth — the controller
        must rate pressure against what is actually serving, not
        what it last asked for)."""
        try:
            snap = json.loads(self.store.get(
                P.KEY_SUPERVISOR_STATS).rstrip(b"\0"))
            r = snap["lanes"][lane].get("r", 1)
            return max(1, int(r))
        except (KeyError, OSError, ValueError, TypeError):
            ctl = self.lanes.get(lane)
            return max(1, ctl.target or 1) if ctl else 1

    @staticmethod
    def _ring_last(rec: dict | None, gauge: str
                   ) -> tuple[float, float] | None:
        """The newest (ts, value) point of a telemetry ring gauge."""
        if rec is None:
            return None
        ring = (rec.get("gauges") or {}).get(gauge)
        if not isinstance(ring, list) or not ring:
            return None
        p = ring[-1]
        if not isinstance(p, list) or len(p) != 2:
            return None
        return float(p[0]), float(p[1])

    @staticmethod
    def _readmit_discount(rec: dict | None) -> float:
        """The occupancy fraction attributable to pages the KV tier
        readmitted between the last two sampler ticks: the newest
        step of the `tier_readmits` counter ring, rated against the
        pool size from the same rings (pages_used + pages_free).
        Returns 0.0 whenever any input is missing or stale — the
        discount is an optimization on the pool signal, never a
        reason to skip a decision."""
        if rec is None:
            return 0.0
        gauges = rec.get("gauges") or {}

        def pt(g, i):
            ring = gauges.get(g)
            if not isinstance(ring, list) or len(ring) < -i:
                return None
            p = ring[i]
            if not isinstance(p, list) or len(p) != 2:
                return None
            try:
                return float(p[1])
            except (TypeError, ValueError):
                return None

        prev, last = pt("tier_readmits", -2), pt("tier_readmits", -1)
        if prev is None or last is None or last <= prev:
            return 0.0
        used, free = pt("pages_used", -1), pt("pages_free", -1)
        if used is None or free is None or used + free <= 0:
            return 0.0
        return min(READMIT_DISCOUNT_CAP, (last - prev) / (used + free))

    # -- the decision ------------------------------------------------------

    def decide_lane(self, lane: str, bounds: tuple[int, int],
                    queue_depth: float | None,
                    shed: float | None, live_r: int,
                    now_mono: float,
                    sample_ts: float | None = None,
                    signal: str = "queue") -> int | None:
        """One lane's hysteresis step.  Returns a NEW target replica
        count, or None (no action).  Pure against its inputs so the
        flapping unit tests can drive synthetic series.  `sample_ts`
        is the ring point's timestamp: a point already counted
        advances NO streak (a controller ticking faster than the
        sampler must not turn one sample into a consecutive run).

        `signal="pool"` reinterprets `queue_depth` as the lane's
        paged-pool occupancy fraction (0..1): the hysteresis band is
        the POOL_* constants, the fraction is NOT divided by the
        replica count (each replica owns its own pool; the telemetry
        gauge is already the fleet-worst view), and scale-up steps by
        ONE replica — occupancy says the pool is full, not how many
        replicas the backlog is worth."""
        ctl = self.lanes.setdefault(lane, _LaneCtl())
        lo, hi = bounds
        if queue_depth is None:
            ctl.reason = "no telemetry"
            self.stats.no_data += 1
            return None
        if sample_ts is not None:
            if sample_ts == ctl.last_sample_ts:
                ctl.reason = "awaiting fresh telemetry"
                return None           # streaks pause, never re-count
            ctl.last_sample_ts = sample_ts
        pooled = signal == "pool"
        if pooled:
            pressure = float(queue_depth)
            up_thr, down_thr = POOL_UP_THRESHOLD, POOL_DOWN_THRESHOLD
        else:
            pressure = queue_depth / max(1, live_r)
            up_thr, down_thr = self.up_threshold, self.down_threshold
        ctl.pressure = round(pressure, 3)
        shed_moved = (shed is not None and ctl.last_shed is not None
                      and shed > ctl.last_shed)
        if shed is not None:
            ctl.last_shed = shed
        if pressure >= up_thr or shed_moved:
            ctl.up_streak += 1
            ctl.down_streak = 0
        elif pressure < down_thr:
            ctl.down_streak += 1
            ctl.up_streak = 0
        else:
            # the dead band between the thresholds: streaks reset, so
            # an input oscillating across ONE threshold cannot bank
            # votes toward the other direction (the no-flap property)
            ctl.up_streak = 0
            ctl.down_streak = 0
        in_cooldown = (now_mono - ctl.last_action_mono
                       < self.cooldown_s)
        if ctl.up_streak >= self.up_consecutive and not in_cooldown:
            # scale-up sizes to the backlog in ONE action: a sustained
            # 8x step must not climb one replica per interval.  The
            # pool signal steps by one — a fraction has no backlog
            # magnitude to size from.
            want = live_r + 1 if pooled else \
                max(live_r + 1,
                    math.ceil(queue_depth / self.up_threshold))
            target = min(hi, want)
            if target > live_r:
                ctl.up_streak = 0
                ctl.last_action_mono = now_mono
                metric = "pool occ" if pooled else "queue/replica"
                ctl.reason = (f"{metric} {pressure:.2f} >= {up_thr:g}"
                              + (" + shed moving" if shed_moved
                                 else ""))
                return target
            ctl.reason = f"at max ({hi})"
            return None
        if ctl.down_streak >= self.down_consecutive \
                and not in_cooldown:
            target = max(lo, live_r - 1)
            if target < live_r:
                ctl.down_streak = 0
                ctl.last_action_mono = now_mono
                metric = "pool occ" if pooled else "queue/replica"
                ctl.reason = (f"idle: {metric} {pressure:.2f} < "
                              f"{down_thr:g} x"
                              f"{self.down_consecutive}")
                return target
            ctl.reason = f"at min ({lo})"
            return None
        ctl.reason = ("cooldown" if in_cooldown and
                      (ctl.up_streak or ctl.down_streak) else "steady")
        return None

    def decide_once(self, now_mono: float | None = None) -> int:
        """One decision cycle over every lane in the policy; returns
        targets written."""
        now_mono = time.monotonic() if now_mono is None else now_mono
        policy = self._policy()
        targets = P.read_scale_targets(self.store)
        wrote = 0
        for lane, bounds in policy.items():
            fault("autoscaler.decide")
            tgt = targets.get(lane)
            if isinstance(tgt, dict) and tgt.get("src") == "manual":
                self.stats.holds += 1
                ctl = self.lanes.setdefault(lane, _LaneCtl())
                ctl.reason = f"manual hold (r={tgt.get('r')})"
                continue
            rec = read_history(self.store, lane)
            signal = self.signals.get(lane, "queue")
            gauge = "pool_occ" if signal == "pool" else "queue_depth"
            q = self._ring_last(rec, gauge)
            shed = self._ring_last(rec, "shed")
            live_r = self._live_r(lane)
            occ = q[1] if q else None
            discount = 0.0
            if occ is not None and signal == "pool":
                # readmitted pages are restored capital, not demand:
                # discount this tick's readmissions out of the pool
                # signal BEFORE the (unchanged) hysteresis sees it
                discount = self._readmit_discount(rec)
                occ = max(0.0, occ - discount)
            target = self.decide_lane(
                lane, bounds, occ,
                shed[1] if shed else None, live_r, now_mono,
                sample_ts=q[0] if q else None, signal=signal)
            self.lanes[lane].readmit_discount = round(discount, 3)
            ctl = self.lanes[lane]
            if target is None:
                # bounds still apply with no action: a policy floor
                # raised above the live count must lift the lane
                lo, hi = bounds
                if live_r < lo:
                    target, ctl.reason = lo, f"below min ({lo})"
                elif live_r > hi:
                    target, ctl.reason = hi, f"above max ({hi})"
            if target is None or target == ctl.target == live_r:
                continue
            P.write_scale_target(self.store, lane, target, src="auto")
            ctl.target = target
            self.stats.decisions += 1
            if target > live_r:
                self.stats.scale_ups += 1
            elif target < live_r:
                self.stats.scale_downs += 1
            self.history.append(
                [round(time.time(), 2), lane, live_r, target,
                 ctl.reason])
            log.info("lane %s: %d -> %d replicas (%s)",
                     lane, live_r, target, ctl.reason)
            wrote += 1
        self.stats.ticks += 1
        return wrote

    # -- heartbeat ---------------------------------------------------------

    def publish_stats(self) -> None:
        payload = {**dataclasses.asdict(self.stats),
                   "interval_s": self.interval_s,
                   "up_threshold": self.up_threshold,
                   "down_threshold": self.down_threshold,
                   "cooldown_s": self.cooldown_s,
                   "generation": self.generation,
                   "lanes": {
                       ln: {"target": ctl.target,
                            "pressure": ctl.pressure,
                            "signal": self.signals.get(ln, "queue"),
                            "readmit_discount": ctl.readmit_discount,
                            "reason": ctl.reason,
                            "up_streak": ctl.up_streak,
                            "down_streak": ctl.down_streak}
                       for ln, ctl in self.lanes.items()},
                   "history": [list(row) for row in self.history]}
        P.publish_heartbeat(self.store, P.KEY_AUTOSCALER_STATS,
                            payload)

    # -- lifecycle ---------------------------------------------------------

    def run(self, *, stop_after: float | None = None,
            heartbeat_interval_s: float = 5.0,
            idle_timeout_ms: int | None = None) -> None:
        """The control loop.  `idle_timeout_ms` is accepted (and
        ignored) so the supervisor's generic lane argv works
        unchanged."""
        self._running = True
        deadline = (time.monotonic() + stop_after) if stop_after \
            else None
        next_beat = 0.0
        next_decide = 0.0
        while self._running:
            now = time.monotonic()
            try:
                if now >= next_decide:
                    self.decide_once(now)
                    next_decide = now + self.interval_s
                if now >= next_beat:
                    # heartbeat on its OWN cadence, never floored by
                    # a long decision interval: a supervised
                    # controller with --scale-interval-s above the
                    # supervisor's heartbeat timeout would otherwise
                    # read as hung and get kill-looped
                    self.publish_stats()
                    next_beat = now + heartbeat_interval_s
            except Exception:
                log.exception("decision cycle failed; continuing")
            if deadline and time.monotonic() > deadline:
                break
            wake = min(next_decide, next_beat)
            time.sleep(min(0.25, max(wake - time.monotonic(), 0.01)))

    def stop(self) -> None:
        self._running = False


def main(argv: list[str] | None = None) -> int:
    """CLI entry: python -m libsplinter_tpu.engine.autoscaler
    --store NAME.  jax-free — supervised restarts cost ms."""
    import argparse

    ap = argparse.ArgumentParser(
        description="splinter-tpu scaling controller (reads the "
                    "telemetry rings, writes __scale_tgt_<lane> targets for "
                    "supervisor's replica sets)")
    ap.add_argument("--store", required=True)
    ap.add_argument("--persistent", action="store_true")
    ap.add_argument("--oneshot", action="store_true")
    ap.add_argument("--interval-s", type=float,
                    default=DEFAULT_INTERVAL_S,
                    help="decision cadence (default 2s)")
    ap.add_argument("--up-threshold", type=float,
                    default=DEFAULT_UP_THRESHOLD,
                    help="queue depth per replica that votes "
                         "scale-up (default 8)")
    ap.add_argument("--down-threshold", type=float,
                    default=DEFAULT_DOWN_THRESHOLD,
                    help="queue depth per replica below which "
                         "sustained idle votes scale-down (default 1)")
    ap.add_argument("--up-consecutive", type=int,
                    default=DEFAULT_UP_CONSECUTIVE)
    ap.add_argument("--down-consecutive", type=int,
                    default=DEFAULT_DOWN_CONSECUTIVE)
    ap.add_argument("--cooldown-s", type=float,
                    default=DEFAULT_COOLDOWN_S,
                    help="minimum seconds between actions per lane")
    ap.add_argument("--idle-timeout-ms", type=int, default=None,
                    help="accepted for supervisor argv parity; unused")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    store = Store.open(args.store, persistent=args.persistent)
    ctl = AutoScaler(store, interval_s=args.interval_s,
                     up_threshold=args.up_threshold,
                     down_threshold=args.down_threshold,
                     up_consecutive=args.up_consecutive,
                     down_consecutive=args.down_consecutive,
                     cooldown_s=args.cooldown_s)
    ctl.attach()
    ctl.publish_stats()
    if args.oneshot:
        n = ctl.decide_once()
        ctl.publish_stats()
        log.info("oneshot wrote %d targets", n)
        return 0
    try:
        ctl.run()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
