"""Host-side radix prefix cache over the block-paged KV pool.

Millions of requests share the same system prompts, few-shot headers,
and RAG boilerplate — and before this module every admission
re-prefilled and re-committed identical pages: a 67 ms bucket-64
prefill that could have been a host-side block-table write.  The
block-paged pool (models/decoder.PagedKVCache) already reads
exclusively through per-row block tables, so the ragged paged kernel
(ops/paged_attention) serves SHARED pages with zero changes — all the
sharing machinery is host-side:

  - **Refcounted pages** (PagedKVCache.refcounts): block tables from
    different rows point at the same full pages; a page returns to
    the free list only when its refcount hits zero.
  - **This tree**: full-page prefixes indexed by token ids, page
    granular — node j of a chain holds the pool page with the K/V of
    tokens [j*page, (j+1)*page) computed IN CONTEXT of the whole
    prefix (K/V at position p depend on every token before p, so a
    page is only reusable under the exact token prefix it was
    computed under — hence a radix tree, not a flat page hash).
  - **Copy-on-write** (PagedKVCache / CompletionModel._cow_fixups):
    a decode append whose target page is shared (or tree-frozen)
    copies the page first, so a writer never mutates a page another
    row — or a future joiner — reads.  Tree pages are otherwise
    FROZEN read-only; for int8 pools that means their per-page scales
    never rescale, which *removes* the stale-scale hazard
    quantize-on-commit pools otherwise carry.

Lifecycle: pages enter the tree at admission (after the committing
row's prefill), while the donor row is still live — a mid-flight
joiner may map a prefix another row is actively decoding from (the
donor's appends only ever touch pages past its prompt).  When every
mapping row finishes, the page's refcount hits zero and it becomes
EVICTABLE: it stays allocated (and instantly re-mappable) until the
pool actually needs the page back, at which point eviction takes the
least-recently-matched zero-ref chain tails first.  Per-tenant page
quotas (engine/qos.py `parse_tenant_quotas`, surfaced through the
tenant ledger in the completer heartbeat) bound how much of the pool
any one tenant's prefixes may squat on.

Invariants the churn drill (tests/test_prefix_cache.py) pins:
refcount 0 <=> (free list membership XOR tree retention); no page is
ever in the free list while a table or the tree references it; a
row's mapped prefix path has monotonically non-increasing refcounts
root -> tail (rows always map whole prefixes), so a zero-ref node's
entire subtree is zero-ref and leaf-first eviction can always make
progress.
"""
from __future__ import annotations

import dataclasses
import itertools


@dataclasses.dataclass
class PrefixCacheStats:
    """Counters the completer heartbeat publishes (prefix_* gauges in
    `spt metrics`, ring history in the telemetry lane, sparklines in
    `spt top`)."""

    hits: int = 0             # admissions matching >= 1 full page
    misses: int = 0           # admissions matching nothing
    hit_tokens: int = 0       # prompt tokens served from the tree
    inserts: int = 0          # pages registered
    evictions: int = 0        # pages reclaimed for the free list
    cow_copies: int = 0       # copy-on-write page copies
    quota_rejects: int = 0    # inserts skipped: tenant over quota
    bytes_saved: int = 0      # KV bytes not re-prefilled/committed


class _Node:
    __slots__ = ("toks", "bid", "parent", "children", "lru", "tenant")

    def __init__(self, toks: tuple, bid: int, parent, tenant: int):
        self.toks = toks            # this page's token ids (exact)
        self.bid = bid              # pool block id holding its K/V
        self.parent = parent        # _Node | None (root child)
        self.children: dict[tuple, _Node] = {}
        self.lru = 0                # last-matched clock tick
        self.tenant = tenant


class PrefixCache:
    """One instance per continuous-batching completer, bound to its
    pool via attach() (re-bound — and emptied — whenever the lane
    rebuilds the pool: abort recovery, spec demotion).  All methods
    are called from the single lane thread; there is no locking, by
    the same single-owner contract as the pool's host scheduler."""

    def __init__(self, page: int, *, max_pages: int | None = None,
                 tenant_quotas: dict[int, int] | None = None,
                 default_quota: int | None = None):
        if page < 1:
            raise ValueError("page must be >= 1")
        self.page = page
        self.max_pages = max_pages
        self.tenant_quotas = dict(tenant_quotas or {})
        self.default_quota = default_quota
        self.stats = PrefixCacheStats()
        self._cache = None            # the bound PagedKVCache
        self._children: dict[tuple, _Node] = {}   # root level
        self._by_bid: dict[int, _Node] = {}
        self._tenant_pages: dict[int, int] = {}
        self._clock = itertools.count(1)
        # zero-ref tree pages, maintained INCREMENTALLY on the pool's
        # refcount 0<->1 transitions (on_zero_ref / on_ref) — the
        # admission path reads evictable_count per waiting request,
        # so an O(tree) scan there would tax the lane thread
        self._zero_ref = 0

    # -- binding -----------------------------------------------------------

    def attach(self, cache) -> None:
        """Bind (or re-bind) to a pool.  The tree references pool
        block ids, so a rebuilt pool invalidates every node — the old
        pool's pages died with it and must not be returned anywhere."""
        self._cache = cache
        self._children = {}
        self._by_bid = {}
        self._tenant_pages = {}
        self._zero_ref = 0

    # -- lookup / mapping ---------------------------------------------------

    def lookup(self, ids) -> tuple[list[int], int]:
        """Walk the tree over `ids` at page granularity.  Returns
        (matched block ids in prefix order, matched token count).
        PURE: no stats, no LRU touch — a lookup whose admission is
        then denied (backpressure, raced slot) must neither inflate
        the hit rate the runbook triages on nor refresh LRU stamps
        for a prefix that never got served.  The admitting caller
        records the outcome via commit_hit() / note_miss()."""
        page = self.page
        n_full = len(ids) // page
        bids: list[int] = []
        cur = self._children
        for j in range(n_full):
            chunk = tuple(int(t) for t in ids[j * page:(j + 1) * page])
            node = cur.get(chunk)
            if node is None:
                break
            bids.append(node.bid)
            cur = node.children
        return bids, len(bids) * page

    def commit_hit(self, ids, match: int) -> None:
        """An admission actually mapped `match` tokens of `ids`: count
        the hit and LRU-touch the served path (re-walk — match/page
        node hops, cheap next to the admission it accompanies)."""
        page = self.page
        tick = next(self._clock)
        cur = self._children
        for j in range(match // page):
            node = cur.get(tuple(int(t)
                                 for t in ids[j * page:(j + 1) * page]))
            if node is None:
                break                  # evicted mid-admission: stale
            node.lru = tick
            cur = node.children
        self.stats.hits += 1
        self.stats.hit_tokens += match

    def note_miss(self) -> None:
        self.stats.misses += 1

    # -- insertion ----------------------------------------------------------

    def insert(self, ids, cache, row: int, tenant: int = 0) -> int:
        """Register the FULL prompt pages of `row` (its table entries
        for pages [0, len(ids)//page)) under their token prefix.
        Pages already present are skipped (the hit path mapped them;
        the row's own duplicates stay private).  Returns pages
        inserted.  A page enters FROZEN: the pool will copy-on-write
        before any append could touch it, and for int8 pools its
        scale never rescales again."""
        if cache is not self._cache:
            return 0                  # stale pool: never adopt its ids
        page = self.page
        n_full = len(ids) // page
        inserted = 0
        parent = None
        cur = self._children
        tick = next(self._clock)
        for j in range(n_full):
            chunk = tuple(int(t) for t in ids[j * page:(j + 1) * page])
            node = cur.get(chunk)
            if node is None:
                bid = int(cache.tables[row, j])
                if bid == 0 or bid in self._by_bid:
                    break             # trash / already-owned: stop
                if not self._admit_page(tenant):
                    break
                node = _Node(chunk, bid, parent, tenant)
                cur[chunk] = node
                self._by_bid[bid] = node
                self._tenant_pages[tenant] = \
                    self._tenant_pages.get(tenant, 0) + 1
                self.stats.inserts += 1
                inserted += 1
            node.lru = tick
            parent = node
            cur = node.children
        return inserted

    def _admit_page(self, tenant: int) -> bool:
        """Quota + global-cap gate for one insert.  Over quota, the
        tenant's own least-recent zero-ref tail evicts first; only
        when the tenant has nothing reclaimable is the insert
        skipped (quota_rejects)."""
        quota = self.tenant_quotas.get(tenant, self.default_quota)
        if quota is not None and \
                self._tenant_pages.get(tenant, 0) >= quota:
            if not self._evict_one(tenant=tenant):
                self.stats.quota_rejects += 1
                return False
        if self.max_pages is not None and \
                len(self._by_bid) >= self.max_pages:
            if not self._evict_one():
                return False
        return True

    # -- pool hooks (called by PagedKVCache) --------------------------------

    def retains(self, bid: int) -> bool:
        """True when the tree references `bid` — the pool asks on
        every COW decision (a frozen page must never be appended
        into, even at refcount 1)."""
        return bid in self._by_bid

    def on_zero_ref(self, bid: int) -> bool:
        """The pool's refcount for `bid` just hit zero.  True = the
        tree retains it (keep it OFF the free list; it is now
        evictable), False = not ours, free normally."""
        if bid in self._by_bid:
            self._zero_ref += 1
            return True
        return False

    def on_ref(self, bid: int) -> None:
        """`bid` went 0 -> 1 references (a joiner mapped an evictable
        page): it is pinned again, not reclaimable."""
        if bid in self._by_bid:
            self._zero_ref -= 1

    def reclaim(self, n: int) -> int:
        """Evict up to `n` least-recently-matched zero-ref pages back
        to the pool's free list (leaf-first; evicting a tail exposes
        its parent).  Returns pages actually reclaimed — the pool's
        allocator calls this when its free list runs dry."""
        done = 0
        while done < n and self._evict_one():
            done += 1
        return done

    def _evict_one(self, tenant: int | None = None) -> bool:
        cache = self._cache
        if cache is None:
            return False
        victim = None
        for node in self._by_bid.values():
            if node.children:
                continue              # leaf-first (cascade exposes it)
            if cache.refcounts[node.bid] != 0:
                continue              # mapped by a live row
            if tenant is not None and node.tenant != tenant:
                continue
            if victim is None or node.lru < victim.lru:
                victim = node
        if victim is None:
            return False
        siblings = (victim.parent.children if victim.parent is not None
                    else self._children)
        siblings.pop(victim.toks, None)
        del self._by_bid[victim.bid]
        self._tenant_pages[victim.tenant] = \
            max(0, self._tenant_pages.get(victim.tenant, 0) - 1)
        self._zero_ref -= 1            # victims are zero-ref by test
        cache._free.append(victim.bid)
        self.stats.evictions += 1
        return True

    # -- gauges -------------------------------------------------------------

    def evictable_count(self) -> int:
        """Zero-ref tree pages: reclaimable capacity the admission
        path may count on top of the free list (a zero-ref node's
        whole subtree is zero-ref — see the module invariants — so
        every one of them is reachable by leaf-first eviction).
        O(1): maintained incrementally on the pool's refcount
        transitions; the churn drill pins it against a brute-force
        recount."""
        return self._zero_ref if self._cache is not None else 0

    def shared_pages(self) -> int:
        return len(self._by_bid)

    def tenant_pages(self) -> dict[int, int]:
        return {t: n for t, n in self._tenant_pages.items() if n}
