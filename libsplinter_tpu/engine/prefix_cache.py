"""Host-side radix prefix cache over the block-paged KV pool.

Millions of requests share the same system prompts, few-shot headers,
and RAG boilerplate — and before this module every admission
re-prefilled and re-committed identical pages: a 67 ms bucket-64
prefill that could have been a host-side block-table write.  The
block-paged pool (models/decoder.PagedKVCache) already reads
exclusively through per-row block tables, so the ragged paged kernel
(ops/paged_attention) serves SHARED pages with zero changes — all the
sharing machinery is host-side:

  - **Refcounted pages** (PagedKVCache.refcounts): block tables from
    different rows point at the same full pages; a page returns to
    the free list only when its refcount hits zero.
  - **This tree**: full-page prefixes indexed by token ids, page
    granular — node j of a chain holds the pool page with the K/V of
    tokens [j*page, (j+1)*page) computed IN CONTEXT of the whole
    prefix (K/V at position p depend on every token before p, so a
    page is only reusable under the exact token prefix it was
    computed under — hence a radix tree, not a flat page hash).
  - **Copy-on-write** (PagedKVCache / CompletionModel._cow_fixups):
    a decode append whose target page is shared (or tree-frozen)
    copies the page first, so a writer never mutates a page another
    row — or a future joiner — reads.  Tree pages are otherwise
    FROZEN read-only; for int8 pools that means their per-page scales
    never rescale, which *removes* the stale-scale hazard
    quantize-on-commit pools otherwise carry.

Lifecycle: pages enter the tree at admission (after the committing
row's prefill), while the donor row is still live — a mid-flight
joiner may map a prefix another row is actively decoding from (the
donor's appends only ever touch pages past its prompt).  When every
mapping row finishes, the page's refcount hits zero and it becomes
EVICTABLE: it stays allocated (and instantly re-mappable) until the
pool actually needs the page back, at which point eviction takes the
least-recently-matched zero-ref chain tails first.  Per-tenant page
quotas (engine/qos.py `parse_tenant_quotas`, surfaced through the
tenant ledger in the completer heartbeat) bound how much of the pool
any one tenant's prefixes may squat on.

Invariants the churn drill (tests/test_prefix_cache.py) pins:
refcount 0 <=> (free list membership XOR tree retention); no page is
ever in the free list while a table or the tree references it; a
row's mapped prefix path has monotonically non-increasing refcounts
root -> tail (rows always map whole prefixes), so a zero-ref node's
entire subtree is zero-ref and leaf-first eviction can always make
progress.
"""
from __future__ import annotations

import dataclasses
import itertools

from ..utils.faults import fault


@dataclasses.dataclass
class PrefixCacheStats:
    """Counters the completer heartbeat publishes (prefix_* gauges in
    `spt metrics`, ring history in the telemetry lane, sparklines in
    `spt top`)."""

    hits: int = 0             # admissions matching >= 1 full page
    misses: int = 0           # admissions matching nothing
    hit_tokens: int = 0       # prompt tokens served from the tree
    inserts: int = 0          # pages registered
    evictions: int = 0        # pages reclaimed for the free list
    cow_copies: int = 0       # copy-on-write page copies
    quota_rejects: int = 0    # inserts skipped: tenant over quota
    bytes_saved: int = 0      # KV bytes not re-prefilled/committed


class _Node:
    __slots__ = ("toks", "bid", "parent", "children", "lru", "tenant",
                 "tier")

    def __init__(self, toks: tuple, bid: int, parent, tenant: int):
        self.toks = toks            # this page's token ids (exact)
        self.bid = bid              # pool block id holding its K/V
        self.parent = parent        # _Node | None (root child)
        self.children: dict[tuple, _Node] = {}
        self.lru = 0                # last-matched clock tick
        self.tenant = tenant
        # 0 = HBM-resident (bid is a live pool page), 1 = demoted to
        # the host-DRAM tier (bid is -1; the bytes live in the bound
        # HostTier and readmit() device_puts them back on a hit).
        # Leaf-first eviction demotes tails before parents, so on any
        # root->leaf path the tier-1 nodes are a contiguous SUFFIX —
        # the invariant lookup_tiered and readmit ride.
        self.tier = 0


class PrefixCache:
    """One instance per continuous-batching completer, bound to its
    pool via attach() (re-bound — and emptied — whenever the lane
    rebuilds the pool: abort recovery, spec demotion).  All methods
    are called from the single lane thread; there is no locking, by
    the same single-owner contract as the pool's host scheduler."""

    def __init__(self, page: int, *, max_pages: int | None = None,
                 tenant_quotas: dict[int, int] | None = None,
                 default_quota: int | None = None):
        if page < 1:
            raise ValueError("page must be >= 1")
        self.page = page
        self.max_pages = max_pages
        self.tenant_quotas = dict(tenant_quotas or {})
        self.default_quota = default_quota
        self.stats = PrefixCacheStats()
        self._cache = None            # the bound PagedKVCache
        self._children: dict[tuple, _Node] = {}   # root level
        self._by_bid: dict[int, _Node] = {}
        self._tenant_pages: dict[int, int] = {}
        self._clock = itertools.count(1)
        # zero-ref tree pages, maintained INCREMENTALLY on the pool's
        # refcount 0<->1 transitions (on_zero_ref / on_ref) — the
        # admission path reads evictable_count per waiting request,
        # so an O(tree) scan there would tax the lane thread
        self._zero_ref = 0
        # host-DRAM spill tier (engine/kv_tier.HostTier): eviction
        # demotes frozen pages here instead of dropping them, and a
        # DRAM hit readmits via device_put instead of re-prefilling.
        # Bound by the owning lane (bind_tier) together with the
        # per-page export/import callables closed over its model+pool.
        self.tier = None
        self._export_page = None      # (bid) -> (bytes, bytes|None)
        self._import_page = None      # (bid, bytes, bytes|None)
        self._demoted = 0             # tier-1 node count (gauge)

    # -- binding -----------------------------------------------------------

    def attach(self, cache) -> None:
        """Bind (or re-bind) to a pool.  The tree references pool
        block ids, so a rebuilt pool invalidates every node — the old
        pool's pages died with it and must not be returned anywhere.
        Host-tier shadows are keyed by node, so they die with the
        tree (the persistent warm layer, if any, survives and the
        owning lane re-loads it after re-binding)."""
        self._cache = cache
        self._children = {}
        self._by_bid = {}
        self._tenant_pages = {}
        self._zero_ref = 0
        self._demoted = 0
        if self.tier is not None:
            self.tier.clear()

    def bind_tier(self, tier, export_page=None,
                  import_page=None) -> None:
        """Arm the DRAM spill tier: `export_page(bid)` host-copies
        one frozen pool page, `import_page(bid, buf, sbuf)` scatters
        one back (models/decoder.py export_page_bytes /
        import_page_bytes, closed over the CURRENT pool — the lane
        re-binds after every pool rebuild)."""
        self.tier = tier
        self._export_page = export_page
        self._import_page = import_page

    # -- lookup / mapping ---------------------------------------------------

    def lookup(self, ids) -> tuple[list[int], int]:
        """Walk the tree over `ids` at page granularity.  Returns
        (matched block ids in prefix order, matched token count).
        PURE: no stats, no LRU touch — a lookup whose admission is
        then denied (backpressure, raced slot) must neither inflate
        the hit rate the runbook triages on nor refresh LRU stamps
        for a prefix that never got served.  The admitting caller
        records the outcome via commit_hit() / note_miss()."""
        page = self.page
        n_full = len(ids) // page
        bids: list[int] = []
        cur = self._children
        for j in range(n_full):
            chunk = tuple(int(t) for t in ids[j * page:(j + 1) * page])
            node = cur.get(chunk)
            if node is None:
                break
            bids.append(node.bid)
            cur = node.children
        return bids, len(bids) * page

    def lookup_tiered(self, ids
                      ) -> tuple[list[int], int, list["_Node"]]:
        """lookup() extended through the DRAM tier: returns
        (hbm_bids, hbm_match_tokens, tier_nodes) where tier_nodes are
        the consecutive DEMOTED nodes continuing the match past the
        HBM prefix (the tier-1-suffix invariant: demotion is
        leaf-first, so they can only trail).  The caller prices them
        as readmit cost — a device_put per page — against the
        re-prefill a miss would pay, and readmit() brings them back.
        PURE like lookup(): no stats, no LRU touch."""
        page = self.page
        n_full = len(ids) // page
        bids: list[int] = []
        nodes: list[_Node] = []
        cur = self._children
        tier = self.tier
        for j in range(n_full):
            chunk = tuple(int(t) for t in ids[j * page:(j + 1) * page])
            node = cur.get(chunk)
            if node is None:
                break
            if node.tier:
                if tier is None or not tier.has(node):
                    break             # shadow gone: unservable tail
                nodes.append(node)
            elif nodes:
                break                 # defensive: HBM past a demote
            else:
                bids.append(node.bid)
            cur = node.children
        return bids, len(bids) * page, nodes

    def commit_hit(self, ids, match: int) -> None:
        """An admission actually mapped `match` tokens of `ids`: count
        the hit and LRU-touch the served path (re-walk — match/page
        node hops, cheap next to the admission it accompanies)."""
        page = self.page
        tick = next(self._clock)
        cur = self._children
        for j in range(match // page):
            node = cur.get(tuple(int(t)
                                 for t in ids[j * page:(j + 1) * page]))
            if node is None:
                break                  # evicted mid-admission: stale
            node.lru = tick
            cur = node.children
        self.stats.hits += 1
        self.stats.hit_tokens += match

    def note_miss(self) -> None:
        self.stats.misses += 1

    # -- insertion ----------------------------------------------------------

    def insert(self, ids, cache, row: int, tenant: int = 0) -> int:
        """Register the FULL prompt pages of `row` (its table entries
        for pages [0, len(ids)//page)) under their token prefix.
        Pages already present are skipped (the hit path mapped them;
        the row's own duplicates stay private).  Returns pages
        inserted.  A page enters FROZEN: the pool will copy-on-write
        before any append could touch it, and for int8 pools its
        scale never rescales again."""
        if cache is not self._cache:
            return 0                  # stale pool: never adopt its ids
        page = self.page
        n_full = len(ids) // page
        inserted = 0
        parent = None
        cur = self._children
        tick = next(self._clock)
        for j in range(n_full):
            chunk = tuple(int(t) for t in ids[j * page:(j + 1) * page])
            node = cur.get(chunk)
            if node is None:
                bid = int(cache.tables[row, j])
                if bid == 0 or bid in self._by_bid:
                    break             # trash / already-owned: stop
                if not self._admit_page(tenant):
                    break
                node = _Node(chunk, bid, parent, tenant)
                cur[chunk] = node
                self._by_bid[bid] = node
                self._tenant_pages[tenant] = \
                    self._tenant_pages.get(tenant, 0) + 1
                self.stats.inserts += 1
                inserted += 1
                # write-through: the page is frozen as of THIS
                # registration, so its host shadow is taken now —
                # demotion later is pure bookkeeping, and the warm
                # snapshot covers the live set, not just evictees
                self._spill(node)
            elif node.tier:
                # a demoted node on the row's freshly prefilled path:
                # the row holds an identical page (same token chain =>
                # same K/V), so promote the node onto the row's block
                bid = int(cache.tables[row, j])
                if bid == 0 or bid in self._by_bid:
                    break
                if not self._admit_page(node.tenant):
                    break
                node.bid = bid
                node.tier = 0
                self._demoted -= 1
                self._by_bid[bid] = node
                self._tenant_pages[node.tenant] = \
                    self._tenant_pages.get(node.tenant, 0) + 1
                self.stats.inserts += 1
                inserted += 1
                if self.tier is not None and not self.tier.has(node):
                    self._spill(node)
            node.lru = tick
            parent = node
            cur = node.children
        return inserted

    def _spill(self, node) -> bool:
        """Take the host-DRAM shadow of a frozen page (fault site
        `tier.spill` — a death mid-spill leaves the HBM copy
        authoritative and the shadow simply untaken).  Overflow
        victims the tier's LRU drops are pruned: a tier-1 node
        without bytes is unservable."""
        tier = self.tier
        if tier is None or self._export_page is None or node.bid <= 0:
            return False
        try:
            fault("tier.spill")
            buf, sbuf = self._export_page(node.bid)
        except Exception:
            tier.spill_failures += 1
            return False              # HBM copy stays authoritative
        tier.spills += 1
        for dead in tier.put(node, buf, sbuf):
            self._drop_tiered(dead)
        return True

    def _drop_tiered(self, node) -> None:
        """A node's host shadow was dropped (tier capacity).  An
        HBM-resident node just loses its shadow (re-spilled on the
        next insert touch); a DRAM-resident one is unservable — prune
        its whole subtree (all tier-1 by the suffix invariant)."""
        if node.tier == 0:
            return
        siblings = (node.parent.children if node.parent is not None
                    else self._children)
        siblings.pop(node.toks, None)
        stack = [node]
        while stack:
            n2 = stack.pop()
            if self.tier is not None:
                self.tier.drop(n2)
            if n2.tier:
                self._demoted -= 1
            stack.extend(n2.children.values())
            n2.children = {}

    def _admit_page(self, tenant: int) -> bool:
        """Quota + global-cap gate for one insert.  Over quota, the
        tenant's own least-recent zero-ref tail evicts first; only
        when the tenant has nothing reclaimable is the insert
        skipped (quota_rejects)."""
        quota = self.tenant_quotas.get(tenant, self.default_quota)
        if quota is not None and \
                self._tenant_pages.get(tenant, 0) >= quota:
            if not self._evict_one(tenant=tenant):
                self.stats.quota_rejects += 1
                return False
        if self.max_pages is not None and \
                len(self._by_bid) >= self.max_pages:
            if not self._evict_one():
                return False
        return True

    # -- pool hooks (called by PagedKVCache) --------------------------------

    def retains(self, bid: int) -> bool:
        """True when the tree references `bid` — the pool asks on
        every COW decision (a frozen page must never be appended
        into, even at refcount 1)."""
        return bid in self._by_bid

    def on_zero_ref(self, bid: int) -> bool:
        """The pool's refcount for `bid` just hit zero.  True = the
        tree retains it (keep it OFF the free list; it is now
        evictable), False = not ours, free normally."""
        if bid in self._by_bid:
            self._zero_ref += 1
            return True
        return False

    def on_ref(self, bid: int) -> None:
        """`bid` went 0 -> 1 references (a joiner mapped an evictable
        page): it is pinned again, not reclaimable."""
        if bid in self._by_bid:
            self._zero_ref -= 1

    def reclaim(self, n: int) -> int:
        """Evict up to `n` least-recently-matched zero-ref pages back
        to the pool's free list (leaf-first; evicting a tail exposes
        its parent).  Returns pages actually reclaimed — the pool's
        allocator calls this when its free list runs dry."""
        done = 0
        while done < n and self._evict_one():
            done += 1
        return done

    def _evict_one(self, tenant: int | None = None) -> bool:
        cache = self._cache
        if cache is None:
            return False
        victim = None
        for node in self._by_bid.values():
            if any(c.tier == 0 for c in node.children.values()):
                continue              # leaf-first among HBM residents
                                      # (cascade exposes it; tier-1
                                      # children already gave back
                                      # their pages)
            if cache.refcounts[node.bid] != 0:
                continue              # mapped by a live row
            if tenant is not None and node.tenant != tenant:
                continue
            if victim is None or node.lru < victim.lru:
                victim = node
        if victim is None:
            return False
        tier = self.tier
        if tier is not None and not tier.has(victim):
            # no shadow yet (write-through failed or was LRU-dropped):
            # one more chance to demote instead of drop
            self._spill(victim)
        bid = victim.bid
        if tier is not None and tier.has(victim):
            # DEMOTE: the HBM page returns to the pool, the node
            # survives DRAM-resident — a future hit readmits it with
            # a device_put instead of a re-prefill.  Same path parks
            # paused sessions' prefixes.
            del self._by_bid[bid]
            self._tenant_pages[victim.tenant] = \
                max(0, self._tenant_pages.get(victim.tenant, 0) - 1)
            self._zero_ref -= 1        # victims are zero-ref by test
            cache._free.append(bid)
            victim.bid = -1
            victim.tier = 1
            self._demoted += 1
            tier.demotions += 1
            self.stats.evictions += 1
            return True
        siblings = (victim.parent.children if victim.parent is not None
                    else self._children)
        siblings.pop(victim.toks, None)
        # dropping an interior node strands any tier-1 children it
        # still carried (their shadows become unreachable chains)
        for child in victim.children.values():
            child.parent = None
            self._drop_tiered(child)
        victim.children = {}
        del self._by_bid[bid]
        self._tenant_pages[victim.tenant] = \
            max(0, self._tenant_pages.get(victim.tenant, 0) - 1)
        self._zero_ref -= 1            # victims are zero-ref by test
        cache._free.append(bid)
        self.stats.evictions += 1
        return True

    # -- DRAM tier: readmission + warm restore ------------------------------

    def readmit(self, nodes, cache) -> list[int]:
        """Bring demoted pages back to HBM in path order: alloc +
        device_put + re-registration, no re-prefill (fault site
        `tier.readmit` fires before each page's alloc so the chaos
        drill can die between a DRAM hit and its import — the shadow
        stays intact and the node stays DRAM-resident).  Pages return
        holding refcount 1; the caller transfers that reference into
        the admitted row's block table (decref-then-map_shared, like
        any freshly committed page).  Stops at the first failure —
        the admission simply prefills the remaining suffix."""
        if cache is not self._cache or self.tier is None \
                or self._import_page is None:
            return []
        tier = self.tier
        out: list[int] = []
        for node in nodes:
            if node.tier == 0:
                break                  # raced back already: stale list
            ent = tier.get(node)       # LRU-touches the shadow
            if ent is None:
                break
            try:
                fault("tier.readmit")
                bid = cache._alloc_page()
            except Exception:
                tier.readmit_failures += 1
                break
            try:
                self._import_page(bid, ent[0], ent[1])
            except Exception:
                cache.refcounts[bid] = 0
                cache._free.append(bid)
                tier.readmit_failures += 1
                break
            node.bid = bid
            node.tier = 0
            self._demoted -= 1
            self._by_bid[bid] = node
            self._tenant_pages[node.tenant] = \
                self._tenant_pages.get(node.tenant, 0) + 1
            node.lru = next(self._clock)
            tier.readmits += 1
            out.append(bid)
        return out

    def adopt_tiered(self, ids, tenant: int = 0):
        """Warm-restore adoption: create (or extend) the chain of
        DRAM-resident nodes covering `ids`' full pages and return the
        tail node (None for sub-page chains).  Restored nodes carry
        no HBM page — the first hit readmits them."""
        page = self.page
        n_full = len(ids) // page
        if n_full == 0:
            return None
        cur = self._children
        parent = None
        node = None
        for j in range(n_full):
            chunk = tuple(int(t) for t in ids[j * page:(j + 1) * page])
            node = cur.get(chunk)
            if node is None:
                node = _Node(chunk, -1, parent, tenant)
                node.tier = 1
                self._demoted += 1
                cur[chunk] = node
            parent = node
            cur = node.children
        return node

    # -- gauges -------------------------------------------------------------

    def evictable_count(self) -> int:
        """Zero-ref tree pages: reclaimable capacity the admission
        path may count on top of the free list (a zero-ref node's
        whole subtree is zero-ref — see the module invariants — so
        every one of them is reachable by leaf-first eviction).
        O(1): maintained incrementally on the pool's refcount
        transitions; the churn drill pins it against a brute-force
        recount."""
        return self._zero_ref if self._cache is not None else 0

    def shared_pages(self) -> int:
        return len(self._by_bid)

    def demoted_pages(self) -> int:
        """DRAM-resident (tier 1) node count — the heartbeat's tier
        occupancy gauge, O(1) like evictable_count."""
        return self._demoted

    def tenant_pages(self) -> dict[int, int]:
        return {t: n for t, n in self._tenant_pages.items() if n}
