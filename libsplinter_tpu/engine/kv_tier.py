"""Tiered KV: HBM -> host-DRAM spill tier + persistent warm layer.

Before this module every page the radix prefix cache gave back was
GONE: `PrefixCache._evict_one` dropped the block to the free list and
the next identical prompt paid a full re-prefill, and every supervised
restart or PR 15 scale-up attached stone cold — elastic capacity
bought cold caches (the failure mode ROADMAP item 3 names).  The tier
splits "reclaim the HBM page" from "forget the KV":

  HBM (tier 0)    the paged pool — pages the ragged kernel reads.
  DRAM (tier 1)   `HostTier`: host copies of FROZEN tree pages (PR
                  14's freeze/refcount machinery marks them immutable,
                  hence safely copyable).  Eviction DEMOTES a page
                  here instead of dropping it; the radix node survives
                  with a `tier` tag and re-admission is a device_put +
                  block-table write (`PrefixCache.readmit`), not a
                  re-prefill.  PowerInfer (arxiv 2312.12456) grounds
                  the hot-set-in-fast-tier split: the working set
                  stays in HBM, the long tail pays one PCIe copy.
  File (warm)     `TierPersist`: the radix index + host-tier pages
                  checkpoint into a file-backed persistent store
                  segment (store.py BACKEND_FILE — the reference's
                  `libsplinter_p.so` build variant, PAPER.md §L2), so
                  a supervised restart or a scale-up replica attaches
                  WARM.

Host copies are written THROUGH at insert time (`PrefixCache._spill`,
fault site `tier.spill`): a page enters the tree frozen and its DRAM
shadow is taken immediately, so demotion at eviction time is pure
bookkeeping and the persistent snapshot always covers the live warm
set — not just whatever happened to be evicted before the crash.

Snapshot protocol (the `__ho_<idx>` write-record-last idiom from the
disagg handoff, epoch-bumped): payload keys land FIRST under an
epoch-namespaced prefix (`__tier_e<E>.p<i>` / `.s<i>` / `.n<i>`), the
index record (`__tier_index`) lands LAST naming that epoch, and only
then is the previous epoch swept.  A crash mid-save leaves the old
record pointing at the old epoch's untouched keys — still a valid
snapshot.  `load()` validates EVERY byte before mutating anything
(version, geometry, per-page lengths), so a torn/partial snapshot is
detected and discarded with a typed reason the heartbeat surfaces
(`tier_restore_reason`), never half-loaded; fault site `tier.restore`
fires between validation and adoption so the chaos drill can prove a
mid-restore death falls back cold with zero admitted loss.
"""
from __future__ import annotations

import json
from collections import OrderedDict

from ..utils.faults import fault

__all__ = ["HostTier", "TierPersist", "tier_geometry"]

# the persistent segment's index record key: written LAST, read FIRST
INDEX_KEY = "__tier_index"


def _page_key(epoch: int, i: int) -> str:
    return f"__tier_e{epoch}.p{i}"


def _scale_key(epoch: int, i: int) -> str:
    return f"__tier_e{epoch}.s{i}"


def _entry_key(epoch: int, i: int) -> str:
    return f"__tier_e{epoch}.n{i}"


def tier_geometry(model, cache) -> dict:
    """The pool geometry a snapshot was taken under.  A restored page
    is raw device bytes — replaying it into a pool with ANY other
    shape/dtype would serve silent garbage, so load() refuses on the
    slightest mismatch (typed reason: geometry_mismatch)."""
    cfg = model.cfg
    return {"page": int(cache.page), "layers": int(cfg.layers),
            "kv_heads": int(cfg.kv_heads),
            "head_dim": int(cfg.head_dim),
            "quantized": bool(getattr(cache, "quantized", False)),
            "wire_dtype": str(model._page_wire_dtype(cache)),
            "page_bytes": int(model.page_wire_bytes(cache))}


def _iter_nodes(pc):
    """(node, full token prefix) over every tree node — the chain a
    node's page was computed under IS its identity (KV at position p
    depends on every token before p)."""
    stack = [((), n) for n in pc._children.values()]
    while stack:
        prefix, node = stack.pop()
        full = prefix + node.toks
        yield node, full
        stack.extend((full, c) for c in node.children.values())


class HostTier:
    """Host-RAM page pool: node -> (page bytes, scale bytes | None),
    LRU-bounded at `capacity` pages.  Single-owner like the tree it
    shadows (the lane thread); dropping an entry for a DRAM-resident
    (tier 1) node makes that node unservable, so the PrefixCache
    prunes it — put() returns the overflow victims for exactly that.
    """

    def __init__(self, capacity_pages: int):
        self.capacity = max(1, int(capacity_pages))
        self._entries: "OrderedDict" = OrderedDict()
        self.dirty = False            # snapshot content changed
        # counters the heartbeat publishes (tier_* gauges)
        self.spills = 0               # host shadow copies taken
        self.spill_failures = 0       # export failed: page stayed HBM
        self.demotions = 0            # evictions turned into demotes
        self.readmits = 0             # DRAM -> HBM device_put returns
        self.readmit_failures = 0
        self.capacity_drops = 0       # shadows LRU-dropped at capacity
        self.restored = 0             # pages adopted from a snapshot

    def __len__(self) -> int:
        return len(self._entries)

    def bytes_held(self) -> int:
        return sum(len(b) + (len(s) if s else 0)
                   for b, s in self._entries.values())

    def has(self, node) -> bool:
        """Membership without an LRU touch — lookups that may be
        denied must not refresh recency (same purity contract as
        PrefixCache.lookup)."""
        return node in self._entries

    def peek(self, node):
        return self._entries.get(node)

    def get(self, node):
        """Fetch for readmission: LRU-touches the entry."""
        ent = self._entries.get(node)
        if ent is not None:
            self._entries.move_to_end(node)
        return ent

    def put(self, node, page_bytes: bytes,
            scale_bytes: bytes | None) -> list:
        """Insert/refresh a shadow; returns the LRU overflow victims
        (nodes whose shadows were dropped to stay under capacity —
        the caller prunes any that were DRAM-resident)."""
        self._entries[node] = (page_bytes, scale_bytes)
        self._entries.move_to_end(node)
        self.dirty = True
        dropped = []
        while len(self._entries) > self.capacity:
            victim, _ = self._entries.popitem(last=False)
            self.capacity_drops += 1
            dropped.append(victim)
        return dropped

    def drop(self, node) -> None:
        if self._entries.pop(node, None) is not None:
            self.dirty = True

    def clear(self) -> None:
        if self._entries:
            self.dirty = True
        self._entries.clear()


class TierPersist:
    """The file-backed warm layer: one persistent store segment per
    serving lane family (BACKEND_FILE — mmap survives the process),
    holding the radix index + host-tier page payloads, epoch-bumped
    and write-record-last.  Replica 0 writes; every spawning replica
    reads, so a scale-up attaches warm from the leader's snapshot."""

    def __init__(self, name: str, *, capacity_pages: int,
                 max_len: int, page_bytes: int):
        from ..store import Store
        self.name = name
        self.epoch = 0
        # per entry: page payload + entry meta (+ scales when
        # quantized) = 3 keys; two epochs coexist transiently during
        # a save, plus the index record and slack
        nslots = 8 * max(8, int(capacity_pages)) + 64
        # the entry meta's token chain is the long pole: up to
        # max_len ids rendered as JSON ints
        max_val = max(4096, int(page_bytes) + 256,
                      int(max_len) * 8 + 512)
        st = None
        try:
            st = Store.open(name, persistent=True)
            if st.max_val < max_val or st.nslots < nslots:
                # geometry grew across a restart (bigger pages or a
                # raised tier capacity): the old segment cannot hold
                # the new snapshot — recreate cold
                st.close()
                st = None
                Store.unlink(name, persistent=True)
        except OSError:
            st = None
        if st is None:
            st = Store.create(name, nslots=nslots, max_val=max_val,
                              vec_dim=8, persistent=True,
                              overwrite=True)
        self.store = st

    def close(self) -> None:
        try:
            self.store.close()
        except Exception:
            pass

    @staticmethod
    def unlink(name: str) -> None:
        from ..store import Store
        Store.unlink(name, persistent=True)

    # -- save ---------------------------------------------------------------

    def save(self, pc, tier: HostTier, geom: dict) -> bool:
        """Checkpoint every shadowed page + its token chain.  Payload
        keys first under the NEW epoch, index record last, previous
        epoch swept only after the record lands — a death anywhere in
        between leaves the old snapshot authoritative."""
        st = self.store
        entries = []
        for node, full in _iter_nodes(pc):
            ent = tier.peek(node)
            if ent is not None:
                entries.append((full, int(node.tenant), ent))
        epoch = self.epoch + 1
        try:
            for i, (full, tenant, (buf, sbuf)) in enumerate(entries):
                st.set(_page_key(epoch, i), buf)
                slen = 0
                if sbuf is not None:
                    st.set(_scale_key(epoch, i), sbuf)
                    slen = len(sbuf)
                st.set(_entry_key(epoch, i), json.dumps(
                    {"ids": [int(t) for t in full],
                     "plen": len(buf), "slen": slen,
                     "tenant": tenant}))
            st.set(INDEX_KEY, json.dumps(
                {"v": 1, "epoch": epoch, "count": len(entries),
                 "geom": geom}))
        except (KeyError, OSError, ValueError):
            # partial new epoch: the old record still points at the
            # old epoch's untouched keys — sweep our orphans
            self._sweep(keep=self.epoch)
            return False
        self.epoch = epoch
        self._sweep(keep=epoch)
        tier.dirty = False
        return True

    def _sweep(self, keep: int) -> None:
        """Drop every epoch-namespaced key outside `keep`; never
        raises (a failed sweep only wastes slots until the next)."""
        st = self.store
        prefix_keep = f"__tier_e{keep}."
        try:
            for key in st.list():
                if key.startswith("__tier_e") \
                        and not key.startswith(prefix_keep):
                    try:
                        st.unset(key)
                    except (KeyError, OSError):
                        continue
        except (KeyError, OSError):
            pass

    # -- load ---------------------------------------------------------------

    def load(self, pc, tier: HostTier, geom: dict) -> tuple[int, str]:
        """Attach warm: validate the whole snapshot, then adopt every
        chain as DRAM-tier radix nodes (readmission to HBM happens
        lazily, on the first hit).  Returns (pages restored, typed
        cold-fallback reason) — reason "" means warm.  NOTHING is
        mutated until every byte has been validated, so a torn
        snapshot is discarded, never half-loaded."""
        st = self.store
        try:
            raw = st.get(INDEX_KEY)
        except (KeyError, OSError):
            return 0, "missing_record"
        try:
            rec = json.loads(raw)
        except ValueError:
            return 0, "torn_header"
        if not isinstance(rec, dict) or rec.get("v") != 1:
            return 0, "torn_header"
        try:
            epoch = int(rec["epoch"])
            count = int(rec["count"])
        except (KeyError, TypeError, ValueError):
            return 0, "torn_header"
        if rec.get("geom") != geom:
            return 0, "geometry_mismatch"
        self.epoch = max(self.epoch, epoch)
        chains = []
        for i in range(count):
            try:
                meta = json.loads(st.get(_entry_key(epoch, i)))
                buf = bytes(st.get(_page_key(epoch, i)))
            except (KeyError, OSError, ValueError):
                return 0, "torn_page"
            ids = meta.get("ids") if isinstance(meta, dict) else None
            if not isinstance(ids, list) \
                    or int(meta.get("plen", -1)) != len(buf) \
                    or len(buf) != geom["page_bytes"]:
                return 0, "torn_page"
            sbuf = None
            slen = int(meta.get("slen", 0))
            if slen:
                try:
                    sbuf = bytes(st.get(_scale_key(epoch, i)))
                except (KeyError, OSError):
                    return 0, "torn_page"
                if len(sbuf) != slen:
                    return 0, "torn_page"
            chains.append((ids, int(meta.get("tenant", 0)),
                           buf, sbuf))
        # every byte validated — the chaos drill crashes/raises HERE
        # (tests/chaos_child.py tier_restore): a mid-restore death
        # must fall back cold, never serve a half-adopted tree
        try:
            fault("tier.restore")
            n = 0
            # parents first, so every chain extends an existing path
            chains.sort(key=lambda c: len(c[0]))
            for ids, tenant, buf, sbuf in chains:
                node = pc.adopt_tiered(ids, tenant)
                if node is None:
                    continue
                for dead in tier.put(node, buf, sbuf):
                    pc._drop_tiered(dead)
                n += 1
        except Exception:
            # clean cold fallback: empty the half-built tree + tier
            if pc._cache is not None:
                pc.attach(pc._cache)
            tier.clear()
            return 0, "restore_failed"
        tier.restored += n
        tier.dirty = False
        return n, ""
