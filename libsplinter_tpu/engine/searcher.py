"""Event-driven query-coalescing search daemon.

BENCH_r05 measured the search cliff: single-query kernel dispatch runs
at ~12 q/s through the tunneled runtime while a QB=256 batch sustains
~2262 q/s — the per-dispatch round trip, not the kernel, bounds
single-client throughput.  The CLI's client-side scoring cannot close
that gap: every client pays its own dispatch.

This daemon moves scoring server-side, mirroring the embedder's
drain/wake structure (engine/embedder.py):

  - blocks on the store's signal group (LBL_SEARCH_REQ label watch);
  - drains ALL pending search requests per wake and COALESCES them
    into QB-bucketed batches against pre-compiled fused top-k
    programs (ops/similarity.topk_program — the streaming Pallas
    kernel: block-local select + merge in VMEM, O(k*Q) off-chip);
  - scores against its own StagedLane (full upload once, O(dirty)
    refresh per drain);
  - commits per-request results back as __sr_<idx> rows and clears
    the request label — N concurrent clients cost ceil(N / QB)
    device dispatches, not N.

Request contract (one slot per request):
  value       JSON {"k": int, "bloom": int?} — the search params
  vector lane the query vector in the SAME slot (the embedding daemon
              puts it there when the client labels its scratch key
              LBL_EMBED_REQ first — the classic CLI flow — or the
              client writes it directly with vec_set)
  labels      LBL_SEARCH_REQ (+ LBL_WAITING), then bump.

Result contract: JSON in search_result_key(request_slot_index) —
{"s": scores, "i": slot indices, "keys": resolved keys, "fetched": K,
"n": valid candidate count} — sorted by similarity desc, system keys
("__" prefix: scratch rows, heartbeats, other requests' slots)
already dropped.  The daemon clears LBL_SEARCH_REQ + LBL_WAITING and
bumps the request key; clients poll their own request key.  A request
whose slot changed mid-service (epoch mismatch) is NOT committed and
is retried next drain — the embedder's race discipline.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Sequence

import numpy as np

from .. import _native as N
from ..obs.devtime import DEVTIME
from ..obs.recorder import FlightRecorder
from ..obs.spans import SpanWriter, sweep_span_stages
from ..store import Store
from ..utils import faults
from ..utils.faults import fault
from ..utils.trace import device_profile, tracer
from . import protocol as P
from .qos import (AdmissionController, TenantLedger, WaitingRow,
                  parse_tenant_weights, prune_idle_counters)
from .resident import CallbackWindow

log = logging.getLogger("libsplinter_tpu.searcher")

# query-count pad buckets: a drain's requests batch into the smallest
# bucket that holds them (chunked through the largest otherwise), so
# the daemon compiles a handful of programs, not one per concurrency
# level.  The floor of 8 matches the kernel's lane-width query pad —
# a single query already computes 8 columns, so coalescing up to 8 is
# literally free.
QB_BUCKETS = (8, 32, 256)

# fetch-k pad buckets (candidates pulled per query).  Bounded by
# ops.similarity.FUSED_K_MAX — the cushion above the request's k
# absorbs post-select drops (system keys, the requester's own row).
K_BUCKETS = (16, 32, 64, 128)
K_CUSHION = 4

# orphaned __sr_<idx> result rows older than this are reaped by the
# periodic sweep (a client that timed out never calls consume_result);
# generous vs the CLI's 2 s submit timeout so no live poller races it
RESULT_TTL_S = 120.0


def _k_bucket(k: int) -> int:
    for b in K_BUCKETS:
        if k <= b:
            return b
    return k                     # beyond the schedule: exact (legacy path)


def _qb_chunks(nq: int) -> list[int]:
    """Decompose a drain's query count into QB bucket sizes with
    padding waste bounded at 2x (the StagedLane _chunk_plan
    discipline): 40 queries batch as [32, 8], never one 256-query
    dispatch scoring 216 zero rows."""
    out: list[int] = []
    smallest, largest = QB_BUCKETS[0], QB_BUCKETS[-1]
    while nq > 0:
        if nq >= largest:
            out.append(largest)
            nq -= largest
            continue
        cover = next(b for b in QB_BUCKETS if nq <= b)
        if cover <= 2 * nq or cover == smallest:
            out.append(cover)                 # tail: waste <= 2x
            break
        out.append(max(b for b in QB_BUCKETS if b <= nq))
        nq -= out[-1]
    return out


@dataclasses.dataclass
class SearcherStats:
    wakes: int = 0
    drains: int = 0
    requests: int = 0            # requests gathered (incl. retried)
    served: int = 0              # results committed
    dispatches: int = 0          # device top-k program calls
    coalesced_max: int = 0       # most requests in one dispatch
    parse_errors: int = 0        # malformed / vectorless requests
    raced: int = 0               # slot changed mid-service; retried
    full_refreshes: int = 0      # lane full uploads
    # -- K-deep dispatch overlap (engine/resident.py): batch k's
    # select+commit resolve while batches k+1..k+K compute ---------
    inflight_peak: int = 0       # max un-awaited batch dispatches held
    ready_selects: int = 0       # batch already complete at select
    blocking_selects: int = 0    # host blocked on the device fetch
    # -- failure-domain accounting (the per-batch firewall) ----------
    batch_faults: int = 0        # batches that failed and degraded
    retried_unfused: int = 0     # recovered by the unfused retry
    retried_single: int = 0      # requests recovered one-by-one
    req_failures: int = 0        # requests failed with error records
    drain_faults: int = 0        # whole drains failed by the firewall
    results_reaped: int = 0      # orphaned __sr_ rows retired
    # -- multi-tenant QoS (engine/qos.py) ----------------------------
    deadline_expired: int = 0    # fast-failed: client deadline passed
    shed: int = 0                # typed overloaded + retry_after_ms
    deferred: int = 0            # held for a later drain (fairness)

    def coalesce_ratio(self) -> float:
        """Requests served per device dispatch (1.0 = no batching win;
        the whole point of the daemon is pushing this toward QB)."""
        return self.served / self.dispatches if self.dispatches else 0.0


class _Request:
    __slots__ = ("idx", "epoch", "k", "bloom", "fast", "qvec", "stamp",
                 "tenant", "deadline", "traced", "span")

    def __init__(self, idx, epoch, k, bloom, fast, qvec, stamp,
                 tenant=0, deadline=None, traced=False):
        self.idx = idx
        self.epoch = epoch
        self.k = k
        self.bloom = bloom
        self.fast = fast         # bf16 MXU scoring requested
        self.qvec = qvec
        self.stamp = stamp       # (trace_id, client_wall_ts) | None
        self.tenant = tenant     # label-word tenant id (0 = untagged)
        self.deadline = deadline  # absolute wall-clock deadline | None
        self.traced = traced     # LBL_TRACED seen at gather (the span
                                 # opens at ADMISSION, not gather — a
                                 # deferred request keeps its stamp
                                 # for the drain that serves it)
        self.span = None         # obs.spans.PendingSpan | None


class Searcher:
    """The daemon object.  Drive it with run() (blocking loop) or
    run_once() (single drain — tests and --oneshot)."""

    def __init__(self, store: Store, *, lane=None,
                 group: int = P.GROUP_SEARCH,
                 use_pallas: bool | None = None,
                 mxu_bf16: bool = False,
                 fused: bool | None = None,
                 interpret: bool = False,
                 block_n: int = 1024,
                 inflight_depth: int = 2,
                 coalesce_window_ms: float = 0.0,
                 admit_cap: int | None = None,
                 queue_high_water: int | None = None,
                 retry_after_ms: int | None = None,
                 tenant_weights: dict[int, float] | None = None,
                 replica: int = 0):
        from ..ops import StagedLane

        self.store = store
        self.group = group
        # elastic lanes (protocol.StripeView): replica r drains only
        # its own slot-index stripe of the request space; the map is
        # store state re-read at each drain, so a supervisor
        # re-stripe lands at the next drain boundary
        self.replica = int(replica)
        self.stripes = P.StripeView(store, "searcher", self.replica)
        self._hb_key = P.replica_stats_key(P.KEY_SEARCH_STATS,
                                           self.replica)
        self._trace_key = P.replica_stats_key(P.KEY_SEARCH_TRACE,
                                              self.replica)
        self.use_pallas = use_pallas
        self.mxu_bf16 = mxu_bf16
        self.fused = fused
        self.interpret = interpret
        self.block_n = block_n
        # K-deep dispatch overlap: un-awaited top-k batch dispatches
        # held before the oldest's select+commit resolves — batch k's
        # host-side commit work overlaps the device computing batches
        # k+1..k+K, so the per-dispatch runtime round trip amortizes
        # to ~floor/K on multi-batch drains.  1 = the pre-PR-7
        # fetch-in-dispatch-order behavior.
        self.inflight_depth = max(1, inflight_depth)
        # >0: sleep this long after a wake before draining, widening
        # the coalescing window at the cost of per-request latency.
        # 0 (default): the natural window — requests landing while a
        # drain's device work flies batch into the next drain.
        self.coalesce_window_ms = coalesce_window_ms
        # multi-tenant QoS (engine/qos.py): admit_cap bounds how many
        # requests one drain services (the fairness granularity —
        # backlog beyond it re-plans next drain with accumulated
        # stride credit; None = service everything, the pre-QoS
        # behavior); queue_high_water bounds the deferred backlog —
        # overflow is shed with the typed overloaded record instead of
        # queueing unboundedly.  Deadline fast-fail is always on: a
        # request that stamps a deadline gets expiry checked whether
        # or not admission control is configured.
        self.admit_cap = admit_cap
        self.qos = AdmissionController(
            weights=tenant_weights, high_water=queue_high_water,
            **({"retry_after_ms": retry_after_ms}
               if retry_after_ms is not None else {}))
        self.tenants = TenantLedger()
        self._had_deferred = False
        self.lane = lane or StagedLane(store)
        self._all_req_rows: list[int] = []
        self.stats = SearcherStats()
        self.generation = 0          # bumped at attach (restart marker)
        self.recorder = FlightRecorder()
        self.spans = SpanWriter(store, "searcher")
        self._trace_published = 0
        self._stage_acc: dict | None = None
        self._bid = -1
        self._running = False

    # -- wiring ------------------------------------------------------------

    def attach(self) -> None:
        """Claim the shard, bind the wake label, arm/join the event
        bus — the embedder's attach sequence under the search ids."""
        st = self.store
        try:
            self._bid = st.shard_claim(P.SHARD_SEARCH, N.ADV_WILLNEED,
                                       P.PRIO_SEARCH, 30_000_000)
        except OSError:
            self._bid = -1
        st.watch_label_register(P.BIT_SEARCH_REQ, self.group)
        st.bus_attach()   # adopts the bus when a crashed owner
                          # left a dead pid in the header
        self.generation = P.bump_generation(st, self._hb_key)
        # compile events ledgered from here carry this generation —
        # a restart's re-warmup is distinguishable in the ring
        DEVTIME.generation = max(DEVTIME.generation, self.generation)

    def warmup(self, ks: Sequence[int] = (10, 64)) -> None:
        """Pre-compile the QB-bucketed top-k programs against the live
        lane so the first coalesced drain of each shape doesn't pay an
        XLA compile on the wake path (.xla_cache persists them).  `ks`
        are REQUEST k values: they map through the same cushion +
        bucket + lane clamp as a real drain's, and the probe mask is
        an ndarray like every real drain's — a different transform (or
        mask=None's different jit pytree) would compile programs no
        serving request ever hits.  The defaults cover the CLI's
        limit-10 fetch (bucket 64 -> k_fetch 128) and direct k<=12
        API requests (k_fetch 16)."""
        with DEVTIME.warmup_phase():
            arr = self.lane.refresh()
            d = self.store.vec_dim
            mask = np.ones(self.store.nslots, np.float32)
            for k in ks:
                k_fetch = min(_k_bucket(k + K_CUSHION),
                              self.store.nslots)
                # both precision variants: a --fast client's first
                # request must not stall a whole coalesced drain on a
                # fresh compile
                for fast in (False, True):
                    fn = self._program(k_fetch, mxu_bf16=fast)
                    for qb in QB_BUCKETS:
                        fn(arr, np.zeros((qb, d), np.float32), mask,
                           self.lane.norms)

    def _program(self, k_fetch: int, mxu_bf16: bool = False):
        from ..ops.similarity import topk_program

        return topk_program(
            k_fetch, batched=True, use_pallas=self.use_pallas,
            mxu_bf16=self.mxu_bf16 or mxu_bf16, block_n=self.block_n,
            fused=self.fused, interpret=self.interpret)

    # -- request gathering -------------------------------------------------

    def _gather_requests(self) -> list[_Request]:
        """Drain stage: discover labelled rows, parse params, gather
        query vectors torn-safely.  Rows mid-write stay labelled and
        retry next drain; rows with malformed params or no query
        vector get an error result immediately (they can never
        succeed, so retrying would spin)."""
        fault("searcher.gather")
        st = self.store
        self.stripes.refresh()        # a re-stripe lands HERE, at the
        rows = st.enumerate_indices(P.LBL_SEARCH_REQ)  # drain boundary
        # the UNfiltered enumeration doubles as this drain's
        # request-scratch mask input (_mask_for): a peer replica's
        # pending request rows hold query vectors too
        self._all_req_rows = [int(i) for i in rows]
        rows = [i for i in rows if self.stripes.owns(int(i))]
        if not rows:
            return []
        out: list[_Request] = []
        rows_a = np.asarray(rows, np.uint32)
        vecs, eps = st.vec_gather(rows_a)
        for j, idx in enumerate(rows):
            idx = int(idx)
            e = int(eps[j])
            if eps[j] == Store.GATHER_TORN:
                continue                      # writer active: next drain
            labels = st.labels_at(idx)
            if not labels & P.LBL_SEARCH_REQ:
                continue                      # serviced by a peer drain
            try:
                raw = st.get_at(idx)
            except (KeyError, OSError):
                continue
            if st.epoch_at(idx) != e:
                continue                      # torn: retried next wake
            self.stats.requests += 1
            try:
                req = json.loads(raw.rstrip(b"\0"))
                k = int(req["k"])
                if k <= 0:
                    raise ValueError("k must be positive")
                bloom = int(req.get("bloom", 0))
                fast = bool(req.get("fast", False))
                deadline = req.get("deadline")
                deadline = float(deadline) if deadline else None
            except (ValueError, KeyError, TypeError):
                self._fail(idx, e, "bad request params")
                continue
            # deadline may also ride the companion stamp (the generic
            # wire form the raw-text lanes use); the JSON field wins
            if deadline is None and labels & P.LBL_DEADLINE:
                deadline = P.read_deadline(st, idx, epoch=e)
            qvec = vecs[j]
            if not np.abs(qvec).max() > 0:
                self._fail(idx, e, "no query vector in request slot")
                continue
            out.append(_Request(idx, e, k, bloom, fast, qvec, None,
                                tenant=P.read_tenant(labels),
                                deadline=deadline,
                                traced=bool(labels & P.LBL_TRACED)))
        return out

    # -- admission (multi-tenant QoS) --------------------------------------

    def _admit(self, reqs: list[_Request]) -> list[_Request]:
        """Partition the gathered requests through the shared admission
        policy: expired deadlines fail fast with a typed record, the
        fairness-ordered admit set (up to admit_cap) is serviced now,
        overflow past queue_high_water is shed with `overloaded` +
        retry_after_ms, and the rest stay labelled for the next drain
        (their tenants lead it — stride state persists)."""
        if not reqs:
            self._had_deferred = False    # backlog gone (or raced):
            return reqs                   # the redrain loop must end
        cap = self.admit_cap if self.admit_cap else len(reqs)
        plan = self.qos.plan(
            [WaitingRow(r, r.tenant, r.deadline) for r in reqs], cap)
        # spans open at the admission decision, not at gather: a
        # DEFERRED request keeps its stamp (and LBL_TRACED) for the
        # drain that actually serves it.  begin() consumes the stamp
        # (the consume-early discipline; span records buffer until
        # the heartbeat-cadence flush).
        for row in (*plan.admit, *plan.expired, *plan.shed):
            r = row.item
            if r.traced:
                r.span = self.spans.begin(r.idx, r.epoch,
                                          tenant=r.tenant)
                r.stamp = r.span.stamp if r.span is not None else None
        for row in plan.expired:
            r = row.item
            self.tenants.bump(r.tenant, "deadline_expired")
            P.clear_deadline(self.store, r.idx)
            self._fail(r.idx, r.epoch, P.ERR_DEADLINE,
                       counter="deadline_expired")
            self.spans.commit(r.span, status=P.ERR_DEADLINE)
        for row in plan.shed:
            r = row.item
            self.tenants.bump(r.tenant, "shed")
            self.stats.shed += 1
            P.clear_deadline(self.store, r.idx)
            self._commit_result(
                r.idx, r.epoch,
                P.overloaded_record(self.qos.retry_after_ms))
            self.spans.commit(r.span, status=P.ERR_OVERLOADED)
        self.stats.deferred += len(plan.deferred)
        self._had_deferred = bool(plan.deferred)
        for row in plan.admit:
            if row.item.tenant or row.item.deadline is not None:
                self.tenants.bump(row.item.tenant, "admitted")
            if row.item.deadline is not None:
                P.clear_deadline(self.store, row.item.idx)
        return [row.item for row in plan.admit]

    def _fail(self, idx: int, epoch: int, err: str, *,
              counter: str = "parse_errors") -> None:
        """Terminal per-request failure: commit an error record and
        clear the labels so the client unblocks immediately instead of
        burning its timeout (parse errors and post-retry batch
        failures share this path; `counter` says which)."""
        setattr(self.stats, counter, getattr(self.stats, counter) + 1)
        self._commit_result(idx, epoch, {"err": err})

    def _fail_span(self, r: _Request) -> None:
        """Commit a FAILED request's span with a typed status — a
        trace tree must never render an error-recorded hop as ok —
        and detach it so _end_trace cannot double-commit."""
        span, r.span = r.span, None
        self.spans.commit(span, status="error")

    # -- masks -------------------------------------------------------------

    def _mask_for(self, bloom: int, req_rows: np.ndarray) -> np.ndarray:
        """Candidate mask for one bloom group (the shared
        protocol.candidate_mask definition); every CURRENT request row
        is masked out of every group (request slots hold query vectors
        — without this, concurrent similar queries would surface each
        other's scratch rows at the top).  The WHOLE enumeration the
        drain's gather captured (_all_req_rows) — not just this
        batch's rows — is what gets masked: under striped replicas a
        peer's still-pending request rows are request scratch too,
        and masking only our own stripe would make R=2 results
        diverge from R=1 (caught by tests/test_elastic.py).  Reusing
        the gather's enumeration costs no extra label scan per bloom
        group."""
        mask = P.candidate_mask(self.store, bloom)
        mask[req_rows] = 0.0
        pending = getattr(self, "_all_req_rows", None)
        if pending:
            mask[np.asarray(pending, np.int64)] = 0.0
        return mask

    # -- the drain ---------------------------------------------------------

    def drain(self, *, wake_ms: float = 0.0) -> int:
        """One drain cycle: gather -> coalesce -> dispatch -> commit.
        Returns the number of requests served."""
        st = self.store
        self.stats.drains += 1
        acc = (dict.fromkeys(P.SEARCH_STAGES, 0.0)
               if tracer.enabled else None)
        self._stage_acc = acc
        if acc is not None:
            acc["wake"] = wake_ms
        with tracer.span("search.drain_cycle"):
            t0 = time.perf_counter()
            reqs = self._admit(self._gather_requests())
            if acc is not None:
                acc["drain"] = (time.perf_counter() - t0) * 1e3
            if not reqs:
                # idle drains stay out of the stage histograms —
                # quantiles must describe serviced requests, not
                # reconciliation sweeps (drain_cycle still counts all)
                self._stage_acc = None
                return 0
            if acc is not None:
                tracer.record("search.wake", wake_ms)
                tracer.record("search.drain", acc["drain"])
            if self._bid >= 0:
                try:
                    st.shard_rebid(self._bid)
                except OSError:
                    pass
            with device_profile("search"):
                try:
                    served = self._service(reqs)
                except Exception as ex:
                    # drain-level firewall: _service already contains
                    # per-batch failures, so anything landing here
                    # (lane refresh, mask build, an exhausted retry
                    # budget) fails the WHOLE drain's requests with
                    # error records — clients unblock, the run loop
                    # never unwinds
                    log.exception("drain failed; failing %d requests",
                                  len(reqs))
                    self.stats.drain_faults += 1
                    for r in reqs:
                        try:
                            self._fail(r.idx, r.epoch,
                                       f"drain failed: {ex}",
                                       counter="req_failures")
                        except Exception:
                            pass      # store down too: retried next drain
                        self._fail_span(r)
                    served = 0
        self._end_trace(reqs)
        self.stats.served += served
        return served

    def _service(self, reqs: list[_Request]) -> int:
        """Score stage (lane refresh + async batched dispatch), select
        stage (the device fetches), commit stage (result rows + label
        clears) — select+commit resolve through a K-deep
        InflightWindow (engine/resident.py), so batch k's host-side
        fetch/commit work overlaps the device computing batches
        k+1..k+K instead of every batch queueing behind a full drain
        of dispatches.  Every batch is its own failure domain: a batch
        whose dispatch or fetch raises degrades through
        _score_degraded (unfused retry, then request-by-request) while
        its siblings commit normally — a device failure mid-service
        must never unwind the run loop or starve unrelated requests."""
        acc = self._stage_acc
        t0 = time.perf_counter()
        full0 = self.lane.full_uploads
        arr = self.lane.refresh()
        self.stats.full_refreshes += self.lane.full_uploads - full0
        req_rows = np.asarray([r.idx for r in reqs], np.int64)

        # select/commit wall + served count accrued by the window's
        # resolver as batches complete (out of lockstep with dispatch)
        state = {"served": 0, "select_ms": 0.0, "commit_ms": 0.0}
        win = CallbackWindow(
            self.inflight_depth,
            lambda payload, pend, ready: self._resolve_batch(
                arr, payload, pend, ready, state))

        # group by (bloom prefilter, bf16 flag) — the kernel mask and
        # the matmul precision are shared across a batch — bucket each
        # group's queries, dispatch each batch and push it into the
        # window: jax's async dispatch queues device work back to
        # back, and the window resolves whatever completes while
        # later batches are still being staged
        groups: dict[tuple, list[_Request]] = {}
        for r in reqs:
            groups.setdefault((r.bloom, r.fast), []).append(r)
        # one mask per BLOOM value: the fast/exact split shares it, and
        # the default mask's O(nslots) epochs() snapshot runs once per
        # drain, not once per precision group
        masks = {bloom: self._mask_for(bloom, req_rows)
                 for bloom in {b for b, _ in groups}}
        for (bloom, fast), group in groups.items():
            mask = masks[bloom]
            lo = 0
            for qb in _qb_chunks(len(group)):
                chunk = group[lo: lo + qb]
                lo += len(chunk)
                # clamped to the lane: an oversized client k (or the
                # CLI's x8 growth crossing nslots) must cost a smaller
                # fetch, never a top_k(k > rows) trace error that
                # poison-pills the drain
                k_fetch = min(
                    _k_bucket(max(r.k for r in chunk) + K_CUSHION),
                    self.store.nslots)
                q = np.zeros((qb, self.store.vec_dim), np.float32)
                for i, r in enumerate(chunk):
                    q[i] = r.qvec
                # dispatch failures defer to the select stage's
                # degradation ladder (pend=None) so sibling batches
                # still queue on the device back to back
                try:
                    fault("searcher.dispatch")
                    fn = self._program(k_fetch, mxu_bf16=fast)
                    pend = fn(arr, q, mask, self.lane.norms)
                except Exception as ex:
                    log.warning("batch dispatch failed: %s", ex)
                    pend = None
                self.stats.dispatches += 1
                self.stats.coalesced_max = max(
                    self.stats.coalesced_max, len(chunk))
                win.push((chunk, k_fetch, mask, q), pend)
        win.flush()
        self.stats.inflight_peak = max(self.stats.inflight_peak,
                                       win.inflight_peak)
        self.stats.ready_selects += win.ready_resolves
        self.stats.blocking_selects += win.blocking_resolves
        t3 = time.perf_counter()
        if acc is not None:
            # the resolver accrued select/commit; score is the
            # remaining host-side wall of the service (refresh, mask
            # build, batching, dispatch) — the stages stay disjoint
            acc["select"] = state["select_ms"]
            acc["commit"] = state["commit_ms"]
            acc["score"] = max(
                (t3 - t0) * 1e3 - state["select_ms"]
                - state["commit_ms"], 0.0)
            for stage in ("score", "select", "commit"):
                tracer.record(f"search.{stage}", acc[stage])
        return state["served"]

    def _resolve_batch(self, arr, payload, pend, ready: bool,
                       state: dict) -> None:
        """Window resolver: one batch's select (device fetch, with the
        per-batch degradation ladder) + commit, in COMPLETION order —
        runs while sibling batches still compute on-device."""
        import jax

        chunk, k_fetch, mask, q = payload
        t1 = time.perf_counter()
        try:
            fault("searcher.select")
            if pend is None:
                raise RuntimeError("batch dispatch failed")
            s_all, i_all = jax.device_get(pend)
            ok = None
        except Exception as ex:
            s_all, i_all, ok = self._score_degraded(
                arr, chunk, q, mask, k_fetch, ex)
        t2 = time.perf_counter()
        state["select_ms"] += (t2 - t1) * 1e3
        for i, r in enumerate(chunk):
            if ok is not None and not ok[i]:
                continue           # already failed with an error record
            try:
                state["served"] += self._commit_hits(
                    r, np.asarray(s_all[i]), np.asarray(i_all[i]),
                    k_fetch)
            except Exception as ex:
                self._fail(r.idx, r.epoch,
                           f"result commit failed: {ex}",
                           counter="req_failures")
                self._fail_span(r)
        state["commit_ms"] += (time.perf_counter() - t2) * 1e3

    def _score_degraded(self, arr, chunk: list[_Request], q, mask,
                        k_fetch: int, ex: Exception):
        """The per-batch degradation ladder: a failed fused batch
        retries UNFUSED at the same shape (the streaming kernel is the
        newest code; the score-matrix path is the battle-tested
        fallback), then request-by-request at the smallest QB bucket.
        Requests that still fail get error records via _fail — fewer
        served queries beat an unwound daemon.  Returns
        (s_all, i_all, ok_rows); ok_rows[i] False = row i already
        failed terminally."""
        import jax

        from ..ops.similarity import topk_program

        self.stats.batch_faults += 1
        log.warning("search batch of %d failed (%s); retrying unfused",
                    len(chunk), ex)
        norms = self.lane.norms
        try:
            fault("searcher.dispatch")
            fn = topk_program(k_fetch, batched=True,
                              use_pallas=self.use_pallas,
                              mxu_bf16=False, block_n=self.block_n,
                              fused=False, interpret=self.interpret)
            s_all, i_all = jax.device_get(fn(arr, q, mask, norms))
            self.stats.retried_unfused += 1
            return s_all, i_all, None
        except Exception as ex2:
            log.warning("unfused retry failed (%s); degrading to "
                        "single-query dispatches", ex2)
        qb0 = QB_BUCKETS[0]
        s_out = np.full((len(chunk), k_fetch), -np.inf, np.float32)
        i_out = np.full((len(chunk), k_fetch), -1, np.int64)
        ok = [False] * len(chunk)
        for i, r in enumerate(chunk):
            try:
                fault("searcher.dispatch")
                q1 = np.zeros((qb0, self.store.vec_dim), np.float32)
                q1[0] = r.qvec
                fn = topk_program(k_fetch, batched=True,
                                  use_pallas=self.use_pallas,
                                  mxu_bf16=False, block_n=self.block_n,
                                  fused=False, interpret=self.interpret)
                s1, i1 = jax.device_get(fn(arr, q1, mask, norms))
                s_out[i], i_out[i] = s1[0], i1[0]
                ok[i] = True
                self.stats.retried_single += 1
            except Exception as ex3:
                try:
                    self._fail(r.idx, r.epoch,
                               f"search failed after retries: {ex3}",
                               counter="req_failures")
                except Exception:
                    pass          # store down too: retried next drain
                self._fail_span(r)
        return s_out, i_out, ok

    # -- commit ------------------------------------------------------------

    def _commit_hits(self, r: _Request, scores: np.ndarray,
                     idxs: np.ndarray, k_fetch: int) -> int:
        """Filter one request's fetched candidates (valid score, live
        key, not a system/scratch row) down to its k and commit."""
        st = self.store
        n_valid = 0
        out_s, out_i, out_k = [], [], []
        for score, idx in zip(scores, idxs):
            if score <= -1e29 or idx < 0:
                break                         # sorted desc: filler next
            n_valid += 1
            if len(out_s) >= r.k:
                continue                      # n_valid still counts
            key = st.key_at(int(idx))
            if key is None or key.startswith("__"):
                continue                      # system/scratch rows
            out_s.append(round(float(score), 6))
            out_i.append(int(idx))
            out_k.append(key)
        rec = {"s": out_s, "i": out_i, "keys": out_k,
               "fetched": int(min(k_fetch, st.nslots)), "n": n_valid}
        return self._commit_result(r.idx, r.epoch, rec)

    def _commit_result(self, idx: int, epoch: int, rec: dict) -> int:
        """Epoch-gated result commit: write __sr_<idx>, clear the
        request labels, bump — but ONLY if the request slot is
        unchanged since the gather (a client racing a rewrite must
        get the NEW query serviced, not the old result).  The record
        carries the request epoch (`e`) and a wall timestamp (`ts`):
        the orphan sweep retires rows whose slot moved on or whose
        client never consumed them."""
        fault("searcher.commit")
        st = self.store
        if st.epoch_at(idx) != epoch:
            self.stats.raced += 1
            return 0
        key = st.key_at(idx)
        if key is None:
            return 0
        rec = dict(rec)
        rec["e"] = int(epoch)
        rec["ts"] = round(time.time(), 3)
        rkey = P.search_result_key(idx)
        # an oversized result halves its hit list until it fits —
        # fewer candidates beat a request wedged forever
        # (publish_trace_ring's degradation discipline)
        while True:
            try:
                st.set(rkey, json.dumps(rec))
                break
            except OSError:
                if not rec.get("s"):
                    rec = {"err": "result too large for store max_val",
                           "e": int(epoch), "ts": round(time.time(), 3)}
                    try:
                        st.set(rkey, json.dumps(rec))
                    except OSError:
                        return 0
                    break
                half = max(len(rec["s"]) // 2, 0)
                rec["s"] = rec["s"][:half]
                rec["i"] = rec["i"][:half]
                rec["keys"] = rec["keys"][:half]
                rec["truncated"] = True
            except KeyError:
                return 0
        # recheck the epoch right before the label flip: the result
        # write above took real time (size-degradation retries), and a
        # client rewriting its slot in that window must get its NEW
        # request serviced next drain — clearing the label here would
        # hand it the OLD query's answer.  (The label stays set, so
        # submit_search never reads the stale __sr_ row, and the next
        # service overwrites it.)
        if st.epoch_at(idx) != epoch:
            self.stats.raced += 1
            return 0
        try:
            st.label_or(rkey, P.LBL_READY)
            st.label_clear(key, P.LBL_SEARCH_REQ | P.LBL_WAITING)
            st.bump(key)
        except (KeyError, OSError):
            return 0
        return 1

    # -- flight recording --------------------------------------------------

    def _end_trace(self, reqs: list[_Request]) -> None:
        acc, self._stage_acc = self._stage_acc, None
        stage_map = ({s: acc[s] for s in P.SEARCH_STAGES}
                     if acc is not None else None)
        # the drain's device window rides the first committed span
        # (drain-scoped attribution, SpanWriter.commit)
        device_ms = DEVTIME.take_lane_ms("searcher")
        committed = 0
        # span commits run whether or not the histogram tracer is on:
        # span capture is always-on, bounded by head sampling
        for r in reqs:
            if r.span is not None:
                self.spans.commit(
                    r.span, stages=stage_map,
                    device_ms=device_ms if committed == 0 else None)
                committed += 1
        if acc is None:
            return
        stage_sum = sum(acc.values())
        tracer.record("search.e2e", stage_sum)
        if not committed:
            # tail-based retention: slow unstamped drains keep full
            # SEARCH_STAGES detail (one `tail: true` span + a slow-log
            # entry resolvable via `spt trace show`)
            thr = self.recorder.slow_threshold_ms()
            if thr is not None and stage_sum > thr:
                tid = self.spans.tail_span(
                    "<drain>", stage_sum, stages=stage_map,
                    device_ms=device_ms if device_ms > 0 else None)
                if tid is not None:
                    self.recorder.record(
                        tid, "<drain>", stage_sum,
                        [[s, round(acc[s], 3)]
                         for s in P.SEARCH_STAGES])
        now_wall = time.time()
        events = [[s, round(acc[s], 3)] for s in P.SEARCH_STAGES]
        for r in reqs:
            if r.stamp is None:
                continue
            tid, ts = r.stamp
            try:
                key = self.store.key_at(r.idx)
            except (KeyError, OSError):
                key = None
            wall = (now_wall - ts) * 1e3 if ts > 0 else stage_sum
            self.recorder.record(tid, key, wall,
                                 [list(e) for e in events])

    # -- daemon loop -------------------------------------------------------

    def run_once(self) -> int:
        """One full drain (tests, --oneshot).  Buffered span records
        flush here; the run loop flushes on the heartbeat cadence."""
        n = self.drain()
        self.spans.flush()
        return n

    def sweep_results(self, *, ttl_s: float = RESULT_TTL_S,
                      now: float | None = None) -> int:
        """Retire orphaned __sr_<idx> result rows.  A client that
        times out never calls consume_result, and a daemon that
        crashed mid-commit leaves rows no client is polling — without
        a reaper they accumulate until the store is full of corpses.
        A row is an orphan when its request slot is gone, its slot
        epoch moved past the one the result was committed under (a
        NEW request owns the slot; its service will write a fresh
        row), or it outlived ttl_s.  Runs on the heartbeat cadence
        (O(nslots) key walk — never on the wake path); a restarted
        daemon's first sweep reclaims the previous generation's
        leftovers.  Returns the reaped count."""
        fault("searcher.sweep")
        st = self.store
        now = time.time() if now is None else now
        pfx = P.SEARCH_RESULT_PREFIX
        reaped = 0
        for key in st.list():
            if not key.startswith(pfx):
                continue
            try:
                idx = int(key[len(pfx):])
            except ValueError:
                continue
            try:
                rec = json.loads(st.get(key).rstrip(b"\0"))
            except (KeyError, OSError, ValueError):
                continue              # unreadable now: next sweep
            if not isinstance(rec, dict):
                rec = {}
            e, ts = rec.get("e"), rec.get("ts")
            if idx >= st.nslots or st.key_at(idx) is None:
                retire = True         # request slot gone entirely
            elif isinstance(e, int) and st.epoch_at(idx) != e:
                retire = True         # slot epoch moved on
            elif isinstance(ts, (int, float)):
                retire = (now - float(ts)) > ttl_s
            else:
                retire = True         # pre-TTL format: unowned legacy row
            if retire:
                try:
                    st.unset(key)
                    reaped += 1
                except (KeyError, OSError):
                    pass
        self.stats.results_reaped += reaped
        # the pending-span staging rows share the same reaper cadence
        # (orphans: raced rewrites, crashed drains nobody re-ran)
        sweep_span_stages(st, ttl_s=ttl_s, now=now)
        return reaped

    def publish_stats(self) -> None:
        """Heartbeat: JSON stats snapshot into __searcher_stats (the
        CLI's daemon-liveness probe reads its ts; `spt metrics`
        renders the rest).  With tracing on, the SEARCH_STAGES
        quantiles and the flight-recorder ring ride along — same
        section contract as the other daemons."""
        self.spans.flush()            # heartbeat cadence, off the
        payload = {**dataclasses.asdict(self.stats),  # wake path
                   "spans_obs": self.spans.counters(),
                   "coalesce_ratio": round(
                       self.stats.coalesce_ratio(), 4),
                   "generation": self.generation,
                   # overlap-window gauge: inflight_peak pinned at
                   # inflight_depth means the window saturates (raise
                   # --inflight-depth for more dispatch amortization)
                   "inflight_depth": self.inflight_depth,
                   "lane": self.lane.counters()}
        if self.replica or self.stripes.epoch:
            payload["replica"] = self.replica
            payload["stripe"] = self.stripes.snapshot()
        if self.admit_cap or self.qos.high_water is not None:
            payload["qos"] = {
                "admit_cap": self.admit_cap or 0,
                "queue_high_water": self.qos.high_water
                if self.qos.high_water is not None else -1,
                "retry_after_ms": self.qos.retry_after_ms}
        tenants = self.tenants.snapshot()
        if tenants:
            # per-tenant admitted/shed/deadline_expired/served_tokens:
            # `spt metrics` renders one labeled series per tenant
            payload["tenants"] = tenants
        prune_idle_counters(
            payload, bool(self.admit_cap
                          or self.qos.high_water is not None
                          or tenants))
        if faults.armed():
            payload["faults"] = faults.stats()
        payload["compile_events"] = DEVTIME.compile_events("searcher")
        devtime = DEVTIME.heartbeat_section("searcher")
        if devtime:
            payload["devtime"] = devtime
        DEVTIME.flush(self.store)
        if tracer.enabled:
            P.attach_trace_sections(payload, tracer, self.recorder,
                                    "search.")
        P.publish_heartbeat(self.store, self._hb_key, payload)
        if tracer.enabled:
            self._trace_published = P.maybe_publish_trace_ring(
                self.store, self._trace_key, self.recorder,
                self._trace_published)

    def run(self, *, idle_timeout_ms: int = 100,
            stop_after: float | None = None,
            heartbeat_interval_s: float = 5.0) -> None:
        """The daemon loop: block on the signal group, drain, repeat.
        The heartbeat doubles as the liveness signal the CLI's
        dispatch check reads, so it publishes on an interval even
        when idle."""
        self._running = True
        st = self.store
        last = st.signal_count(self.group)
        deadline = (time.monotonic() + stop_after) if stop_after else None
        next_beat = 0.0                       # publish immediately
        next_retire_check = 0.0
        while self._running:
            got = st.signal_wait(self.group, last,
                                 timeout_ms=idle_timeout_ms)
            t_wake = time.perf_counter()
            # loop-level exception firewall: the drain already fails
            # requests instead of raising, so anything landing here is
            # a gather/store-level surprise — log it and keep serving
            # (the crash-only discipline: the loop never unwinds, and
            # a real crash is the supervisor's job to absorb)
            try:
                if got is not None:
                    last = got
                    self.stats.wakes += 1
                    if self.coalesce_window_ms > 0:
                        time.sleep(self.coalesce_window_ms / 1e3)
                    self.drain(
                        wake_ms=(time.perf_counter() - t_wake) * 1e3)
                    # work-conserving under admit_cap: a drain that
                    # deferred backlog (fairness granularity, not a
                    # throughput cap) re-drains immediately — each
                    # pass re-plans admission with accumulated stride
                    # credit, so the backlog clears in fair slices
                    # instead of waiting out the heartbeat cadence
                    redrains = 0
                    while self._had_deferred and self._running \
                            and redrains < 256:
                        redrains += 1
                        self.drain()
                now = time.monotonic()
                if now >= next_beat:
                    if got is None:
                        # reconciliation on the heartbeat cadence,
                        # never per idle timeout: a request whose
                        # pulse raced a prior drain (or a torn row
                        # left pending) retries here without an
                        # O(nslots) label scan every idle wakeup.  A
                        # restarted daemon's FIRST pass through here
                        # reclaims the stranded requests (label bit
                        # set, no inflight owner) a crashed
                        # predecessor left behind.
                        self.drain()
                    self.sweep_results()
                    self.publish_stats()
                    next_beat = now + heartbeat_interval_s
                if self.replica and now >= next_retire_check:
                    # scale-down drain: stripes closed by the
                    # supervisor; the drain above finished in-flight
                    # work, so exit cleanly and let it reap us
                    next_retire_check = now + 1.0
                    if self.stripes.poll_retired():
                        log.info("replica %d destriped — retiring",
                                 self.replica)
                        self.publish_stats()
                        break
            except Exception:
                self.stats.drain_faults += 1
                log.exception("run loop cycle failed; continuing")
                now = time.monotonic()
            if deadline and now > deadline:
                break

    def stop(self) -> None:
        self._running = False


# -- client side -----------------------------------------------------------

def daemon_live(store: Store, *, max_age_s: float = 15.0) -> bool:
    """True when a search daemon is live enough to route a query
    through — the CLI's dispatch probe.  Heartbeat freshness alone
    used to hold the answer for max_age_s after a crash (every client
    then burned its full submit timeout); now the heartbeat's pid is
    kill-0 probed, so a dead daemon reads dead instantly, and a
    supervisor heartbeat whose breaker marked the search lane down
    vetoes dispatch outright (protocol.heartbeat_live)."""
    return P.heartbeat_live(store, P.KEY_SEARCH_STATS,
                            max_age_s=max_age_s, lane="searcher")


def submit_search(store: Store, key: str, k: int, *, bloom: int = 0,
                  fast: bool = False,
                  timeout_ms: int = 2000,
                  tenant: int = 0,
                  deadline_ms: float | None = None,
                  trace=None,
                  retry: bool = True) -> dict | None:
    """Client side: turn `key` (whose vector lane already holds the
    embedded query) into a search request and wait for the daemon's
    result.  fast requests bf16 MXU scoring server-side (the CLI's
    --fast).  Returns the result record, or None on timeout (callers
    fall back to client-side scoring).

    `tenant` tags the request's label word for per-tenant admission;
    `deadline_ms` (relative) rides the request JSON as an absolute
    wall-clock deadline the daemon fast-fails behind.  The submit
    routes through the shared retry wrapper (engine/client.py): a
    typed `overloaded` shed is retried after its retry_after_ms hint
    (jittered) inside the same timeout budget, and a lane whose
    supervisor breaker is open fails fast instead of burning the
    timeout (retry=False restores one bare attempt)."""
    from .client import PENDING, call_with_retries, wait_with_repulse

    deadline_ts = (time.time() + deadline_ms / 1e3
                   if deadline_ms is not None else None)

    def attempt(left_ms: float) -> dict | None:
        idx = store.find_index(key)
        req = {"k": int(k), "bloom": int(bloom), "fast": bool(fast)}
        if deadline_ts is not None:
            req["deadline"] = round(deadline_ts, 6)
        store.set(key, json.dumps(req))
        if tenant:
            P.stamp_tenant(store, key, tenant)
        if trace:
            P.stamp_trace_ctx(store, key, trace)
        store.label_or(key, P.LBL_SEARCH_REQ | P.LBL_WAITING)
        store.bump(key)

        def check():
            if store.labels(key) & P.LBL_SEARCH_REQ:
                return PENDING
            try:
                raw = store.get(P.search_result_key(idx))
                return json.loads(raw.rstrip(b"\0"))
            except (KeyError, OSError, ValueError):
                return None

        return wait_with_repulse(store, key, left_ms, check)

    if not retry:
        return attempt(timeout_ms)
    return call_with_retries(attempt, timeout_ms=timeout_ms,
                             store=store, lane="searcher")


def consume_result(store: Store, key: str) -> None:
    """Retire a serviced request: drop the result row (the request key
    itself is the caller's to keep or unset)."""
    try:
        store.unset(P.search_result_key(store.find_index(key)))
    except (KeyError, OSError):
        pass


def main(argv: list[str] | None = None) -> int:
    """CLI entry: python -m libsplinter_tpu.engine.searcher --store NAME"""
    import argparse

    ap = argparse.ArgumentParser(
        description="splinter-tpu search daemon (query-coalescing fused "
                    "top-k over the store's vector lane)")
    ap.add_argument("--store", required=True)
    ap.add_argument("--persistent", action="store_true")
    ap.add_argument("--oneshot", action="store_true")
    ap.add_argument("--fast", action="store_true",
                    help="bf16 MXU scoring (2x kernel throughput, "
                         "~2e-2 score precision)")
    ap.add_argument("--coalesce-window-ms", type=float, default=0.0)
    ap.add_argument("--inflight-depth", type=int, default=2,
                    help="K-deep dispatch overlap: un-awaited top-k "
                         "batch dispatches held before the oldest's "
                         "select+commit resolves (1 = fetch in "
                         "dispatch order, the pre-overlap behavior)")
    ap.add_argument("--idle-timeout-ms", type=int, default=100)
    ap.add_argument("--replica", type=int, default=0,
                    help="striped replica index (elastic lanes): "
                         "drain only the stripes the lane's stripe "
                         "map assigns this replica; heartbeat "
                         "publishes replica-suffixed "
                         "(__searcher_stats.rN)")
    ap.add_argument("--admit-cap", type=int, default=None,
                    help="multi-tenant QoS: max requests serviced per "
                         "drain (the fairness granularity; backlog "
                         "re-plans next drain with stride credit; "
                         "default: unlimited)")
    ap.add_argument("--queue-high-water", type=int, default=None,
                    help="multi-tenant QoS: max deferred backlog — "
                         "overflow is shed with a typed `overloaded` "
                         "result + retry_after_ms hint (default: "
                         "never shed)")
    ap.add_argument("--retry-after-ms", type=int, default=None,
                    help="retry hint carried by shed results")
    ap.add_argument("--tenant-weights", default=None,
                    help="per-tenant fair-share weights, "
                         "TENANT:W[,TENANT:W...] (unlisted tenants "
                         "weigh 1)")
    ap.add_argument("--warmup", action="store_true",
                    help="pre-compile the QB-bucketed top-k programs "
                         "before serving")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    if os.environ.get("SPTPU_FORCE_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    from ..utils.jaxplatform import enable_compile_cache
    enable_compile_cache()
    store = Store.open(args.store, persistent=args.persistent)
    sr = Searcher(store, mxu_bf16=args.fast,
                  inflight_depth=args.inflight_depth,
                  coalesce_window_ms=args.coalesce_window_ms,
                  admit_cap=args.admit_cap,
                  queue_high_water=args.queue_high_water,
                  retry_after_ms=args.retry_after_ms,
                  tenant_weights=parse_tenant_weights(
                      args.tenant_weights),
                  replica=args.replica)
    sr.attach()
    if args.warmup:
        t0 = time.monotonic()
        sr.warmup()
        log.info("warmup compiled in %.1fs", time.monotonic() - t0)
    if args.oneshot:
        n = sr.run_once()
        log.info("oneshot served %d searches", n)
        return 0
    try:
        sr.run(idle_timeout_ms=args.idle_timeout_ms)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
