"""Disaggregated prefill/decode serving lanes (ROADMAP item 1).

Prefill is compute-bound (dense bucket programs + the per-bucket
commit scatter); decode is memory-bound (the ragged paged-attention
chunk).  The unified continuous completer interleaves both, so a long
joiner's prefill stalls every live decode chunk and drains the K-deep
window.  These two Completer subclasses split the phases across lane
types behind the UNCHANGED label protocol (TPLA, arxiv 2508.15881, is
the blueprint; the queue-wait/service decomposition the spans already
measure per phase says the split pays):

  PrefillLane   WAITING -> SERVICING: renders + claims exactly like
                the unified lane, runs ONLY dense bucket prefill into
                a scratch pool row (suffix-only under prefix sharing),
                samples + streams the first token, exports the row's
                pages to `__ho_<idx>` wire keys, lands the handoff
                record, and flips the row to DECODE_READY.  QoS here
                is phase-aware: plan() gets the rolling prefill-wall
                EMA as slack, so a deadline that would expire inside
                prefill fast-fails BEFORE paying it.

  DecodeLane    DECODE_READY -> SERVICING|DECODE_READY: adopts
                committed rows at chunk edges through run_continuous's
                _lane_admit hook and runs ONLY ragged paged decode —
                its K-deep window is never again stalled by a joiner's
                prefill.  Adoption seats the row exactly where a
                unified join would have left it (carry token, budget,
                reservation), so greedy output is byte-identical.

The handoff is crash-safe both directions: a died prefill lane's
half-committed row is still SERVICING in ITS stripes — stripe-scoped
recovery sweeps the orphan wire keys and re-queues it WAITING; a died
decode lane's adopted rows carry SERVICING|DECODE_READY — recovery
truncates the slot back to the handoff byte length (`plen`) and drops
SERVICING, so any live decode replica re-adopts from the wire pages
(or re-prefills from the recorded token ids when the wire is gone).
Zero admitted requests are ever lost.

PR 15's elastic lanes get what they were built for: `prefill` and
`decode` are two supervisor LaneSpec types with different autoscaler
signals (prefill scales on queue pressure, decode on pool occupancy),
their own stripe maps, replica heartbeats (__prefill_stats /
__decode_stats) and devtime programs (prefill.bucket_commit /
decode.paged_chunk).
"""
from __future__ import annotations

import time

from ..obs.devtime import DEVTIME
from ..utils.faults import fault
from ..utils.trace import tracer
from . import protocol as P
from .completer import Completer

__all__ = ["PrefillLane", "DecodeLane"]


class PrefillLane(Completer):
    """The compute-bound half: dense bucket prefill + commit scatter
    only, handing each committed row off at DECODE_READY."""

    LANE = "prefill"
    HB_KEY = P.KEY_PREFILL_STATS

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if getattr(self, "_model", None) is None:
            raise ValueError(
                "disaggregated lanes require a model backend "
                "(generate_fn cannot export KV pages)")
        # paged programs register under "prefill.*" (the alias maps
        # paged_commit -> bucket_commit, so the ledger shows the
        # ROADMAP's `prefill.bucket_commit`); the trunk + samplers
        # stay canonical "completer.*"
        self._model.devtime_lane = self.LANE
        # rolling prefill wall EMA (seconds) — the phase-aware QoS
        # slack: a deadline inside the expected prefill cost
        # fast-fails before paying it
        self._pf_ema_s = 0.0
        self._lane_stats = {"handoffs": 0, "handoff_failed": 0,
                            "handoff_wire_mb": 0.0}

    def _max_wire_pages(self) -> int:
        """Worst-case wire-page count one slot's handoff can occupy —
        the sweep bound when no record survived to consult."""
        cfg = self._model.cfg
        return -(-cfg.max_len // max(1, self.page_size))

    def _reclaim_stranded(self) -> int:
        """Prefill-crash recovery: a SERVICING row in OUR stripes died
        mid-prefill or mid-export (the DECODE_READY flip lands LAST,
        after the record) — sweep any orphan wire keys and re-queue it
        WAITING.  The restarted stream re-renders from scratch, same
        as the unified lane's crash story.

        Rows carrying DECODE_READY are past the flip and belong to
        the decode lane (its stripe map is independent over the same
        slot space — a live decode replica may be mid-decode on the
        row under SERVICING|DECODE_READY): never touch their record
        or wire pages here."""
        st = self.store
        self.stripes.refresh()
        n = 0
        for idx in st.enumerate_indices(P.LBL_SERVICING):
            if not self.stripes.owns(int(idx)):
                continue
            try:
                labels = st.labels_at(idx)
            except (KeyError, OSError):
                continue
            if labels & P.LBL_DECODE_READY:
                continue
            key = st.key_at(idx)
            if key is None:
                continue
            P.clear_handoff(st, idx, pages=self._max_wire_pages())
            try:
                st.label_clear(key, P.LBL_SERVICING)
                st.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
                n += 1
            except (KeyError, OSError):
                continue
        if n:
            self.stats.reclaimed += n
            self._debug(f"reclaimed {n} stranded prefill rows")
        return n

    def warmup_paged(self) -> None:
        super().warmup_paged()
        if self._paged_ok():
            # the first handoff at serve time must not compile
            self._model.warmup_handoff(self._ensure_paged_cache(),
                                       export=True, adopt=False)

    def _lane_payload(self, payload: dict) -> None:
        payload["lane"] = self.LANE
        payload.update(self._lane_stats)
        payload["prefill_wall_ema_ms"] = round(self._pf_ema_s * 1e3, 3)

    # -- the prefill+handoff service ---------------------------------------

    def _handoff_one(self, idx: int) -> bool:
        """Serve one WAITING slot: claim, dense prefill into the
        scratch row, sample + stream the first token, export the pages
        to wire keys, land the record, flip DECODE_READY.  Returns
        True when the slot was consumed (handed off, finished, or
        typed-rejected); False leaves it WAITING for the next cycle
        (backpressure / race)."""
        import numpy as np
        st = self.store
        m, tok = self._model, self._tok
        cache = self._ensure_paged_cache()
        peek = self._read_rendered(idx)
        if peek is None:
            return False
        ids = self._clip_context(tok.encode(peek[1]), bucketed=True)
        pc = getattr(cache, "prefix_cache", None)
        hit_bids: list[int] = []
        match = 0
        tier_nodes: list = []
        if pc is not None and len(ids):
            hit_bids, match, tier_nodes = pc.lookup_tiered(ids)
            # keep >= 1 suffix token to prefill: the handoff needs
            # the last-position logits for the first sample (the
            # unified lane's fully-covered replay trick needs a
            # decode chunk this lane never runs).  Trim the DRAM run
            # first — dropping a tier node costs nothing readmitted
            # yet, dropping an HBM page forfeits committed work
            while tier_nodes \
                    and match + len(tier_nodes) * cache.page \
                    >= len(ids):
                tier_nodes = tier_nodes[:-1]
            while hit_bids and not tier_nodes and match >= len(ids):
                hit_bids = hit_bids[:-1]
                match -= cache.page
            if not hit_bids and not tier_nodes:
                match = 0
        if len(ids):
            # peek-before-claim backpressure, prompt-only: the DECODE
            # reservation is the adopting lane's pool's problem
            need = cache.pages_needed(len(ids)) - len(hit_bids)
            pinned = sum(1 for b in hit_bids
                         if cache.refcounts[b] == 0)
            if need > cache.available_pages - pinned:
                self.stats.join_backpressure += 1
                return False
        tenant, dl = self._qos_meta(idx)
        prep = self._prepare(idx, peek=peek)
        if prep is None:
            return False
        key, _rendered, t0, _stamp = prep
        if not len(ids):
            self._finalize(key, t0, 0, False)
            return True
        tp0 = time.perf_counter()
        row = 0                       # serial scratch row
        if hit_bids or tier_nodes:
            fault("completer.prefix_map")
            if hit_bids:
                # pin the HBM prefix FIRST: readmission allocations
                # below can trigger reclaim, and an unpinned zero-ref
                # hit page would be fair game for that eviction pass
                cache.map_shared(row, hit_bids)
            if tier_nodes:
                # DRAM hit: readmitted pages arrive holding refcount
                # 1 — drop each to zero-ref (tree-retained), then let
                # map_shared's 0→1 bump pin them for the scratch row.
                # Partial readmission just lengthens the suffix
                tier_bids = pc.readmit(tier_nodes, cache)
                for b in tier_bids:
                    cache._decref(b)
                if tier_bids:
                    cache.map_shared(row, tier_bids)
                hit_bids = hit_bids + tier_bids
                match += len(tier_bids) * cache.page
            if not hit_bids:
                pc.note_miss()       # every readmit failed
            else:
                cache.lengths[row] = match
                pc.commit_hit(ids, match)
                pc.stats.bytes_saved += \
                    match * cache.kv_bytes_per_token()
                if tenant:
                    self.tenants.bump(tenant, "prefix_hit_pages",
                                      len(hit_bids))
        elif pc is not None:
            pc.note_miss()
        suffix = ids[match:]
        if not cache.ensure(row, len(ids)):
            # defensive (pinned-aware gate above): re-queue, same as
            # the unified admit()'s unreachable branch
            cache.free_row(row)
            self.stats.join_backpressure += 1
            self._requeue_failed([idx])
            return True
        try:
            if getattr(cache, "quantized", False) and suffix:
                fault("completer.kv_quant_commit")
            if hit_bids:
                logits = m.paged_append_prefill(
                    cache, np.asarray(suffix, np.int32), row)
            else:
                logits = m.paged_prefill_row(
                    cache, np.asarray(ids, np.int32), row)
            if pc is not None:
                ins = pc.insert(ids, cache, row, tenant)
                if ins and tenant:
                    self.tenants.bump(tenant, "prefix_cached_pages",
                                      ins)
            # splint: ignore[SPL201] reason=the documented host "sample" stage (CONT_INFER_STAGES): one scalar draw per request so the first token streams before the handoff
            t = int(m.sample(logits))
            tp1 = time.perf_counter()
            tracer.record("infer.join", (tp1 - tp0) * 1e3)

            n_tok = truncated = vanished = 0
            if t != tok.eos_id:
                res = self._flush(key, tok.token_to_piece(t))
                truncated, vanished = res == "full", res == "gone"
                n_tok = 1
            if t == tok.eos_id or self.max_new <= 1 \
                    or truncated or vanished:
                # nothing left to decode (or the slot is full/gone):
                # this row finishes IN the prefill lane — no handoff
                self._finalize(key, t0, n_tok, bool(truncated),
                               bool(vanished))
                return True

            # -- the handoff: wire pages, record, DECODE_READY flip --
            wire_pages = 0
            if m.page_wire_bytes(cache) < st.max_val - 1:
                try:
                    pages_b, scales_b = m.export_row_pages(cache, row)
                    for j, buf in enumerate(pages_b):
                        pk = P.handoff_page_key(idx, j)
                        st.set(pk, buf)
                        st.label_or(pk, P.LBL_DEBUG)
                        if scales_b[j] is not None:
                            sk = P.handoff_scale_key(idx, j)
                            st.set(sk, scales_b[j])
                            st.label_or(sk, P.LBL_DEBUG)
                    wire_pages = len(pages_b)
                    self._lane_stats["handoff_wire_mb"] = round(
                        self._lane_stats["handoff_wire_mb"]
                        + wire_pages * m.page_wire_bytes(cache) / 1e6,
                        3)
                except (KeyError, OSError):
                    # store too full for the wire: the record's token
                    # ids still let the decode lane re-prefill
                    P.clear_handoff(st, idx,
                                    pages=self._max_wire_pages())
                    wire_pages = 0
            # the chaos matrix crashes HERE — wire keys written, no
            # record, row still SERVICING: _reclaim_stranded must
            # sweep the orphans and re-queue (tests/test_disagg.py)
            fault("prefill.handoff")
            rec = {"len": int(len(ids)),
                   "ids": [int(i) for i in ids],
                   "carry": t, "n_tok": 1,
                   "remaining": self.max_new - 1,
                   "disp_left": self.max_new - 1,
                   "plen": st.value_len(key), "t0": int(t0),
                   "tenant": int(tenant),
                   "deadline": dl, "wire_pages": wire_pages,
                   "quant": bool(getattr(cache, "quantized", False))}
            if not P.write_handoff_record(st, idx, rec):
                # no record -> no adoption, ever: finish with the
                # token already streamed instead of stranding the
                # client (runbook triage: handoff_failed)
                P.clear_handoff(st, idx, pages=max(wire_pages, 1))
                self._lane_stats["handoff_failed"] += 1
                self._finalize(key, t0, 1, False)
                return True
            span = self._live_spans.pop(key, None)
            device_ms = DEVTIME.take_lane_ms(self.LANE) \
                + DEVTIME.take_lane_ms("completer")
            st.label_clear(key, P.LBL_SERVICING)
            st.label_or(key, P.LBL_DECODE_READY)
            # the handoff has LANDED (record + DECODE_READY): from
            # here on nothing may escape — run_continuous's failure
            # handler would re-queue a row the decode lane already
            # owns (WAITING|DECODE_READY with no record = the first
            # token streams twice).  Bookkeeping errors are swallowed.
            try:
                st.bump(key)
            except (KeyError, OSError):
                pass
            wall = time.perf_counter() - tp0
            try:
                tracer.record("infer.handoff",
                              (time.perf_counter() - tp1) * 1e3)
                self.spans.commit(
                    span,
                    stages={"join": round((tp1 - tp0) * 1e3, 3),
                            "handoff": round(
                                (time.perf_counter() - tp1) * 1e3, 3)},
                    extra={"tokens": 1},
                    device_ms=device_ms if device_ms > 0 else None)
            except Exception:
                pass
            self._lane_stats["handoffs"] += 1
            self.stats.tokens += 1
            # the phase-aware slack: admission rejects deadlines that
            # land inside the NEXT request's expected prefill wall
            self._pf_ema_s = (0.8 * self._pf_ema_s + 0.2 * wall
                              if self._pf_ema_s else wall)
            self.qos_slack_s = self._pf_ema_s
            return True
        finally:
            cache.free_row(row)

    def run_continuous(self, *, idle_timeout_ms: int = 100,
                       stop_after: float | None = None) -> None:
        """The prefill lane's serve loop: drain WAITING keys through
        _handoff_one, phase-aware admission order, heartbeat cadence
        and scale-down retire identical to the sibling lanes.  Models
        without the paged surface fall back to the unified lane."""
        if not self._paged_ok():
            return super().run_continuous(
                idle_timeout_ms=idle_timeout_ms, stop_after=stop_after)
        st = self.store
        self._running = True
        deadline = (time.monotonic() + stop_after) if stop_after else None
        last = st.signal_count(self.group)
        next_beat = time.monotonic() + 2.0
        cache = self._ensure_paged_cache()
        self.publish_stats()          # the attach-complete signal
        while self._running:
            now = time.monotonic()
            if deadline and now > deadline:
                break
            if now >= next_beat:
                next_beat = now + 2.0
                self.publish_stats()
                if self.replica and self.stripes.poll_retired():
                    self._debug("replica destriped — retiring")
                    break
            try:
                self.stripes.refresh()
                waiting = [i for i in
                           st.enumerate_indices(P.LBL_INFER_REQ)
                           if self.stripes.owns(int(i))]
                n = 0
                if waiting:
                    cap = (len(waiting) if self.qos.high_water is None
                           else min(len(waiting),
                                    max(1, self.qos.high_water)))
                    for idx in self._admit_waiting(waiting, cap):
                        if not self._running:
                            break
                        try:
                            if self._handoff_one(idx):
                                n += 1
                        except Exception as ex:
                            self.stats.faults += 1
                            self._debug(
                                f"prefill of slot {idx} failed: {ex}")
                            try:
                                handed = bool(
                                    st.labels_at(idx)
                                    & P.LBL_DECODE_READY)
                            except (KeyError, OSError):
                                handed = False
                            if not handed:
                                # only rows still on OUR side of the
                                # flip are re-queued; a DECODE_READY
                                # row belongs to the decode lane and
                                # keeps its record + wire pages
                                self._requeue_failed([idx])
                                P.clear_handoff(
                                    st, idx,
                                    pages=self._max_wire_pages())
                            # the failure may have escaped a donating
                            # program: rebuild the pool outright (the
                            # unified abort_all recovery)
                            self._paged_cache = None
                            cache = self._ensure_paged_cache()
                if n == 0:
                    got = st.signal_wait(self.group, last,
                                         timeout_ms=idle_timeout_ms)
                    if got is not None:
                        last = got
                        self.stats.wakes += 1
            except Exception as ex:
                self.stats.faults += 1
                self._debug(f"prefill cycle failed: {ex}")


class DecodeLane(Completer):
    """The memory-bound half: ragged paged decode only.  Admission is
    ADOPTION of DECODE_READY handoffs at chunk edges — the lane's
    K-deep window is never stalled by a joiner's prefill."""

    LANE = "decode"
    HB_KEY = P.KEY_DECODE_STATS
    WATCH_BIT = P.BIT_DECODE_READY

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if getattr(self, "_model", None) is None:
            raise ValueError(
                "disaggregated lanes require a model backend "
                "(generate_fn cannot import KV pages)")
        self._model.devtime_lane = self.LANE
        self._lane_admit = self._adopt_ready
        self._lane_stats = {"adopted": 0, "readopted": 0,
                            "adopt_backpressure": 0,
                            "handoff_refill": 0}

    def _reclaim_stranded(self) -> int:
        """Decode-crash recovery: an adopted row in OUR stripes
        carries SERVICING|DECODE_READY.  Truncate the slot back to
        the handoff byte length (`plen` — drop the dead adopter's
        partial tail, greedy re-decode reproduces it byte-exact) and
        drop SERVICING, so any live decode replica re-adopts it from
        the wire pages (or re-prefills from the record's ids).  A
        DECODE_READY row with no surviving record falls back to the
        WAITING queue.

        SERVICING-only rows are NOT ours: decode ownership always
        carries SERVICING|DECODE_READY, so a bare SERVICING row is a
        live prefill replica's in-flight claim (the two lanes' stripe
        maps are independent over the same slot space) — touching it
        would double-service the request."""
        st = self.store
        self.stripes.refresh()
        n = 0
        for idx in st.enumerate_indices(P.LBL_SERVICING):
            if not self.stripes.owns(int(idx)):
                continue
            key = st.key_at(idx)
            if key is None:
                continue
            try:
                labels = st.labels_at(idx)
            except (KeyError, OSError):
                continue
            if not labels & P.LBL_DECODE_READY:
                continue
            rec = P.read_handoff_record(st, idx)
            try:
                if rec is not None:
                    plen = int(rec.get("plen", 0))
                    if plen and st.value_len(key) > plen:
                        st.set(key, st.get(key)[:plen])
                    st.label_clear(key, P.LBL_SERVICING)
                    st.bump(key)      # back to bare DECODE_READY
                else:
                    P.clear_handoff(st, idx)
                    st.label_clear(key, P.LBL_SERVICING
                                   | P.LBL_DECODE_READY)
                    st.label_or(key,
                                P.LBL_INFER_REQ | P.LBL_WAITING)
                n += 1
            except (KeyError, OSError):
                continue
        if n:
            self.stats.reclaimed += n
            self._debug(f"re-opened {n} adopted rows for re-adoption")
        return n

    def warmup_paged(self) -> None:
        super().warmup_paged()
        if self._paged_ok():
            # the first adoption at serve time must not compile
            self._model.warmup_handoff(self._ensure_paged_cache(),
                                       export=False, adopt=True)

    def _lane_payload(self, payload: dict) -> None:
        payload["lane"] = self.LANE
        payload.update(self._lane_stats)

    def _lane_row_done(self, row: dict) -> None:
        """A finished/killed adopted row retires its handoff state —
        record + wire pages leave the store with the request."""
        idx = row.get("ho_idx")
        if idx is not None:
            P.clear_handoff(self.store, idx)

    def _reject_ready(self, idx: int, key: str, rec: dict) -> bool:
        """Deadline-expired before adoption: typed terminal reject of
        a DECODE_READY row (the handoff analog of _terminal_reject —
        that one requires LBL_INFER_REQ, which the prefill claim
        consumed)."""
        st = self.store
        try:
            st.label_clear(key, P.LBL_DECODE_READY)
            st.set(key, P.DEADLINE_EXPIRED_DIAGNOSTIC)
            st.label_or(key, P.LBL_READY)
            st.bump(key)
        except (KeyError, OSError):
            return False
        P.clear_handoff(st, idx)
        self.stats.deadline_expired += 1
        tenant = int(rec.get("tenant") or 0)
        if tenant:
            self.tenants.bump(tenant, "deadline_expired")
        return True

    def _adopt_ready(self, free: list[int], ctx: dict) -> int:
        """run_continuous's admission, decode edition: enumerate
        DECODE_READY handoffs in OUR stripes and seat each exactly
        where a unified join would have left it — carry token riding
        the fresh column, full worst-case page reservation, serial
        guard.  A row the pool cannot cover stays DECODE_READY
        (adopt_backpressure — never a mid-decode strand)."""
        import numpy as np
        st = self.store
        m = self._model
        cache = ctx["cache"]
        rows, fresh = ctx["rows"], ctx["fresh"]
        self.stripes.refresh()
        ready = [i for i in st.enumerate_indices(P.LBL_DECODE_READY)
                 if self.stripes.owns(int(i))]
        if not ready:
            return 0
        n = 0
        now_wall = time.time()
        for idx in ready:
            if not free:
                break
            try:
                labels = st.labels_at(idx)
            except (KeyError, OSError):
                continue
            if labels & P.LBL_SERVICING \
                    or not labels & P.LBL_DECODE_READY:
                continue              # adopted already / raced away
            rec = P.read_handoff_record(st, idx)
            if rec is None:
                continue              # record not landed yet
            key = st.key_at(idx)
            if key is None:
                continue
            dl = rec.get("deadline")
            if dl is not None and dl <= now_wall:
                # phase-aware QoS, decode side: an expired handoff
                # dies before consuming pool or a batch slot
                self._reject_ready(idx, key, rec)
                continue
            plen = int(rec.get("plen", 0))
            reserve = ctx["worst_len"](int(rec["len"]))
            if cache.pages_needed(reserve) > cache.available_pages:
                self._lane_stats["adopt_backpressure"] += 1
                continue              # stays DECODE_READY
            ta = time.perf_counter()
            try:
                st.label_or(key, P.LBL_SERVICING)
                st.bump(key)
            except (KeyError, OSError):
                continue
            # the chaos matrix crashes HERE — row claimed, nothing
            # imported: recovery re-opens it for re-adoption
            fault("decode.adopt")
            try:
                if plen and st.value_len(key) > plen:
                    # a dead adopter's partial tail (re-adoption
                    # without an intervening restart): greedy decode
                    # reproduces it byte-exact from the carry
                    st.set(key, st.get(key)[:plen])
                    self._lane_stats["readopted"] += 1
            except (KeyError, OSError):
                pass
            r = free[0]
            adopted = False
            wire = int(rec.get("wire_pages", 0))
            if wire > 0:
                pages_b, scales_b = [], []
                try:
                    for j in range(wire):
                        pages_b.append(
                            bytes(st.get(P.handoff_page_key(idx, j))))
                        if rec.get("quant"):
                            scales_b.append(bytes(
                                st.get(P.handoff_scale_key(idx, j))))
                        else:
                            scales_b.append(None)
                    adopted = m.paged_adopt_row(
                        cache, r, int(rec["len"]), pages_b,
                        scales_b if rec.get("quant") else None)
                except (KeyError, OSError, ValueError):
                    adopted = False
            if not adopted:
                # wire pages gone/mismatched (or never written): the
                # record's token ids re-prefill the prompt here —
                # greedy determinism keeps the bytes exact, and the
                # recorded carry still supplies the first token
                if not cache.ensure(r, int(rec["len"])):
                    self._unadopt(key)
                    continue
                self._lane_stats["handoff_refill"] += 1
                m.paged_prefill_row(
                    cache,
                    np.asarray(rec["ids"], np.int32), r)
            if not cache.ensure(r, reserve):
                # defensive: the reservation gate above makes this
                # unreachable — un-claim rather than strand mid-decode
                cache.free_row(r)
                self._unadopt(key)
                self._lane_stats["adopt_backpressure"] += 1
                continue
            free.pop(0)
            rows[r] = {"key": key, "t0": int(rec["t0"]),
                       "n_tok": int(rec["n_tok"]), "pending": b"",
                       "remaining": int(rec["remaining"]),
                       "stamp": None, "deadline": dl,
                       "tenant": int(rec.get("tenant") or 0),
                       "serial": next(ctx["serial"]),
                       "disp_left": int(rec["disp_left"]),
                       "spans": None,
                       "wall0": time.perf_counter(),
                       "ho_idx": int(idx)}
            fresh[r] = int(rec["carry"])
            ctx["span"](rows[r], "adopt",
                        (time.perf_counter() - ta) * 1e3)
            self._lane_stats["adopted"] += 1
            n += 1
        return n

    def _unadopt(self, key: str) -> None:
        """Back out a claimed-but-unseatable adoption: drop SERVICING,
        keep DECODE_READY — the row stays adoptable."""
        try:
            self.store.label_clear(key, P.LBL_SERVICING)
            self.store.bump(key)
        except (KeyError, OSError):
            pass
