"""Daemon supervisor — crash-only process management for the lanes,
and the replica-set owner of the elastic-lane subsystem.

The reference survives hostile clients because every interaction is a
lock-free slot protocol; the daemons themselves, though, are single
processes — one XLA RESOURCE_EXHAUSTED past the firewalls, one
injected `crash`, one OOM kill, and a lane is gone until an operator
notices.  This module is the missing layer of the serving fault model
("Crash-Only Software": recovery IS startup, so make restart the
first-class path):

  - each lane (embedder / completer / searcher / ...) runs as a CHILD
    process (`python -m libsplinter_tpu.engine.<lane> --store ...`);
  - the supervisor watches pids (waitpid-level truth) AND heartbeats
    (a live pid with a stale heartbeat is a hung daemon — it gets
    SIGKILLed and restarted, the crash-only remedy);
  - crashes restart with jittered exponential backoff (base doubling
    per consecutive crash, 0.5–1.5x jitter so a pod of supervisors
    never thunders back in lockstep);
  - a circuit breaker (N crashes inside a window) marks the lane DOWN
    in the supervisor heartbeat instead of burning CPU on a crash
    loop; CLI clients consult that marker (protocol.lane_down via
    daemon_live) and skip dispatch instead of timing out.  After a
    cooldown the breaker half-opens: one probe child — surviving
    closes the breaker, crashing re-opens it.

Elastic lanes (ROADMAP item 4): beyond "restart N fixed children",
the supervisor owns each lane's REPLICA SET.  A lane may run up to
`LANES[lane].max_replicas` striped replicas (each drains a disjoint
slot-index stripe — protocol.StripeView); desired counts arrive
through per-lane `__scale_tgt_<lane>` store keys (written by the autoscaler
lane or `spt scale set`), and the supervisor applies them:

  - scale-UP spawns replica N with `--replica N` and re-stripes the
    lane over the enlarged set in one epoch-bumped map write;
  - scale-DOWN is a drain protocol: the retiring replica's stripes
    are marked CLOSED (no replica claims new work from them), the
    child finishes its in-flight work and exits on its own when it
    sees itself assigned nothing (the run loops' poll_retired check)
    — or is reaped at the drain deadline — and only THEN are the
    closed stripes reclaimed (stranded SERVICING rows re-queued via
    the existing stranded-request machinery) and re-assigned to the
    survivors.  A replica crash-killed mid-scale-down takes the same
    path: retiring + dead = retired, reclaim runs, nothing strands.

Chaos drills: when SPTPU_FAULT is set in the supervisor's
environment, it is handed to each lane's FIRST child only and
stripped from respawns (a drill asserts the restart recovers — an
inherited crash@1 would re-fire in every generation and prove
nothing).  --keep-faults opts back into inheriting, which is how you
demo the breaker.

Usage: `spt supervise` (cli/supervise.py) or
`python -m libsplinter_tpu.engine.supervisor --store NAME`.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import subprocess
import sys
import time
from collections import deque
from typing import NamedTuple

from ..store import Store
from ..utils.faults import fault
from . import protocol as P

log = logging.getLogger("libsplinter_tpu.supervisor")


class LaneSpec(NamedTuple):
    """One supervisable lane: child module, canonical heartbeat key,
    the hard replica ceiling (1 = the lane cannot stripe), and the
    baked-in argv the lane type always passes its children (user
    --<lane>-args append after these)."""
    module: str
    heartbeat_key: str
    max_replicas: int = 1
    args: tuple = ()


# lane name -> LaneSpec.  The lane names are the public vocabulary:
# supervisor heartbeat sections, `spt metrics` labels, stripe-map
# keys, and protocol.lane_down all use them.  max_replicas bounds
# what any scale target (auto or manual) may request.
LANES: dict[str, LaneSpec] = {
    "embedder": LaneSpec("libsplinter_tpu.engine.embedder",
                         P.KEY_EMBED_STATS, 8),
    "completer": LaneSpec("libsplinter_tpu.engine.completer",
                          P.KEY_COMPLETE_STATS, 4),
    "searcher": LaneSpec("libsplinter_tpu.engine.searcher",
                         P.KEY_SEARCH_STATS, 8),
    # the pipeline lane (server-side scripted chains): jax-free, so a
    # supervised restart costs milliseconds, not an XLA warmup
    "pipeliner": LaneSpec("libsplinter_tpu.engine.pipeliner",
                          P.KEY_SCRIPT_STATS, 8),
    # the telemetry sampler (heartbeat-history rings): jax-free; its
    # rings live in the STORE, so a restart resumes them intact
    "telemetry": LaneSpec("libsplinter_tpu.engine.telemetry",
                          P.KEY_TELEMETRY_STATS, 1),
    # the scaling controller (QoS-driven replica counts): jax-free;
    # its decisions land in __scale_tgt_<lane> keys, its state in the store —
    # a restarted controller resumes from the live policy + rings
    "autoscaler": LaneSpec("libsplinter_tpu.engine.autoscaler",
                           P.KEY_AUTOSCALER_STATS, 1),
    # disaggregated serving (engine/disagg.py): the completer daemon
    # split into its two phases behind the same label protocol.  The
    # autoscaler drives them on DIFFERENT signals — prefill on queue
    # pressure, decode on paged-pool occupancy (_publish_policy) —
    # and --pin-chips lands their replicas on disjoint chips.
    "prefill": LaneSpec("libsplinter_tpu.engine.completer",
                        P.KEY_PREFILL_STATS, 4,
                        ("--phase", "prefill")),
    "decode": LaneSpec("libsplinter_tpu.engine.completer",
                       P.KEY_DECODE_STATS, 4,
                       ("--phase", "decode")),
}


@dataclasses.dataclass
class LaneProc:
    """One supervised lane replica's runtime state."""

    name: str
    module: str
    heartbeat_key: str
    replica: int = 0
    proc: object | None = None
    pid: int = 0
    state: str = "init"          # starting|running|backoff|down|retiring
    generation: int = 0          # spawn count
    restarts: int = 0            # respawns after a crash/hang
    consecutive: int = 0         # crashes since the last healthy run
    backoff_ms: float = 0.0      # the live backoff, for the heartbeat
    backoff_until: float = 0.0   # monotonic deadline
    breaker_opens: int = 0
    breaker_until: float = 0.0   # monotonic half-open probe time
    half_open: bool = False      # probing after a breaker cooldown
    hung_kills: int = 0          # stale-heartbeat SIGKILLs
    retiring: bool = False       # scale-down drain in progress
    retire_deadline: float = 0.0  # monotonic: reap past this
    # the stripe set this replica owned when its retire began: parked
    # CLOSED until the post-reap reclaim (recomputing it from a later
    # assignment would hand a still-draining replica's rows away)
    closed_stripes: tuple = ()
    # two-phase scale-UP: the share destined for a freshly-spawned
    # replica parks CLOSED until its first heartbeat proves attach is
    # over — attach runs the stripe-scoped stranded-SERVICING reclaim,
    # and a new replica that owned stripes at attach could "reclaim"
    # a live incumbent's re-striped in-flight row (double-serve)
    pending_stripes: tuple = ()
    last_exit: int | None = None
    spawn_mono: float = 0.0
    spawn_wall: float = 0.0
    crash_times: deque = dataclasses.field(default_factory=deque)

    def snapshot(self) -> dict:
        """The per-replica heartbeat section (what `spt metrics`
        renders and protocol.lane_down consults)."""
        return {"state": self.state, "pid": self.pid,
                "generation": self.generation,
                "restarts": self.restarts,
                "consecutive_crashes": self.consecutive,
                "backoff_ms": round(self.backoff_ms, 1),
                "breaker_opens": self.breaker_opens,
                "hung_kills": self.hung_kills,
                "last_exit": self.last_exit}


class Supervisor:
    """Drive with run() (blocking loop) or poll_once() (one
    supervision step — tests and deterministic drills).

    spawn_fn and clock are injectable: tests supervise dummy children
    (no jax import) on a compressed timeline."""

    def __init__(self, store_name: str, *,
                 lanes=("embedder", "completer", "searcher"),
                 persistent: bool = False,
                 lane_args: dict[str, list[str]] | None = None,
                 backoff_base_ms: float = 500.0,
                 backoff_max_ms: float = 30_000.0,
                 breaker_threshold: int = 5,
                 breaker_window_s: float = 60.0,
                 breaker_cooldown_s: float = 30.0,
                 heartbeat_timeout_s: float = 30.0,
                 startup_grace_s: float = 60.0,
                 healthy_after_s: float = 30.0,
                 keep_faults: bool = False,
                 scale: dict[str, tuple[int, int]] | None = None,
                 scale_knobs: dict | None = None,
                 drain_deadline_s: float = 5.0,
                 chip_pins: dict[str, str] | None = None,
                 spawn_fn=None, clock=None,
                 store: Store | None = None):
        self.store_name = store_name
        self.persistent = persistent
        self.lane_args = lane_args or {}
        # per-lane device pin (--pin-chips): children see it as
        # SPTPU_CHIP_PIN and bind jax.default_device before warmup, so
        # e.g. prefill and decode replicas land on disjoint chips
        self.chip_pins = dict(chip_pins or {})
        self.backoff_base_ms = backoff_base_ms
        self.backoff_max_ms = backoff_max_ms
        self.breaker_threshold = breaker_threshold
        self.breaker_window_s = breaker_window_s
        self.breaker_cooldown_s = breaker_cooldown_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        # a fresh child pays imports + XLA compiles before its first
        # heartbeat: the hang detector must not eat the startup
        self.startup_grace_s = startup_grace_s
        self.healthy_after_s = healthy_after_s
        self.keep_faults = keep_faults
        # scale-down drain budget: a retiring replica gets this long
        # to finish in-flight work after its stripes close before the
        # supervisor reaps it (voluntary exit is the fast path)
        self.drain_deadline_s = drain_deadline_s
        self._spawn_fn = spawn_fn or self._spawn_child
        self._clock = clock or time.monotonic
        self._rng = random.Random()
        self.store = store or Store.open(store_name,
                                         persistent=persistent)
        unknown = [ln for ln in lanes if ln not in LANES]
        if unknown:
            raise ValueError(f"unknown lanes {unknown} "
                             f"(supervisable: {sorted(LANES)})")
        # replica sets: replicas[lane][r] -> LaneProc.  self.lanes
        # keeps the replica-0 view (the canonical replica every
        # pre-elastic caller — tests, lane_down, spt health — reads).
        self.replicas: dict[str, dict[int, LaneProc]] = {
            name: {0: LaneProc(name, LANES[name].module,
                               LANES[name].heartbeat_key)}
            for name in lanes}
        self.lanes = {name: reps[0]
                      for name, reps in self.replicas.items()}
        # per-lane scaling bounds (min, max), from --scale; a lane
        # absent here still accepts MANUAL targets clamped to
        # (1, max_replicas)
        self.scale: dict[str, tuple[int, int]] = {}
        for lane, (lo, hi) in (scale or {}).items():
            if lane not in LANES:
                raise ValueError(f"--scale names unknown lane {lane!r}")
            cap = LANES[lane].max_replicas
            if cap <= 1:
                raise ValueError(
                    f"lane {lane!r} is not scalable (max_replicas 1)")
            lo = max(1, int(lo))
            hi = min(cap, max(lo, int(hi)))
            self.scale[lane] = (lo, hi)
        self.retired = 0             # replicas drained + reaped
        self.scale_events = 0        # applied target changes
        self.polls = 0
        self._running = False
        if self.scale:
            self._publish_policy(scale_knobs or {})

    # -- scaling policy ----------------------------------------------------

    def _publish_policy(self, knobs: dict) -> None:
        """Write the scaling policy the autoscaler lane reads: the
        per-lane bounds plus the controller knobs `spt supervise`
        was given.  Store state, so `spt scale status` and a
        restarted controller both read the same truth."""
        # per-lane scaling SIGNAL: the disaggregated decode lane is
        # paced by paged-pool occupancy (its backlog is adopted rows'
        # KV residency, not queue depth); every other lane scales on
        # the classic queue-pressure signal
        rec = {"v": 1,
               "lanes": {ln: {"min": lo, "max": hi,
                              "signal": ("pool" if ln == "decode"
                                         else "queue")}
                         for ln, (lo, hi) in self.scale.items()}}
        for k in ("interval_s", "up_threshold", "down_threshold",
                  "cooldown_s"):
            if knobs.get(k) is not None:
                rec[k] = knobs[k]
        try:
            self.store.set(P.KEY_SCALE_POLICY, json.dumps(rec))
        except (KeyError, OSError):
            pass

    # -- spawning ----------------------------------------------------------

    def _child_env(self, lane: LaneProc) -> dict:
        env = dict(os.environ)
        if (lane.generation > 1 or lane.replica > 0) \
                and not self.keep_faults:
            # chaos-drill contract: injected faults hit the FIRST
            # generation of the canonical replica only; respawns and
            # scale-up replicas must prove clean service
            env.pop("SPTPU_FAULT", None)
        pin = self.chip_pins.get(lane.name)
        if pin:
            env["SPTPU_CHIP_PIN"] = pin
        return env

    def _spawn_child(self, lane: LaneProc):
        argv = [sys.executable, "-m", lane.module,
                "--store", self.store_name]
        if self.persistent:
            argv.append("--persistent")
        if lane.replica > 0:
            argv += ["--replica", str(lane.replica)]
        argv += list(LANES[lane.name].args)
        argv += self.lane_args.get(lane.name, [])
        return subprocess.Popen(argv, env=self._child_env(lane))

    def _spawn(self, lane: LaneProc, now: float) -> None:
        lane.generation += 1
        if lane.generation > 1:
            lane.restarts += 1
        lane.spawn_mono = now
        lane.spawn_wall = time.time()
        lane.backoff_until = 0.0
        try:
            lane.proc = self._spawn_fn(lane)
            lane.pid = getattr(lane.proc, "pid", 0)
            lane.state = "starting"
            log.info("lane %s: spawned pid %d (generation %d)",
                     self._display(lane), lane.pid, lane.generation)
        except Exception as ex:
            # a spawn that cannot even exec counts as an instant crash
            log.error("lane %s: spawn failed: %s",
                      self._display(lane), ex)
            lane.proc = None
            lane.pid = 0
            self._crashed(lane, -1, now)

    @staticmethod
    def _display(lane: LaneProc) -> str:
        return (lane.name if lane.replica == 0
                else f"{lane.name}.r{lane.replica}")

    # -- crash bookkeeping -------------------------------------------------

    def _crashed(self, lane: LaneProc, code: int, now: float) -> None:
        lane.proc = None
        lane.pid = 0
        lane.last_exit = code
        lane.consecutive += 1
        lane.crash_times.append(now)
        while (lane.crash_times
               and now - lane.crash_times[0] > self.breaker_window_s):
            lane.crash_times.popleft()
        log.warning("lane %s: exited %s (crash %d in window)",
                    self._display(lane), code, len(lane.crash_times))
        if (lane.half_open
                or len(lane.crash_times) >= self.breaker_threshold):
            # breaker: a half-open probe crashing re-opens instantly;
            # otherwise N crashes / window trip it
            lane.state = "down"
            lane.half_open = False
            lane.breaker_opens += 1
            lane.breaker_until = now + self.breaker_cooldown_s
            lane.crash_times.clear()
            lane.backoff_ms = 0.0
            log.error("lane %s: circuit breaker OPEN for %.1fs",
                      self._display(lane), self.breaker_cooldown_s)
            return
        lane.state = "backoff"
        base = min(self.backoff_base_ms * 2 ** (lane.consecutive - 1),
                   self.backoff_max_ms)
        lane.backoff_ms = base * self._rng.uniform(0.5, 1.5)
        lane.backoff_until = now + lane.backoff_ms / 1e3

    def _heartbeat_age(self, lane: LaneProc) -> float | None:
        """Seconds since the lane's OWN child published a heartbeat;
        None when no heartbeat from this generation exists yet."""
        try:
            snap = json.loads(
                self.store.get(lane.heartbeat_key).rstrip(b"\0"))
            ts = float(snap.get("ts", 0.0))
        except (KeyError, OSError, ValueError, AttributeError):
            return None
        if ts < lane.spawn_wall:
            return None              # a previous generation's snapshot
        return time.time() - ts

    # -- the supervision step ----------------------------------------------

    def poll_once(self, now: float | None = None) -> None:
        """One step: reap exits, enforce backoff/breaker/retire
        timers, hang-check heartbeats, respawn, apply scale targets,
        publish."""
        fault("supervisor.poll")
        now = self._clock() if now is None else now
        self.polls += 1
        for lane_name, reps in self.replicas.items():
            for lane in list(reps.values()):
                if lane.retiring:
                    self._watch_retiring(lane_name, lane, now)
                    continue
                if lane.proc is not None:
                    rc = lane.proc.poll()
                    if rc is not None:
                        self._crashed(lane, rc, now)
                    else:
                        self._watch_live(lane, now)
                if lane.proc is None:
                    if lane.state == "down":
                        if now >= lane.breaker_until:
                            lane.half_open = True
                            log.warning("lane %s: breaker half-open, "
                                        "probing", self._display(lane))
                            self._spawn(lane, now)
                    elif lane.state in ("init", "backoff"):
                        if now >= lane.backoff_until:
                            self._spawn(lane, now)
        self._apply_scale(now)
        self.publish()

    def _watch_live(self, lane: LaneProc, now: float) -> None:
        age = self._heartbeat_age(lane)
        uptime = now - lane.spawn_mono
        if age is not None and age < self.heartbeat_timeout_s:
            if lane.state == "starting":
                lane.state = "running"
            if lane.pending_stripes:
                # scale-up phase 2: the first heartbeat means attach
                # (and its stranded reclaim) finished — hand the
                # parked share over now
                lane.pending_stripes = ()
                self._restripe(lane.name)
                log.info("lane %s: promoted into the stripe map",
                         self._display(lane))
            if (lane.consecutive or lane.half_open) \
                    and uptime >= self.healthy_after_s:
                # survived long enough: close the breaker / reset the
                # backoff ladder
                lane.consecutive = 0
                lane.half_open = False
                lane.backoff_ms = 0.0
                lane.crash_times.clear()
            return
        stale = (uptime > self.startup_grace_s
                 if age is None
                 else age > self.heartbeat_timeout_s
                 and uptime > self.heartbeat_timeout_s)
        if stale:
            # live pid, dead heartbeat: a hung daemon serves nobody —
            # SIGKILL (crash-only: the restart path IS the recovery
            # path) and let the normal crash machinery restart it
            log.error("lane %s: heartbeat stale (age %s, uptime "
                      "%.1fs) — killing pid %d", self._display(lane),
                      f"{age:.1f}s" if age is not None else "never",
                      uptime, lane.pid)
            lane.hung_kills += 1
            try:
                lane.proc.kill()
                lane.proc.wait(timeout=10)
            except Exception:
                pass
            self._crashed(lane, -signal.SIGKILL, now)

    # -- elastic scaling ---------------------------------------------------

    def _active_ids(self, lane_name: str) -> list[int]:
        """Replica ids currently serving (not retiring)."""
        return sorted(r for r, ln in self.replicas[lane_name].items()
                      if not ln.retiring)

    def _desired_r(self, lane_name: str,
                   targets: dict[str, dict]) -> int | None:
        """The clamped desired replica count for a lane, or None (no
        target — leave the lane alone).  `targets` is one
        read_scale_targets snapshot shared across the whole
        _apply_scale pass (the read walks the keyspace — once per
        poll, not once per lane)."""
        spec = LANES[lane_name]
        if spec.max_replicas <= 1:
            return None
        tgt = targets.get(lane_name)
        if not isinstance(tgt, dict):
            return None
        try:
            r = int(tgt.get("r", 0))
        except (TypeError, ValueError):
            return None
        if r < 1:
            return None
        lo, hi = self.scale.get(lane_name, (1, spec.max_replicas))
        return max(lo, min(hi, r))

    def _restripe(self, lane_name: str) -> None:
        """One epoch-bumped stripe-map write: READY replicas (active,
        past their scale-up handoff) own everything except the parked
        stripes — retiring replicas' closed shares plus spawning
        replicas' pending shares.  With only replica 0 ready and
        nothing parked, the map clears back to the single-replica
        default.  Stripes may move between live RUNNING replicas here
        (a promotion reshapes the round-robin): that is safe — only
        ATTACH-time reclaim may touch SERVICING rows, and every
        running replica is past its attach."""
        reps = self.replicas[lane_name]
        ready = sorted(r for r, ln in reps.items()
                       if not ln.retiring and not ln.pending_stripes)
        closed = sorted(
            {s for ln in reps.values() if ln.retiring
             for s in ln.closed_stripes})
        # pending section: a spawning replica reads it to know it is
        # awaiting promotion, NOT retired (StripeView.retired).  Its
        # planned share stays OWNED by the incumbents meanwhile —
        # the lane keeps full coverage through the child's whole
        # startup (and forever, if the child crash-loops and never
        # heartbeats); only retiring replicas' closed shares are
        # unserved, and those are deadline-bounded.
        pend = {r: list(ln.pending_stripes)
                for r, ln in reps.items()
                if ln.pending_stripes and not ln.retiring}
        if ready == [0] and not closed and not pend:
            P.clear_stripe_map(self.store, lane_name)
            return
        width = P.DEFAULT_STRIPE_WIDTH
        owners = P.default_stripe_owners(ready or [0], width)
        if closed:
            cset = set(closed)
            owners = {r: [s for s in ss if s not in cset]
                      for r, ss in owners.items()}
        P.write_stripe_map(self.store, lane_name, owners,
                           width=width, closed=closed,
                           pending=pend)

    def _apply_scale(self, now: float) -> None:
        """Reconcile each lane's replica set with its desired count:
        spawn-then-promote up (two-phase), drain-protocol down."""
        targets = P.read_scale_targets(self.store)
        for lane_name in list(self.replicas):
            desired = self._desired_r(lane_name, targets)
            if desired is None:
                continue
            active = self._active_ids(lane_name)
            if desired > len(active):
                spec = LANES[lane_name]
                reps = self.replicas[lane_name]
                new_ids = []
                while len(self._active_ids(lane_name)) < desired:
                    r = next(i for i in range(spec.max_replicas + 1)
                             if i not in reps)
                    reps[r] = LaneProc(
                        lane_name, spec.module,
                        P.replica_stats_key(spec.heartbeat_key, r),
                        replica=r)
                    new_ids.append(r)
                    self._spawn(reps[r], now)
                # scale-up phase 1: the new replicas are recorded
                # PENDING — incumbents keep serving their planned
                # shares until each one's first heartbeat proves
                # attach (and its stripe-scoped stranded reclaim) is
                # over.  An attach that already owned stripes could
                # reclaim a live incumbent's re-striped in-flight
                # SERVICING row as "stranded" and double-serve it;
                # holding the share with the incumbents instead of
                # parking it closed also means full lane coverage
                # through the child's whole startup.  The promotion
                # in _watch_live hands the share over.
                full = P.default_stripe_owners(
                    sorted(set(active) | set(new_ids)),
                    P.DEFAULT_STRIPE_WIDTH)
                for r in new_ids:
                    reps[r].pending_stripes = tuple(full.get(r, ()))
                self._restripe(lane_name)
                self.scale_events += 1
                log.info("lane %s: scaled up to %d replicas "
                         "(pending until first heartbeat)",
                         lane_name, desired)
            elif desired < len(active):
                # retire highest replica ids first; replica 0 (the
                # canonical heartbeat) never retires
                for r in sorted(active, reverse=True)[
                        : len(active) - desired]:
                    if r == 0:
                        continue
                    self._retire_replica(lane_name,
                                         self.replicas[lane_name][r],
                                         now)
                self.scale_events += 1

    def _retire_replica(self, lane_name: str, lane: LaneProc,
                        now: float) -> None:
        """Scale-down phase 1: close the replica's stripes (nobody —
        including the retiring replica — claims NEW work from them),
        then let the child drain its in-flight work to the deadline.
        The replica's run loop sees itself assigned nothing and exits
        voluntarily; _watch_retiring reaps stragglers."""
        fault("supervisor.retire")
        # the stripes this replica owns RIGHT NOW (from the live map;
        # its default share if a map never landed) park closed
        rec = P.read_stripe_map(self.store, lane_name)
        if rec is not None and isinstance(rec.get("owners"), dict):
            closing = [int(s) for s in
                       rec["owners"].get(str(lane.replica), [])]
        else:
            full = P.default_stripe_owners(
                self._active_ids(lane_name), P.DEFAULT_STRIPE_WIDTH)
            closing = full.get(lane.replica, [])
        lane.retiring = True
        lane.state = "retiring"
        lane.retire_deadline = now + self.drain_deadline_s
        lane.closed_stripes = tuple(closing)
        self._restripe(lane_name)
        log.info("lane %s: retiring (stripes %s closed, drain "
                 "deadline %.1fs)", self._display(lane), closing,
                 self.drain_deadline_s)

    def _watch_retiring(self, lane_name: str, lane: LaneProc,
                        now: float) -> None:
        """Scale-down phase 2: reap the drained (or expired, or
        crash-killed) replica, reclaim stragglers from its closed
        stripes, and re-assign them to the survivors."""
        rc = lane.proc.poll() if lane.proc is not None else -1
        if rc is None:
            if now < lane.retire_deadline:
                return                # still draining in-flight work
            # drain deadline passed: reap (TERM then KILL) — the
            # straggler reclaim below re-queues whatever it held
            log.warning("lane %s: drain deadline passed — reaping "
                        "pid %d", self._display(lane), lane.pid)
            try:
                lane.proc.terminate()
                lane.proc.wait(timeout=2)
            except Exception:
                try:
                    lane.proc.kill()
                    lane.proc.wait(timeout=5)
                except Exception:
                    pass
        self.replicas[lane_name].pop(lane.replica, None)
        self.retired += 1
        self._reclaim_closed(lane_name, lane.closed_stripes)
        self._restripe(lane_name)     # closed stripes -> survivors
        self._drop_replica_keys(lane)
        log.info("lane %s: retired (replica set now %s)",
                 self._display(lane), self._active_ids(lane_name))

    def _drop_replica_keys(self, lane: LaneProc) -> None:
        """Retire a replica's suffixed heartbeat / trace / generation
        keys with it — discovery-based readers (`spt top`, `spt
        metrics`, the telemetry sampler) enumerate these, and a
        leftover key would render a permanently-[DEAD] replica the
        supervisor will never restart.  Replica 0's canonical keys
        always stay (the lane itself lives on)."""
        if lane.replica == 0:
            return
        keys = [lane.heartbeat_key, lane.heartbeat_key + "_gen"]
        if "_stats" in lane.heartbeat_key:
            keys.append(lane.heartbeat_key.replace("_stats",
                                                   "_trace"))
        for k in keys:
            try:
                self.store.unset(k)
            except (KeyError, OSError):
                pass

    def _reclaim_closed(self, lane_name: str,
                        closed: tuple | list) -> int:
        """The straggler reclaim: once a retiring replica is REAPED,
        any request it died holding sits in ITS closed stripes with
        nobody left to finish it.  WAITING rows (embedder / searcher
        / pipeliner requests keep their request label until commit)
        need nothing — the re-stripe hands them to a survivor's next
        drain.  Completer rows flipped to SERVICING are re-queued to
        WAITING here, exactly the existing stranded-request recovery
        (Completer._reclaim_stranded), run from the supervisor
        because the owning process no longer exists.  Only the
        reaped replica's OWN stripes are touched — a sibling replica
        still draining its closed share keeps its in-flight rows.

        The disaggregated lanes reclaim per their handoff contract
        (engine/disagg.py): a dead PREFILL replica's SERVICING rows
        drop any half-written handoff wire state and re-queue to
        WAITING (the request re-prefills — nothing was streamed from
        a handed-off row yet); a dead DECODE replica's adopted rows
        (SERVICING with DECODE_READY still set and an intact handoff
        record) roll BACK to bare DECODE_READY with the slot
        truncated to the record's prompt length, so a surviving
        decode replica re-adopts from the carry token instead of
        replaying partial output into the stream.

        Known bound: a claim that PREDATES an earlier re-stripe can
        sit in a stripe this replica no longer owned at retire time
        and is not swept here — the window is one in-flight request
        spanning two scale actions (cooldown-separated), and
        claim-owner stamping is the follow-up that would close it."""
        if lane_name not in ("completer", "prefill", "decode") \
                or not closed:
            return 0
        rec = P.read_stripe_map(self.store, lane_name)
        closed = set(closed)
        st = self.store
        width = (P.DEFAULT_STRIPE_WIDTH if rec is None
                 else int(rec.get("width", P.DEFAULT_STRIPE_WIDTH)))
        n = 0
        try:
            servicing = st.enumerate_indices(P.LBL_SERVICING)
        except (KeyError, OSError):
            return 0
        for idx in servicing:
            if P.stripe_of(idx, width) not in closed:
                continue
            try:
                key = st.key_at(idx)
                if key is None:
                    continue
                labels = st.labels_at(idx)
                if lane_name == "decode":
                    if not labels & P.LBL_DECODE_READY:
                        # SERVICING-only: a live prefill replica's
                        # in-flight claim (decode ownership always
                        # carries SERVICING|DECODE_READY) — not this
                        # lane's to reclaim
                        continue
                    hrec = P.read_handoff_record(st, idx)
                    if hrec is None:
                        # adopted row whose handoff record vanished:
                        # nothing to resume from — full re-prefill
                        st.label_clear(
                            key,
                            P.LBL_SERVICING | P.LBL_DECODE_READY)
                        st.label_or(
                            key, P.LBL_INFER_REQ | P.LBL_WAITING)
                    else:
                        plen = int(hrec.get("plen", 0))
                        if plen and st.value_len(key) > plen:
                            st.set(key, st.get(key)[:plen])
                        st.label_clear(key, P.LBL_SERVICING)
                    st.bump(key)
                    n += 1
                    continue
                if lane_name == "prefill":
                    if labels & P.LBL_DECODE_READY:
                        # past the handoff flip: the row (and its
                        # record + wire pages) now belongs to the
                        # decode lane — a live decode replica may be
                        # mid-decode on it
                        continue
                    P.clear_handoff(st, idx)
                st.label_clear(key, P.LBL_SERVICING)
                st.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
                n += 1
            except (KeyError, OSError):
                continue
        if n:
            log.info("lane %s: reclaimed %d stranded SERVICING rows "
                     "from closed stripes", lane_name, n)
        return n

    # -- heartbeat ---------------------------------------------------------

    def publish(self) -> None:
        lanes_sec = {}
        for name, reps in self.replicas.items():
            sec = reps[0].snapshot() if 0 in reps else {
                "state": "retired"}
            extra = {str(r): ln.snapshot()
                     for r, ln in sorted(reps.items()) if r > 0}
            if extra:
                sec["replicas"] = extra
            sec["r"] = len(self._active_ids(name))
            if name in self.scale:
                lo, hi = self.scale[name]
                sec["scale_min"], sec["scale_max"] = lo, hi
            lanes_sec[name] = sec
        payload = {
            "polls": self.polls,
            "retired": self.retired,
            "scale_events": self.scale_events,
            "lanes": lanes_sec,
        }
        P.publish_heartbeat(self.store, P.KEY_SUPERVISOR_STATS, payload)

    # -- lifecycle ---------------------------------------------------------

    def run(self, *, poll_interval_s: float = 0.5,
            stop_after: float | None = None) -> None:
        self._running = True
        deadline = (self._clock() + stop_after) if stop_after else None
        try:
            while self._running:
                try:
                    self.poll_once()
                except Exception:
                    # the supervisor of the crash-safe layer must hold
                    # itself to the same standard
                    log.exception("supervision step failed; continuing")
                if deadline and self._clock() > deadline:
                    break
                time.sleep(poll_interval_s)
        finally:
            self.shutdown()

    def stop(self) -> None:
        self._running = False

    def shutdown(self, *, grace_s: float = 5.0) -> None:
        """Terminate every child: SIGTERM, bounded wait, SIGKILL."""
        procs = [ln for reps in self.replicas.values()
                 for ln in reps.values()]
        for lane in procs:
            if lane.proc is None:
                continue
            try:
                lane.proc.terminate()
            except Exception:
                pass
        for lane in procs:
            if lane.proc is None:
                continue
            try:
                lane.proc.wait(timeout=grace_s)
            except Exception:
                try:
                    lane.proc.kill()
                    lane.proc.wait(timeout=grace_s)
                except Exception:
                    pass
            lane.proc = None
            lane.pid = 0
            lane.state = "init"
        for name, reps in self.replicas.items():
            for r in [r for r in reps if r > 0]:
                self._drop_replica_keys(reps.pop(r))
            P.clear_stripe_map(self.store, name)
        self.publish()


def arm_scale(lanes: list[str], scale_specs,
              knobs: dict | None,
              lane_args: dict[str, list[str]]
              ) -> dict[str, tuple[int, int]]:
    """The ONE --scale plumbing both `spt supervise` and
    supervisor.main() share: parse the bounds, auto-arm the
    control-plane lanes (the controller needs the telemetry rings
    and something to write targets), and forward the controller
    knobs to the autoscaler child's argv (belt to the policy key's
    suspenders — the child honors the policy values either way).
    Mutates `lanes`/`lane_args` in place; returns the bounds dict
    for Supervisor(scale=...).  Raises ValueError on a malformed
    spec."""
    scale = parse_scale_spec(scale_specs)
    for extra in ("telemetry", "autoscaler"):
        if extra not in lanes:
            lanes.append(extra)
    knobs = knobs or {}
    ctl_args = lane_args.setdefault("autoscaler", [])
    for flag, knob in (("--interval-s", "interval_s"),
                       ("--up-threshold", "up_threshold"),
                       ("--down-threshold", "down_threshold"),
                       ("--cooldown-s", "cooldown_s")):
        if knobs.get(knob) is not None:
            ctl_args += [flag, str(knobs[knob])]
    return scale


def parse_scale_spec(specs) -> dict[str, tuple[int, int]]:
    """`--scale lane=min:max` (or lane=max, min defaulting to 1) into
    Supervisor's bounds dict.  Raises ValueError on malformed input —
    a typo'd lane or bound must fail at parse, not mid-run."""
    out: dict[str, tuple[int, int]] = {}
    for spec in specs:
        lane, sep, rng = spec.partition("=")
        lane = lane.strip()
        if not sep or not lane:
            raise ValueError(
                f"--scale wants LANE=MIN:MAX, got {spec!r}")
        if lane not in LANES:
            raise ValueError(
                f"--scale names unknown lane {lane!r} "
                f"(supervisable: {sorted(LANES)})")
        if LANES[lane].max_replicas <= 1:
            raise ValueError(
                f"--scale: lane {lane!r} is not scalable "
                f"(max_replicas 1)")
        lo_s, sep2, hi_s = rng.partition(":")
        try:
            if sep2:
                lo, hi = int(lo_s), int(hi_s)
            else:
                lo, hi = 1, int(lo_s)
        except ValueError:
            raise ValueError(
                f"--scale wants LANE=MIN:MAX, got {spec!r}") from None
        if lo < 1 or hi < lo:
            raise ValueError(
                f"--scale {spec!r}: want 1 <= MIN <= MAX")
        out[lane.strip()] = (lo, hi)
    return out


def parse_chip_pins(spec: str) -> dict[str, str]:
    """Parse --pin-chips "prefill=0,decode=1" -> {"prefill": "0",
    "decode": "1"}.  The value is an opaque device ordinal forwarded
    to children as SPTPU_CHIP_PIN (utils.jaxplatform.apply_chip_pin
    binds jax.default_device to it, degrading to a warning when the
    host has fewer devices — so one spt invocation works on both the
    multi-chip pod and the 1-device CI box).  A malformed spec fails
    startup: a typo must never silently co-locate the lanes."""
    out: dict[str, str] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        lane, sep, dev = part.partition("=")
        lane, dev = lane.strip(), dev.strip()
        if not sep or not lane or not dev:
            raise ValueError(
                f"--pin-chips wants LANE=DEVICE, got {part!r}")
        if lane not in LANES:
            raise ValueError(
                f"--pin-chips names unknown lane {lane!r} "
                f"(supervisable: {sorted(LANES)})")
        out[lane] = dev
    return out


def main(argv: list[str] | None = None) -> int:
    """CLI entry: python -m libsplinter_tpu.engine.supervisor
    --store NAME [--lanes embedder,searcher] [child flags via
    --embedder-args/--completer-args/--searcher-args]."""
    import argparse
    import shlex

    ap = argparse.ArgumentParser(
        description="splinter-tpu daemon supervisor (child-process "
                    "lanes, heartbeat+pid watch, jittered-backoff "
                    "restart, circuit breaker, striped replica sets)")
    ap.add_argument("--store", required=True)
    ap.add_argument("--persistent", action="store_true")
    ap.add_argument("--lanes", default="embedder,completer,searcher",
                    help="comma-separated lanes to supervise")
    # tunables default to None here so Supervisor.__init__ (and
    # Supervisor.run) stay the single source of truth for defaults —
    # only user-set flags are forwarded
    ap.add_argument("--poll-interval-s", type=float, default=None)
    ap.add_argument("--backoff-base-ms", type=float, default=None)
    ap.add_argument("--backoff-max-ms", type=float, default=None)
    ap.add_argument("--breaker-threshold", type=int, default=None,
                    help="crashes inside --breaker-window-s that trip "
                         "the breaker (lane marked down)")
    ap.add_argument("--breaker-window-s", type=float, default=None)
    ap.add_argument("--breaker-cooldown-s", type=float, default=None)
    ap.add_argument("--heartbeat-timeout-s", type=float, default=None)
    ap.add_argument("--startup-grace-s", type=float, default=None)
    ap.add_argument("--stop-after", type=float, default=None)
    ap.add_argument("--keep-faults", action="store_true",
                    help="keep SPTPU_FAULT armed for respawned "
                         "children too (default: first generation "
                         "only — the chaos-drill contract)")
    ap.add_argument("--scale", action="append", default=[],
                    metavar="LANE=MIN:MAX",
                    help="elastic bounds for a lane's replica set "
                         "(repeatable); arms the autoscaler policy")
    ap.add_argument("--scale-interval-s", type=float, default=None,
                    help="autoscaler decision cadence")
    ap.add_argument("--scale-up-threshold", type=float, default=None,
                    help="queue depth per replica that votes scale-up")
    ap.add_argument("--scale-down-threshold", type=float,
                    default=None,
                    help="queue depth per replica below which "
                         "sustained idle votes scale-down")
    ap.add_argument("--scale-cooldown-s", type=float, default=None,
                    help="minimum seconds between scaling actions "
                         "per lane")
    ap.add_argument("--drain-deadline-s", type=float, default=None,
                    help="scale-down: seconds a retiring replica "
                         "gets to finish in-flight work")
    ap.add_argument("--tier-pages", type=int, default=0,
                    metavar="N",
                    help="arm the host-DRAM KV spill tier on every "
                         "serving lane (completer/prefill/decode "
                         "children get --kv-tier-pages N): evicted "
                         "prefix pages demote to host RAM and readmit "
                         "without a re-prefill (engine/kv_tier.py)")
    ap.add_argument("--tier-persist", action="store_true",
                    help="with --tier-pages: checkpoint the warm set "
                         "into a file-backed persistent segment "
                         "(children get bare --kv-tier-persist, i.e. "
                         "<store>-kvtier) so supervised restarts and "
                         "scale-up replicas attach WARM.  Replica 0 "
                         "of each lane writes the snapshot; every "
                         "spawn — restart or scale-up — loads it")
    ap.add_argument("--pin-chips", default="",
                    metavar="LANE=DEV[,LANE=DEV]",
                    help="per-lane device pin, e.g. "
                         "'prefill=0,decode=1' lands the two "
                         "disaggregated lanes on disjoint chips "
                         "(children see SPTPU_CHIP_PIN; off-range "
                         "pins degrade to a warning on small hosts)")
    for lane in LANES:
        ap.add_argument(f"--{lane}-args", default="",
                        help=f"extra argv for the {lane} child "
                             "(shell-quoted)")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    lane_args = {lane: shlex.split(getattr(args, f"{lane}_args"))
                 for lane in LANES}
    if args.tier_persist and not args.tier_pages:
        ap.error("--tier-persist requires --tier-pages N")
    if args.tier_pages:
        # tier convenience flags fan out to every serving lane; an
        # explicit per-lane --kv-tier-pages in --<lane>-args wins
        # (argparse keeps the last occurrence)
        for ln in ("completer", "prefill", "decode"):
            if ln in lane_args:
                extra = ["--kv-tier-pages", str(args.tier_pages)]
                if args.tier_persist:
                    extra.append("--kv-tier-persist")
                lane_args[ln] = extra + lane_args[ln]
    sup_kw = {name: val for name in
              ("backoff_base_ms", "backoff_max_ms",
               "breaker_threshold", "breaker_window_s",
               "breaker_cooldown_s", "heartbeat_timeout_s",
               "startup_grace_s", "drain_deadline_s")
              if (val := getattr(args, name)) is not None}
    if args.keep_faults:
        sup_kw["keep_faults"] = True
    if args.pin_chips:
        try:
            sup_kw["chip_pins"] = parse_chip_pins(args.pin_chips)
        except ValueError as ex:
            ap.error(str(ex))
    lanes = [ln.strip() for ln in args.lanes.split(",") if ln.strip()]
    if args.scale:
        knobs = {"interval_s": args.scale_interval_s,
                 "up_threshold": args.scale_up_threshold,
                 "down_threshold": args.scale_down_threshold,
                 "cooldown_s": args.scale_cooldown_s}
        try:
            sup_kw["scale"] = arm_scale(lanes, args.scale, knobs,
                                        lane_args)
        except ValueError as ex:
            ap.error(str(ex))
        sup_kw["scale_knobs"] = knobs
    run_kw = {}
    if args.poll_interval_s is not None:
        run_kw["poll_interval_s"] = args.poll_interval_s
    if args.stop_after is not None:
        run_kw["stop_after"] = args.stop_after
    sup = Supervisor(
        args.store,
        lanes=tuple(lanes),
        persistent=args.persistent,
        lane_args=lane_args,
        **sup_kw)
    try:
        sup.run(**run_kw)
    except KeyboardInterrupt:
        sup.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
