"""Daemon supervisor — crash-only process management for the lanes.

The reference survives hostile clients because every interaction is a
lock-free slot protocol; the daemons themselves, though, are single
processes — one XLA RESOURCE_EXHAUSTED past the firewalls, one
injected `crash`, one OOM kill, and a lane is gone until an operator
notices.  This module is the missing layer of the serving fault model
("Crash-Only Software": recovery IS startup, so make restart the
first-class path):

  - each lane (embedder / completer / searcher) runs as a CHILD
    process (`python -m libsplinter_tpu.engine.<lane> --store ...`);
  - the supervisor watches pids (waitpid-level truth) AND heartbeats
    (a live pid with a stale heartbeat is a hung daemon — it gets
    SIGKILLed and restarted, the crash-only remedy);
  - crashes restart with jittered exponential backoff (base doubling
    per consecutive crash, 0.5–1.5x jitter so a pod of supervisors
    never thunders back in lockstep);
  - a circuit breaker (N crashes inside a window) marks the lane DOWN
    in the supervisor heartbeat instead of burning CPU on a crash
    loop; CLI clients consult that marker (protocol.lane_down via
    daemon_live) and skip dispatch instead of timing out.  After a
    cooldown the breaker half-opens: one probe child — surviving
    closes the breaker, crashing re-opens it;
  - restart / backoff / breaker counters publish through the existing
    obs surface (__supervisor_stats; `spt metrics` renders them).

Chaos drills: when SPTPU_FAULT is set in the supervisor's
environment, it is handed to each lane's FIRST child only and
stripped from respawns (a drill asserts the restart recovers — an
inherited crash@1 would re-fire in every generation and prove
nothing).  --keep-faults opts back into inheriting, which is how you
demo the breaker.

Usage: `spt supervise` (cli/supervise.py) or
`python -m libsplinter_tpu.engine.supervisor --store NAME`.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import signal
import subprocess
import sys
import time
from collections import deque

from ..store import Store
from ..utils.faults import fault
from . import protocol as P

log = logging.getLogger("libsplinter_tpu.supervisor")

# lane name -> (child module, heartbeat key).  The lane names are the
# public vocabulary: supervisor heartbeat sections, `spt metrics`
# labels, and protocol.lane_down all use them.
LANES: dict[str, tuple[str, str]] = {
    "embedder": ("libsplinter_tpu.engine.embedder", P.KEY_EMBED_STATS),
    "completer": ("libsplinter_tpu.engine.completer",
                  P.KEY_COMPLETE_STATS),
    "searcher": ("libsplinter_tpu.engine.searcher", P.KEY_SEARCH_STATS),
    # the pipeline lane (server-side scripted chains): jax-free, so a
    # supervised restart costs milliseconds, not an XLA warmup
    "pipeliner": ("libsplinter_tpu.engine.pipeliner",
                  P.KEY_SCRIPT_STATS),
    # the telemetry sampler (heartbeat-history rings): jax-free; its
    # rings live in the STORE, so a restart resumes them intact
    "telemetry": ("libsplinter_tpu.engine.telemetry",
                  P.KEY_TELEMETRY_STATS),
}


@dataclasses.dataclass
class LaneProc:
    """One supervised lane's runtime state."""

    name: str
    module: str
    heartbeat_key: str
    proc: object | None = None
    pid: int = 0
    state: str = "init"          # starting|running|backoff|down
    generation: int = 0          # spawn count
    restarts: int = 0            # respawns after a crash/hang
    consecutive: int = 0         # crashes since the last healthy run
    backoff_ms: float = 0.0      # the live backoff, for the heartbeat
    backoff_until: float = 0.0   # monotonic deadline
    breaker_opens: int = 0
    breaker_until: float = 0.0   # monotonic half-open probe time
    half_open: bool = False      # probing after a breaker cooldown
    hung_kills: int = 0          # stale-heartbeat SIGKILLs
    last_exit: int | None = None
    spawn_mono: float = 0.0
    spawn_wall: float = 0.0
    crash_times: deque = dataclasses.field(default_factory=deque)

    def snapshot(self) -> dict:
        """The per-lane heartbeat section (what `spt metrics` renders
        and protocol.lane_down consults)."""
        return {"state": self.state, "pid": self.pid,
                "generation": self.generation,
                "restarts": self.restarts,
                "consecutive_crashes": self.consecutive,
                "backoff_ms": round(self.backoff_ms, 1),
                "breaker_opens": self.breaker_opens,
                "hung_kills": self.hung_kills,
                "last_exit": self.last_exit}


class Supervisor:
    """Drive with run() (blocking loop) or poll_once() (one
    supervision step — tests and deterministic drills).

    spawn_fn and clock are injectable: tests supervise dummy children
    (no jax import) on a compressed timeline."""

    def __init__(self, store_name: str, *,
                 lanes=("embedder", "completer", "searcher"),
                 persistent: bool = False,
                 lane_args: dict[str, list[str]] | None = None,
                 backoff_base_ms: float = 500.0,
                 backoff_max_ms: float = 30_000.0,
                 breaker_threshold: int = 5,
                 breaker_window_s: float = 60.0,
                 breaker_cooldown_s: float = 30.0,
                 heartbeat_timeout_s: float = 30.0,
                 startup_grace_s: float = 60.0,
                 healthy_after_s: float = 30.0,
                 keep_faults: bool = False,
                 spawn_fn=None, clock=None,
                 store: Store | None = None):
        self.store_name = store_name
        self.persistent = persistent
        self.lane_args = lane_args or {}
        self.backoff_base_ms = backoff_base_ms
        self.backoff_max_ms = backoff_max_ms
        self.breaker_threshold = breaker_threshold
        self.breaker_window_s = breaker_window_s
        self.breaker_cooldown_s = breaker_cooldown_s
        self.heartbeat_timeout_s = heartbeat_timeout_s
        # a fresh child pays imports + XLA compiles before its first
        # heartbeat: the hang detector must not eat the startup
        self.startup_grace_s = startup_grace_s
        self.healthy_after_s = healthy_after_s
        self.keep_faults = keep_faults
        self._spawn_fn = spawn_fn or self._spawn_child
        self._clock = clock or time.monotonic
        self._rng = random.Random()
        self.store = store or Store.open(store_name,
                                         persistent=persistent)
        unknown = [ln for ln in lanes if ln not in LANES]
        if unknown:
            raise ValueError(f"unknown lanes {unknown} "
                             f"(supervisable: {sorted(LANES)})")
        self.lanes = {name: LaneProc(name, *LANES[name])
                      for name in lanes}
        self.polls = 0
        self._running = False

    # -- spawning ----------------------------------------------------------

    def _child_env(self, lane: LaneProc) -> dict:
        env = dict(os.environ)
        if lane.generation > 1 and not self.keep_faults:
            # chaos-drill contract: injected faults hit the FIRST
            # generation only; the respawn must prove clean recovery
            env.pop("SPTPU_FAULT", None)
        return env

    def _spawn_child(self, lane: LaneProc):
        argv = [sys.executable, "-m", lane.module,
                "--store", self.store_name]
        if self.persistent:
            argv.append("--persistent")
        argv += self.lane_args.get(lane.name, [])
        return subprocess.Popen(argv, env=self._child_env(lane))

    def _spawn(self, lane: LaneProc, now: float) -> None:
        lane.generation += 1
        if lane.generation > 1:
            lane.restarts += 1
        lane.spawn_mono = now
        lane.spawn_wall = time.time()
        lane.backoff_until = 0.0
        try:
            lane.proc = self._spawn_fn(lane)
            lane.pid = getattr(lane.proc, "pid", 0)
            lane.state = "starting"
            log.info("lane %s: spawned pid %d (generation %d)",
                     lane.name, lane.pid, lane.generation)
        except Exception as ex:
            # a spawn that cannot even exec counts as an instant crash
            log.error("lane %s: spawn failed: %s", lane.name, ex)
            lane.proc = None
            lane.pid = 0
            self._crashed(lane, -1, now)

    # -- crash bookkeeping -------------------------------------------------

    def _crashed(self, lane: LaneProc, code: int, now: float) -> None:
        lane.proc = None
        lane.pid = 0
        lane.last_exit = code
        lane.consecutive += 1
        lane.crash_times.append(now)
        while (lane.crash_times
               and now - lane.crash_times[0] > self.breaker_window_s):
            lane.crash_times.popleft()
        log.warning("lane %s: exited %s (crash %d in window)",
                    lane.name, code, len(lane.crash_times))
        if (lane.half_open
                or len(lane.crash_times) >= self.breaker_threshold):
            # breaker: a half-open probe crashing re-opens instantly;
            # otherwise N crashes / window trip it
            lane.state = "down"
            lane.half_open = False
            lane.breaker_opens += 1
            lane.breaker_until = now + self.breaker_cooldown_s
            lane.crash_times.clear()
            lane.backoff_ms = 0.0
            log.error("lane %s: circuit breaker OPEN for %.1fs",
                      lane.name, self.breaker_cooldown_s)
            return
        lane.state = "backoff"
        base = min(self.backoff_base_ms * 2 ** (lane.consecutive - 1),
                   self.backoff_max_ms)
        lane.backoff_ms = base * self._rng.uniform(0.5, 1.5)
        lane.backoff_until = now + lane.backoff_ms / 1e3

    def _heartbeat_age(self, lane: LaneProc) -> float | None:
        """Seconds since the lane's OWN child published a heartbeat;
        None when no heartbeat from this generation exists yet."""
        try:
            snap = json.loads(
                self.store.get(lane.heartbeat_key).rstrip(b"\0"))
            ts = float(snap.get("ts", 0.0))
        except (KeyError, OSError, ValueError, AttributeError):
            return None
        if ts < lane.spawn_wall:
            return None              # a previous generation's snapshot
        return time.time() - ts

    # -- the supervision step ----------------------------------------------

    def poll_once(self, now: float | None = None) -> None:
        """One step: reap exits, enforce backoff/breaker timers, hang-
        check heartbeats, respawn, publish."""
        fault("supervisor.poll")
        now = self._clock() if now is None else now
        self.polls += 1
        for lane in self.lanes.values():
            if lane.proc is not None:
                rc = lane.proc.poll()
                if rc is not None:
                    self._crashed(lane, rc, now)
                else:
                    self._watch_live(lane, now)
            if lane.proc is None:
                if lane.state == "down":
                    if now >= lane.breaker_until:
                        lane.half_open = True
                        log.warning("lane %s: breaker half-open, "
                                    "probing", lane.name)
                        self._spawn(lane, now)
                elif lane.state in ("init", "backoff"):
                    if now >= lane.backoff_until:
                        self._spawn(lane, now)
        self.publish()

    def _watch_live(self, lane: LaneProc, now: float) -> None:
        age = self._heartbeat_age(lane)
        uptime = now - lane.spawn_mono
        if age is not None and age < self.heartbeat_timeout_s:
            if lane.state == "starting":
                lane.state = "running"
            if (lane.consecutive or lane.half_open) \
                    and uptime >= self.healthy_after_s:
                # survived long enough: close the breaker / reset the
                # backoff ladder
                lane.consecutive = 0
                lane.half_open = False
                lane.backoff_ms = 0.0
                lane.crash_times.clear()
            return
        stale = (uptime > self.startup_grace_s
                 if age is None
                 else age > self.heartbeat_timeout_s
                 and uptime > self.heartbeat_timeout_s)
        if stale:
            # live pid, dead heartbeat: a hung daemon serves nobody —
            # SIGKILL (crash-only: the restart path IS the recovery
            # path) and let the normal crash machinery restart it
            log.error("lane %s: heartbeat stale (age %s, uptime "
                      "%.1fs) — killing pid %d", lane.name,
                      f"{age:.1f}s" if age is not None else "never",
                      uptime, lane.pid)
            lane.hung_kills += 1
            try:
                lane.proc.kill()
                lane.proc.wait(timeout=10)
            except Exception:
                pass
            self._crashed(lane, -signal.SIGKILL, now)

    # -- heartbeat ---------------------------------------------------------

    def publish(self) -> None:
        payload = {
            "polls": self.polls,
            "lanes": {n: ln.snapshot()
                      for n, ln in self.lanes.items()},
        }
        P.publish_heartbeat(self.store, P.KEY_SUPERVISOR_STATS, payload)

    # -- lifecycle ---------------------------------------------------------

    def run(self, *, poll_interval_s: float = 0.5,
            stop_after: float | None = None) -> None:
        self._running = True
        deadline = (self._clock() + stop_after) if stop_after else None
        try:
            while self._running:
                try:
                    self.poll_once()
                except Exception:
                    # the supervisor of the crash-safe layer must hold
                    # itself to the same standard
                    log.exception("supervision step failed; continuing")
                if deadline and self._clock() > deadline:
                    break
                time.sleep(poll_interval_s)
        finally:
            self.shutdown()

    def stop(self) -> None:
        self._running = False

    def shutdown(self, *, grace_s: float = 5.0) -> None:
        """Terminate every child: SIGTERM, bounded wait, SIGKILL."""
        for lane in self.lanes.values():
            if lane.proc is None:
                continue
            try:
                lane.proc.terminate()
            except Exception:
                pass
        for lane in self.lanes.values():
            if lane.proc is None:
                continue
            try:
                lane.proc.wait(timeout=grace_s)
            except Exception:
                try:
                    lane.proc.kill()
                    lane.proc.wait(timeout=grace_s)
                except Exception:
                    pass
            lane.proc = None
            lane.pid = 0
            lane.state = "init"
        self.publish()


def main(argv: list[str] | None = None) -> int:
    """CLI entry: python -m libsplinter_tpu.engine.supervisor
    --store NAME [--lanes embedder,searcher] [child flags via
    --embedder-args/--completer-args/--searcher-args]."""
    import argparse
    import shlex

    ap = argparse.ArgumentParser(
        description="splinter-tpu daemon supervisor (child-process "
                    "lanes, heartbeat+pid watch, jittered-backoff "
                    "restart, circuit breaker)")
    ap.add_argument("--store", required=True)
    ap.add_argument("--persistent", action="store_true")
    ap.add_argument("--lanes", default="embedder,completer,searcher",
                    help="comma-separated lanes to supervise")
    # tunables default to None here so Supervisor.__init__ (and
    # Supervisor.run) stay the single source of truth for defaults —
    # only user-set flags are forwarded
    ap.add_argument("--poll-interval-s", type=float, default=None)
    ap.add_argument("--backoff-base-ms", type=float, default=None)
    ap.add_argument("--backoff-max-ms", type=float, default=None)
    ap.add_argument("--breaker-threshold", type=int, default=None,
                    help="crashes inside --breaker-window-s that trip "
                         "the breaker (lane marked down)")
    ap.add_argument("--breaker-window-s", type=float, default=None)
    ap.add_argument("--breaker-cooldown-s", type=float, default=None)
    ap.add_argument("--heartbeat-timeout-s", type=float, default=None)
    ap.add_argument("--startup-grace-s", type=float, default=None)
    ap.add_argument("--stop-after", type=float, default=None)
    ap.add_argument("--keep-faults", action="store_true",
                    help="keep SPTPU_FAULT armed for respawned "
                         "children too (default: first generation "
                         "only — the chaos-drill contract)")
    for lane in LANES:
        ap.add_argument(f"--{lane}-args", default="",
                        help=f"extra argv for the {lane} child "
                             "(shell-quoted)")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    lane_args = {lane: shlex.split(getattr(args, f"{lane}_args"))
                 for lane in LANES}
    sup_kw = {name: val for name in
              ("backoff_base_ms", "backoff_max_ms",
               "breaker_threshold", "breaker_window_s",
               "breaker_cooldown_s", "heartbeat_timeout_s",
               "startup_grace_s")
              if (val := getattr(args, name)) is not None}
    if args.keep_faults:
        sup_kw["keep_faults"] = True
    run_kw = {}
    if args.poll_interval_s is not None:
        run_kw["poll_interval_s"] = args.poll_interval_s
    if args.stop_after is not None:
        run_kw["stop_after"] = args.stop_after
    sup = Supervisor(
        args.store,
        lanes=tuple(ln.strip() for ln in args.lanes.split(",")
                    if ln.strip()),
        persistent=args.persistent,
        lane_args=lane_args,
        **sup_kw)
    try:
        sup.run(**run_kw)
    except KeyboardInterrupt:
        sup.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
