"""Streaming completion daemon — the splainference analog.

The TPU-native replacement for the reference's completion sidecar
(splainference.cpp; SURVEY.md §2.2, §3.3).  Clients write a prompt to a
key, set the inference-waiting label (0x1<<60) and bump; this daemon:

  - claims shard 0x5F1A at priority 200 and re-bids every 32 generated
    tokens (splainference.cpp:51-62,355-364);
  - wakes on its signal group, enumerates waiting keys
    (splainference.cpp:582-589);
  - per key: epoch-stable prompt read → fetches the system-prompt key
    FRESH each request (splainference.cpp:114-128,212-215) → renders a
    chat template with bare fallback (splainference.cpp:132-169) →
    flips WAITING→SERVICING + bump → overwrites the slot with the
    rendered prompt (splainference.cpp:266-269) → prefills the decoder
    → token loop sampling top-p 0.9 / temp 0.7, streaming pieces into
    the slot via append flushed at word boundaries or every 8 tokens
    (splainference.cpp:86,102-109,306-365) so readers watch val_len
    grow → truncates at max_val with an oom marker
    (splainference.cpp:336-344) → clears the KV cache, backfills ctime,
    flips SERVICING→READY + bump (splainference.cpp:378-392);
  - appends debug chatter to the shared __debug key
    (splainference.cpp:94-100);
  - cold-start: drains any pre-existing waiting keys
    (splainference.cpp:541-551).

The decoder is a JAX causal LM with a device-resident KV cache
(models/decoder.py); generation compiles once per bucket and never
recompiles in the token loop.
"""
from __future__ import annotations

import dataclasses
import errno
import logging
import os
import time
from collections import deque
from typing import Callable, Iterator

from .. import _native as N
from ..obs.devtime import DEVTIME
from ..obs.recorder import FlightRecorder
from ..obs.spans import SpanWriter
from ..store import Store
from ..utils import faults
from ..utils.faults import fault
from ..utils.trace import tracer
from . import protocol as P
from .qos import (AdmissionController, TenantLedger, WaitingRow,
                  parse_tenant_quotas, parse_tenant_weights,
                  prune_idle_counters)

log = logging.getLogger("libsplinter_tpu.completer")

# A generator backend: (prompt_text) -> iterator of byte pieces.
GenerateFn = Callable[[str], Iterator[bytes]]

OOM_MARKER = b"\n[truncated: value buffer full]"


TEMPLATES = ("none", "chatml", "llama2", "llama3")


def render_prompt(user: str, system: str | None,
                  template: str = "chatml") -> str:
    """Chat-template render with bare fallback
    (splainference.cpp:132-169: llama_chat_apply_template else
    'system\\n\\nuser' concatenation).  Supported: chatml, llama2,
    llama3, none.  Unknown names raise — 'auto' must be resolved via
    detect_template() BEFORE construction, never silently rendered as
    some default dialect."""
    if template == "none" or not template:
        return f"{system}\n\n{user}" if system else user
    if template == "llama2":
        sys_block = f"<<SYS>>\n{system}\n<</SYS>>\n\n" if system else ""
        return f"<s>[INST] {sys_block}{user} [/INST]"
    if template == "llama3":
        out = ["<|begin_of_text|>"]
        if system:
            out.append("<|start_header_id|>system<|end_header_id|>\n\n"
                       f"{system}<|eot_id|>")
        out.append("<|start_header_id|>user<|end_header_id|>\n\n"
                   f"{user}<|eot_id|>")
        out.append("<|start_header_id|>assistant<|end_header_id|>\n\n")
        return "".join(out)
    if template == "chatml":
        out = []
        if system:
            out.append(f"<|im_start|>system\n{system}<|im_end|>\n")
        out.append(f"<|im_start|>user\n{user}<|im_end|>\n")
        out.append("<|im_start|>assistant\n")
        return "".join(out)
    raise ValueError(
        f"unknown chat template {template!r} (supported: "
        f"{', '.join(TEMPLATES)}; 'auto' resolves via detect_template)")


def detect_template(chat_template: str | None) -> str:
    """Map a checkpoint's embedded Jinja chat template (GGUF metadata
    tokenizer.chat_template) to the nearest built-in renderer — the
    analog of llama.cpp's template fingerprinting.  Unknown templates
    fall back to bare concatenation rather than guessing a wrong
    special-token dialect."""
    if not chat_template:
        return "none"
    if "<|im_start|>" in chat_template:
        return "chatml"
    if "<|start_header_id|>" in chat_template:
        return "llama3"
    if "[INST]" in chat_template:
        return "llama2"
    return "none"


@dataclasses.dataclass
class CompleterStats:
    wakes: int = 0
    completions: int = 0
    tokens: int = 0
    truncated: int = 0
    raced: int = 0
    vanished: int = 0                 # keys deleted mid-request
    faults: int = 0                   # per-key failures the firewall ate
    reclaimed: int = 0                # stranded SERVICING rows re-queued
    join_backpressure: int = 0        # admissions deferred: pool full
    spec_demotions: int = 0           # speculative -> plain fallbacks
    # -- multi-tenant QoS (engine/qos.py) ----------------------------
    deadline_expired: int = 0         # fast-failed: deadline passed
    shed: int = 0                     # typed overloaded + retry hint
    deferred: int = 0                 # held for a later drain/chunk
    # -- K-deep decode overlap (engine/resident.py): un-awaited paged
    # decode chunks held while the host emits/admits ----------------
    inflight_peak: int = 0
    # mid-decode deadline aborts (continuous lane): rows whose
    # deadline expired at a chunk edge, retired with the typed
    # DEADLINE_EXPIRED record and their pages freed immediately
    killed_mid_decode: int = 0


class Completer:
    """Drive with run() (blocking loop), run_once() (single drain), or
    process_key() directly.  A fake generate_fn substitutes for the
    decoder in tests (the daemon-level test gap called out in
    SURVEY.md §4)."""

    # lane identity — the disaggregated prefill/decode lanes
    # (engine/disagg.py) subclass this daemon and override these:
    # LANE names the stripe map, span lane, debug prefix and devtime
    # lane; HB_KEY the heartbeat base key; WATCH_BIT the label
    # transition the lane wakes on (a decode lane watches
    # DECODE_READY handoffs, not fresh INFER_REQ arrivals).
    LANE = "completer"
    HB_KEY = P.KEY_COMPLETE_STATS
    WATCH_BIT = P.BIT_INFER_REQ

    def __init__(self, store: Store, generate_fn: GenerateFn | None = None,
                 *, model=None, tokenizer=None,
                 max_new_tokens: int = 256,
                 flush_tokens: int = 8,
                 rebid_tokens: int = 32,
                 template: str = "chatml",
                 group: int = P.GROUP_INFER,
                 batch_cap: int | None = None,
                 page_size: int = 128,
                 pool_pages: int | None = None,
                 kv_dtype: str | None = None,
                 inflight_depth: int | None = None,
                 spec_min_acceptance: float = 0.2,
                 queue_high_water: int | None = None,
                 retry_after_ms: int | None = None,
                 tenant_weights: dict[int, float] | None = None,
                 prefix_cache: bool = True,
                 prefix_cache_pages: int | None = None,
                 prefix_quotas: dict[int, int] | None = None,
                 prefix_default_quota: int | None = None,
                 kv_tier_pages: int = 0,
                 kv_tier_persist: str | None = None,
                 replica: int = 0):
        self.store = store
        # elastic lanes (protocol.StripeView): replica r drains only
        # its own slot-index stripe; stranded-SERVICING reclaim is
        # stripe-scoped too, so a restarted replica can never steal a
        # live peer's in-flight rows
        self.replica = int(replica)
        self.stripes = P.StripeView(store, self.LANE, self.replica)
        self._hb_key = P.replica_stats_key(self.HB_KEY,
                                           self.replica)
        self._trace_key = P.replica_stats_key(P.KEY_COMPLETE_TRACE,
                                              self.replica)
        self.max_new = max_new_tokens
        self.flush_tokens = flush_tokens
        self.rebid_tokens = rebid_tokens
        # per-lane defaults: the dense drains keep the r05-proven 8
        # (a wider dense batch multiplies (B, max_len, KH, D) cache
        # HBM — the very wall this PR removes), while the continuous
        # lane defaults to 32 because the block-paged pool's HBM
        # scales with live tokens instead of batch x max_len.  An
        # explicit batch_cap applies to both lanes unchanged.
        self.batch_cap = 8 if batch_cap is None else batch_cap
        self.paged_batch_cap = 32 if batch_cap is None else batch_cap
        self.page_size = page_size
        self.pool_pages = pool_pages
        # paged-pool storage dtype (--kv-dtype): "int8" quantizes the
        # continuous lane's KV pool (per-page scales, dequant inside
        # the ragged kernel) so cache bytes per token halve vs bf16 —
        # the headroom --batch-cap/--pool-pages then spend on batch
        # width.  "int4" packs two 4-bit codes per byte on top of the
        # same scale discipline — a QUARTER of bf16's cache bytes, so
        # the same pool serves 4x the batch.  None keeps the model's
        # native dtype.
        if kv_dtype not in (None, "bf16", "f32", "int8", "int4"):
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r} (bf16 | f32 | int8 | int4)")
        self.kv_dtype = kv_dtype
        # K-deep decode overlap on the continuous lane: the chunk
        # pipeline runs K deep — dispatch chunk K, then collect the
        # OLDEST while the newest computes (the token hand-off between
        # chunks rides the device, PendingChunk.last), so the host's
        # emit/flush/admit work overlaps device compute and the
        # per-chunk runtime round trip amortizes.  K counts the chunk
        # being collected: K-1 chunks stay un-awaited between loop
        # iterations (one less than the searcher/embedder windows,
        # whose depth bounds fully un-awaited entries), and 1 =
        # collect each chunk before dispatching the next — the
        # pre-overlap sync cadence.
        self.inflight_depth = (2 if inflight_depth is None
                               else max(1, inflight_depth))
        self.spec_min_acceptance = spec_min_acceptance
        self._spec_hist: list[tuple[int, int]] = []
        self._spec_acceptance_rolling: float | None = None
        self._paged_cache = None
        # multi-tenant QoS (engine/qos.py): every drain/admission
        # cycle orders the waiting keys fairly across tenants (stride
        # credit persists, so a starved tenant leads the next cycle);
        # queue_high_water bounds the waiting backlog — overflow is
        # claimed and READY-flipped with a typed overloaded JSON value
        # carrying retry_after_ms.  Deadline fast-fail is always on
        # for requests carrying a deadline stamp.
        self.qos = AdmissionController(
            weights=tenant_weights, high_water=queue_high_water,
            **({"retry_after_ms": retry_after_ms}
               if retry_after_ms is not None else {}))
        # phase-aware deadline slack: a request whose deadline will
        # pass before the lane's service phase even starts should
        # fast-fail NOW instead of paying prefill first.  The prefill
        # lane (engine/disagg.py) feeds this from a rolling prefill-
        # wall EMA; 0.0 keeps the unified lane's exact-expiry check.
        self.qos_slack_s = 0.0
        self.tenants = TenantLedger()
        self._had_deferred = False
        # join-backpressure memo, idx -> (slot epoch, pages needed):
        # instance state (not a run_continuous local) so the heartbeat
        # can publish its size and the sweep can bound it — under
        # sustained shedding it would otherwise grow per denied key
        self._bp_memo: dict[int, tuple[int, int]] = {}
        self._bp_memo_cap = 4096
        # cross-request prefix sharing (engine/prefix_cache.py): the
        # continuous lane's radix tree over the paged pool.  Built
        # lazily with the pool (plain PagedKVCache only — the paired
        # speculative pools don't share); pages_needed/backpressure
        # then count only the uncached suffix of each admission.
        self._prefix_enabled = bool(prefix_cache)
        self._prefix_cache_pages = prefix_cache_pages
        self._prefix_quotas = dict(prefix_quotas or {})
        self._prefix_default_quota = prefix_default_quota
        self.prefix_cache = None
        # tiered KV (engine/kv_tier.py): a host-DRAM spill tier under
        # the radix tree — _evict_one demotes zero-ref pages to host
        # RAM instead of dropping, and a radix hit on a demoted page
        # readmits via device_put + block-table write instead of a
        # re-prefill.  kv_tier_persist names a file-backed store
        # segment the warm set checkpoints into (write-record-last,
        # epoch-bumped), so a supervised restart attaches WARM;
        # replica 0 owns the snapshot writes, every replica loads.
        self._tier_pages = max(0, int(kv_tier_pages))
        self._tier_persist_name = kv_tier_persist
        self.kv_tier = None
        self._tier_store = None
        self._tier_restore: tuple[int, str] = (0, "off")
        self._tier_last_save = 0.0
        if template not in TEMPLATES:
            raise ValueError(
                f"unknown chat template {template!r} (supported: "
                f"{', '.join(TEMPLATES)}; resolve 'auto' with "
                "detect_template first)")
        self.template = template
        self.group = group
        self.stats = CompleterStats()
        # flight recorder for the serial (process_key) path: clients
        # stamp infer requests exactly like embed ones
        # (protocol.stamp_trace); batched/continuous paths aggregate
        # through the span histograms only
        self.recorder = FlightRecorder()
        self.spans = SpanWriter(store, self.LANE)
        # disaggregated decode lane (engine/disagg.py): when set,
        # run_continuous's admit() delegates to this callable —
        # admission becomes ADOPTION of DECODE_READY handoffs and the
        # WAITING queue belongs to the prefill lanes
        self._lane_admit = None
        # pending spans between _prepare and _finalize, keyed by the
        # request key (every service path pairs the two); bounded by
        # in-flight work, with a hard cap against pathological leaks
        self._live_spans: dict[str, object] = {}
        self._trace_published = 0      # ring state last published
        # HBM watermarks: pool-occupancy high-water sampled at chunk
        # edges + heartbeats, reset only at attach (generation scope)
        self._pages_used_peak = 0
        self._pool_mb_peak = 0.0
        self.generation = 0            # bumped at attach (restart marker)
        self._bid = -1
        self._running = False

        if generate_fn is not None:
            self.generate_fn = generate_fn
        else:
            if model is None:
                from ..models import CompletionModel, DecoderConfig
                # default vocab sized for the byte tokenizer (259 ids,
                # padded to a lane-friendly 512); real checkpoints bring
                # their own matching cfg+tokenizer pair
                model = CompletionModel(DecoderConfig(vocab_size=512))
            if tokenizer is None:
                from ..models import ByteTokenizer
                tokenizer = ByteTokenizer()
            self._model = model
            self._tok = tokenizer
            self.generate_fn = self._model_generate

    # -- wiring ------------------------------------------------------------

    def attach(self) -> None:
        st = self.store
        try:
            self._bid = st.shard_claim(P.SHARD_COMPLETE, N.ADV_WILLNEED,
                                       P.PRIO_COMPLETE, 30_000_000)
        except OSError:
            self._bid = -1
        st.watch_label_register(self.WATCH_BIT, self.group)
        st.bus_attach()   # adopts the bus when a crashed owner
                          # left a dead pid in the header
        self.generation = P.bump_generation(st, self._hb_key)
        # compile events ledgered from here carry this generation —
        # a restart's re-warmup is distinguishable in the ring
        DEVTIME.generation = max(DEVTIME.generation, self.generation)
        self._reclaim_stranded()

    def _reclaim_stranded(self) -> int:
        """Crash recovery: a daemon that died mid-completion leaves
        its key in SERVICING — no label watch fires for it again, so
        without this it is wedged forever.  Each stripe has ONE owner
        (the supervisor's invariant, per-replica under elastic
        lanes), so at attach every SERVICING row in OUR stripes is a
        previous generation's stranded request: flip it back to
        WAITING and let the cold-start drain re-serve it (the client
        sees a restarted stream, same as the reference's crash
        story).  Rows outside our stripes belong to live peer
        replicas mid-service — never touched; a permanently-dead
        replica's rows are the supervisor's straggler reclaim.

        Known bound (mirrors Supervisor._reclaim_closed's): a live
        peer's claim that predates a re-stripe can sit in OUR
        current stripes and would be re-queued here as stranded —
        the window needs an in-flight request to span a stripe
        promotion AND our own crash+respawn; claim-owner stamping
        is the follow-up that would close it."""
        st = self.store
        self.stripes.refresh()
        n = 0
        for idx in st.enumerate_indices(P.LBL_SERVICING):
            if not self.stripes.owns(idx):
                continue
            key = st.key_at(idx)
            if key is None:
                continue
            try:
                st.label_clear(key, P.LBL_SERVICING)
                st.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
                n += 1
            except (KeyError, OSError):
                continue
        if n:
            self.stats.reclaimed += n
            self._debug(f"reclaimed {n} stranded SERVICING requests")
        return n

    def _requeue_failed(self, idxs: list[int]) -> int:
        """Firewall tail for run_once: an exception escaping
        process_key/process_batch after _prepare flipped rows to
        SERVICING leaves them label-invisible — the sweep enumerates
        LBL_INFER_REQ and, with the daemon still alive, the attach()
        reclaim never runs.  Flip the failed batch's SERVICING rows
        back to WAITING so the next sweep re-serves them instead of
        wedging their clients until timeout."""
        st = self.store
        n = 0
        for idx in idxs:
            try:
                if not (st.labels_at(idx) & P.LBL_SERVICING):
                    continue
                key = st.key_at(idx)
                if key is None:
                    continue
                st.label_clear(key, P.LBL_SERVICING)
                st.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
                n += 1
            except (KeyError, OSError):
                continue
        if n:
            self.stats.reclaimed += n
            self._debug(f"re-queued {n} SERVICING rows after a drain "
                        "fault")
        return n

    # -- multi-tenant QoS --------------------------------------------------

    def _qos_meta(self, idx: int) -> tuple[int, float | None]:
        """(tenant, deadline) for a waiting slot — tenant from the
        label word (free: one read), deadline from the companion stamp
        only when LBL_DEADLINE flags it."""
        st = self.store
        try:
            labels = st.labels_at(idx)
        except (KeyError, OSError):
            return 0, None
        deadline = None
        if labels & P.LBL_DEADLINE:
            try:
                deadline = P.read_deadline(st, idx,
                                           epoch=st.epoch_at(idx))
            except (KeyError, OSError):
                deadline = None
        return P.read_tenant(labels), deadline

    def _terminal_reject(self, idx: int, payload: bytes,
                         counter: str, tenant: int) -> bool:
        """Claim-and-reject a waiting request without spending a batch
        slot: the slot's value becomes the typed JSON payload
        (overloaded + retry_after_ms, or deadline_expired) and the
        label trifecta lands at READY — the client (engine/client.py)
        parses the record instead of burning its timeout."""
        st = self.store
        span = None
        try:
            if st.epoch_at(idx) & 1:
                return False          # writer active: next cycle
            labels = st.labels_at(idx)
            if not labels & P.LBL_INFER_REQ:
                return False          # recycled since enumeration
            key = st.key_at(idx)
            if key is None:
                return False
            if labels & P.LBL_TRACED:
                # the typed reject is this request's whole service:
                # open + commit its span around the claim (before the
                # payload write moves the epoch), then retire the
                # stamp the span protocol left in place
                span = self.spans.begin(idx, st.epoch_at(idx),
                                        tenant=tenant)
                P.consume_trace_stamp(st, idx)
            st.label_clear(key, P.LBL_INFER_REQ | P.LBL_WAITING)
            st.set(key, payload)
            st.label_or(key, P.LBL_READY)
            st.bump(key)
        except (KeyError, OSError):
            return False
        self.spans.commit(span, status=(
            P.ERR_DEADLINE if counter == "deadline_expired"
            else P.ERR_OVERLOADED))
        P.clear_deadline(st, idx)
        setattr(self.stats, counter,
                getattr(self.stats, counter) + 1)
        self.tenants.bump(tenant, counter)
        return True

    def _admit_waiting(self, idxs: list[int],
                       capacity: int) -> list[int]:
        """Order one cycle's waiting keys through the shared admission
        policy: expired deadlines reject fast, the fairness-ordered
        admit set (up to capacity) is returned for service, overflow
        past queue_high_water is shed with the typed overloaded
        record, the rest stay WAITING (their tenants lead the next
        cycle — stride state persists).  With no QoS config and no
        stamped rows this is a cheap pass-through."""
        if not idxs:
            return idxs
        rows: list[WaitingRow] = []
        tagged = False
        for idx in idxs:
            tenant, deadline = self._qos_meta(idx)
            tagged = tagged or tenant or deadline is not None
            rows.append(WaitingRow(idx, tenant, deadline))
        if not tagged and self.qos.high_water is None \
                and capacity >= len(idxs):
            self._had_deferred = False
            return idxs
        plan = self.qos.plan(rows, capacity,
                             slack_s=self.qos_slack_s)
        for row in plan.expired:
            self._terminal_reject(row.item,
                                  P.DEADLINE_EXPIRED_DIAGNOSTIC,
                                  "deadline_expired", row.tenant)
        for row in plan.shed:
            self._terminal_reject(
                row.item,
                P.overloaded_payload(self.qos.retry_after_ms),
                "shed", row.tenant)
        self.stats.deferred += len(plan.deferred)
        self._had_deferred = bool(plan.deferred)
        return [row.item for row in plan.admit]

    def _sweep_bp_memo(self) -> int:
        """Bound the join-backpressure memo: evict entries whose slot
        epoch moved on (rewritten/recycled — the memo'd pages-needed
        no longer describes the slot's request) or whose request label
        is gone (served, shed, or deadline-rejected).  Runs on the
        heartbeat cadence; under sustained shedding the memo would
        otherwise grow one entry per denied key forever.  A hard size
        cap (_bound_bp_memo, stale-first) backstops even a
        pathological store."""
        st = self.store
        dropped = 0
        for idx, (e, _need) in list(self._bp_memo.items()):
            try:
                if st.epoch_at(idx) != e or \
                        not st.labels_at(idx) & P.LBL_INFER_REQ:
                    del self._bp_memo[idx]
                    dropped += 1
            except (KeyError, OSError):
                self._bp_memo.pop(idx, None)
                dropped += 1
        return dropped + self._bound_bp_memo()

    def _bound_bp_memo(self) -> int:
        """Enforce the memo's hard size cap, evicting by SLOT-EPOCH
        STALENESS first: an entry whose slot epoch moved (or whose
        slot is gone) memoizes a request that no longer exists, while
        a live entry — however old — is a denied request the memo
        exists to keep cheap (evicting it re-pays render+tokenize on
        every subsequent chunk).  The old oldest-insertion policy did
        exactly that backwards: a long-lived denied request was the
        FIRST thing dropped while freshly-stale newcomers survived.
        Insertion-order eviction remains only as the final tiebreak
        among live entries."""
        over = len(self._bp_memo) - self._bp_memo_cap
        if over <= 0:
            return 0
        st = self.store
        dropped = 0
        for idx, (e, _need) in list(self._bp_memo.items()):
            if dropped >= over:
                break
            try:
                stale = st.epoch_at(idx) != e
            except (KeyError, OSError):
                stale = True
            if stale:
                self._bp_memo.pop(idx, None)
                dropped += 1
        while len(self._bp_memo) > self._bp_memo_cap:
            self._bp_memo.pop(next(iter(self._bp_memo)))
            dropped += 1
        return dropped

    def _debug(self, msg: str) -> None:
        """Append to the shared debug log key
        (splainference.cpp:94-100)."""
        st = self.store
        try:
            if P.KEY_DEBUG not in st:
                st.set(P.KEY_DEBUG, b"")
                st.label_or(P.KEY_DEBUG, P.LBL_DEBUG)
            st.append(P.KEY_DEBUG, f"[{self.LANE}] {msg}\n")
        except OSError:
            pass                      # debug channel full: not an error

    # -- model backend -----------------------------------------------------

    def _clip_context(self, ids: list[int], *, bucketed: bool) -> list[int]:
        """Keep the most recent context that still leaves max_new decode
        slots in the window.  Serial prefill parks the decode position
        at the REAL prompt length, so its budget is raw
        (max_len - max_new - 1).  Batched prefill left-pads to a bucket
        and parks at the BUCKET width (models/decoder.py prefill_batch),
        so the batched budget must be the largest bucket that still
        fits — a raw budget would round up into the window and strand
        every row with ~zero decode room."""
        m = self._model
        if bucketed:
            budget = self._batched_budget()
            assert budget is not None, \
                "run_once must route to serial when no bucket fits"
        else:
            budget = m.cfg.max_len - self.max_new - 1
            if budget < 1:
                budget = m.cfg.max_len // 2
        return ids[-budget:] if len(ids) > budget else ids

    def _batched_budget(self) -> int | None:
        """Largest prompt budget the BATCHED path can serve: the widest
        padding bucket strictly inside the window (prefill_batch
        requires max(lens) < max_len and parks the decode position at
        the bucket width), preferring one that also leaves max_new
        decode slots.  None when every bucket is the window itself —
        batched prefill would have zero decode room, so run_once falls
        back to serial serving for that geometry."""
        m = self._model
        usable = [b for b in m.buckets if b < m.cfg.max_len]
        if not usable:
            return None
        fit = [b for b in usable if b + self.max_new <= m.cfg.max_len]
        return fit[-1] if fit else usable[-1]

    def _model_generate(self, prompt: str) -> Iterator[bytes]:
        m, tok = self._model, self._tok
        ids = self._clip_context(tok.encode(prompt), bucketed=False)
        import numpy as np
        try:
            # chunk-at-a-time on-device decode: the host syncs once per
            # flush_tokens tokens, not once per token (VERDICT r1
            # item 5; cadence from splainference.cpp:333-354)
            for t in m.generate_tokens(np.asarray(ids, np.int32),
                                       self.max_new,
                                       chunk=max(1, self.flush_tokens)):
                if t == tok.eos_id:
                    break
                yield tok.token_to_piece(t)
        finally:
            m.reset()                 # llama_memory_clear analog

    # -- the completion ----------------------------------------------------

    def _read_rendered(self, idx: int):
        """Guarded prompt read + fresh system-prompt fetch + template
        render — NO side effects, so callers can peek a request (e.g.
        to check it fits a live batch) without claiming it.  Returns
        (key, rendered) or None."""
        st = self.store
        e = st.epoch_at(idx)
        if e & 1:
            return None               # writer active: next wake
        if not st.labels_at(idx) & P.LBL_INFER_REQ:
            return None               # slot recycled since enumeration:
                                      # never service a key that didn't ask
        key = st.key_at(idx)
        if key is None:
            return None
        try:
            prompt = st.get_at(idx).rstrip(b"\0").decode(
                "utf-8", errors="replace")
        except Exception:
            return None
        if st.epoch_at(idx) != e:
            self.stats.raced += 1
            return None               # torn read: re-queued by next wake

        # system prompt fetched fresh each request
        system = None
        try:
            system = st.get(P.KEY_SYSTEM_PROMPT).decode(
                "utf-8", errors="replace")
        except KeyError:
            pass
        return key, render_prompt(prompt, system, self.template)

    def _prepare(self, idx: int, peek: tuple | None = None):
        """The per-key request head (splainference.cpp:190-269):
        _read_rendered plus the claim side effects — WAITING→SERVICING
        flip, slot overwrite with the rendered prompt.  A caller that
        already peeked passes its (key, rendered) to avoid re-reading.
        Returns (key, rendered, t0, stamp) or None; stamp is the
        request's consumed trace stamp (serial path records it, the
        batched/continuous paths aggregate via spans only — consuming
        HERE means no path can leave a stale stamp to corrupt a later
        request's flight record)."""
        fault("completer.render")
        st = self.store
        if peek is None:
            peek = self._read_rendered(idx)
        if peek is None:
            return None
        key, rendered = peek

        stamp = None
        if st.labels_at(idx) & P.LBL_TRACED:
            # span begin consumes the stamp (the unstaged consume-
            # early discipline — exactly the old consume semantics),
            # and the PendingSpan carries the context to _finalize.
            # Consumed even with tracing OFF; recorded only when on.
            span = self.spans.begin(idx, st.epoch_at(idx),
                                    tenant=P.read_tenant(
                                        st.labels_at(idx)))
            if span is not None:
                if len(self._live_spans) > 1024:
                    self._live_spans.clear()   # spans are best-effort
                self._live_spans[key] = span
                stamp = span.stamp if tracer.enabled else None

        # QoS accounting at the claim (the real admission moment):
        # tagged requests count per tenant, and a consumed deadline
        # stamp must not linger to misjudge a later slot occupant
        try:
            labels_now = st.labels_at(idx)
        except (KeyError, OSError):
            labels_now = 0
        if labels_now & (P.TENANT_MASK | P.LBL_DEADLINE):
            self.tenants.bump(P.read_tenant(labels_now), "admitted")
            if labels_now & P.LBL_DEADLINE:
                P.clear_deadline(st, idx)

        # WAITING → SERVICING, visible to watchers immediately
        st.label_clear(key, P.LBL_INFER_REQ | P.LBL_WAITING)
        st.label_or(key, P.LBL_SERVICING)
        st.bump(key)

        # slot now holds the rendered prompt; generation appends after it
        t0 = Store.now()
        data = rendered.encode("utf-8")
        try:
            st.set(key, data)
        except OSError:               # rendered prompt alone overflows —
            st.set(key, data[: st.max_val - 1])   # slice BYTES, not chars
        return key, rendered, t0, stamp

    def _finalize(self, key: str, t0: int, n_tok: int,
                  truncated: bool, vanished: bool = False,
                  stages: dict | None = None) -> None:
        """The per-key request tail: oom bookkeeping, ctime backfill
        with tick delta (splainference.cpp:282,383-387),
        SERVICING→READY flip.  A key deleted mid-request must fail
        alone — in a batch, a raising tail would strand the SIBLING
        rows in SERVICING forever — and is counted as vanished, not as
        a completion or a max_val truncation."""
        fault("completer.commit")
        st = self.store
        span = self._live_spans.pop(key, None)
        # the request's device window (dispatch->collect wall across
        # its decode chunks) — drain-scoped, SpanWriter.commit.  Split
        # lanes drain BOTH accumulators: the paged programs register
        # under the lane's own devtime name, the trunk + samplers stay
        # under the canonical "completer" lane.
        device_ms = DEVTIME.take_lane_ms(self.LANE)
        if self.LANE != "completer":
            device_ms += DEVTIME.take_lane_ms("completer")
        if span is None and stages:
            # tail-based retention: a slow request that carried no
            # trace stamp still keeps full INFER_STAGES detail — one
            # `tail: true` span, slow-log-resolvable by trace id
            thr = self.recorder.slow_threshold_ms()
            wall = sum(stages.values())
            if thr is not None and wall > thr:
                tid = self.spans.tail_span(
                    key, wall, stages=stages,
                    extra={"tokens": n_tok},
                    device_ms=device_ms if device_ms > 0 else None)
                if tid is not None:
                    self.recorder.record(
                        tid, key, wall,
                        [[n, round(float(ms), 3)]
                         for n, ms in stages.items()])
        if vanished:
            self.stats.vanished += 1
            self._debug(f"key {key!r} vanished mid-request")
            self.spans.commit(span, status="error", stages=stages)
            return
        if truncated:
            self.stats.truncated += 1
            self._debug(f"completion for {key!r} truncated at max_val")
        try:
            st.stamp(key, which=0, ticks_ago=Store.now() - t0)
        except Exception:
            pass
        try:
            # DECODE_READY cleared too: on the disaggregated decode
            # lane a finishing row carries SERVICING|DECODE_READY and
            # leaving the handoff bit set would invite a re-adoption
            # of a completed request (a no-op clear elsewhere)
            st.label_clear(key, P.LBL_SERVICING | P.LBL_DECODE_READY)
            st.label_or(key, P.LBL_READY)
            st.bump(key)
        except (KeyError, OSError):
            self.stats.vanished += 1
            self._debug(f"key {key!r} vanished mid-request")
            self.spans.commit(span, status="error", stages=stages)
            return
        self.spans.commit(span, stages=stages,
                          extra={"tokens": n_tok},
                          device_ms=device_ms if device_ms > 0
                          else None)
        self.stats.completions += 1
        self.stats.tokens += n_tok
        try:
            tenant = P.read_tenant(st.labels(key))
        except (KeyError, OSError):
            tenant = 0
        if tenant:
            # tenant bits survive the claim (only INFER/WAITING were
            # cleared), so goodput attribution needs no plumbing
            self.tenants.bump(tenant, "served_tokens", n_tok)

    def _rebid(self) -> None:
        if self._bid >= 0:
            try:
                self.store.shard_rebid(self._bid)
            except OSError:
                pass

    # -- disaggregated-lane hooks (engine/disagg.py overrides) -------------

    def _lane_row_done(self, row: dict) -> None:
        """A continuous-lane row retired (finish or mid-decode kill).
        The decode lane deletes the row's handoff record + wire pages
        here; the unified lane has nothing to clean up."""

    def _lane_payload(self, payload: dict) -> None:
        """Lane-specific heartbeat sections (handoff counters,
        adoption gauges) land here just before publish."""

    def process_key(self, idx: int) -> bool:
        """Run one completion for slot idx.  Returns True if serviced.

        With SPTPU_TRACE=1 the request decomposes into the
        protocol.INFER_STAGES histogram spans, and a client-stamped
        request (protocol.stamp_trace) gets a flight-recorder entry
        with the stage event sequence + client-measured wall time."""
        traced = tracer.enabled
        tr0 = time.perf_counter()
        prep = self._prepare(idx)
        if prep is None:
            return False
        tr1 = time.perf_counter()
        key, rendered, t0, stamp = prep
        n_tok, pending = 0, b""
        truncated = vanished = False
        try:
            fault("completer.generate")
            for piece in self.generate_fn(rendered):
                pending += piece
                n_tok += 1
                boundary = piece.endswith((b" ", b"\n", b"\t"))
                if boundary or n_tok % self.flush_tokens == 0:
                    r = self._flush(key, pending)
                    if r != "ok":
                        truncated = r == "full"
                        vanished = r == "gone"
                        break
                    pending = b""
                if self.rebid_tokens and n_tok % self.rebid_tokens == 0:
                    self._rebid()
            if pending and not truncated and not vanished:
                r = self._flush(key, pending)
                truncated = r == "full"
                vanished = r == "gone"
        except Exception as ex:       # model failure must not wedge WAITING
            self._debug(f"generation failed for {key!r}: {ex}")
        tr2 = time.perf_counter()
        self._finalize(key, t0, n_tok, truncated, vanished)
        if traced:
            tr3 = time.perf_counter()
            stages = ((tr1 - tr0) * 1e3, (tr2 - tr1) * 1e3,
                      (tr3 - tr2) * 1e3)
            for name, ms in zip(P.INFER_STAGES, stages):
                tracer.record(f"infer.{name}", ms)
            tracer.record("infer.e2e", (tr3 - tr0) * 1e3)
            if stamp is not None:
                tid, ts = stamp
                wall = ((time.time() - ts) * 1e3 if ts > 0
                        else (tr3 - tr0) * 1e3)
                self.recorder.record(
                    tid, key, wall,
                    [[n, round(ms, 3)]
                     for n, ms in zip(P.INFER_STAGES, stages)])
        return True

    def process_batch(self, idxs: list[int]) -> int:
        """Service up to batch_cap waiting keys as ONE batched decode.

        The reference is strictly serial — one llama.cpp context per
        request (splainference.cpp:414-448, 306-365).  Here the decoder
        left-pads every prompt into one bucket and decodes all rows per
        device step (models/decoder.py generate_batch), so N concurrent
        requests cost ~one request's wall clock.  Per-key protocol is
        IDENTICAL to process_key: label trifecta, rendered-prompt
        overwrite, word-boundary/8-token streaming appends, per-row oom
        truncation, ctime backfill, __debug on failure."""
        import numpy as np

        m, tok = self._model, self._tok
        prepped = []                  # (key, t0, ids)
        done_early = 0
        for idx in idxs:
            prep = self._prepare(idx)
            if prep is None:
                continue
            key, rendered, t0, _stamp = prep   # consumed by _prepare
            ids = self._clip_context(tok.encode(rendered), bucketed=True)
            if not len(ids):
                # an empty prompt must fail alone, not poison the whole
                # batch via prefill_batch's empty-prompt ValueError
                self._finalize(key, t0, 0, False)
                done_early += 1
                continue
            prepped.append((key, t0, np.asarray(ids, np.int32)))
        if not prepped:
            return done_early

        B = len(prepped)
        n_tok = [0] * B
        pending = [b""] * B
        done = [False] * B
        truncated = [False] * B
        vanished = [False] * B
        total = 0
        try:
            fault("completer.generate")
            gen = m.generate_batch([p[2] for p in prepped], self.max_new,
                                   chunk=max(1, self.flush_tokens))
            for col in gen:           # (B,) token column per step
                for r in range(B):
                    if done[r]:
                        continue      # speculative token: discard
                    t = int(col[r])
                    if t == tok.eos_id:
                        done[r] = True
                        continue
                    key = prepped[r][0]
                    piece = tok.token_to_piece(t)
                    pending[r] += piece
                    n_tok[r] += 1
                    boundary = piece.endswith((b" ", b"\n", b"\t"))
                    if boundary or n_tok[r] % self.flush_tokens == 0:
                        res = self._flush(key, pending[r])
                        if res != "ok":
                            truncated[r] = res == "full"
                            vanished[r] = res == "gone"
                            done[r] = True
                        pending[r] = b""
                total += 1
                if self.rebid_tokens and total % self.rebid_tokens == 0:
                    self._rebid()
                if all(done):
                    break
        except Exception as ex:       # model failure must not wedge WAITING
            self._debug(f"batched generation failed: {ex}")
        finally:
            m.reset()
        for r in range(B):
            key, t0, _ = prepped[r]
            if pending[r] and not truncated[r] and not vanished[r]:
                res = self._flush(key, pending[r])
                truncated[r] = res == "full"
                vanished[r] = res == "gone"
            self._finalize(key, t0, n_tok[r], truncated[r], vanished[r])
        return B + done_early

    def _flush(self, key: str, data: bytes) -> str:
        """Append a flushed run; on overflow truncate-and-mark
        (splainference.cpp:336-344).  Returns "ok", "full" (value at
        max_val — an OOM truncation), or "gone" (client deleted the
        key mid-request — stops THIS row without touching its batch,
        and must NOT be reported as a truncation)."""
        st = self.store
        try:
            st.append(key, data)
            return "ok"
        except KeyError:
            return "gone"
        except OSError as ex:
            if ex.errno != errno.EMSGSIZE:
                raise
            try:
                room = st.max_val - 1 - st.value_len(key)
                tail = data[: max(0, room - len(OOM_MARKER))] + OOM_MARKER
                st.append(key, tail[: max(0, room)])
            except (KeyError, OSError):
                pass
            return "full"

    # -- continuous batching (block-paged) --------------------------------

    def _paged_ok(self) -> bool:
        """True when the model can serve the block-paged continuous
        lane (paged_supported) with a usable bucket geometry."""
        m = getattr(self, "_model", None)
        return (m is not None
                and getattr(m, "paged_supported", False)
                and self.paged_batch_cap >= 2
                and self._batched_budget() is not None)

    def _ensure_paged_cache(self):
        if self._paged_cache is None:
            self._paged_cache = self._model.init_paged(
                self.paged_batch_cap, page=self.page_size,
                pool_pages=self.pool_pages, kv_dtype=self.kv_dtype)
            cache = self._paged_cache
            if self._prefix_enabled and hasattr(cache, "map_shared"):
                # (re)bind the radix tree to THIS pool: a rebuilt
                # pool (abort recovery, spec demotion) invalidates
                # every cached page id, so attach() empties the tree
                if self.prefix_cache is None:
                    from .prefix_cache import PrefixCache
                    self.prefix_cache = PrefixCache(
                        self.page_size,
                        max_pages=self._prefix_cache_pages,
                        tenant_quotas=self._prefix_quotas,
                        default_quota=self._prefix_default_quota)
                self.prefix_cache.attach(cache)
                cache.prefix_cache = self.prefix_cache
                if self._tier_pages:
                    self._bind_tier(cache)
        return self._paged_cache

    def _bind_tier(self, cache) -> None:
        """Wire the host-DRAM spill tier under the freshly-attached
        radix tree, then (when persistence is on) load the last good
        snapshot so THIS generation starts warm.  attach() just
        cleared the tree + tier, so a rebuilt pool always reloads
        from the persistent layer rather than trusting stale bids."""
        from .kv_tier import HostTier, TierPersist, tier_geometry
        m = self._model
        if self.kv_tier is None:
            self.kv_tier = HostTier(self._tier_pages)
        self.prefix_cache.bind_tier(
            self.kv_tier,
            export_page=lambda bid, _c=cache, _m=m:
                _m.export_page_bytes(_c, bid),
            import_page=lambda bid, buf, sbuf, _c=cache, _m=m:
                _m.import_page_bytes(_c, bid, buf, sbuf))
        if not self._tier_persist_name:
            return
        geom = tier_geometry(m, cache)
        try:
            if self._tier_store is None:
                self._tier_store = TierPersist(
                    self._tier_persist_name,
                    capacity_pages=self._tier_pages,
                    max_len=m.cfg.max_len,
                    page_bytes=geom["page_bytes"])
            self._tier_restore = self._tier_store.load(
                self.prefix_cache, self.kv_tier, geom)
        except OSError:
            # persistence degraded (segment unopenable) — serve cold
            # with the in-RAM tier only; the reason reaches heartbeat
            self._tier_store = None
            self._tier_restore = (0, "restore_failed")

    def warmup_paged(self) -> None:
        """Pre-compile the continuous lane's whole program set (paged
        prefill buckets + commit scatters + the chunked paged decode
        step) against the SAME pool geometry run_continuous will
        serve with — compile_count stays flat across join/finish/join
        cycles afterwards."""
        if not self._paged_ok():
            return
        cache = self._ensure_paged_cache()
        self._model.warmup_paged(cache,
                                 chunk=max(1, self.flush_tokens),
                                 max_prompt=self._batched_budget())
        if self.kv_tier is not None:
            # spill/readmit ride the handoff gather/scatter programs —
            # warm both so tier traffic never compiles post-warmup
            # (the PR 17 no-recompile gate covers tiered lanes too)
            self._model.warmup_handoff(cache, export=True, adopt=True)

    def run_continuous(self, *, idle_timeout_ms: int = 100,
                       stop_after: float | None = None) -> None:
        """Continuous batched serving over the block-paged KV pool:
        requests join and leave the live batch at chunk boundaries
        (vLLM-style slot scheduling over decoder.PagedKVCache +
        ops/paged_attention).

        batch_cap rows decode together, each over its OWN logical
        positions 0..len-1 in pages of a global pool — there is no
        shared window: a joiner prefills its FULL prompt into freshly
        allocated pages at any time (no join budget, no oversized-
        joiner deferral), a finished row's pages return to the pool
        immediately (no full-batch cache reset), and a row ends at
        ITS window edge, not the batch's.  Admission is gated on free
        pages: a request whose worst case (prompt + max_new rounded
        up to a decode-chunk boundary, capped at the window) exceeds
        the pool stays WAITING and
        join_backpressure counts the deferral — backpressure, never a
        mid-decode strand.  Sharded models serve this lane too (PR 8:
        kv-head-sharded pools + shard_map'd ragged kernel,
        parallel/serve.py), as do quantized pools (--kv-dtype int8
        with per-page scales and dequant in-kernel; int4 packs two
        codes per byte on the same discipline) and speculative
        models (PR 9: the wrapper implements the paged surface —
        drafts verify through the paged kernel's multi-query stack;
        a tripped acceptance floor swaps in the target at the next
        idle point; the lockstep target/draft pools shard on kv
        heads like everything else, so spec-paged composes with
        --tp).  Models whose module cannot thread a mesh
        (paged_supported False) and window-only bucket geometries
        fall back to run()."""
        if not self._paged_ok():
            return self.run(idle_timeout_ms=idle_timeout_ms,
                            stop_after=stop_after)
        import itertools

        import numpy as np

        m = self._model
        st = self.store
        tok_izer = self._tok
        B = self.paged_batch_cap
        cfg = m.cfg
        cache = self._ensure_paged_cache()
        # pod-sharded lane (ShardedCompletionModel): the dispatch gets
        # its own fault site so the chaos matrix can crash/raise inside
        # a sharded decode specifically (operations.md catalog)
        sharded = getattr(m, "mesh", None) is not None
        self._running = True
        deadline = (time.monotonic() + stop_after) if stop_after else None
        last = st.signal_count(self.group)
        next_beat = time.monotonic() + 2.0
        self.publish_stats()          # the attach-complete signal

        rows: list[dict | None] = [None] * B
        # K-deep chunk window (engine/resident.py discipline): up to
        # inflight_depth dispatched chunks fly un-awaited; the token
        # hand-off between chunks stays ON DEVICE (PendingChunk.last),
        # and each entry snapshots (row, serial) of the rows live at
        # its dispatch so a lagged collect can never emit into a row a
        # later admission re-seated (the serial is the guard — pages a
        # stale in-flight chunk touches are either still owned by the
        # finished row or fully overwritten by the joiner's commit
        # scatter, which the device executes in dispatch order).
        window: deque = deque()       # (PendingChunk, [(row, serial)])
        serial = itertools.count()
        carry = None                  # device-side last-token column
        # host-fed fresh tokens: a row whose token was produced on the
        # host since the last dispatch (a joiner's prefill sample)
        # rides this column; -1 = take the device carry
        fresh = np.full((B,), -1, np.int32)
        rebid_due = 0                 # decoded steps since last rebid
        step = max(1, self.flush_tokens)   # decode chunk granularity
        # backpressured requests, idx -> (slot epoch, pages needed):
        # admit() runs every chunk, and re-rendering + re-tokenizing a
        # denied prompt each time would burn host CPU alongside device
        # decode — the memo re-checks only free_pages until the slot
        # is rewritten (epoch moves) or the pool might fit it.  The
        # dict is instance state (self._bp_memo) so the heartbeat
        # publishes its size and _sweep_bp_memo bounds it — under
        # sustained shedding it used to leak one entry per denied key
        bp_memo = self._bp_memo
        bp_memo.clear()

        def worst_len(n_ids: int) -> int:
            """Worst-case cache length for an admitted prompt.  Decode
            appends whole `step`-token chunks (paged_decode_chunk),
            so the final chunk can grow the cache up to step-1 tokens
            PAST the prompt + max_new budget — the admission
            reservation must cover that chunk-boundary ceiling, or a
            fully reserved pool could still raise mid-decode and
            abort every live row.  The first output token comes from
            the prefill sample; the remaining max_new - 1 arrive in
            whole chunks."""
            chunks = (-(-(self.max_new - 1) // step)
                      if self.max_new > 1 else 0)
            return min(n_ids + chunks * step, cfg.max_len)

        def span(row: dict | None, name: str, ms: float) -> None:
            """Accumulate a stage span: the lane histogram always, the
            row's flight-recorder event list when the request was
            client-stamped (LBL_TRACED)."""
            tracer.record(f"infer.{name}", ms)
            if row is not None and row.get("spans") is not None:
                row["spans"].append([name, round(ms, 3)])

        def _lane_ctx() -> dict:
            """The adoption context a disaggregated decode lane's
            _lane_admit hook seats rows through — everything a join
            would have touched, snapshot-fresh (cache is rebound
            after abort_all, so it must be read HERE, not captured
            at loop entry)."""
            return {"rows": rows, "fresh": fresh, "cache": cache,
                    "serial": serial, "step": step,
                    "worst_len": worst_len, "span": span,
                    "finish": finish}

        def admit() -> int:
            """Fill free rows from waiting keys.  EVERY admission is a
            join — the prompt prefills into freshly allocated pages
            right here, whether the batch is empty or mid-decode.
            Reserving prompt + max_new pages up front means decode can
            never exhaust the pool mid-flight; a request the pool
            cannot cover yet stays WAITING (join_backpressure)."""
            free = [r for r in range(B) if rows[r] is None]
            if not free:
                return 0
            if self._lane_admit is not None:
                # disaggregated decode lane (engine/disagg.py):
                # admission is ADOPTION of DECODE_READY handoffs at
                # this chunk edge — the WAITING queue belongs to the
                # prefill lanes, and a joiner's dense prefill never
                # runs here (the whole point of the split)
                return self._lane_admit(free, _lane_ctx())
            self.stripes.refresh()    # admission IS this lane's drain
            waiting = [i for i in st.enumerate_indices(P.LBL_INFER_REQ)
                       if self.stripes.owns(int(i))]
            if not waiting:
                return 0
            # multi-tenant admission before any render: fair order
            # across tenants, expired deadlines rejected fast, backlog
            # past high water shed typed.  Pool-backpressured rows are
            # EXCLUDED from the fairness plan entirely — they are not
            # admissible this cycle, and letting the planner "admit"
            # them would charge their tenant's stride pass every chunk
            # for a row the pool can never seat, pushing that tenant
            # behind peers it was never actually served ahead of.
            # Their deadlines still matter: an expired blocked row is
            # rejected typed right here.
            plannable = []
            now_wall = time.time()
            for w_idx in waiting:
                memo = bp_memo.get(w_idx)
                if memo is not None \
                        and memo[0] == st.epoch_at(w_idx) \
                        and memo[1] > cache.available_pages:
                    tenant, dl = self._qos_meta(w_idx)
                    if dl is not None and dl <= now_wall:
                        if self._terminal_reject(
                                w_idx, P.DEADLINE_EXPIRED_DIAGNOSTIC,
                                "deadline_expired", tenant):
                            bp_memo.pop(w_idx, None)
                    continue
                plannable.append(w_idx)
            n = 0
            traced = tracer.enabled
            pc = getattr(cache, "prefix_cache", None)
            for idx in self._admit_waiting(plannable, len(free)):
                if not free:
                    break
                e = st.epoch_at(idx)
                memo = bp_memo.get(idx)
                if memo is not None and memo[0] == e:
                    if memo[1] > cache.available_pages:
                        continue      # still too big: skip the render
                    del bp_memo[idx]  # pool may fit now: peek fresh
                # peek BEFORE claiming: a backpressured request stays
                # WAITING untouched (a claim would overwrite its slot
                # with the rendered prompt)
                peek = self._read_rendered(idx)
                if peek is None:
                    continue
                ids = self._clip_context(tok_izer.encode(peek[1]),
                                         bucketed=True)
                # radix-tree walk BEFORE the page math: every hit
                # page is a page the pool does not need free — the
                # admission reservation (and the backpressure memo)
                # counts only the UNCACHED suffix, plus one page for
                # the copy-on-write a fully cached prompt's replay
                # append will take
                hit_bids: list[int] = []
                match = 0
                tier_nodes: list = []
                if pc is not None and len(ids):
                    # tier-aware walk: an HBM run, then (optionally) a
                    # run of demoted pages whose bytes live in host
                    # RAM — those cost a readmit (device_put + table
                    # write) instead of a re-prefill, and the pool
                    # pages they land in come out of the same `need`
                    # budget the uncached suffix would have used
                    hit_bids, match, tier_nodes = pc.lookup_tiered(ids)
                    if (match + len(tier_nodes) * cache.page
                            == len(ids) and len(ids) < 2):
                        # a fully-covered 1-token prompt would enter
                        # at lengths 0 — the DEAD-row sentinel; serve
                        # it as a miss (page size 1 is a test-only
                        # geometry anyway)
                        hit_bids, match, tier_nodes = [], 0, []
                match_all = match + len(tier_nodes) * cache.page
                full_cover = ((bool(hit_bids) or bool(tier_nodes))
                              and match_all == len(ids))
                reserve = 0
                if len(ids):
                    reserve = min(worst_len(len(ids))
                                  + (step if full_cover else 0),
                                  cfg.max_len)
                    need = (cache.pages_needed(reserve)
                            - len(hit_bids)
                            + (1 if full_cover else 0))
                    # zero-ref hit pages count in available_pages as
                    # reclaimable supply, but map_shared is about to
                    # PIN them — they cannot also feed this row's new
                    # allocations, so subtract them from the supply
                    # side or a warm near-full pool would admit a row
                    # whose ensure() then comes up short
                    pinned = sum(1 for b in hit_bids
                                 if cache.refcounts[b] == 0)
                    if need > cache.available_pages - pinned:
                        self.stats.join_backpressure += 1
                        bp_memo[idx] = (e, need + pinned)
                        self._bound_bp_memo()
                        continue      # pool full: next cycle retries
                tenant, _dl = self._qos_meta(idx)
                prep = self._prepare(idx, peek=peek)
                if prep is None:
                    continue
                key, rendered, t0, stamp = prep
                if not len(ids):
                    self._finalize(key, t0, 0, False)
                    continue
                r = free.pop(0)
                rows[r] = {"key": key, "t0": t0, "n_tok": 0,
                           "pending": b"", "remaining": self.max_new,
                           "stamp": stamp,
                           # deadline retained for the chunk-edge
                           # mid-decode abort (the __dl_ stamp itself
                           # was consumed at the claim)
                           "deadline": _dl, "tenant": tenant,
                           # serial: the lagged-collect guard (a chunk
                           # in flight across this row's re-seat must
                           # never emit into the newcomer); disp_left:
                           # decode steps still dispatchable before
                           # every budgeted token is in flight
                           "serial": next(serial),
                           "disp_left": self.max_new - 1,
                           "spans": ([] if traced and stamp is not None
                                     else None),
                           "wall0": time.perf_counter()}
                ta = time.perf_counter()
                if hit_bids or tier_nodes:
                    # the chaos matrix crashes HERE (mid table-
                    # mapping, after the claim): the restarted lane
                    # rebuilds pool + tree from scratch, so a death
                    # between refcount bumps can strand nothing
                    fault("completer.prefix_map")
                    if hit_bids:
                        # pin the HBM prefix FIRST: readmission
                        # allocations below can trigger reclaim, and
                        # an unpinned zero-ref hit page would be fair
                        # game for the very eviction pass serving it
                        cache.map_shared(r, hit_bids)
                    if tier_nodes:
                        # DRAM hit: readmit demoted pages.  They come
                        # back holding refcount 1; drop each to
                        # zero-ref (tree-retained, off the free list)
                        # then map — map_shared's 0→1 bump re-pins
                        # them for this row with the tree reference
                        # accounted exactly once.  A partial
                        # readmission (pool pressure, injected fault)
                        # just shortens the hit — the rest re-prefills
                        tier_bids = pc.readmit(tier_nodes, cache)
                        for b in tier_bids:
                            cache._decref(b)
                        if tier_bids:
                            cache.map_shared(r, tier_bids)
                        hit_bids = hit_bids + tier_bids
                        match += len(tier_bids) * cache.page
                        if len(tier_bids) < len(tier_nodes):
                            full_cover = False
                    if not hit_bids:
                        pc.note_miss()   # every readmit failed
                    else:
                        cache.lengths[r] = (len(ids) - 1 if full_cover
                                            else match)
                        # hit/LRU recorded only now — a denied or
                        # raced admission must not inflate the hit
                        # rate the runbook triages on
                        pc.commit_hit(ids, match)
                        pc.stats.bytes_saved += \
                            match * cache.kv_bytes_per_token()
                        if tenant:
                            self.tenants.bump(tenant,
                                              "prefix_hit_pages",
                                              len(hit_bids))
                elif pc is not None and len(ids):
                    pc.note_miss()
                # the uncached tail AFTER tier readmission: a partial
                # readmit lengthens the suffix the prefill must cover
                suffix = ids[match:]
                if not cache.ensure(r, reserve):
                    # defensive: the pinned-aware gate above makes
                    # this unreachable, but a seated row WITHOUT its
                    # reservation would strand mid-decode and abort
                    # the whole batch — re-queue it instead
                    cache.free_row(r)
                    rows[r] = None
                    free.insert(0, r)
                    self._live_spans.pop(key, None)
                    self.stats.join_backpressure += 1
                    self._requeue_failed([idx])
                    continue
                if traced and hit_bids:
                    span(rows[r], "prefix_hit",
                         (time.perf_counter() - ta) * 1e3)
                if getattr(cache, "quantized", False) and suffix:
                    # the quantized append/commit path: the commit
                    # scatter about to run quantizes the prompt's K/V
                    # into int8 pages (per-page scales) — the chaos
                    # matrix crashes HERE to prove a mid-quantized-
                    # commit death restarts clean with no poisoned
                    # pages (tests/chaos_child.py completer_quant)
                    fault("completer.kv_quant_commit")
                if suffix:
                    ta = time.perf_counter()
                    if hit_bids:
                        # uncached tail only, attending the mapped
                        # prefix through the ragged paged kernel
                        logits = m.paged_append_prefill(
                            cache, np.asarray(suffix, np.int32), r)
                    else:
                        logits = m.paged_prefill_row(
                            cache, np.asarray(ids, np.int32), r)
                    tb = time.perf_counter()
                    if pc is not None:
                        # freshly committed full prompt pages join
                        # the tree NOW, donor still live — the next
                        # identical admission maps them even while
                        # this row decodes
                        ins = pc.insert(ids, cache, r, tenant)
                        if ins and tenant:
                            self.tenants.bump(
                                tenant, "prefix_cached_pages", ins)
                    # splint: ignore[SPL201] reason=the documented host "sample" stage (CONT_INFER_STAGES): one scalar draw per JOIN so the row's first token emits before the next chunk, not per decode step
                    t = int(m.sample(logits))
                    if traced:
                        tc = time.perf_counter()
                        span(rows[r], "join", (tb - ta) * 1e3)
                        span(rows[r], "sample", (tc - tb) * 1e3)
                    emit(r, t)
                    if rows[r] is not None:
                        fresh[r] = t  # host-side token: next dispatch
                else:                 # reads it over the device carry
                    # FULLY cached prompt: no prefill at all.  The
                    # row enters at lengths = P-1 and the next decode
                    # chunk replays the last prompt token into the
                    # shared tail page's private copy; the chunk's
                    # first sampled column is the row's first output
                    # token, so the full budget stays dispatchable.
                    # The COW runs EAGERLY here — the admission need
                    # counted that page, and deferring the copy to
                    # dispatch would let a later admission consume it
                    # and strand this row mid-decode.
                    m._cow_fixups(cache)
                    rows[r]["disp_left"] = self.max_new
                    fresh[r] = int(ids[-1])
                n += 1
            return n

        def emit(r: int, t: int) -> None:
            """One sampled token for row r: eos / flush / budget."""
            row = rows[r]
            if t == tok_izer.eos_id:
                finish(r)
                return
            row["pending"] += tok_izer.token_to_piece(t)
            row["n_tok"] += 1
            row["remaining"] -= 1
            boundary = row["pending"].endswith((b" ", b"\n", b"\t"))
            if boundary or row["n_tok"] % self.flush_tokens == 0:
                tf = time.perf_counter()
                res = self._flush(row["key"], row["pending"])
                if tracer.enabled:
                    span(row, "flush",
                         (time.perf_counter() - tf) * 1e3)
                row["pending"] = b""
                if res != "ok":
                    finish(r, truncated=res == "full",
                           vanished=res == "gone")
                    return
            if row["remaining"] <= 0:
                finish(r)

        def finish(r: int, truncated: bool = False,
                   vanished: bool = False) -> None:
            row = rows[r]
            if row["pending"] and not truncated and not vanished:
                res = self._flush(row["key"], row["pending"])
                truncated = res == "full"
                vanished = res == "gone"
            stages = None
            if row.get("spans"):
                stages = {}
                for name, ms in row["spans"]:
                    stages[name] = stages.get(name, 0.0) + ms
            self._finalize(row["key"], row["t0"], row["n_tok"],
                           truncated, vanished, stages=stages)
            if row.get("stamp") is not None \
                    and row.get("spans") is not None:
                tid, ts = row["stamp"]
                wall = ((time.time() - ts) * 1e3 if ts > 0 else
                        (time.perf_counter() - row["wall0"]) * 1e3)
                self.recorder.record(tid, row["key"], wall,
                                     row["spans"])
            self._lane_row_done(row)  # decode lane: retire the
            cache.free_row(r)         # handoff record + wire pages
            rows[r] = None            # pages back to the pool NOW
            fresh[r] = -1

        def kill_expired() -> int:
            """Mid-decode deadline aborts (PR 10's standing debt):
            at each chunk edge, a live row whose deadline passed is
            retired with the typed DEADLINE_EXPIRED record, its pages
            freed immediately (refcount-aware — shared prefix pages
            just drop one reference), and its batch slot reopened.
            An expired row must stop consuming pool and slots NOW —
            lagged in-flight chunks are serial-guarded, so their
            tokens for the dead row evaporate."""
            now_wall = time.time()
            n = 0
            for r in range(B):
                row = rows[r]
                if row is None or not row.get("deadline") \
                        or row["deadline"] > now_wall:
                    continue
                key = row["key"]
                span_rec = self._live_spans.pop(key, None)
                try:
                    st.label_clear(key, P.LBL_SERVICING
                                   | P.LBL_DECODE_READY)
                    st.set(key, P.DEADLINE_EXPIRED_DIAGNOSTIC)
                    st.label_or(key, P.LBL_READY)
                    st.bump(key)
                except (KeyError, OSError):
                    pass
                self.spans.commit(span_rec, status=P.ERR_DEADLINE)
                self._lane_row_done(row)
                cache.free_row(r)     # pool pages back NOW
                rows[r] = None
                fresh[r] = -1
                self.stats.killed_mid_decode += 1
                self.stats.deadline_expired += 1
                if row.get("tenant"):
                    self.tenants.bump(row["tenant"],
                                      "deadline_expired")
                n += 1
            return n

        def collect(entry) -> None:
            """Resolve one in-flight chunk: force the block (the one
            device->host transfer per chunk) and emit its columns to
            the rows that were live at ITS dispatch — serial-guarded,
            so tokens for a finished-and-re-seated row are discarded,
            never delivered to the newcomer."""
            pend, live = entry
            tc0 = time.perf_counter()
            blk = pend.block()
            # pool-occupancy high-water: chunk edges see the peak
            # (prefills landed, nothing freed yet) — heartbeats alone
            # would miss short bursts
            used = cache.used_pages
            if used > self._pages_used_peak:
                self._pages_used_peak = used
            if tracer.enabled:
                # collect = the host's blocked wait on the chunk; the
                # decode span now measures only the (async) dispatch
                ms = (time.perf_counter() - tc0) * 1e3
                tracer.record("infer.collect", ms)
                for r, ser in live:
                    row = rows[r]
                    if row is not None and row["serial"] == ser \
                            and row.get("spans") is not None:
                        row["spans"].append(["collect", round(ms, 3)])
            for c in range(pend.n):
                for r, ser in live:
                    row = rows[r]
                    if row is not None and row["serial"] == ser:
                        emit(r, int(blk[r, c]))

        def abort_all(reason: str) -> None:
            """Model failure must not wedge WAITING/SERVICING (the
            invariant process_key/process_batch keep): every live row
            finalizes with what it already streamed and the pool
            starts clean."""
            nonlocal cache, carry
            self._debug(f"continuous batch aborted: {reason}")
            # in-flight chunks may be poisoned by the same failure:
            # drop them (rows finalize with what they streamed)
            window.clear()
            carry = None
            fresh[:] = -1
            for r in range(B):
                if rows[r] is not None:
                    finish(r)
            # the failure may have escaped a DONATING program (commit
            # scatter / decode chunk) after it consumed the device
            # pools but before the reassignment — reusing them would
            # raise "buffer donated" on every admission forever.
            # Rebuild the pool outright: the dense path's
            # reset()-then-fresh-cache recovery, paged edition.
            self._paged_cache = None
            cache = self._ensure_paged_cache()
            bp_memo.clear()

        try:
            while self._running:
                now = time.monotonic()
                if deadline and now > deadline:
                    break
                if now >= next_beat:
                    next_beat = now + 2.0
                    # speculative degradation rides the heartbeat
                    # cadence on this lane (run_once's per-drain hook
                    # never fires here): a tripped floor swaps
                    # self._model to the target NOW, and the lane
                    # adopts it at the next idle point below
                    self._maybe_demote_spec()
                    # same cadence: bound the join-backpressure memo
                    # (evict rewritten / no-longer-waiting slots)
                    self._sweep_bp_memo()
                    self.publish_stats()
                    # warm-layer checkpoint rides the same beat —
                    # dirty-gated, so a quiet tier costs one flag read
                    self._tier_checkpoint()

                try:
                    if all(r is None for r in rows):
                        # nothing live: retire any in-flight chunks
                        # (their rows finished — serial guards drop
                        # every column) and reset the device carry
                        while window:
                            collect(window.popleft())
                        carry = None
                        if self._model is not m:
                            # demotion decided mid-run: adopt the
                            # target model at this idle point (no live
                            # rows, no in-flight chunks — the paired
                            # spec pools retire with their wrapper and
                            # a fresh pool serves the plain model)
                            m = self._model
                            sharded = getattr(m, "mesh",
                                              None) is not None
                            self._paged_cache = None
                            cache = self._ensure_paged_cache()
                            bp_memo.clear()
                            self._debug(
                                "continuous lane adopted the demoted "
                                "(plain) model")
                        if admit() == 0:
                            if self.replica \
                                    and self.stripes.poll_retired():
                                # scale-down drain: stripes closed,
                                # nothing live, window drained — exit
                                # cleanly and let the supervisor reap
                                self._debug(
                                    "replica destriped — retiring")
                                break
                            got = st.signal_wait(
                                self.group, last,
                                timeout_ms=idle_timeout_ms)
                            if got is not None:
                                last = got
                                self.stats.wakes += 1
                        continue

                    if any(r is None for r in rows):
                        admit()       # joiners enter at ANY time —
                        # even with chunks in flight: the serial guard
                        # keeps lagged collects out of re-seated rows

                    kill_expired()    # chunk-edge deadline aborts

                    # per-row edges: a row without window room for the
                    # next chunk, or whose whole token budget is
                    # already in flight, must not be dispatched again.
                    # Its final tokens are still in the window —
                    # collect oldest-first until the edge rows have
                    # finished (budget-exhausted rows self-finish the
                    # moment their last tokens emit, so the common
                    # end-of-request edge drains only the entries that
                    # carry those tokens, preserving the overlap for
                    # the rest of the batch), then force any survivor
                    # (a true window-edge row) closed
                    edge = [r for r in range(B) if rows[r] is not None
                            and (int(cache.lengths[r]) + step
                                 > cfg.max_len
                                 or rows[r]["disp_left"] <= 0)]
                    if edge:
                        while window and any(rows[r] is not None
                                             for r in edge):
                            collect(window.popleft())
                        for r in edge:
                            if rows[r] is not None:
                                finish(r)
                    if all(r is None for r in rows):
                        continue

                    td = time.perf_counter()
                    if sharded:
                        fault("completer.sharded_dispatch")
                    pend = m.paged_decode_chunk_async(
                        cache, fresh, step, carry=carry)
                    live = [(r, rows[r]["serial"]) for r in range(B)
                            if rows[r] is not None]
                    if tracer.enabled:
                        # decode = the async dispatch (host-side);
                        # the blocked wait surfaces as the collect
                        # span when the window forces the chunk.  One
                        # chunk = one histogram sample, whatever the
                        # occupancy — per-row recording would make
                        # decode quantiles occupancy-weighted, unlike
                        # every other stage; traced rows still each
                        # get the shared span in their event list
                        ms = (time.perf_counter() - td) * 1e3
                        tracer.record("infer.decode", ms)
                        for r, _ in live:
                            if rows[r].get("spans") is not None:
                                rows[r]["spans"].append(
                                    ["decode", round(ms, 3)])
                    carry = pend.last
                    fresh[:] = -1
                    for r, _ in live:
                        rows[r]["disp_left"] -= step
                    window.append((pend, live))
                    self.stats.inflight_peak = max(
                        self.stats.inflight_peak, len(window))
                    rebid_due += step
                    if self.rebid_tokens and rebid_due >= self.rebid_tokens:
                        rebid_due = 0
                        self._rebid()
                    # K-deep window: collect the oldest chunk only
                    # once inflight_depth are un-awaited — its emit/
                    # flush host work overlaps the newest chunk's
                    # device compute, so the per-chunk dispatch floor
                    # amortizes instead of serializing
                    while len(window) >= self.inflight_depth:
                        collect(window.popleft())
                except Exception as ex:
                    abort_all(str(ex))
        finally:
            # stop()/stop_after mid-batch: never strand keys in
            # SERVICING; the pool is reusable for the next run.
            # In-flight tokens are delivered first — a stopped stream
            # keeps everything that was already decoded.
            try:
                while window:
                    collect(window.popleft())
            except Exception:
                pass              # poisoned futures: keep what landed
            for r in range(B):
                if rows[r] is not None:
                    finish(r)
            cache.reset()
            if self.prefix_cache is not None:
                # a stopped lane returns the WHOLE pool: cached pages
                # are a warm-serving optimization, not a shutdown
                # liability (the zero-leaked-pages contract).  With
                # the tier bound, every reclaimed page DEMOTES to
                # host RAM first — this is demote-on-retire, and the
                # forced checkpoint below persists the full warm set
                # so the replacement generation attaches warm
                self.prefix_cache.reclaim(cache.n_blocks)
            self._tier_checkpoint(force=True)

    def _tier_checkpoint(self, force: bool = False) -> None:
        """Snapshot radix index + host-tier pages into the persistent
        segment (kv_tier.TierPersist.save: payload under the NEW
        epoch first, index record last, old epoch swept after — a
        torn write leaves the previous snapshot authoritative).
        Replica 0 owns the writes; peers only load.  Beat-cadence
        calls are dirty-gated and rate-limited; force is the retire
        path, where the warm set must land before the process exits."""
        if (self._tier_store is None or self.kv_tier is None
                or self.prefix_cache is None or self.replica != 0):
            return
        now = time.monotonic()
        if not force and (not self.kv_tier.dirty
                          or now - self._tier_last_save < 5.0):
            return
        self._tier_last_save = now
        from .kv_tier import tier_geometry
        try:
            self._tier_store.save(
                self.prefix_cache, self.kv_tier,
                tier_geometry(self._model, self._paged_cache))
        except Exception as ex:
            self._debug(f"tier checkpoint failed: {ex}")

    # -- drain loop --------------------------------------------------------

    def run_once(self) -> int:
        """Enumerate waiting keys and service them (cold-start drain and
        per-wake drain are the same sweep, splainference.cpp:541-551).
        With a model backend, waiting keys are served in batches of
        batch_cap through one left-padded decode each; a custom
        generate_fn serves serially (its contract is one prompt)."""
        st = self.store
        self.stripes.refresh()        # a re-stripe lands HERE, at the
        idxs = [i for i in st.enumerate_indices(P.LBL_INFER_REQ)
                if self.stripes.owns(int(i))]   # drain boundary
        if not idxs:
            self._had_deferred = False    # nothing waiting: the
            return 0                      # redrain loop must end
        # multi-tenant admission: fair order across tenants, expired
        # deadlines rejected fast, backlog past high water shed with
        # the typed overloaded record.  With a high-water mark set,
        # one drain also bounds its own work to the mark (deferred
        # rows stay WAITING; run()'s work-conserving re-drain takes
        # them next, in fair slices)
        cap = (len(idxs) if self.qos.high_water is None
               else min(len(idxs), max(1, self.qos.high_water)))
        idxs = self._admit_waiting(idxs, cap)
        if not idxs:
            return 0
        if self._bid >= 0:
            try:
                st.shard_rebid(self._bid)
                st.madvise(self._bid, N.ADV_WILLNEED, timeout_ms=0)
            except OSError:
                pass
        n = 0
        batched = getattr(self, "_model", None) is not None \
            and self.generate_fn == self._model_generate \
            and self.batch_cap > 1 \
            and hasattr(self._model, "prefill_batch") \
            and self._batched_budget() is not None
        # per-key/per-batch exception firewall: generation failures are
        # already contained inside process_key/process_batch, so
        # anything raising through is a protocol/store-level surprise —
        # it must cost ITS keys (any left SERVICING are flipped back to
        # WAITING for the next sweep), never the drain's siblings or
        # the run loop itself
        if batched:
            for lo in range(0, len(idxs), self.batch_cap):
                batch = idxs[lo: lo + self.batch_cap]
                try:
                    n += self.process_batch(batch)
                except Exception as ex:
                    self.stats.faults += 1
                    self._debug(f"batch drain failed: {ex}")
                    self._requeue_failed(batch)
        else:
            for idx in idxs:
                self._rebid()
                try:
                    if self.process_key(idx):
                        n += 1
                except Exception as ex:
                    self.stats.faults += 1
                    self._debug(f"request at slot {idx} failed: {ex}")
                    self._requeue_failed([idx])
        if n:
            self._maybe_demote_spec()
        self.spans.flush()            # oneshot drains land their
        return n                      # spans; run() uses heartbeats

    # -- speculative degradation ------------------------------------------

    def _spec_acceptance(self) -> float | None:
        """The live speculative acceptance rate, or None when the
        model isn't speculative (including after a demotion — the
        rolling rate that triggered it survives in
        _spec_acceptance_rolling for the heartbeat)."""
        m = getattr(self, "_model", None)
        if m is None or not hasattr(m, "acceptance_rate"):
            return None
        try:
            return float(m.acceptance_rate)
        except Exception:
            return None

    def _maybe_demote_spec(self) -> None:
        """Speculative decode graceful degradation: r05 measured 6.0
        tok/s at acceptance=0.05 — a draft that the target rejects is
        strictly WORSE than plain decode (every rejected proposal cost
        a draft forward and bought nothing).  Track a rolling
        acceptance over the recent drains; when it stays under
        spec_min_acceptance with enough proposals behind it, swap the
        model for its own target and decode plain for the rest of the
        run (spec_demotions counts it; 0 disables the floor)."""
        m = getattr(self, "_model", None)
        if (m is None or self.spec_min_acceptance <= 0
                or not hasattr(m, "acceptance_rate")
                or not hasattr(m, "target")):
            return
        if not self._spec_hist:
            self._spec_hist.append((0, 0))
        self._spec_hist.append((m.stats_proposed, m.stats_accepted))
        if len(self._spec_hist) > 8:
            self._spec_hist.pop(0)
        p0, a0 = self._spec_hist[0]
        dp = m.stats_proposed - p0
        da = m.stats_accepted - a0
        if dp < 32:
            return                    # not enough evidence yet
        rate = da / dp
        self._spec_acceptance_rolling = rate
        if rate < self.spec_min_acceptance:
            self.stats.spec_demotions += 1
            self._debug(
                f"speculative acceptance {rate:.3f} < floor "
                f"{self.spec_min_acceptance}: demoting to plain "
                "decode (target model) for the rest of the run")
            self._model = m.target

    def _pool_shard_occupancy(self, tp: int) -> dict:
        """Per-tp-shard view of the paged pool, MEASURED from the
        placed device buffers (not assumed from the host scheduler):
        each key is the tp position a shard's kv-head slice covers,
        `shard_mb` its actual on-device pool bytes (k+v, all layers).
        Page counts are host-global (every shard backs every page at
        1/tp of its bytes) — the bytes are the placement signal: a
        broken placement collapses the key set (a replicated pool
        covers the full kv-head range -> one key) or inflates
        shard_mb, so the dashboard shows it instead of rendering a
        fabricated uniform number."""
        cache = self._paged_cache
        out: dict = {}
        try:
            arr = cache.k_pools[0]
            kh = arr.shape[1]
            per_shard = max(1, kh // tp)
            layers = len(cache.k_pools)

            def positions(a) -> dict[str, int]:
                seen: dict[str, int] = {}
                for sh in a.addressable_shards:
                    sl = (sh.index[1] if len(sh.index) > 1
                          else slice(None))
                    start = sl.start or 0
                    pos = str(start // per_shard)
                    # replicas (the dp axis) carry identical bytes:
                    # keep one measurement per tp position
                    seen.setdefault(pos, sh.data.nbytes)
                return seen

            seen = positions(arr)
            sseen: dict[str, int] = {}
            if getattr(cache, "quantized", False):
                # int8 pools: the per-page scales shard on the same
                # kv-head axis — their bytes belong to the shard too
                sseen = positions(cache.k_scales[0])
            for pos, nbytes in sorted(seen.items()):
                out[pos] = {
                    "free": cache.free_pages,
                    "used": cache.used_pages,
                    "shard_mb": round(
                        (nbytes + sseen.get(pos, 0)) * 2 * layers
                        / 1e6, 3),
                }
        except Exception:
            return {}            # obs must never take the lane down
        return out

    def publish_stats(self) -> None:
        """Heartbeat: JSON stats snapshot into the debug-labeled
        __completer_stats key (the structured counterpart of the
        reference's __debug chatter; sidecar group-63 watch surfaces
        it).  SPTPU_TRACE=1 adds histogram-sourced INFER_STAGES
        quantiles, recorder accounting, and the slow log."""
        self.spans.flush()            # heartbeat cadence, off the
        payload = dataclasses.asdict(self.stats)      # wake path
        payload["spans_obs"] = self.spans.counters()
        payload["generation"] = self.generation
        if self.replica or self.stripes.epoch:
            payload["replica"] = self.replica
            payload["stripe"] = self.stripes.snapshot()
        if not self.stats.killed_mid_decode \
                and self._paged_cache is None:
            payload.pop("killed_mid_decode", None)  # dense lane:
                                                    # dead gauge
        # decode-overlap gauge: inflight_peak pinned here means the
        # chunk window saturates (sptpu_completer_inflight_depth)
        payload["inflight_depth"] = self.inflight_depth
        # join-backpressure memo occupancy: growth here with flat
        # admissions means denied keys are piling up (the sweep
        # bounds it, but the gauge shows the pressure)
        payload["bp_memo"] = len(self._bp_memo)
        if self.qos.high_water is not None:
            payload["qos"] = {
                "queue_high_water": self.qos.high_water,
                "retry_after_ms": self.qos.retry_after_ms}
        tenants = self.tenants.snapshot()
        if tenants:
            # per-tenant admitted/shed/deadline_expired/served_tokens
            # — `spt metrics` renders one labeled series per tenant
            payload["tenants"] = tenants
        prune_idle_counters(
            payload, bool(self.qos.high_water is not None or tenants))
        if not self._bp_memo and self._paged_cache is None:
            payload.pop("bp_memo", None)  # dense lane: dead gauge
        acc = self._spec_acceptance()
        if acc is not None:
            # sptpu_completer_spec_acceptance in `spt metrics`
            payload["spec_acceptance"] = round(acc, 4)
        elif self._spec_acceptance_rolling is not None:
            # demoted: keep the rolling rate that tripped the floor
            payload["spec_acceptance"] = round(
                self._spec_acceptance_rolling, 4)
        mesh = getattr(getattr(self, "_model", None), "mesh", None)
        if mesh is not None:
            # pod-sharded lane: the tensor-parallel degree rides the
            # heartbeat (sptpu_completer_tp) so dashboards can tell a
            # sharded daemon from a single-chip one at a glance
            payload["tp"] = int(mesh.shape.get("tp", 1))
        m_now = getattr(self, "_model", None)
        if hasattr(m_now, "stats_proposed"):
            # speculative draft/verify token counters
            # (sptpu_completer_spec_* in `spt metrics`): drafted =
            # proposals the draft generated, verified = positions the
            # target scored, accepted = proposals the target kept
            payload["spec_draft_tokens"] = int(m_now.stats_proposed)
            payload["spec_accepted_tokens"] = int(m_now.stats_accepted)
            payload["spec_verified_tokens"] = int(
                getattr(m_now, "stats_verified", 0))
        if self._paged_cache is not None:
            # sptpu_completer_pages_{free,used} pool gauges
            payload["pages_free"] = self._paged_cache.free_pages
            payload["pages_used"] = self._paged_cache.used_pages
            payload["live_tokens"] = self._paged_cache.live_tokens()
            if self._paged_cache.used_pages > self._pages_used_peak:
                self._pages_used_peak = self._paged_cache.used_pages
            payload["pages_used_peak"] = self._pages_used_peak
        pc = self.prefix_cache
        if pc is not None:
            # prefix-cache gauges (sptpu_completer_prefix_* in `spt
            # metrics`; the telemetry lane rings prefix_hits and
            # prefix_shared_pages, `spt top` sparklines them)
            s = pc.stats
            payload["prefix_hits"] = s.hits
            payload["prefix_misses"] = s.misses
            payload["prefix_hit_tokens"] = s.hit_tokens
            payload["prefix_evictions"] = s.evictions
            payload["prefix_shared_pages"] = pc.shared_pages()
            payload["prefix_evictable"] = pc.evictable_count()
            payload["prefix_cow_copies"] = s.cow_copies
            payload["prefix_bytes_saved"] = s.bytes_saved
            for t, pages in pc.tenant_pages().items():
                # per-tenant cache residency beside the QoS ledger
                # counters — the quota-pressure incident view.
                # Untagged traffic (tenant 0) stays out: the tenants
                # section is for tagged deployments (its residency is
                # already prefix_shared_pages), and the convention is
                # that untagged traffic never creates the section
                if t:
                    tenants.setdefault(
                        str(t), {})["prefix_pages"] = pages
            if tenants and "tenants" not in payload:
                payload["tenants"] = tenants
        if self.kv_tier is not None:
            # tiered-KV gauges (sptpu_completer_tier_* in `spt
            # metrics`): occupancy the autoscaler weighs against HBM
            # pages, readmit-rate the runbook triages warm serving
            # by, and the restore verdict (`tier_restored` pages +
            # typed `tier_restore_reason` on a cold fallback) that
            # tells an operator whether a restart attached warm
            tier = self.kv_tier
            payload["tier_pages"] = len(tier)
            payload["tier_mb"] = round(tier.bytes_held() / 2**20, 3)
            payload["tier_spills"] = tier.spills
            payload["tier_spill_failures"] = tier.spill_failures
            payload["tier_demotions"] = tier.demotions
            payload["tier_readmits"] = tier.readmits
            payload["tier_readmit_failures"] = tier.readmit_failures
            payload["tier_capacity_drops"] = tier.capacity_drops
            payload["tier_restored"] = self._tier_restore[0]
            if self._tier_restore[1] not in ("", "off"):
                payload["tier_restore_reason"] = self._tier_restore[1]
            if pc is not None:
                payload["tier_demoted"] = pc.demoted_pages()
            if self._tier_store is not None:
                payload["tier_snapshot_epoch"] = \
                    self._tier_store.epoch
        if self._paged_cache is not None:
            # the pool's storage dtype + bytes MEASURED from the
            # placed device buffers (values + scales): `spt metrics`
            # renders sptpu_completer_kv_pool_info{kv_dtype=...} and
            # sptpu_completer_pool_mb — the honest int8-halves-bytes
            # evidence, not a shape*itemsize estimate
            kvd = getattr(self._paged_cache, "kv_dtype", None)
            if kvd:
                payload["kv_dtype"] = kvd
            try:
                payload["pool_mb"] = self._paged_cache.device_mb()
                if payload["pool_mb"] > self._pool_mb_peak:
                    self._pool_mb_peak = payload["pool_mb"]
                # HBM high-water across pool swaps (abort recovery
                # re-allocates; a restart resets with the generation)
                payload["pool_mb_peak"] = round(self._pool_mb_peak, 3)
            except Exception:
                pass
            if mesh is not None and int(mesh.shape.get("tp", 1)) > 1:
                shards = self._pool_shard_occupancy(
                    int(mesh.shape["tp"]))
                if shards:
                    payload["pages_shard"] = shards
        if faults.armed():
            payload["faults"] = faults.stats()
        payload["compile_events"] = DEVTIME.compile_events(self.LANE)
        devtime = DEVTIME.heartbeat_section(self.LANE)
        if self.LANE != "completer":
            # split lanes: the trunk + sampler programs register under
            # the canonical "completer" devtime lane — their compiles
            # and quantiles belong to this daemon's heartbeat too
            payload["compile_events"] += \
                DEVTIME.compile_events("completer")
            devtime.update(DEVTIME.heartbeat_section("completer"))
        if devtime:
            payload["devtime"] = devtime
        self._lane_payload(payload)
        DEVTIME.flush(self.store)
        if tracer.enabled:
            P.attach_trace_sections(payload, tracer, self.recorder,
                                    "infer.")
        P.publish_heartbeat(self.store, self._hb_key, payload)
        if tracer.enabled:
            self._trace_published = P.maybe_publish_trace_ring(
                self.store, self._trace_key, self.recorder,
                self._trace_published)

    def run(self, *, idle_timeout_ms: int = 100,
            stop_after: float | None = None) -> None:
        self._running = True
        last = self.store.signal_count(self.group)
        deadline = (time.monotonic() + stop_after) if stop_after else None
        next_sweep = time.monotonic() + 2.0
        self.publish_stats()          # the attach-complete signal
        self.run_once()               # cold start
        while self._running:
            got = self.store.signal_wait(self.group, last,
                                         timeout_ms=idle_timeout_ms)
            now = time.monotonic()
            # heartbeat cadence is independent of the wake path — a
            # daemon at full load must still look alive to watchers
            do_sweep = now >= next_sweep
            if do_sweep:
                next_sweep = now + 2.0
            # loop-level firewall (run_once already contains per-key
            # failures; this catches gather/store-level surprises)
            try:
                if got is not None:
                    last = got
                    self.stats.wakes += 1
                    self.run_once()
                    # work-conserving under a high-water drain bound:
                    # deferred WAITING rows re-drain immediately in
                    # fair slices instead of waiting out the sweep
                    redrains = 0
                    while self._had_deferred and self._running \
                            and redrains < 256:
                        redrains += 1
                        self.run_once()
                elif do_sweep:
                    self.run_once()
                if do_sweep:
                    self._sweep_bp_memo()
                    self.publish_stats()
                    if self.replica and self.stripes.poll_retired():
                        # scale-down drain: the drains above finished
                        # in-flight work; exit and let the supervisor
                        # reap us
                        log.info("replica %d destriped — retiring",
                                 self.replica)
                        break
            except Exception as ex:
                self.stats.faults += 1
                log.exception("run loop cycle failed; continuing")
                self._debug(f"run loop cycle failed: {ex}")
            if deadline and now > deadline:
                break

    def stop(self) -> None:
        self._running = False


def main(argv: list[str] | None = None) -> int:
    """CLI entry: python -m libsplinter_tpu.engine.completer --store NAME"""
    import argparse

    ap = argparse.ArgumentParser(
        description="splinter-tpu completion daemon (streaming JAX "
                    "decoder over the store's label protocol)")
    ap.add_argument("--store", required=True)
    ap.add_argument("--persistent", action="store_true")
    ap.add_argument("--oneshot", action="store_true")
    ap.add_argument("--max-new-tokens", type=int, default=256)
    ap.add_argument("--template", default="auto",
                    help="chat template: auto (fingerprint the GGUF's "
                         "tokenizer.chat_template), chatml, llama2, "
                         "llama3, or none (bare system\\n\\nprompt)")
    ap.add_argument("--temp", type=float, default=0.7)
    ap.add_argument("--top-p", type=float, default=0.9)
    ap.add_argument("--idle-timeout-ms", type=int, default=100)
    ap.add_argument("--replica", type=int, default=0,
                    help="striped replica index (elastic lanes): "
                         "drain only the stripes the lane's stripe "
                         "map assigns this replica; heartbeat "
                         "publishes replica-suffixed "
                         "(__completer_stats.rN)")
    ap.add_argument("--weights",
                    help="decoder checkpoint: .safetensors (HF llama "
                         "naming) or .gguf (llama.cpp naming; geometry "
                         "and tokenizer come from the GGUF metadata).  "
                         "The literal value 'int8' is a sentinel: no "
                         "checkpoint, seeded-random weights held "
                         "per-output-channel int8 (shorthand for "
                         "--weights-int8 with no path)")
    ap.add_argument("--n-ctx", type=int, default=None,
                    help="context window / KV-cache length override "
                         "(default: the checkpoint's trained window, or "
                         "2048 for seeded-random weights)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree: shard the decoder "
                         "(params + KV cache — incl. the paged block "
                         "pools with --continuous: kv-head-sharded "
                         "pools, shard_map'd ragged kernel) over a "
                         "tp-axis mesh of this many devices "
                         "(parallel.serve; must divide the model's "
                         "heads and kv_heads)")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel degree for MoE checkpoints: "
                         "shard the stacked expert FFNs over an ep "
                         "mesh axis (must divide the model's "
                         "expert_count; composes with --tp)")
    ap.add_argument("--batch-cap", type=int, default=None,
                    help="serve up to this many waiting keys "
                         "concurrently (1 = serial, the reference's "
                         "cadence).  Default: 32 with --continuous "
                         "(the block-paged pool's HBM scales with "
                         "live tokens, so batch width no longer pays "
                         "for B x max_len padding), 8 otherwise (a "
                         "wider DENSE batch still multiplies "
                         "B x max_len cache HBM)")
    ap.add_argument("--page-size", type=int, default=128,
                    help="KV pool page size in tokens (continuous "
                         "serving; must be a multiple of the 128-"
                         "lane tile on TPU hardware)")
    ap.add_argument("--pool-pages", type=int, default=None,
                    help="total pages in the paged KV pool (default: "
                         "batch-cap full windows — cap it lower to "
                         "spend cache HBM on batch width instead of "
                         "padding; admission backpressures when the "
                         "pool is full)")
    ap.add_argument("--kv-dtype", choices=("bf16", "f32", "int8", "int4"),
                    default=None,
                    help="paged KV pool storage dtype (continuous "
                         "serving; default: the model's native "
                         "activation dtype).  int8 stores the pool "
                         "quantized with per-page per-kv-head scales "
                         "— cache HBM per token halves vs bf16 "
                         "(quarters vs f32), the ragged paged-"
                         "attention kernel dequantizes in register, "
                         "and the freed bytes buy batch width "
                         "(--batch-cap) inside the same --pool-pages "
                         "envelope.  int4 packs two 4-bit codes per "
                         "byte under the same scale discipline — a "
                         "QUARTER of bf16's cache bytes, 4x the "
                         "batch in the same envelope, at a coarser "
                         "(documented) greedy-agreement tolerance")
    ap.add_argument("--inflight-depth", type=int, default=None,
                    help="continuous lane: paged decode chunk "
                         "pipeline depth — dispatch chunk K, collect "
                         "the oldest while the newest computes (the "
                         "inter-chunk token hand-off stays on-"
                         "device), so host emit/admit work overlaps "
                         "device compute.  Default 2; 1 restores the "
                         "collect-every-chunk sync cadence")
    ap.add_argument("--spec-min-acceptance", type=float, default=0.2,
                    help="speculative decoding floor: when the "
                         "rolling draft acceptance stays below this, "
                         "demote to plain decode for the rest of the "
                         "run (0 disables; the completer heartbeat "
                         "publishes sptpu_completer_spec_acceptance)")
    ap.add_argument("--quantized", action="store_true",
                    help="int8 weight residency: keep attention/MLP "
                         "kernels in HBM as Q8_0-geometry int8 + "
                         "per-block scales (models/quant.py; "
                         "dequantizes before the matmul)")
    ap.add_argument("--weights-int8", action="store_true",
                    help="PER-OUTPUT-CHANNEL int8 weight residency "
                         "(models/quant.py ChannelQuantDense): the "
                         "matmul runs on int8-resident kernels with "
                         "f32 accumulation and dequantizes on the MXU "
                         "OUTPUT — one multiply per output column, no "
                         "per-block float weight rebuild between HBM "
                         "and the MXU.  Mutually exclusive with "
                         "--quantized; '--weights int8' is shorthand "
                         "for this with seeded-random weights")
    ap.add_argument("--warmup", action="store_true",
                    help="pre-compile prefill buckets + decode "
                         "programs before serving (first requests "
                         "otherwise pay the compiles; .xla_cache "
                         "persists them across restarts)")
    ap.add_argument("--draft-weights",
                    help="speculative decoding: a small draft .gguf "
                         "(same tokenizer family; geometry from its "
                         "metadata) proposes --gamma tokens per "
                         "target forward (models/speculative.py); "
                         "serial serving only")
    ap.add_argument("--draft-layers", type=int, default=None,
                    help="SELF-DRAFTING speculative decode: draft "
                         "with a truncated view of the target's own "
                         "first N layers (no second checkpoint; the "
                         "param subtree aliases the target's "
                         "weights).  Unlike --draft-weights this "
                         "serves the batched continuous lane too — "
                         "drafts verify through the paged kernel's "
                         "multi-query stack.  ~3/4 of the target's "
                         "depth is a good starting point")
    ap.add_argument("--gamma", type=int, default=4,
                    help="speculative proposal length per verify step")
    ap.add_argument("--continuous", action="store_true",
                    help="continuous batching: requests join/leave the "
                         "live batch at chunk boundaries instead of "
                         "waiting for whole drains (run_continuous)")
    ap.add_argument("--phase", choices=("unified", "prefill", "decode"),
                    default="unified",
                    help="disaggregated serving (engine/disagg.py): "
                         "'prefill' runs only dense bucket prefill and "
                         "hands each committed row off at "
                         "DECODE_READY; 'decode' adopts handoffs at "
                         "chunk edges and runs only ragged paged "
                         "decode — its K-deep window is never stalled "
                         "by a joiner's prefill.  Both imply "
                         "--continuous.  Default: the unified daemon "
                         "that interleaves the two phases")
    ap.add_argument("--no-prefix-cache", action="store_true",
                    help="disable cross-request prefix sharing on "
                         "the continuous lane (default on: shared "
                         "prompt prefixes map refcounted pool pages "
                         "into the joiner's block table instead of "
                         "re-prefilling — engine/prefix_cache.py; "
                         "the A/B knob scripts/prefix_speedup_check "
                         "measures against)")
    ap.add_argument("--prefix-cache-pages", type=int, default=None,
                    help="global cap on pool pages the prefix cache "
                         "may retain (default: unlimited — zero-ref "
                         "cached pages are reclaimed LRU-first "
                         "whenever the pool actually needs them)")
    ap.add_argument("--prefix-quota", default=None,
                    help="per-tenant prefix-cache page quotas, "
                         "TENANT:PAGES[,TENANT:PAGES...] (unlisted "
                         "tenants are unbounded; over-quota inserts "
                         "evict the tenant's own zero-ref pages "
                         "first, then skip)")
    ap.add_argument("--kv-tier-pages", type=int, default=0,
                    help="host-DRAM KV spill tier capacity in pool "
                         "pages (engine/kv_tier.py): evicted zero-ref "
                         "prefix pages demote to host RAM and readmit "
                         "via device_put + block-table write instead "
                         "of a re-prefill (default 0: off)")
    ap.add_argument("--kv-tier-persist", nargs="?", const="auto",
                    default=None,
                    help="checkpoint the radix index + host-tier "
                         "pages into a file-backed persistent store "
                         "segment so restarts and scale-up replicas "
                         "attach WARM (write-record-last, epoch-"
                         "bumped; torn snapshots fall back cold, "
                         "typed in heartbeat).  Optional value names "
                         "the segment; bare flag derives "
                         "<store>-kvtier.  Replica 0 writes, all "
                         "replicas load")
    ap.add_argument("--queue-high-water", type=int, default=None,
                    help="multi-tenant QoS: max waiting backlog — "
                         "overflow is claimed and READY-flipped with "
                         "a typed {\"err\": \"overloaded\", "
                         "\"retry_after_ms\": N} value instead of "
                         "queueing unboundedly (default: never shed)")
    ap.add_argument("--retry-after-ms", type=int, default=None,
                    help="retry hint carried by shed responses")
    ap.add_argument("--tenant-weights", default=None,
                    help="per-tenant fair-share weights, "
                         "TENANT:W[,TENANT:W...] (unlisted weigh 1)")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    if os.environ.get("SPTPU_FORCE_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    from ..utils.jaxplatform import apply_chip_pin, enable_compile_cache
    if os.environ.get("SPTPU_CHIP_PIN"):
        # supervisor lane placement (spt supervise --pin-chips):
        # prefill and decode replicas land on disjoint chips
        apply_chip_pin(os.environ["SPTPU_CHIP_PIN"])
    enable_compile_cache()
    store = Store.open(args.store, persistent=args.persistent)
    from ..models import CompletionModel, DecoderConfig
    tokenizer = None
    template = args.template
    if args.weights == "int8":
        # `--weights int8` sentinel: no checkpoint file — run the
        # seeded-random decoder with per-output-channel int8 weight
        # residency (the bench/docs spelling of --weights-int8)
        args.weights = None
        args.weights_int8 = True
    if args.weights and args.weights.endswith(".gguf"):
        from ..models.gguf import (GgufFile, decoder_config_from_gguf,
                                   load_tokenizer)
        overrides = {"max_len": args.n_ctx} if args.n_ctx else {}
        with GgufFile(args.weights) as gf:   # parse the container once
            cfg = decoder_config_from_gguf(gf, **overrides)
            tokenizer = load_tokenizer(gf)
            if template == "auto":
                # fingerprint the checkpoint's embedded Jinja template
                # (llama.cpp reads the same metadata for its pick)
                template = detect_template(
                    gf.metadata.get("tokenizer.chat_template"))
                log.info("--template auto resolved to %r", template)
    else:
        cfg = DecoderConfig(max_len=args.n_ctx or 2048)
        if args.weights:
            log.warning(
                "--weights %s has no tokenizer metadata; falling back to "
                "the byte-level tokenizer, which will NOT match a real "
                "checkpoint's vocabulary — use the model's .gguf export "
                "for faithful generation", args.weights)
    if template == "auto":
        # no GGUF metadata to fingerprint: the reference's own fallback
        # when llama_chat_apply_template has no template is bare
        # system\n\nprompt concatenation
        template = "none"
        log.info("--template auto with no GGUF metadata: using 'none'")
    if args.quantized and args.weights_int8:
        raise SystemExit(
            "--quantized and --weights-int8 are mutually exclusive: "
            "both claim the attention/MLP kernels (Q8_0 blocks vs "
            "per-output-channel) — pick one weight residency")
    if args.quantized:
        cfg = dataclasses.replace(cfg, quantized=True)
    if args.weights_int8:
        # chaos site: the channel-quantization pass over the loaded
        # checkpoint (CompletionModel.__init__ ->
        # quantize_decoder_params(mode="channel")) — inject here so
        # the supervisor sees the crash BEFORE any program compiles
        fault("completer.weight_quant")
        cfg = dataclasses.replace(cfg, weights_int8=True)
    mesh = None
    if args.tp > 1 or args.ep > 1:
        from ..parallel.mesh import make_mesh
        mesh = make_mesh(tp=args.tp, ep=args.ep)  # dp inferred
        log.info("sharded decode: tp=%d ep=%d", args.tp, args.ep)
    mkw = dict(weights=args.weights, top_p=args.top_p, temp=args.temp)
    from ..models import MoeDecoderConfig, moe_completion_model
    if isinstance(cfg, MoeDecoderConfig):
        # a Mixtral-family GGUF resolves to the MoE config; the same
        # daemon stack serves it (models/moe.py)
        log.info("MoE checkpoint: %d experts, top-%d routing",
                 cfg.n_experts, cfg.top_k)
        model = moe_completion_model(cfg, mesh, **mkw)
    elif mesh is not None:
        from ..parallel import ShardedCompletionModel
        model = ShardedCompletionModel(cfg, mesh, **mkw)
    else:
        model = CompletionModel(cfg, **mkw)
    if args.draft_weights and args.draft_layers:
        raise SystemExit(
            "--draft-weights and --draft-layers are mutually "
            "exclusive: the first drafts with a separate checkpoint "
            "(serial lane only), the second with a truncated view of "
            "the target (continuous lane capable) — pick one")
    if args.draft_weights:
        from ..models import SpeculativeCompletionModel
        if not args.draft_weights.endswith(".gguf"):
            # a safetensors file carries no geometry metadata, and a
            # draft small enough to be useful is never default-sized —
            # guessing would crash deep in the loader
            raise SystemExit(
                "--draft-weights requires a .gguf draft (geometry and "
                "tokenizer come from its metadata); export the draft "
                "via models/gguf_writer.py if needed")
        from ..models.gguf import GgufFile, decoder_config_from_gguf
        with GgufFile(args.draft_weights) as gf:
            dcfg = decoder_config_from_gguf(gf)
        draft = CompletionModel(dcfg, weights=args.draft_weights,
                                top_p=args.top_p, temp=args.temp)
        model = SpeculativeCompletionModel(model, draft,
                                           gamma=args.gamma)
        log.info("speculative decoding: gamma=%d draft=%s",
                 args.gamma, args.draft_weights)
    elif args.draft_layers:
        from ..models import SpeculativeCompletionModel, self_draft_model
        draft = self_draft_model(model, args.draft_layers)
        model = SpeculativeCompletionModel(model, draft,
                                           gamma=args.gamma)
        log.info("self-drafting speculative decode: first %d of %d "
                 "layers, gamma=%d (drafts verify through the paged "
                 "kernel on the continuous lane)",
                 args.draft_layers, cfg.layers, args.gamma)
    cls = Completer
    if args.phase != "unified":
        from .disagg import DecodeLane, PrefillLane
        cls = PrefillLane if args.phase == "prefill" else DecodeLane
    comp = cls(store, model=model, tokenizer=tokenizer,
                     max_new_tokens=args.max_new_tokens,
                     template=template, batch_cap=args.batch_cap,
                     page_size=args.page_size,
                     pool_pages=args.pool_pages,
                     kv_dtype=args.kv_dtype,
                     inflight_depth=args.inflight_depth,
                     spec_min_acceptance=args.spec_min_acceptance,
                     queue_high_water=args.queue_high_water,
                     retry_after_ms=args.retry_after_ms,
                     tenant_weights=parse_tenant_weights(
                         args.tenant_weights),
                     prefix_cache=not args.no_prefix_cache,
                     prefix_cache_pages=args.prefix_cache_pages,
                     prefix_quotas=parse_tenant_quotas(
                         args.prefix_quota),
                     kv_tier_pages=args.kv_tier_pages,
                     kv_tier_persist=(
                         f"{args.store}-kvtier"
                         if args.kv_tier_persist == "auto"
                         else args.kv_tier_persist),
                     replica=args.replica)
    comp.attach()
    continuous = args.continuous or args.phase != "unified"
    if args.warmup:
        t0 = time.monotonic()
        paged = continuous and comp._paged_ok()
        if paged:
            # the continuous lane only ever runs the paged program
            # set (paged prefill buckets + commit scatters + chunked
            # paged decode) — compiling the serial/dense sweep too
            # would roughly double first-boot warmup for programs
            # this lane never executes.  A join/finish/join cycle at
            # serve time must never compile.
            comp.warmup_paged()
        else:
            kw = {}
            if comp.batch_cap > 1 \
                    and hasattr(model, "prefill_batch") \
                    and comp._batched_budget() is not None:
                kw["batch"] = comp.batch_cap   # dense batched shapes
            model.warmup(chunk=comp.flush_tokens, **kw)
        log.info("warmup compiled in %.1fs (.xla_cache persists "
                 "programs across restarts)", time.monotonic() - t0)
    if args.oneshot:
        n = comp.run_once()
        log.info("oneshot serviced %d completions", n)
        return 0
    try:
        if continuous:
            comp.run_continuous(idle_timeout_ms=args.idle_timeout_ms)
        else:
            comp.run(idle_timeout_ms=args.idle_timeout_ms)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
