"""The pipeline lane — server-side scripted RAG chains.

Every multi-stage workload before this daemon chained client-side:
`spt loadgen --scenario rag-churn` pays a client round trip per
ingest -> embed -> top-k -> complete hop, each hop a submit + poll
against a different lane.  The reference's whole identity is the
opposite — a "cooperative userspace hypervisor" running Lua programs
*next to the data* (splinter_cli_cmd_lua.c) — so this lane moves the
orchestration server-side: a request is ONE slot carrying a Lua
script (inline source, or the name of a stored `__script_<name>`
program), executed in a sandboxed runtime whose splinter verbs are
**yielding coroutine awaits**:

  - `splinter.submit_embed(key, text)`, `submit_search(key, k)`,
    `submit_completion(key, prompt)`, `sleep(s)` issue the
    NON-BLOCKING submit (set + QoS stamps + label + bump — the
    engine/client.py wire discipline) and suspend the script's
    coroutine; ONE drain loop multiplexes every in-flight script,
    polling awaited slots and resuming whichever became ready — no
    blocking wait anywhere on the lane's pump path;
  - every verb inherits the REQUEST's tenant id and absolute
    deadline (`stamp_tenant` / `stamp_deadline` ride through), so
    admission, stride fairness, and deadline fast-fail in the
    downstream lanes span the whole chain, not one hop;
  - sandboxing is enforced in the host (scripting/sandbox.py): step
    budget, verb budget, capped coroutines, allocation guard,
    deadline-derived wall clock, no `os`/`io` — a hostile script dies
    with a typed record (`budget_exceeded` / `deadline_expired` /
    `script_error`) while sibling in-flight scripts run unharmed.

Request contract (one slot per request):
  value    JSON {"script": "<lua source>"} or {"name": "<stored>"},
           optional "args": [...] (script `arg` table / varargs),
           optional "deadline": absolute wall-clock ts (the searcher's
           JSON form; the `__dl_<idx>` companion stamp works too)
  labels   LBL_SCRIPT_REQ (+ LBL_WAITING), tenant bits, then bump.

Result contract: JSON in script_result_key(request_slot_index)
(`__pr_<idx>`) — {"ok": true, "ret": [...]} or a typed error record —
then LBL_SCRIPT_REQ + LBL_WAITING clear and the request key bumps.
LBL_SCRIPT_REQ stays SET while a script executes: a lane crash
mid-script leaves the label up, so the restarted daemon's first drain
reclaims and re-runs the request (crash-only recovery — scripts are
re-runnable by contract, like every slot protocol here).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import time

from .. import _native as N
from ..obs.recorder import FlightRecorder
from ..obs.devtime import DEVTIME
from ..obs.spans import SpanWriter, sweep_span_stages
from ..scripting.microlua import LuaCoroutine, LuaError, LuaTable
from ..scripting.sandbox import (KILL_BUDGET, KILL_DEADLINE,
                                 ScriptBudget, compile_chunk,
                                 make_sandboxed_runtime)
from ..store import Store
from ..utils import faults
from ..utils.faults import fault
from ..utils.trace import tracer
from . import protocol as P
from .qos import (AdmissionController, TenantLedger, WaitingRow,
                  parse_tenant_weights, prune_idle_counters)

log = logging.getLogger("libsplinter_tpu.pipeliner")

# orphaned __pr_<idx> result rows older than this are reaped by the
# heartbeat-cadence sweep (the searcher's __sr_ discipline)
RESULT_TTL_S = 120.0

# typed error vocabulary beyond the protocol's overload/deadline pair
ERR_SCRIPT = "script_error"

# async verbs must resolve through the lane's pump loop; everything
# else in the splinter table is a fast host call
ASYNC_VERBS = ("submit_embed", "submit_search", "submit_completion",
               "sleep")


@dataclasses.dataclass
class PipelinerStats:
    wakes: int = 0
    drains: int = 0
    requests: int = 0            # script requests gathered
    parse_errors: int = 0        # malformed request JSON / bad source
    scripts_started: int = 0
    scripts_completed: int = 0   # finished ok (result committed)
    scripts_failed: int = 0      # typed script_error results
    scripts_killed: int = 0      # budget/deadline kills
    killed_budget: int = 0
    killed_deadline: int = 0
    verbs_total: int = 0         # async verb dispatches, all scripts
    raced: int = 0               # slot changed mid-script; not committed
    results_reaped: int = 0      # orphaned __pr_ rows retired
    # -- multi-tenant QoS (engine/qos.py) ----------------------------
    deadline_expired: int = 0    # fast-failed at admission
    shed: int = 0                # typed overloaded + retry_after_ms
    deferred: int = 0            # held for a later drain (fairness)


class _Await:
    """One suspended verb: what the script is waiting for and where.
    The pump loop polls these; `wake_ts` serves the sleep verb."""

    __slots__ = ("kind", "key", "idx", "k", "wake_ts", "t0")

    def __init__(self, kind, key=None, idx=-1, k=0, wake_ts=0.0):
        self.kind = kind
        self.key = key
        self.idx = idx
        self.k = k
        self.wake_ts = wake_ts
        self.t0 = time.perf_counter()


class ScriptRun:
    """One admitted script's runtime state."""

    __slots__ = ("idx", "epoch", "key", "tenant", "deadline", "rt",
                 "co", "await_", "verbs", "verb_counts", "stages",
                 "span", "t_start", "label")

    def __init__(self, idx, epoch, key, tenant, deadline, rt, co,
                 span, label):
        self.idx = idx
        self.epoch = epoch
        self.key = key
        self.tenant = tenant
        self.deadline = deadline
        self.rt = rt
        self.co = co
        self.await_ = None
        self.verbs = 0
        self.verb_counts: dict[str, int] = {}
        self.stages = dict.fromkeys(P.SCRIPT_STAGES, 0.0)
        self.span = span             # obs.spans.PendingSpan | None
        self.t_start = time.perf_counter()
        self.label = label           # "inline" or the stored name

    @property
    def stamp(self):
        """(trace_id, client_wall_ts) | None — the recorder's view."""
        return self.span.stamp if self.span is not None else None


class _Request:
    __slots__ = ("idx", "epoch", "src", "args", "label", "tenant",
                 "deadline", "traced", "fresh")

    def __init__(self, idx, epoch, src, args, label, tenant, deadline,
                 traced):
        self.idx = idx
        self.epoch = epoch
        self.src = src
        self.args = args
        self.label = label
        self.tenant = tenant
        self.deadline = deadline
        self.traced = traced
        self.fresh = True        # first gather (False = deferred memo)


def _lua_to_json(v, depth: int = 0):
    """Script return values -> JSON-able (bounded; a LuaTable renders
    as a list when array-like, else a string-keyed dict)."""
    if depth > 4:
        return "..."
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, LuaTable):
        n = v.length()
        if n and len(v.data) == n:
            return [_lua_to_json(v.get(i + 1), depth + 1)
                    for i in range(min(n, 64))]
        return {str(k): _lua_to_json(val, depth + 1)
                for k, val in list(v.data.items())[:64]}
    return str(v)


class Pipeliner:
    """The daemon object.  Drive it with run() (blocking loop) or
    run_once() (pump to idle — tests and --oneshot).  Deliberately
    jax-free: the lane orchestrates the other three daemons' work, it
    never touches a device itself."""

    def __init__(self, store: Store, *, group: int = P.GROUP_SCRIPT,
                 max_scripts: int = 32,
                 max_steps: int | None = None,
                 max_coroutines: int | None = None,
                 max_sleep_s: float | None = None,
                 max_verbs: int | None = None,
                 queue_high_water: int | None = None,
                 retry_after_ms: int | None = None,
                 tenant_weights: dict[int, float] | None = None,
                 replica: int = 0):
        self.store = store
        self.group = group
        # elastic lanes (protocol.StripeView): replica r gathers only
        # its own slot-index stripe; in-flight scripts keep their
        # request label SET while executing, so closed stripes during
        # a scale-down drain are what keeps a survivor from re-running
        # a retiring replica's live chains
        self.replica = int(replica)
        self.stripes = P.StripeView(store, "pipeliner", self.replica)
        self._hb_key = P.replica_stats_key(P.KEY_SCRIPT_STATS,
                                           self.replica)
        self._trace_key = P.replica_stats_key(P.KEY_SCRIPT_TRACE,
                                              self.replica)
        # max_scripts is the lane's admit cap: the concurrency bound
        # (each in-flight script pins one sandbox + one host
        # coroutine thread) and the fairness granularity in one knob
        self.max_scripts = max(1, max_scripts)
        budget_kw = {}
        if max_steps is not None:
            budget_kw["max_steps"] = max_steps
        if max_coroutines is not None:
            budget_kw["max_coroutines"] = max_coroutines
        if max_sleep_s is not None:
            budget_kw["max_sleep_s"] = max_sleep_s
        if max_verbs is not None:
            budget_kw["max_verbs"] = max_verbs
        self._budget_kw = budget_kw
        self.qos = AdmissionController(
            weights=tenant_weights, high_water=queue_high_water,
            **({"retry_after_ms": retry_after_ms}
               if retry_after_ms is not None else {}))
        self.tenants = TenantLedger()
        self.stats = PipelinerStats()
        self.verb_counts: dict[str, int] = {}
        self.runs: dict[int, ScriptRun] = {}
        # deferred-backlog memo: a row gathered but not admitted keeps
        # its PARSED request here, so later drains neither re-parse
        # its JSON / re-fetch its stored source nor re-count it in
        # the requests/deferred stats (the busy loop re-plans
        # admission every time capacity frees)
        self._parsed: dict[tuple[int, int], _Request] = {}
        self.generation = 0
        self.recorder = FlightRecorder()
        # staged (crash recovery with attempt counts: scripts live
        # whole chains) + eager (the pump is host orchestration, not
        # a device wake path — spans land the moment a script ends)
        self.spans = SpanWriter(store, "pipeliner", staged=True,
                                eager=True)
        self._trace_published = 0
        self._bid = -1
        self._running = False

    # -- wiring ------------------------------------------------------------

    def attach(self) -> None:
        st = self.store
        try:
            self._bid = st.shard_claim(P.SHARD_SCRIPT, N.ADV_WILLNEED,
                                       P.PRIO_SCRIPT, 30_000_000)
        except OSError:
            self._bid = -1
        st.watch_label_register(P.BIT_SCRIPT_REQ, self.group)
        st.bus_attach()   # adopts the bus when a crashed owner
                          # left a dead pid in the header
        self.generation = P.bump_generation(st, self._hb_key)

    # -- request gathering -------------------------------------------------

    def _gather(self) -> list[_Request]:
        st = self.store
        self.stripes.refresh()        # a re-stripe lands HERE, at the
        rows = st.enumerate_indices(P.LBL_SCRIPT_REQ)  # gather boundary
        out: list[_Request] = []
        for idx in rows:
            idx = int(idx)
            if not self.stripes.owns(idx) and idx not in self.runs:
                continue              # a peer replica's stripe (rows
                                      # WE are executing stay ours)
            e = st.epoch_at(idx)
            live = self.runs.get(idx)
            if live is not None:
                if live.epoch == e:
                    continue                  # already executing
                # raced rewrite: the client rewrote the slot while its
                # old script ran — retire the stale run uncommitted,
                # the fresh request is gathered below
                self._retire(live, raced=True)
            labels = st.labels_at(idx)
            if not labels & P.LBL_SCRIPT_REQ:
                continue
            cached = self._parsed.get((idx, e))
            if cached is not None:
                cached.fresh = False
                out.append(cached)
                continue
            try:
                raw = st.get_at(idx)
            except (KeyError, OSError):
                continue
            if st.epoch_at(idx) != e or (e & 1):
                continue                      # torn: next drain
            self.stats.requests += 1
            src = None
            label = "inline"
            try:
                req = json.loads(raw.rstrip(b"\0"))
                if not isinstance(req, dict):
                    raise ValueError("request must be a JSON object")
                if req.get("script"):
                    src = str(req["script"])
                elif req.get("name"):
                    label = str(req["name"])
                    src = self._stored_source(label)
                    if src is None:
                        self._fail(idx, e,
                                   f"unknown stored script {label!r}")
                        continue
                else:
                    raise ValueError("request names no script")
                args = req.get("args") or []
                if not isinstance(args, list):
                    raise ValueError("args must be a list")
                deadline = req.get("deadline")
                deadline = float(deadline) if deadline else None
            except (ValueError, KeyError, TypeError) as ex:
                self._fail(idx, e, f"bad script request: {ex}")
                continue
            if deadline is None and labels & P.LBL_DEADLINE:
                deadline = P.read_deadline(st, idx, epoch=e)
            req = _Request(idx, e, src, args, label,
                           P.read_tenant(labels), deadline,
                           bool(labels & P.LBL_TRACED))
            self._parsed[(idx, e)] = req
            out.append(req)
        # prune memo entries whose row is no longer pending (label
        # cleared by a commit we missed, raced rewrite, key vanished)
        live = {(r.idx, r.epoch) for r in out}
        for k in list(self._parsed):
            if k not in live:
                del self._parsed[k]
        return out

    def _stored_source(self, name: str) -> str | None:
        try:
            raw = self.store.get(P.stored_script_key(name))
        except (KeyError, OSError):
            return None
        return raw.rstrip(b"\0").decode("utf-8", "replace")

    # -- admission (multi-tenant QoS) --------------------------------------

    def _admit(self, reqs: list[_Request]) -> None:
        """The shared admission policy over the gathered backlog:
        capacity is the lane's free concurrency (max_scripts minus
        in-flight), expired deadlines fail fast typed, overflow past
        the high-water mark sheds typed, the rest stay labelled for a
        later drain with stride credit."""
        if not reqs:
            return
        cap = self.max_scripts - len(self.runs)
        plan = self.qos.plan(
            [WaitingRow(r, r.tenant, r.deadline) for r in reqs], cap)
        for row in (*plan.admit, *plan.expired, *plan.shed):
            r = row.item
            if r.traced:
                r.traced = False
                # span begin reads the stamp NON-destructively (it
                # must survive a mid-chain crash so the restarted
                # lane's re-run keeps the chain identity) and stages
                # the pending span; the commit retires both
                span = self.spans.begin(r.idx, r.epoch,
                                        tenant=r.tenant)
            else:
                span = None
            row.span = span       # type: ignore[attr-defined]
        for row in plan.expired:
            r = row.item
            self._parsed.pop((r.idx, r.epoch), None)
            self.stats.deadline_expired += 1
            self.tenants.bump(r.tenant, "deadline_expired")
            P.clear_deadline(self.store, r.idx)
            self._commit(r.idx, r.epoch, {"err": P.ERR_DEADLINE})
            self.spans.commit(getattr(row, "span", None),
                              status=P.ERR_DEADLINE)
        for row in plan.shed:
            r = row.item
            self._parsed.pop((r.idx, r.epoch), None)
            self.stats.shed += 1
            self.tenants.bump(r.tenant, "shed")
            P.clear_deadline(self.store, r.idx)
            self._commit(r.idx, r.epoch,
                         P.overloaded_record(self.qos.retry_after_ms))
            self.spans.commit(getattr(row, "span", None),
                              status=P.ERR_OVERLOADED)
        # deferral counts FIRST sights only: the memo re-offers a
        # deferred row every re-plan, which must not inflate the stat
        self.stats.deferred += sum(
            1 for row in plan.deferred if row.item.fresh)
        for row in plan.admit:
            r = row.item
            self._parsed.pop((r.idx, r.epoch), None)
            if r.tenant or r.deadline is not None:
                self.tenants.bump(r.tenant, "admitted")
            if r.deadline is not None:
                P.clear_deadline(self.store, r.idx)
            self._start(r, getattr(row, "span", None))

    # -- script lifecycle --------------------------------------------------

    def _start(self, req: _Request, span) -> None:
        """Parse stage: build the sandbox, compile the chunk, wrap it
        in the host coroutine, then run its first slice."""
        t0 = time.perf_counter()
        key = self.store.key_at(req.idx)
        if key is None:
            return
        budget = ScriptBudget(deadline_ts=req.deadline,
                              **self._budget_kw)
        try:
            rt = make_sandboxed_runtime(self.store, budget)
            run = ScriptRun(req.idx, req.epoch, key, req.tenant,
                            req.deadline, rt, None, span, req.label)
            self._overlay_verbs(rt, run)
            fn = compile_chunk(rt, req.src, chunk_name=req.label)
            arg = LuaTable({0: req.label})
            for i, a in enumerate(req.args):
                arg.set(i + 1, a)
            rt.globals["arg"] = arg
            run.co = LuaCoroutine(fn, rt)
        except LuaError as ex:
            self._fail(req.idx, req.epoch, f"parse: {ex}")
            self.spans.commit(span, status=ERR_SCRIPT)
            return
        run.stages["parse"] = (time.perf_counter() - t0) * 1e3
        self.stats.scripts_started += 1
        self.runs[req.idx] = run
        self._resume(run, tuple(req.args))

    def _resume(self, run: ScriptRun, values: tuple) -> None:
        """One execution slice: resume the script's coroutine with the
        awaited result and interpret how it came back (suspended on a
        new await, returned, or died).  The fault site here is the
        exec path: a `raise` fails ONE script typed, a `crash` is the
        supervised-restart drill."""
        t0 = time.perf_counter()
        try:
            fault("pipeliner.exec")
            out = run.co.resume(values)
        except Exception as ex:             # injected raise / host bug
            run.stages["exec"] += (time.perf_counter() - t0) * 1e3
            self._finish(run, {"err": ERR_SCRIPT,
                               "detail": f"exec failed: {ex}"})
            return
        run.stages["exec"] += (time.perf_counter() - t0) * 1e3
        if out[0] and run.co.status == "suspended":
            payload = out[1] if len(out) > 1 else None
            if isinstance(payload, _Await):
                run.await_ = payload
                return
            # a stray top-level coroutine.yield is not an await — the
            # script has no resumer but us, so it can only die
            self._finish(run, {"err": ERR_SCRIPT,
                               "detail": "yield outside an async "
                                         "splinter verb"})
            return
        if out[0]:                           # returned cleanly
            ret = [_lua_to_json(v) for v in out[1:]]
            self._finish(run, {"ok": True, "ret": ret})
            return
        self._finish(run, self._error_record(run, out[1]))

    def _error_record(self, run: ScriptRun, payload) -> dict:
        """Classify a script death: the sandbox's typed kills first
        (kill_reason survives the coroutine boundary), then a script
        that error()'d a bare typed string propagates it (the library
        scripts re-raise a downstream verb's typed rejection), else a
        plain script_error."""
        reason = run.rt.kill_reason
        if reason == KILL_BUDGET:
            return {"err": KILL_BUDGET, "detail": str(payload)}
        if reason == KILL_DEADLINE:
            return {"err": P.ERR_DEADLINE, "detail": str(payload)}
        if payload == P.ERR_OVERLOADED:
            return P.overloaded_record(self.qos.retry_after_ms)
        if payload == P.ERR_DEADLINE:
            return {"err": P.ERR_DEADLINE}
        return {"err": ERR_SCRIPT, "detail": str(payload)}

    def _finish(self, run: ScriptRun, rec: dict) -> None:
        """Terminal: account, commit the typed/ok record, retire."""
        err = rec.get("err")
        if err is None:
            self.stats.scripts_completed += 1
        elif err == KILL_BUDGET:
            self.stats.scripts_killed += 1
            self.stats.killed_budget += 1
        elif err == P.ERR_DEADLINE:
            self.stats.scripts_killed += 1
            self.stats.killed_deadline += 1
            self.tenants.bump(run.tenant, "deadline_expired")
        else:
            self.stats.scripts_failed += 1
        t0 = time.perf_counter()
        self._commit(run.idx, run.epoch, rec)
        run.stages["commit"] = (time.perf_counter() - t0) * 1e3
        self.spans.commit(
            run.span, status=err or "ok",
            stages={s: run.stages[s] for s in P.SCRIPT_STAGES},
            extra={"script": run.label, "verbs": run.verbs})
        self._record_trace(run)
        self._retire(run)

    def _retire(self, run: ScriptRun, raced: bool = False) -> None:
        if raced:
            self.stats.raced += 1
        self.runs.pop(run.idx, None)
        try:
            if run.co is not None and run.co.status == "suspended":
                run.co.close()
            run.rt.close()
        except Exception:                    # reclaim must never wedge
            pass

    def _kill(self, run: ScriptRun, reason: str, detail: str) -> None:
        """Kill a SUSPENDED script from the pump loop (deadline passed
        while it waited): typed record out, coroutine unwound."""
        run.rt.kill_reason = run.rt.kill_reason or reason
        rec = ({"err": P.ERR_DEADLINE, "detail": detail}
               if reason == KILL_DEADLINE
               else {"err": KILL_BUDGET, "detail": detail})
        self._finish(run, rec)

    def _fail(self, idx: int, epoch: int, detail: str) -> None:
        self.stats.parse_errors += 1
        self._commit(idx, epoch, {"err": ERR_SCRIPT, "detail": detail})

    # -- the sandboxed verb surface ----------------------------------------

    def _overlay_verbs(self, rt, run: ScriptRun) -> None:
        """Swap the lane's async verbs into the runtime's splinter
        table.  Each verb issues the non-blocking submit with the
        REQUEST's tenant + deadline stamped through, then suspends the
        script's coroutine on an _Await the pump loop resolves."""
        st = self.store
        spl = rt.modules["splinter"]

        def guard(name: str) -> None:
            fault("pipeliner.verb")
            run.verbs += 1
            run.verb_counts[name] = run.verb_counts.get(name, 0) + 1
            self.stats.verbs_total += 1
            self.verb_counts[name] = self.verb_counts.get(name, 0) + 1
            if run.verbs > rt.budget.max_verbs:
                rt.kill(KILL_BUDGET,
                        f"script exceeded its "
                        f"{rt.budget.max_verbs}-verb budget")
            if rt.budget.expired():
                # killed BEFORE dispatching the verb: an expired
                # script must not submit work nobody waits for
                rt.kill(KILL_DEADLINE,
                        f"deadline passed before verb {name!r}")
            if not rt._co_stack or rt._co_stack[-1] is not run.co:
                raise LuaError(f"{name}: async splinter verbs must "
                               f"be called from the script's main "
                               f"body, not a nested coroutine")

        def suspend(aw: _Await):
            got = run.co.yield_((aw,))
            return got if len(got) != 1 else got[0]

        def _stamp(key: str) -> None:
            if run.tenant:
                P.stamp_tenant(st, key, run.tenant)
            if run.deadline is not None:
                P.stamp_deadline(st, key, run.deadline)
            _stamp_trace(key)

        def _stamp_trace(key: str) -> None:
            # trace-context propagation: every verb the script
            # dispatches joins the REQUEST's trace, parented on the
            # script's own span — one trace id spans the whole chain
            if run.span is not None:
                P.stamp_trace(st, key, trace_id=run.span.tid,
                              parent=run.span.span)

        def submit_embed(key, text):
            guard("submit_embed")
            key = str(key)
            st.set(key, str(text))
            # a reused key may still carry CTX_EXCEEDED from a
            # previous over-long text (the client helper's discipline)
            st.label_clear(key, P.LBL_CTX_EXCEEDED)
            _stamp(key)
            st.label_or(key, P.LBL_EMBED_REQ | P.LBL_WAITING)
            st.bump(key)
            return suspend(_Await("embed", key))

        def submit_search(key, k, bloom=0):
            guard("submit_search")
            key = str(key)
            params = {"k": int(k), "bloom": int(bloom or 0)}
            if run.deadline is not None:
                params["deadline"] = round(run.deadline, 6)
            st.set(key, json.dumps(params))
            idx = st.find_index(key)
            if run.tenant:
                P.stamp_tenant(st, key, run.tenant)
            _stamp_trace(key)
            st.label_or(key, P.LBL_SEARCH_REQ | P.LBL_WAITING)
            st.bump(key)
            return suspend(_Await("search", key, idx=idx, k=int(k)))

        def submit_completion(key, prompt):
            guard("submit_completion")
            key = str(key)
            st.set(key, str(prompt))
            st.label_clear(key, P.LBL_READY | P.LBL_SERVICING)
            _stamp(key)
            st.label_or(key, P.LBL_INFER_REQ | P.LBL_WAITING)
            st.bump(key)
            return suspend(_Await("complete", key))

        def sleep(seconds):
            guard("sleep")
            wake = time.time() + rt.budget.clamp_sleep(float(seconds))
            suspend(_Await("sleep", wake_ts=wake))
            return 0

        for name, fn in (("submit_embed", submit_embed),
                         ("submit_search", submit_search),
                         ("submit_completion", submit_completion),
                         ("sleep", sleep)):
            spl.set(name, fn)

    # -- await resolution --------------------------------------------------

    def _poll_await(self, aw: _Await):
        """(ready, result) for one suspended verb.  `result` is what
        the verb returns to the script: True / LuaTable / str on
        success, (None, "<typed err>") on a downstream rejection."""
        st = self.store
        if aw.kind == "sleep":
            return (time.time() >= aw.wake_ts, 0)
        try:
            labels = st.labels(aw.key)
        except KeyError:
            return True, (None, "key vanished mid-request")
        if aw.kind == "embed":
            from .client import PENDING, classify_embed_result
            res = classify_embed_result(st, aw.key, labels)
            if res is PENDING:
                return False, None
            if res is True:
                return True, True
            return True, (None, str(res.get("err")))
        if aw.kind == "search":
            if labels & P.LBL_SEARCH_REQ:
                return False, None
            rec = None
            try:
                raw = st.get(P.search_result_key(aw.idx))
                rec = json.loads(raw.rstrip(b"\0"))
            except (KeyError, OSError, ValueError):
                pass
            try:
                st.unset(P.search_result_key(aw.idx))
            except (KeyError, OSError):
                pass
            if not isinstance(rec, dict):
                return True, (None, "search result lost")
            if rec.get("err"):
                return True, (None, str(rec["err"]))
            return True, LuaTable.from_list(
                [str(k) for k in rec.get("keys", [])])
        # complete
        if not labels & P.LBL_READY:
            return False, None
        try:
            raw = st.get(aw.key)
        except (KeyError, OSError):
            return True, (None, "completion lost")
        rec = P.parse_error_payload(raw)
        if rec is not None:
            return True, (None, str(rec.get("err")))
        return True, raw.rstrip(b"\0").decode("utf-8", "replace")

    # -- result commit -----------------------------------------------------

    def _commit(self, idx: int, epoch: int, rec: dict) -> int:
        """Epoch-gated result commit (the searcher's __sr_ discipline):
        write __pr_<idx>, clear the request labels, bump — only if the
        slot is unchanged since the gather."""
        st = self.store
        if st.epoch_at(idx) != epoch:
            self.stats.raced += 1
            return 0
        key = st.key_at(idx)
        if key is None:
            return 0
        rec = dict(rec)
        rec["e"] = int(epoch)
        rec["ts"] = round(time.time(), 3)
        rkey = P.script_result_key(idx)
        try:
            st.set(rkey, json.dumps(rec))
        except OSError:
            rec.pop("ret", None)
            rec["err"] = rec.get("err", "result too large for store")
            rec["truncated"] = True
            try:
                st.set(rkey, json.dumps(rec))
            except (KeyError, OSError):
                return 0
        except KeyError:
            return 0
        if st.epoch_at(idx) != epoch:
            self.stats.raced += 1
            return 0
        try:
            st.label_or(rkey, P.LBL_READY)
            st.label_clear(key, P.LBL_SCRIPT_REQ | P.LBL_WAITING)
            st.bump(key)
        except (KeyError, OSError):
            return 0
        return 1

    # -- the pump ----------------------------------------------------------

    def pump(self, gather: bool = True) -> int:
        """One scheduler pass: admit new requests (skippable — the
        run loop only gathers when the wake signal moved, so the
        sub-ms await-polling cadence never pays the backlog scan),
        kill expired scripts, resume every script whose await
        resolved.  Returns the number of resumes (0 = nothing to do;
        callers idle)."""
        self.stats.drains += 1
        if gather:
            self._admit(self._gather())
        moved = 0
        for run in list(self.runs.values()):
            if self.runs.get(run.idx) is not run:
                continue                      # retired by a sibling
            if run.rt.budget.expired():
                self._kill(run, KILL_DEADLINE,
                           "deadline passed while the script was "
                           "suspended")
                moved += 1
                continue
            aw = run.await_
            if aw is None:
                continue
            ready, result = self._poll_await(aw)
            if not ready:
                continue
            run.stages["verb"] += (time.perf_counter() - aw.t0) * 1e3
            run.await_ = None
            moved += 1
            self._resume(run, result if isinstance(result, tuple)
                         else (result,))
        return moved

    def run_once(self, *, timeout_s: float = 30.0) -> int:
        """Pump until the lane is idle (no in-flight scripts and no
        labelled backlog) or `timeout_s` passes — tests and --oneshot.
        Returns completed+failed+killed script count for the call."""
        t0 = time.monotonic()
        done0 = (self.stats.scripts_completed + self.stats.scripts_failed
                 + self.stats.scripts_killed + self.stats.parse_errors)
        while time.monotonic() - t0 < timeout_s:
            moved = self.pump()
            if not self.runs and not moved and \
                    not self.store.enumerate_indices(P.LBL_SCRIPT_REQ):
                break
            if not moved:
                time.sleep(0.001)
        return (self.stats.scripts_completed + self.stats.scripts_failed
                + self.stats.scripts_killed + self.stats.parse_errors
                - done0)

    # -- housekeeping ------------------------------------------------------

    def sweep_results(self, *, ttl_s: float = RESULT_TTL_S,
                      now: float | None = None) -> int:
        """Retire orphaned __pr_<idx> rows (client timed out and never
        consumed, or a previous generation's leftovers) — the
        searcher's sweep discipline on the heartbeat cadence."""
        st = self.store
        now = time.time() if now is None else now
        pfx = P.SCRIPT_RESULT_PREFIX
        reaped = 0
        for key in st.list():
            if not key.startswith(pfx):
                continue
            try:
                idx = int(key[len(pfx):])
            except ValueError:
                continue
            try:
                rec = json.loads(st.get(key).rstrip(b"\0"))
            except (KeyError, OSError, ValueError):
                continue
            if not isinstance(rec, dict):
                rec = {}
            e, ts = rec.get("e"), rec.get("ts")
            if idx >= st.nslots or st.key_at(idx) is None:
                retire = True
            elif isinstance(e, int) and st.epoch_at(idx) != e:
                retire = True
            elif isinstance(ts, (int, float)):
                retire = (now - float(ts)) > ttl_s
            else:
                retire = True
            if retire:
                try:
                    st.unset(key)
                    reaped += 1
                except (KeyError, OSError):
                    pass
        self.stats.results_reaped += reaped
        # the pending-span staging rows share the same reaper cadence
        # (orphans: raced rewrites, crashed chains nobody re-drained)
        sweep_span_stages(st, ttl_s=ttl_s, now=now)
        return reaped

    def _record_trace(self, run: ScriptRun) -> None:
        if not tracer.enabled:
            return
        for stage in P.SCRIPT_STAGES:
            tracer.record(f"script.{stage}", run.stages[stage])
        wall = (time.perf_counter() - run.t_start) * 1e3
        tracer.record("script.e2e", wall)
        if run.stamp is not None:
            tid, ts = run.stamp
            client_wall = ((time.time() - ts) * 1e3 if ts > 0
                           else wall)
            slot = self.recorder.record(
                tid, run.key, client_wall,
                [[s, round(run.stages[s], 3)]
                 for s in P.SCRIPT_STAGES])
            # chain identity on the ring entry: the script name, its
            # span id, and the per-verb dispatch counts — `spt trace
            # tail` on the script lane correlates with `spt trace
            # show <id>`'s span tree.  ALWAYS assigned: ring slots
            # are REUSED dicts, and a stale key left by the previous
            # occupant would attach phantom verbs to the wrong script
            slot["script"] = run.label
            slot["span"] = (run.span.span if run.span is not None
                            else None)
            slot["verbs"] = (dict(run.verb_counts)
                             if run.verb_counts else None)

    def publish_stats(self) -> None:
        payload = {**dataclasses.asdict(self.stats),
                   "spans_obs": self.spans.counters(),
                   "scripts_active": len(self.runs),
                   "max_scripts": self.max_scripts,
                   "generation": self.generation}
        if self.replica or self.stripes.epoch:
            payload["replica"] = self.replica
            payload["stripe"] = self.stripes.snapshot()
        if self.verb_counts:
            # per-verb dispatch counters: `spt metrics` renders one
            # sptpu_pipeliner_verb_<name> series per verb
            payload["verbs"] = dict(self.verb_counts)
        if self.qos.high_water is not None:
            payload["qos"] = {
                "admit_cap": self.max_scripts,
                "queue_high_water": self.qos.high_water,
                "retry_after_ms": self.qos.retry_after_ms}
        tenants = self.tenants.snapshot()
        if tenants:
            payload["tenants"] = tenants
        prune_idle_counters(
            payload, bool(self.qos.high_water is not None or tenants))
        if faults.armed():
            payload["faults"] = faults.stats()
        # the pipeliner dispatches no jitted programs of its own, but
        # in-process co-located lanes may have buffered ledger events
        # — flush on the same heartbeat cadence as every other lane
        DEVTIME.flush(self.store)
        if tracer.enabled:
            P.attach_trace_sections(payload, tracer, self.recorder,
                                    "script.")
        P.publish_heartbeat(self.store, self._hb_key, payload)
        if tracer.enabled:
            self._trace_published = P.maybe_publish_trace_ring(
                self.store, self._trace_key, self.recorder,
                self._trace_published)

    # -- daemon loop -------------------------------------------------------

    def run(self, *, idle_timeout_ms: int = 50,
            stop_after: float | None = None,
            heartbeat_interval_s: float = 5.0) -> None:
        """The daemon loop: block on the signal group while idle, poll
        tightly while scripts are in flight (their awaits resolve via
        OTHER lanes' bumps on OTHER keys — the short poll is what
        keeps chain hops at milliseconds instead of wake latencies)."""
        self._running = True
        st = self.store
        last = st.signal_count(self.group)
        deadline = (time.monotonic() + stop_after) if stop_after \
            else None
        next_beat = 0.0
        next_retire_check = 0.0
        re_gather = False
        while self._running:
            try:
                if self.runs:
                    # in-flight scripts: sub-ms await polling (each
                    # chain hop costs the downstream lane's service
                    # time plus THIS cadence — a 5 ms quantum here
                    # would hand back most of the round trips the
                    # lane exists to remove); the backlog scan runs
                    # only when the wake signal moved
                    cnt = st.signal_count(self.group)
                    gather = cnt != last or re_gather
                    if cnt != last:
                        last = cnt
                        self.stats.wakes += 1
                    moved = self.pump(gather=gather)
                    # a finished script freed capacity: the next pass
                    # re-plans admission over any deferred backlog
                    re_gather = bool(moved)
                    if not moved:
                        time.sleep(0.0002)
                else:
                    got = st.signal_wait(self.group, last,
                                         timeout_ms=idle_timeout_ms)
                    if got is not None:
                        last = got
                        self.stats.wakes += 1
                    self.pump()
                now = time.monotonic()
                if now >= next_beat:
                    self.sweep_results()
                    self.publish_stats()
                    next_beat = now + heartbeat_interval_s
                if self.replica and not self.runs \
                        and now >= next_retire_check:
                    # scale-down drain: stripes closed, every live
                    # chain committed — exit and let the supervisor
                    # reap us
                    next_retire_check = now + 1.0
                    if self.stripes.poll_retired():
                        log.info("replica %d destriped — retiring",
                                 self.replica)
                        self.publish_stats()
                        break
            except Exception:
                log.exception("run loop cycle failed; continuing")
                now = time.monotonic()
            if deadline and now > deadline:
                break
        # leave no parked coroutine threads behind
        for run in list(self.runs.values()):
            self._retire(run)

    def stop(self) -> None:
        self._running = False


# -- client side -----------------------------------------------------------

def daemon_live(store: Store, *, max_age_s: float = 15.0) -> bool:
    """True when a pipeline lane is live enough to route scripts to
    (heartbeat fresh + pid alive + breaker not open)."""
    return P.heartbeat_live(store, P.KEY_SCRIPT_STATS,
                            max_age_s=max_age_s, lane="pipeliner")


def store_script(store: Store, name: str, source: str) -> None:
    """Publish a named script (`spt pipeline put`): the server-side
    program a request can invoke by name."""
    store.set(P.stored_script_key(name), source)


def submit_script(store: Store, key: str, *, script: str | None = None,
                  name: str | None = None, args: list | None = None,
                  timeout_ms: float = 10_000,
                  tenant: int = 0,
                  deadline_ms: float | None = None,
                  trace=None,
                  retry: bool = True):
    """Client side: submit a script request on `key` and wait for its
    result record.  Returns the parsed __pr_ record ({"ok": true,
    "ret": [...]} or a typed error dict), or None on timeout / down
    lane.  Exactly one of `script` (inline source) / `name` (stored)
    is required."""
    from .client import (PENDING, call_with_retries, _stamp_qos,
                         wait_with_repulse)

    if bool(script) == bool(name):
        raise ValueError("need exactly one of script= / name=")
    deadline_ts = (time.time() + deadline_ms / 1e3
                   if deadline_ms is not None else None)

    def attempt(left_ms: float):
        req: dict = {"args": list(args or [])}
        if script:
            req["script"] = script
        else:
            req["name"] = name
        if deadline_ts is not None:
            req["deadline"] = round(deadline_ts, 6)
        store.set(key, json.dumps(req))
        idx = store.find_index(key)
        _stamp_qos(store, key, tenant, None,   # deadline rides JSON
                   trace)
        store.label_or(key, P.LBL_SCRIPT_REQ | P.LBL_WAITING)
        store.bump(key)

        def check():
            try:
                labels = store.labels(key)
            except KeyError:
                return None
            if labels & P.LBL_SCRIPT_REQ:
                return PENDING
            try:
                raw = store.get(P.script_result_key(idx))
                return json.loads(raw.rstrip(b"\0"))
            except (KeyError, OSError, ValueError):
                return None

        return wait_with_repulse(store, key, left_ms, check)

    if not retry:
        return attempt(timeout_ms)
    return call_with_retries(attempt, timeout_ms=timeout_ms,
                             store=store, lane="pipeliner")


def consume_script_result(store: Store, key: str) -> None:
    """Retire a serviced script request's result row."""
    try:
        store.unset(P.script_result_key(store.find_index(key)))
    except (KeyError, OSError):
        pass


def main(argv: list[str] | None = None) -> int:
    """CLI entry: python -m libsplinter_tpu.engine.pipeliner
    --store NAME.  Deliberately jax-free — the lane starts in
    milliseconds, so supervised restarts are cheap."""
    import argparse

    ap = argparse.ArgumentParser(
        description="splinter-tpu pipeline lane (server-side scripted "
                    "RAG chains in a sandboxed Lua host)")
    ap.add_argument("--store", required=True)
    ap.add_argument("--persistent", action="store_true")
    ap.add_argument("--oneshot", action="store_true")
    ap.add_argument("--max-scripts", type=int, default=32,
                    help="in-flight script cap (concurrency bound AND "
                         "admission capacity per drain)")
    ap.add_argument("--max-steps", type=int, default=None,
                    help="per-script interpreter step budget "
                         "(default 1000000; past it the script dies "
                         "with a typed budget_exceeded record)")
    ap.add_argument("--max-verbs", type=int, default=None,
                    help="per-script async-verb budget (default 256)")
    ap.add_argument("--max-sleep-s", type=float, default=None,
                    help="per-call splinter.sleep clamp (default 30)")
    ap.add_argument("--max-coroutines", type=int, default=None,
                    help="per-script coroutine cap (default 16)")
    ap.add_argument("--queue-high-water", type=int, default=None,
                    help="max deferred backlog — overflow is shed "
                         "with a typed `overloaded` result")
    ap.add_argument("--retry-after-ms", type=int, default=None)
    ap.add_argument("--tenant-weights", default=None,
                    help="per-tenant fair-share weights, "
                         "TENANT:W[,TENANT:W...]")
    ap.add_argument("--idle-timeout-ms", type=int, default=50)
    ap.add_argument("--replica", type=int, default=0,
                    help="striped replica index (elastic lanes): "
                         "gather only the stripes the lane's stripe "
                         "map assigns this replica; heartbeat "
                         "publishes replica-suffixed "
                         "(__pipeliner_stats.rN)")
    ap.add_argument("--seed-library", action="store_true",
                    help="store the built-in scenario scripts "
                         "(rag-churn / agent-loop / multi-hop / "
                         "map-reduce) before serving")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    store = Store.open(args.store, persistent=args.persistent)
    pl = Pipeliner(store, max_scripts=args.max_scripts,
                   max_steps=args.max_steps,
                   max_verbs=args.max_verbs,
                   max_sleep_s=args.max_sleep_s,
                   max_coroutines=args.max_coroutines,
                   queue_high_water=args.queue_high_water,
                   retry_after_ms=args.retry_after_ms,
                   tenant_weights=parse_tenant_weights(
                       args.tenant_weights),
                   replica=args.replica)
    pl.attach()
    if args.seed_library:
        from ..scripting.library import seed_library
        seed_library(store)
    pl.publish_stats()
    if args.oneshot:
        n = pl.run_once()
        log.info("oneshot ran %d scripts", n)
        return 0
    try:
        pl.run(idle_timeout_ms=args.idle_timeout_ms)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
