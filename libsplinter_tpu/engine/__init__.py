"""Serving daemons over the store's event bus: the embedding daemon
(embedder.py), the completion daemon (completer.py), and the
query-coalescing search daemon (searcher.py), sharing one coordination
contract (protocol.py) and supervised as child processes by
supervisor.py (crash restart + circuit breaker)."""
from . import protocol

__all__ = ["protocol", "Searcher", "daemon_live", "submit_search",
           "Supervisor"]

_SEARCHER_API = ("Searcher", "daemon_live", "submit_search")


def __getattr__(name):
    # lazy: `python -m libsplinter_tpu.engine.searcher` must not find
    # the module pre-imported by its own package (runpy warns), and
    # protocol-only importers skip the daemon modules entirely
    if name in _SEARCHER_API:
        from . import searcher
        return getattr(searcher, name)
    if name == "Supervisor":
        from . import supervisor
        return supervisor.Supervisor
    raise AttributeError(name)
