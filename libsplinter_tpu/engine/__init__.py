"""Serving daemons over the store's event bus: the embedding daemon
(embedder.py), the completion daemon (completer.py), the
query-coalescing search daemon (searcher.py), and the pipeline lane
(pipeliner.py — server-side scripted chains in a sandboxed Lua host),
sharing one coordination contract (protocol.py) and supervised as
replica sets of child processes by supervisor.py (crash restart +
circuit breaker + striped elastic scaling, replica counts driven by
autoscaler.py off the telemetry rings)."""
from . import protocol

__all__ = ["protocol", "Searcher", "daemon_live", "submit_search",
           "Supervisor", "AutoScaler"]

_SEARCHER_API = ("Searcher", "daemon_live", "submit_search")


def __getattr__(name):
    # lazy: `python -m libsplinter_tpu.engine.searcher` must not find
    # the module pre-imported by its own package (runpy warns), and
    # protocol-only importers skip the daemon modules entirely
    if name in _SEARCHER_API:
        from . import searcher
        return getattr(searcher, name)
    if name == "Supervisor":
        from . import supervisor
        return supervisor.Supervisor
    if name == "AutoScaler":
        from . import autoscaler
        return autoscaler.AutoScaler
    raise AttributeError(name)
