"""The telemetry sampler — heartbeat history for the elastic-lane
signal plane.

Every lane publishes a point-in-time heartbeat; nothing keeps
history, so the questions the scaling controller of ROADMAP item 4
must answer — is queue depth trending up? did shed counters move when
the offered rate stepped? what was the p99 a minute ago? — have no
data.  This lane scrapes every lane heartbeat on its cadence into
FIXED-SIZE time-series rings stored IN the store (one `__tele_<lane>`
key per lane), so:

  - the rings survive the sampler itself (a supervised restart picks
    up where the dead generation left off — the rings are store
    state, not process state);
  - any client renders history with plain store reads (`spt top`,
    `spt metrics --history`) — no sidecar database, the reference's
    "everything is a key" discipline;
  - the sampler is supervisable (`spt supervise --lanes ...,telemetry`)
    and deliberately jax-free: restarts cost milliseconds.

Gauges per lane: queue depth (labelled-request count — measured from
the store, not trusted from the heartbeat), shed / deferred /
deadline_expired counters, the lane's main progress counter, stage
p99s when tracing is on, pool occupancy on the completer, and
per-tenant admitted counts.  Ring write degrades by halving its
length when the snapshot outgrows max_val — shorter history beats
none (the publish_trace_ring discipline).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import time

from ..store import Store
from . import protocol as P

log = logging.getLogger("libsplinter_tpu.telemetry")

# lane -> (heartbeat key, request label for the queue-depth gauge)
SCRAPE_LANES: dict[str, tuple[str, int]] = {
    "embedder": (P.KEY_EMBED_STATS, P.LBL_EMBED_REQ),
    "completer": (P.KEY_COMPLETE_STATS, P.LBL_INFER_REQ),
    "searcher": (P.KEY_SEARCH_STATS, P.LBL_SEARCH_REQ),
    "pipeliner": (P.KEY_SCRIPT_STATS, P.LBL_SCRIPT_REQ),
    # the disaggregated completer phases (engine/disagg.py): prefill's
    # queue is the classic waiting-request backlog; decode's "queue"
    # is the handed-off rows awaiting adoption — and its scaling
    # signal is the pool_occ gauge derived below, not queue depth
    "prefill": (P.KEY_PREFILL_STATS, P.LBL_INFER_REQ),
    "decode": (P.KEY_DECODE_STATS, P.LBL_DECODE_READY),
}

# heartbeat counters copied into the rings when present (beyond the
# always-sampled queue_depth); one progress counter per lane so
# goodput is derivable from any two samples.  PROGRESS_FIELDS is
# shared with `spt top` — one table, so a new lane cannot appear in
# one surface and silently miss the other.
_COUNTER_GAUGES = ("shed", "deferred", "deadline_expired")
PROGRESS_FIELDS = {"embedder": "embedded",
                   "completer": "completions",
                   "searcher": "served",
                   "pipeliner": "scripts_completed",
                   "prefill": "handoffs",
                   "decode": "completions"}
_EXTRA = {"completer": ("pages_free", "pages_used", "tokens",
                        "prefix_hits", "prefix_shared_pages",
                        "pool_mb", "pool_mb_peak",
                        "pages_used_peak", "compile_events",
                        "tier_pages", "tier_readmits",
                        "tier_restored"),
          "embedder": ("compile_count", "compile_events"),
          "searcher": ("compile_events",),
          "pipeliner": ("scripts_active",),
          "prefill": ("handoff_failed", "handoff_wire_mb",
                      "prefix_hits", "prefill_wall_ema_ms",
                      "compile_events", "tier_pages",
                      "tier_readmits"),
          "decode": ("pages_free", "pages_used", "tokens",
                     "adopted", "readopted", "adopt_backpressure",
                     "handoff_refill", "compile_events",
                     "tier_pages", "tier_readmits")}

DEFAULT_INTERVAL_S = 2.0
DEFAULT_RING_LEN = 64


@dataclasses.dataclass
class TelemetryStats:
    samples: int = 0             # sampler ticks completed
    lanes_seen: int = 0          # lanes with a readable heartbeat, last tick
    points: int = 0              # gauge points appended, lifetime
    write_errors: int = 0        # ring writes that failed outright
    shrinks: int = 0             # ring writes that had to halve history


class TelemetrySampler:
    """Drive with run() (blocking loop) or sample_once() (one tick —
    tests and --oneshot)."""

    def __init__(self, store: Store, *,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 ring_len: int = DEFAULT_RING_LEN):
        self.store = store
        self.interval_s = max(0.05, interval_s)
        self.ring_len = max(4, ring_len)
        self.stats = TelemetryStats()
        self.generation = 0
        self._running = False

    # -- wiring ------------------------------------------------------------

    def attach(self) -> None:
        self.generation = P.bump_generation(self.store,
                                            P.KEY_TELEMETRY_STATS)

    # -- sampling ----------------------------------------------------------

    def _read_heartbeat(self, key: str) -> dict | None:
        try:
            snap = json.loads(self.store.get(key).rstrip(b"\0"))
        except (KeyError, OSError, ValueError):
            return None
        return snap if isinstance(snap, dict) else None

    def _read_lane_snaps(self, base: str,
                         disc: dict | None = None) -> list[dict]:
        """Every replica heartbeat of a lane (elastic lanes publish
        replica-suffixed keys — base, base.r1, ...), in replica
        order.  `disc` is a shared replica_heartbeat_map result so
        one tick pays one discovery enumeration."""
        rows = (disc or P.replica_heartbeat_map(
            self.store, (base,)))[base]
        out = []
        for _r, key in rows:
            snap = self._read_heartbeat(key)
            if snap is not None:
                out.append(snap)
        return out

    def _gauges_for(self, lane: str,
                    snaps: list[dict] | dict | None) -> dict:
        """One tick's gauge values for a lane.  queue_depth is always
        measured (label enumeration over the WHOLE lane — the store
        is the truth, a stale heartbeat is not, and under striped
        replicas no single replica's view covers the queue); the rest
        come from the replica heartbeats when any exist — counters
        and progress SUM across replicas, stage p99s take the worst
        replica, and a `replicas` gauge counts live publishers so the
        controller and `spt top` can see R move."""
        _, label = SCRAPE_LANES[lane]
        out: dict[str, float] = {
            "queue_depth": float(len(
                self.store.enumerate_indices(label)))}
        if isinstance(snaps, dict):
            snaps = [snaps]
        if not snaps:
            return out
        live = sum(1 for s in snaps
                   if not isinstance(s.get("pid"), int)
                   or P.pid_alive(s["pid"]))
        if len(snaps) > 1 or any("replica" in s for s in snaps):
            out["replicas"] = float(live)
        prog = PROGRESS_FIELDS.get(lane)
        for snap in snaps:
            for g in _COUNTER_GAUGES + _EXTRA.get(lane, ()):
                v = snap.get(g)
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    out[g] = out.get(g, 0.0) + float(v)
            if prog is not None and isinstance(snap.get(prog),
                                               (int, float)):
                out["progress"] = out.get("progress", 0.0) \
                    + float(snap[prog])
            # paged-pool occupancy fraction — the decode lane's
            # scaling signal (autoscaler `signal: "pool"`).  Each
            # replica owns its own pool, so the fleet-WORST replica
            # is the scaling truth (one exhausted pool refuses
            # adoption no matter how empty its siblings are).
            pu, pf = snap.get("pages_used"), snap.get("pages_free")
            if isinstance(pu, (int, float)) \
                    and isinstance(pf, (int, float)) and pu + pf > 0:
                out["pool_occ"] = max(out.get("pool_occ", 0.0),
                                      float(pu) / float(pu + pf))
            # stage p99s (tracing on): e2e + every published stage —
            # the quantiles section carries prefix-stripped stage
            # names; across replicas the WORST p99 is the SLO truth
            q = snap.get("quantiles")
            if isinstance(q, dict):
                for stage, row in q.items():
                    if isinstance(row, dict) and "p99_ms" in row:
                        k = f"p99_{stage}_ms"
                        out[k] = max(out.get(k, 0.0),
                                     float(row["p99_ms"]))
            # per-tenant goodput inputs (admitted is the open-loop
            # admission truth; served_tokens where the lane meters
            # tokens)
            tenants = snap.get("tenants")
            if isinstance(tenants, dict):
                for t, row in tenants.items():
                    if not isinstance(row, dict):
                        continue
                    for f in ("admitted", "served_tokens"):
                        v = row.get(f)
                        if isinstance(v, (int, float)):
                            k = f"tenant{t}_{f}"
                            out[k] = out.get(k, 0.0) + float(v)
        return out

    def _append(self, lane: str, gauges: dict, now: float) -> None:
        """Read-modify-write the lane's ring key, bounded to ring_len
        samples per gauge; an oversized snapshot halves its history
        until it fits."""
        st = self.store
        key = P.telemetry_key(lane)
        try:
            rec = json.loads(st.get(key).rstrip(b"\0"))
            if not isinstance(rec, dict) or rec.get("v") != 1:
                rec = {}
        except (KeyError, OSError, ValueError):
            rec = {}
        rings = rec.get("gauges")
        if not isinstance(rings, dict):
            rings = {}
        ts = round(now, 1)
        for name, val in gauges.items():
            ring = rings.get(name)
            if not isinstance(ring, list):
                ring = rings[name] = []
            ring.append([ts, round(float(val), 3)])
            del ring[:-self.ring_len]
            self.stats.points += 1
        body = {"v": 1, "lane": lane, "interval_s": self.interval_s,
                "n": int(rec.get("n", 0)) + 1, "ts": ts,
                "gauges": rings}
        keep = self.ring_len
        while True:
            try:
                st.set(key, json.dumps(body))
                return
            except OSError:
                keep //= 2
                if keep < 1:
                    self.stats.write_errors += 1
                    return
                self.stats.shrinks += 1
                body["gauges"] = {g: r[-keep:]
                                  for g, r in rings.items()}
            except KeyError:
                self.stats.write_errors += 1
                return

    def sample_once(self, now: float | None = None) -> int:
        """One tick over every scrape lane; returns lanes sampled."""
        now = time.time() if now is None else now
        seen = 0
        disc = P.replica_heartbeat_map(
            self.store, [hb for hb, _ in SCRAPE_LANES.values()])
        for lane, (hb_key, _) in SCRAPE_LANES.items():
            try:
                snaps = self._read_lane_snaps(hb_key, disc)
                if snaps:
                    seen += 1
                self._append(lane, self._gauges_for(lane, snaps), now)
            except Exception:        # telemetry must never wedge: a
                log.exception("sampling %s failed; continuing", lane)
        self.stats.samples += 1
        self.stats.lanes_seen = seen
        return seen

    # -- heartbeat ---------------------------------------------------------

    def publish_stats(self) -> None:
        payload = {**dataclasses.asdict(self.stats),
                   "interval_s": self.interval_s,
                   "ring_len": self.ring_len,
                   "generation": self.generation}
        P.publish_heartbeat(self.store, P.KEY_TELEMETRY_STATS, payload)

    # -- lifecycle ---------------------------------------------------------

    def run(self, *, stop_after: float | None = None,
            heartbeat_interval_s: float = 5.0,
            idle_timeout_ms: int | None = None) -> None:
        """The sampler loop.  `idle_timeout_ms` is accepted (and
        ignored) so the supervisor's generic lane argv works
        unchanged."""
        self._running = True
        deadline = (time.monotonic() + stop_after) if stop_after \
            else None
        next_beat = 0.0
        while self._running:
            t0 = time.monotonic()
            try:
                self.sample_once()
                if t0 >= next_beat:
                    self.publish_stats()
                    next_beat = t0 + heartbeat_interval_s
            except Exception:
                log.exception("sampler tick failed; continuing")
            if deadline and time.monotonic() > deadline:
                break
            elapsed = time.monotonic() - t0
            time.sleep(max(self.interval_s - elapsed, 0.01))

    def stop(self) -> None:
        self._running = False


def read_history(store, lane: str) -> dict | None:
    """A lane's telemetry ring, or None: {"gauges": {name: [[ts, v],
    ...]}, ...} — what `spt top` / `spt metrics --history` render."""
    try:
        rec = json.loads(store.get(P.telemetry_key(lane)).rstrip(b"\0"))
    except (KeyError, OSError, ValueError):
        return None
    if not isinstance(rec, dict) or rec.get("v") != 1:
        return None
    return rec


def main(argv: list[str] | None = None) -> int:
    """CLI entry: python -m libsplinter_tpu.engine.telemetry
    --store NAME.  jax-free — supervised restarts cost ms."""
    import argparse

    ap = argparse.ArgumentParser(
        description="splinter-tpu telemetry sampler (heartbeat "
                    "history rings for spt top / spt metrics "
                    "--history / the scaling controller)")
    ap.add_argument("--store", required=True)
    ap.add_argument("--persistent", action="store_true")
    ap.add_argument("--oneshot", action="store_true")
    ap.add_argument("--interval-s", type=float,
                    default=DEFAULT_INTERVAL_S,
                    help="scrape cadence (default 2s)")
    ap.add_argument("--ring-len", type=int, default=DEFAULT_RING_LEN,
                    help="samples kept per gauge (default 64)")
    ap.add_argument("--idle-timeout-ms", type=int, default=None,
                    help="accepted for supervisor argv parity; unused")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    store = Store.open(args.store, persistent=args.persistent)
    tel = TelemetrySampler(store, interval_s=args.interval_s,
                           ring_len=args.ring_len)
    tel.attach()
    tel.publish_stats()
    if args.oneshot:
        n = tel.sample_once()
        tel.publish_stats()
        log.info("oneshot sampled %d lanes", n)
        return 0
    try:
        tel.run()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
