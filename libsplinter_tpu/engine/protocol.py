"""The coordination contract between clients and the inference daemons.

Mirrors the reference's label state machine and well-known keys
(splinterrc_example:83-85, splinter.h:477-491, splinference.cpp:50-89,
splainference.cpp:51-109; SURVEY.md §2.2) so a client written against the
reference's conventions finds identical behavior here.
"""
import itertools
import json
import os
import time
from collections.abc import Sequence

from .. import _native as N

# --- bloom labels (bit masks) -------------------------------------------
LBL_EMBED_REQ = 0x1            # "embed me" — wakes the embedding daemon
LBL_WAITING = 0x40             # client is blocked on this key
LBL_CTX_EXCEEDED = 0x80        # input exceeded the model context window
LBL_CHUNK = 0x200              # ingest: document chunk
LBL_META = 0x400               # ingest: metadata slot
LBL_SCRIPT_REQ = 0x1 << 56     # "run my script" — wakes the pipeline lane
LBL_SEARCH_REQ = 0x1 << 57     # "search me" — wakes the search daemon
LBL_TRACED = 0x1 << 58         # request carries a trace stamp (obs)
LBL_DEADLINE = 0x1 << 52       # request carries a deadline stamp (QoS)
LBL_DECODE_READY = 0x1 << 53   # prefill committed; awaiting decode adoption
LBL_DEBUG = 0x1 << 59          # debug channel (sidecar watches this)
LBL_INFER_REQ = 0x1 << 60      # "complete me" — wakes the completion daemon
LBL_SERVICING = 0x1 << 61      # completion in progress
LBL_READY = 0x1 << 62          # completion finished

# --- bloom bit indices (for watch_label_register) -----------------------
BIT_EMBED_REQ = 0
BIT_WAITING = 6
BIT_CTX_EXCEEDED = 7
BIT_SCRIPT_REQ = 56
BIT_SEARCH_REQ = 57
BIT_DEADLINE = 52
BIT_DECODE_READY = 53
BIT_DEBUG = 59
BIT_INFER_REQ = 60

# --- multi-tenant QoS label field ----------------------------------------
# The tenant id rides the request's own bloom label word, bits 48..51
# (ids 1..15; 0 = the untagged default tenant), the way LBL_TRACED
# rides bit 58: daemons read every candidate's label word anyway, so
# tenant discovery costs nothing, and one tenant's waiting rows can be
# enumerated cheaply with a bloom prefilter
# (enumerate_indices(tenant_label(t) | LBL_SEARCH_REQ)).  Daemons
# never clear the tenant field — it survives the WAITING->SERVICING->
# READY trifecta so post-hoc accounting can still attribute the slot.
TENANT_SHIFT = 48
TENANT_BITS = 4
TENANT_MASK = ((1 << TENANT_BITS) - 1) << TENANT_SHIFT
MAX_TENANT = (1 << TENANT_BITS) - 1            # 15


def tenant_label(tenant: int) -> int:
    """The label bits encoding `tenant` (1..MAX_TENANT; 0 = none)."""
    if not 0 <= tenant <= MAX_TENANT:
        raise ValueError(
            f"tenant id must be 0..{MAX_TENANT}, got {tenant}")
    return tenant << TENANT_SHIFT


def read_tenant(labels: int) -> int:
    """Extract the tenant id from a slot's label word (0 = untagged)."""
    return (labels & TENANT_MASK) >> TENANT_SHIFT


def stamp_tenant(store, key: str, tenant: int) -> None:
    """Client-side: tag the pending request on `key` with its tenant id
    (best after set, before the bump — like stamp_trace).  Replaces any
    previous tenant tag.  Never raises: a missing key is the caller's
    race to discover."""
    bits = tenant_label(tenant)                # validates range
    try:
        store.label_clear(key, TENANT_MASK)
        if bits:
            store.label_or(key, bits)
    except (KeyError, OSError):
        pass

# --- signal groups -------------------------------------------------------
GROUP_EMBED = 2                # embedding daemon wake group
GROUP_INFER = 3                # completion daemon wake group
GROUP_SEARCH = 4               # search daemon wake group
GROUP_SCRIPT = 5               # pipeline (scripted-chain) lane wake group
GROUP_DEBUG = 63               # sidecar debug group

# --- shard ids / priorities (cooperative advisement) --------------------
SHARD_EMBED = 0x5F10
SHARD_COMPLETE = 0x5F1A
SHARD_SEARCH = 0x5F1B
SHARD_SCRIPT = 0x5F1C
PRIO_EMBED_LIVE = 40
PRIO_EMBED_BACKFILL = 20
PRIO_COMPLETE = 200
PRIO_SEARCH = 150
PRIO_SCRIPT = 100

# --- well-known keys -----------------------------------------------------
KEY_DONE_LANE = "__lane_dw_2"  # pulsed after each committed embedding
KEY_DEBUG = "__debug"          # append-only shared debug log
KEY_SYSTEM_PROMPT = "__system_prompt"
# periodic daemon heartbeats: JSON stats snapshots, debug-labeled so
# the sidecar's group-63 watch surfaces them (the reference's only
# runtime telemetry is the __debug append channel; these are the
# structured counterpart).  Every lane's heartbeat carries the
# dispatch-overlap gauges (PR 7, engine/resident.py): inflight_depth
# (the configured K) + inflight_peak, and on the embedder the
# resident-ring gauges (ring_depth / ring_occupancy /
# resident_iterations / ring_faults) in their own size-droppable
# "dispatch" section — `spt metrics` renders them flat as
# sptpu_<lane>_inflight_depth etc., so saturation of the overlap
# window is visible in production.
KEY_EMBED_STATS = "__embedder_stats"
KEY_COMPLETE_STATS = "__completer_stats"
KEY_SEARCH_STATS = "__searcher_stats"
KEY_SCRIPT_STATS = "__pipeliner_stats"
# disaggregated completion lanes (prefill / decode split): each lane
# type heartbeats under its own key so telemetry, `spt metrics`, and
# the autoscaler read the two phases as separate lanes — a unified
# completer keeps KEY_COMPLETE_STATS untouched
KEY_PREFILL_STATS = "__prefill_stats"
KEY_DECODE_STATS = "__decode_stats"
# the supervisor's own heartbeat (engine/supervisor.py): per-lane
# process state — pid, generation, restart/backoff/breaker counters,
# and the breaker's down marker CLI clients consult before dispatching
# to a lane (daemon_live checks it so a broken lane fails fast instead
# of burning the full submit timeout)
KEY_SUPERVISOR_STATS = "__supervisor_stats"
SEARCH_SCRATCH_PREFIX = "__sqtmp_"   # search query scratch key per pid
# search-daemon results: one JSON row per serviced request, keyed by
# the REQUEST's slot index (__sr_<idx>) — the client polls its request
# key and reads the companion once LBL_SEARCH_REQ clears
SEARCH_RESULT_PREFIX = "__sr_"
# pipeline-lane results: one JSON row per finished script, keyed by
# the REQUEST's slot index (__pr_<idx>) — {"ok": true, "ret": [...]}
# or a typed error record ({"err": "budget_exceeded" | "script_error"
# | "deadline_expired" | "overloaded", ...}); the client polls its
# request key and reads the companion once LBL_SCRIPT_REQ clears
SCRIPT_RESULT_PREFIX = "__pr_"
# stored named scripts (the reference's "programs next to the data"):
# `spt pipeline put NAME file.lua` writes the source under
# __script_<NAME>; a request naming it ({"name": "NAME"}) runs it
# server-side without shipping the source per call
SCRIPT_STORE_PREFIX = "__script_"
# flight-recorder dumps (obs/recorder.py): each daemon publishes its
# ring of per-request wake->commit traces here alongside its stats
# heartbeat; `spt trace tail` reads them cross-process
KEY_EMBED_TRACE = "__embedder_trace"
KEY_COMPLETE_TRACE = "__completer_trace"
KEY_SEARCH_TRACE = "__searcher_trace"
KEY_SCRIPT_TRACE = "__pipeliner_trace"

# context guard: reject inputs >= this fraction of the model window
CTX_GUARD_FRACTION = 0.9

# --- commit-pipeline stage contract --------------------------------------
# The wake->commit path decomposes into these stages; every stats
# surface (the embedder heartbeat's quantiles section, bench's
# stage_quantiles, flight-recorder event sequences) uses these names
# so dashboards and before/after comparisons line up.  device_wait is
# the time the host BLOCKED on a
# device future; overlapped device time (future in flight while the
# host staged the next batch) is reported separately as overlap_ms /
# overlap_ratio, not as a stage — it costs no wake-path wall time.
PIPELINE_STAGES = ("drain", "tokenize", "dispatch", "device_wait",
                   "commit")

# the completion daemon's per-request decomposition (serial path):
# render = guarded prompt read + system-prompt fetch + template +
# WAITING->SERVICING claim; generate = the token loop incl. streaming
# appends; commit = oom bookkeeping + ctime backfill + READY flip
INFER_STAGES = ("render", "generate", "commit")

# the continuous (block-paged) lane's decomposition, published under
# the same infer.* histogram prefix: join = one row's prompt prefill
# into freshly allocated pages (admission IS a join — there is no
# fresh-batch/live-batch distinction); sample = the host draw of its
# first token; decode = the ASYNC dispatch of a flush_tokens-step
# paged decode chunk (the span every live row shares); collect = the
# host's blocked wait forcing a chunk out of the K-deep in-flight
# window (engine/resident.py — with the window saturated this is
# where the amortized dispatch floor surfaces); flush = a streaming
# append run.  A client-stamped request (stamp_trace) gets a
# flight-recorder entry with its accumulated spans, so `spt trace
# tail` reconstructs batched-lane requests too, not just the serial
# path's.  prefix_hit = the host-side radix walk + shared-page table
# mapping of a prefix-cache hit (engine/prefix_cache.py) — its span
# next to `join` is how `spt trace show` attributes first-token
# latency to cache hits vs suffix prefill.  Under disaggregated
# serving two more stages bracket the page-ownership transfer:
# handoff = the prefill lane's export + record write + DECODE_READY
# flip, adopt = the decode lane's claim + page import + row seating.
CONT_INFER_STAGES = ("join", "sample", "decode", "collect", "flush",
                     "prefix_hit", "handoff", "adopt")

# the search daemon's per-drain decomposition: wake = signal to drain
# entry (the coalescing window's scheduling cost); drain = request
# discovery + param parse + torn-safe query-vector gather; score =
# lane refresh + async device dispatch of the fused top-k programs
# (host-side, the device computes in flight); select = the blocking
# device fetch of the O(k*Q) candidate rows; commit = per-request
# filtering + __sr_<idx> result writes + label clears + bumps
SEARCH_STAGES = ("wake", "drain", "score", "select", "commit")

# the pipeline lane's per-script decomposition: parse = source fetch
# (inline or stored) + chunk compile + sandbox construction; exec =
# host-interpreter wall (every coroutine resume slice of the script's
# own Lua steps); verb = time the script spent suspended on async
# splinter verbs (submit_embed / submit_search / submit_completion /
# sleep — the downstream lanes' service time as the script saw it);
# commit = the __pr_<idx> result write + label clear + bump
SCRIPT_STAGES = ("parse", "exec", "verb", "commit")


def search_result_key(idx: int) -> str:
    return f"{SEARCH_RESULT_PREFIX}{idx}"


def script_result_key(idx: int) -> str:
    return f"{SCRIPT_RESULT_PREFIX}{idx}"


def stored_script_key(name: str) -> str:
    return f"{SCRIPT_STORE_PREFIX}{name}"


def candidate_mask(store, bloom: int = 0):
    """THE search candidate mask — one definition the CLI's client-side
    scoring and the search daemon share, so their candidate sets
    cannot diverge: a bloom prefilter enumerates labelled rows; the
    default is every live row (written at least once, not mid-write —
    even nonzero epoch)."""
    import numpy as np

    if bloom:
        mask = np.zeros(store.nslots, np.float32)
        mask[store.enumerate_indices(bloom)] = 1.0
        return mask
    eps = store.epochs()
    return ((eps != 0) & ((eps & np.uint64(1)) == 0)).astype(np.float32)

# latency-probe short-circuit: drains at or below this many candidate
# rows skip the windowed big-batch machinery and dispatch immediately
# on the pre-compiled small-bucket programs (Embedder.probe_batch_max
# overrides per instance)
PROBE_BATCH_MAX_DEFAULT = 8

# --- request trace ids ----------------------------------------------------
# A client that wants its request's wake->commit journey reconstructed
# stamps a trace CONTEXT next to the request label: after set +
# label_or (LBL_EMBED_REQ / LBL_INFER_REQ), ideally before the bump,
# it writes "<trace_id>:<wall_ts>:<slot_epoch>[:<parent>:<span>]"
# into the slot-indexed companion key trace_stamp_key(idx).  The
# epoch field makes stamps self-invalidating (a daemon discards a
# stamp whose epoch doesn't match the request it gathered) — clients
# implementing the convention by hand must include it or forfeit that
# protection.  The two trailing fields are the DISTRIBUTED-tracing
# extension (PR 13): `parent` is the span id this request hangs
# under in the trace tree (0 = root) and `span` is the id assigned to
# THIS request's span — pre-assigned by the stamper so chained hops
# (the pipeline lane's verbs, a client-side rag chain) share one
# trace id across lanes while every hop stays addressable.  Legacy
# 3-field stamps parse as parent=0, span=trace_id.  The servicing
# daemon consumes the stamp when it COMMITS the row (not at drain —
# the stamp must survive a mid-service crash so the restarted lane's
# span still carries the chain identity), appends the request's stage
# events to its flight recorder (SPTPU_TRACE=1) and commits a span
# record into the shared span ring (obs/spans.py, always on) — so
# any single chain is reconstructable cross-process via `spt trace
# show <id>`.  Ids are (pid << 24 | counter): unique across
# concurrent clients without coordination, and the originating pid is
# recoverable (id >> 24).
TRACE_STAMP_PREFIX = "__tr_"

# pending-span staging rows (obs/spans.py): one per in-service traced
# request, keyed by the REQUEST's slot index — the crash-surviving
# half of the span protocol (a restarted lane recovers the chain
# identity, the original queue-enter clock, and the attempt count
# from here).  Orphans (slot epoch moved, or TTL) are swept by
# shed_orphan_stamp's discard path and the lanes' heartbeat-cadence
# sweeps, mirroring the __sr_ reaper.
SPAN_STAGE_PREFIX = "__sp_"

# the shared bounded span ring: committed span records land in
# span_ring_key(head % ring size) slots, the head claimed atomically
# through the BIGUINT counter key — multi-writer safe across all
# four lanes, bounded by construction (old spans overwrite)
SPAN_RING_PREFIX = "__span_"
KEY_SPAN_HEAD = "__span_head"

# the compile-event ring (obs/devtime.py): the named-program
# registry's ledger of jit compile events — {program, lane,
# shapes_key, duration_ms, generation, cause} records land in
# compile_ring_key(head % ring size) slots under the span ring's
# slot-claim discipline (atomic BIGUINT head, bounded by
# construction).  `spt trace export` hangs these on their own
# Perfetto track; scripts/compile_gate_check.py asserts the ring
# holds zero runtime-cause events after warmup.
COMPILE_RING_PREFIX = "__compile_"
KEY_COMPILE_HEAD = "__compile_head"

# telemetry-history rings (engine/telemetry.py): one per scraped
# lane, fixed-size time series of the lane's heartbeat gauges —
# the signal plane the elastic-lane scaling controller reads
TELEMETRY_PREFIX = "__tele_"
KEY_TELEMETRY_STATS = "__telemetry_stats"

# --- elastic lanes: striped replica groups --------------------------------
# A lane may run R replicas behind the SAME label-routing protocol.
# Replicas never coordinate directly: each one drains only its own
# disjoint STRIPE of the request space (a request's stripe is its
# slot index modulo the stripe width — the slot index is what the
# label-word enumeration already hands every drain, the way bloom
# groups partition search candidates), so two replicas can never race
# a claim.  The stripe map is STORE state under stripe_map_key(lane):
# a re-stripe is one epoch-bumped table write that in-flight replicas
# pick up at their next drain — between the write and the pick-up a
# request is at worst serviced by the OLD owner (still exclusive), so
# no request is ever orphaned between stripe owners.  Stripes with
# owner -1 are CLOSED: no replica claims new work from them (the
# supervisor's scale-down drain protocol parks a retiring replica's
# stripes closed until the straggler reclaim re-assigns them).
STRIPE_MAP_PREFIX = "__stripe_"
DEFAULT_STRIPE_WIDTH = 16
# replica-suffixed heartbeat keys: replica 0 keeps the canonical
# KEY_*_STATS name (every existing liveness probe and dashboard reads
# it unchanged), replica N > 0 publishes under "<base>.rN" — `spt
# top` / `spt metrics` / telemetry discover the suffixed keys via
# replica_heartbeat_keys() instead of a hardcoded one-key read
REPLICA_SUFFIX = ".r"
# the scaling controller's wiring (engine/autoscaler.py): the
# supervisor writes the policy (per-lane min:max bounds + controller
# knobs) once at startup, the controller (or `spt scale set`) writes
# desired replica counts into PER-LANE target keys
# (__scale_tgt_<lane> — one writer owns one lane's key at a time, so
# the autoscaler acting on lane A can never clobber an operator's
# concurrent manual hold on lane B the way a shared read-modify-write
# JSON map could), and the supervisor applies them — spawn on
# scale-up, drain-protocol retire on scale-down.  All plain JSON
# store keys, so `spt scale status` is nothing but reads.
KEY_SCALE_POLICY = "__scale_policy"
SCALE_TARGET_PREFIX = "__scale_tgt_"
KEY_AUTOSCALER_STATS = "__autoscaler_stats"

# --- disaggregated prefill/decode handoff ---------------------------------
# The prefill lane commits a row's prompt K/V, samples its first
# token, then hands the row to a decode lane THROUGH THE STORE: a
# JSON handoff record under handoff_key(idx) (generation budget,
# prompt ids for the re-prefill fallback, the sampled carry token,
# byte offsets for crash truncation) plus optional raw wire pages
# under handoff_page_key(idx, j) — the per-layer-stacked K/V bytes of
# each committed page, so a decode lane with its OWN pool imports the
# prefill without recomputing it (handoff_scale_key carries the int8
# page scales when the pool is quantized).  The row's label flips
# SERVICING -> DECODE_READY at the same moment; adoption sets
# SERVICING on top (both bits = decode-phase in flight) and finish
# clears everything to READY.  Crash safety both directions falls out
# of the label machine: a died prefill lane leaves SERVICING-only
# rows its stripe-scoped reclaim resets to WAITING (stale __ho_ keys
# deleted with them), a died decode lane leaves SERVICING|DECODE_READY
# rows that fall back to DECODE_READY (slot value truncated to the
# record's prompt length; greedy decode replays byte-identically).
# Wire keys persist until decode finish and are bounded by the lane
# batch (one in-flight handoff set per prefill seat).
HANDOFF_PREFIX = "__ho_"


def handoff_key(idx: int) -> str:
    return f"{HANDOFF_PREFIX}{idx}"


def handoff_page_key(idx: int, j: int) -> str:
    """Wire page j of slot idx's handoff: raw bytes, all layers
    stacked (layers, kv_heads, page, head_dim) k then v."""
    return f"{HANDOFF_PREFIX}{idx}.p{j}"


def handoff_scale_key(idx: int, j: int) -> str:
    """Wire page j's int8 scales: (layers, kv_heads) f32 k then v."""
    return f"{HANDOFF_PREFIX}{idx}.s{j}"


def write_handoff_record(store, idx: int, rec: dict) -> bool:
    """Land the handoff record for slot idx (debug-labeled so the
    sweep machinery can find strays).  Returns False when the store
    rejects it — the prefill lane then falls back to finishing the
    row itself rather than stranding it half-handed-off."""
    try:
        store.set(handoff_key(idx), json.dumps({"v": 1, **rec}))
        store.label_or(handoff_key(idx), LBL_DEBUG)
        return True
    except (KeyError, OSError):
        return False


def read_handoff_record(store, idx: int) -> dict | None:
    """Slot idx's handoff record, or None (absent / unparseable /
    wrong version)."""
    try:
        rec = json.loads(store.get(handoff_key(idx)).rstrip(b"\0"))
    except (KeyError, OSError, ValueError):
        return None
    if not isinstance(rec, dict) or rec.get("v") != 1:
        return None
    return rec


def clear_handoff(store, idx: int, pages: int = 0) -> None:
    """Retire slot idx's handoff record and its wire pages (decode
    finish, or prefill-crash reclaim).  `pages` bounds the wire-key
    sweep; with 0 the record's own page count is consulted first.
    Never raises."""
    if not pages:
        rec = read_handoff_record(store, idx)
        if rec is not None:
            try:
                pages = int(rec.get("wire_pages", 0))
            except (TypeError, ValueError):
                pages = 0
    try:
        store.unset(handoff_key(idx))
    except (KeyError, OSError):
        pass
    for j in range(max(0, int(pages))):
        for k in (handoff_page_key(idx, j), handoff_scale_key(idx, j)):
            try:
                store.unset(k)
            except (KeyError, OSError):
                pass


def trace_stamp_key(idx: int) -> str:
    return f"{TRACE_STAMP_PREFIX}{idx}"


def span_stage_key(idx: int) -> str:
    return f"{SPAN_STAGE_PREFIX}{idx}"


def span_ring_key(i: int) -> str:
    return f"{SPAN_RING_PREFIX}{i}"


def compile_ring_key(i: int) -> str:
    return f"{COMPILE_RING_PREFIX}{i}"


def telemetry_key(lane: str) -> str:
    return f"{TELEMETRY_PREFIX}{lane}"


def stripe_map_key(lane: str) -> str:
    return f"{STRIPE_MAP_PREFIX}{lane}"


def stripe_of(idx: int, width: int = DEFAULT_STRIPE_WIDTH) -> int:
    """The stripe a request belongs to: its slot index modulo the
    stripe width.  Deterministic, uniform, and derived from the one
    thing every drain already holds for every candidate row."""
    return int(idx) % max(1, int(width))


def replica_stats_key(base: str, replica: int = 0) -> str:
    """Replica r's heartbeat/trace key: the canonical `base` for
    replica 0, `base.rN` for N > 0."""
    r = int(replica)
    return base if r <= 0 else f"{base}{REPLICA_SUFFIX}{r}"


def parse_replica_key(key: str, base: str) -> int | None:
    """Inverse of replica_stats_key: the replica index, or None when
    `key` is not a replica key of `base`."""
    if key == base:
        return 0
    pfx = base + REPLICA_SUFFIX
    if not key.startswith(pfx):
        return None
    try:
        r = int(key[len(pfx):])
    except ValueError:
        return None
    return r if r > 0 else None


def replica_heartbeat_map(store, bases: Sequence[str]
                          ) -> dict[str, list[tuple[int, str]]]:
    """Discover every lane's heartbeat keys in ONE debug-label
    enumeration: {base: [(replica, key), ...]} sorted by replica,
    each list always starting with (0, base).  Suffixed keys are
    found through the bloom prefilter (every heartbeat is
    LBL_DEBUG-labeled), never a per-base key walk — a multi-lane
    render (`spt top` frame, `spt metrics`, a telemetry tick) pays
    one scan, and a scaled lane's extra replicas appear in every
    reader automatically."""
    found: dict[str, dict[int, str]] = {b: {0: b} for b in bases}
    try:
        keys = store.enumerate_keys(LBL_DEBUG)
    except (KeyError, OSError):
        keys = []
    for k in keys:
        for b in bases:
            r = parse_replica_key(k, b)
            if r:
                found[b][r] = k
                break
    return {b: sorted(m.items()) for b, m in found.items()}


def replica_heartbeat_keys(store, base: str) -> list[tuple[int, str]]:
    """One lane's heartbeat keys: [(replica, key), ...] — the
    single-base view of replica_heartbeat_map."""
    return replica_heartbeat_map(store, (base,))[base]


def default_stripe_owners(replicas: Sequence[int] | int,
                          width: int = DEFAULT_STRIPE_WIDTH
                          ) -> dict[int, list[int]]:
    """Round-robin the stripes over the given replica ids (or over
    0..R-1 for an int): every stripe owned, ownership disjoint."""
    ids = (list(range(replicas)) if isinstance(replicas, int)
           else sorted(set(int(r) for r in replicas)))
    if not ids:
        return {}
    out: dict[int, list[int]] = {r: [] for r in ids}
    for s in range(max(1, int(width))):
        out[ids[s % len(ids)]].append(s)
    return out


def read_stripe_map(store, lane: str) -> dict | None:
    """The lane's live stripe map, or None (no map = the single-
    replica deployment: replica 0 owns everything).  Shape:
    {"v": 1, "epoch": E, "width": W,
     "owners": {"<replica>": [stripe, ...]}, "closed": [stripe, ...],
     "pending": {"<replica>": [stripe, ...]}}
    `pending` lists the planned shares of replicas mid scale-up
    handoff: those replicas own NOTHING yet (the incumbents keep
    serving the planned stripes until the promotion write), but they
    are NOT retired — the retire signal is being in neither `owners`
    nor `pending`."""
    try:
        rec = json.loads(store.get(stripe_map_key(lane)).rstrip(b"\0"))
    except (KeyError, OSError, ValueError):
        return None
    if not isinstance(rec, dict) or rec.get("v") != 1:
        return None
    return rec


def write_stripe_map(store, lane: str,
                     owners: dict[int, list[int]], *,
                     width: int = DEFAULT_STRIPE_WIDTH,
                     closed: Sequence[int] = (),
                     pending: dict[int, Sequence[int]]
                     | None = None) -> int:
    """Commit a re-stripe: ONE epoch-bumped table write in-flight
    replicas pick up at their next drain.  Returns the new epoch.
    Never raises — a failed write leaves the previous map standing
    (still a consistent, fully-owned assignment)."""
    prev = read_stripe_map(store, lane)
    epoch = int(prev.get("epoch", 0)) + 1 if prev else 1
    rec = {"v": 1, "epoch": epoch, "width": max(1, int(width)),
           "owners": {str(int(r)): sorted(int(s) for s in ss)
                      for r, ss in owners.items()},
           "closed": sorted(int(s) for s in closed),
           "ts": time.time()}
    if pending:
        rec["pending"] = {str(int(r)): sorted(int(s) for s in ss)
                          for r, ss in pending.items() if ss}
    try:
        store.set(stripe_map_key(lane), json.dumps(rec))
    except (KeyError, OSError):
        return int(prev.get("epoch", 0)) if prev else 0
    return epoch


def clear_stripe_map(store, lane: str) -> None:
    """Drop the lane back to the single-replica default (replica 0
    owns everything).  Never raises."""
    try:
        store.unset(stripe_map_key(lane))
    except (KeyError, OSError):
        pass


class StripeView:
    """A replica's cached view of its lane's stripe map — the one
    stripe-filter every drain shares.  refresh() re-reads the map (a
    drain-entry call: the map is one tiny JSON key, and picking up a
    re-stripe at the NEXT drain is exactly the handoff contract);
    owns(idx) is the candidate filter; `retired` goes True when a
    live map assigns this replica nothing (the supervisor's scale-
    down signal — the replica finishes in-flight work and exits).

    With NO map in the store, replica 0 owns every stripe (the
    pre-elastic single-process deployment, byte-identical behavior)
    and a replica > 0 owns NOTHING — a mis-started extra replica
    without a map must never double-serve."""

    def __init__(self, store, lane: str, replica: int = 0):
        self.store = store
        self.lane = lane
        self.replica = int(replica)
        self.epoch = 0
        self.width = DEFAULT_STRIPE_WIDTH
        self._stripes: frozenset[int] | None = (
            None if self.replica == 0 else frozenset())
        self._have_map = False
        self._pending = False         # scale-up handoff in progress

    def refresh(self) -> None:
        rec = read_stripe_map(self.store, self.lane)
        if rec is None:
            self._have_map = False
            self.epoch = 0
            self.width = DEFAULT_STRIPE_WIDTH
            self._stripes = (None if self.replica == 0
                             else frozenset())
            self._pending = False
            return
        self._have_map = True
        self.epoch = int(rec.get("epoch", 0))
        self.width = max(1, int(rec.get("width",
                                        DEFAULT_STRIPE_WIDTH)))
        owners = rec.get("owners")
        mine = () if not isinstance(owners, dict) else \
            owners.get(str(self.replica), ())
        self._stripes = frozenset(int(s) for s in mine)
        pend = rec.get("pending")
        self._pending = bool(
            isinstance(pend, dict)
            and pend.get(str(self.replica)))

    def owns(self, idx: int) -> bool:
        if self._stripes is None:
            return True
        return stripe_of(idx, self.width) in self._stripes

    @property
    def retired(self) -> bool:
        """True when a live stripe map lists this replica NEITHER as
        an owner NOR as pending — the drain signal: stop claiming,
        finish in-flight, exit.  A PENDING replica (scale-up handoff:
        its share parks closed until the supervisor sees its first
        heartbeat) owns nothing yet but is absolutely not retired.
        Replica 0 never retires (it is the canonical replica the
        liveness probes read)."""
        return (self.replica > 0 and self._have_map
                and not self._stripes and not self._pending)

    def poll_retired(self) -> bool:
        """Force-refresh, then answer `retired` — the run loops'
        heartbeat-cadence check."""
        self.refresh()
        return self.retired

    def snapshot(self) -> dict:
        """The heartbeat's `stripe` section."""
        return {"replica": self.replica, "epoch": self.epoch,
                "width": self.width,
                "stripes": (-1 if self._stripes is None
                            else len(self._stripes))}


def scale_target_key(lane: str) -> str:
    return f"{SCALE_TARGET_PREFIX}{lane}"


def read_scale_target(store, lane: str) -> dict | None:
    """One lane's desired replica count: {"r": N, "src":
    "auto"|"manual", "ts": ...}, or None."""
    try:
        rec = json.loads(
            store.get(scale_target_key(lane)).rstrip(b"\0"))
    except (KeyError, OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) and "r" in rec else None


def read_scale_targets(store) -> dict[str, dict]:
    """Every lane's desired replica count: {lane: {"r": N, "src":
    "auto"|"manual", "ts": ...}}.  Written by the autoscaler and
    `spt scale set` (one PER-LANE key each — no shared-map
    read-modify-write to race), applied by the supervisor."""
    out: dict[str, dict] = {}
    try:
        keys = [k for k in store.list()
                if k.startswith(SCALE_TARGET_PREFIX)]
    except (KeyError, OSError):
        return out
    for k in keys:
        lane = k[len(SCALE_TARGET_PREFIX):]
        rec = read_scale_target(store, lane)
        if rec is not None:
            out[lane] = rec
    return out


def write_scale_target(store, lane: str, r: int | None, *,
                       src: str = "manual") -> None:
    """Set (or with r=None clear) one lane's desired replica count —
    one whole-key write to the lane's OWN target key, so concurrent
    writers of different lanes can never lose each other's entries.
    A "manual" entry is a HOLD: the autoscaler leaves that lane alone
    until `spt scale set <lane>=auto` clears it.  Never raises."""
    try:
        if r is None:
            store.unset(scale_target_key(lane))
        else:
            store.set(scale_target_key(lane), json.dumps(
                {"v": 1, "r": max(1, int(r)), "src": src,
                 "ts": round(time.time(), 3)}))
    except (KeyError, OSError):
        pass


def read_scale_policy(store) -> dict | None:
    """The supervisor-published scaling policy: {"lanes": {lane:
    {"min": m, "max": M}}, "interval_s": ..., "up_threshold": ...,
    "down_threshold": ..., "cooldown_s": ...}."""
    try:
        rec = json.loads(store.get(KEY_SCALE_POLICY).rstrip(b"\0"))
    except (KeyError, OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


_trace_counter = itertools.count(1)


def next_trace_id() -> int:
    return (os.getpid() << 24) | (next(_trace_counter) & 0xFFFFFF)


def stamp_trace(store, key: str, *, trace_id: int | None = None,
                parent: int = 0,
                span: int | None = None) -> int | None:
    """Client-side: mark the pending request on `key` for flight
    recording + span capture (best after set+label, before the bump —
    a daemon racing the stamp then can't service the row stampless).
    Bare `stamp_trace(store, key)` starts a NEW trace (span id ==
    trace id, the root); passing `trace_id` (+ `parent`) joins an
    existing one — the chained-hop form every client verb and the
    pipeline lane's verbs use.  Returns the SPAN id assigned to this
    request (== the trace id for a root stamp), or None when the
    stamp could not land (tracing must never fail a request).

    LBL_TRACED on the request key is the cheap discovery signal: the
    daemon's candidate filter already reads every row's label word, so
    untraced rows cost one bit-test — never a stamp-key lookup.  The
    stamp embeds the row's CURRENT epoch: a daemon finding a stamp
    whose epoch doesn't match the request it gathered discards it as
    stale (a leftover from a request serviced before the stamp
    landed, or from a pre-tracing daemon run) instead of attributing
    it — and its seconds-old wall clock — to the wrong request."""
    try:
        idx = store.find_index(key)
        if trace_id is None:
            tid = next_trace_id()
            span = tid if span is None else span
        else:
            tid = int(trace_id)
            span = next_trace_id() if span is None else span
        sk = trace_stamp_key(idx)
        store.set(sk, f"{tid}:{time.time():.6f}:{store.epoch_at(idx)}"
                      f":{int(parent)}:{int(span)}")
        store.label_or(sk, LBL_DEBUG)
        store.label_or(key, LBL_TRACED)
        return span
    except (KeyError, OSError):
        return None


def stamp_trace_ctx(store, key: str, trace) -> int | None:
    """Normalize the client verbs' `trace=` argument into a stamp:
    `True` starts a fresh root trace; an int trace id stamps a hop of
    that trace parented on its root; a `(trace_id, parent_span)`
    tuple places the hop explicitly (the pipeline lane's verbs and
    chained client calls use this).  Returns the hop's span id (or
    None — tracing never fails a request)."""
    if not trace:
        return None
    if trace is True:
        return stamp_trace(store, key)
    if isinstance(trace, tuple):
        return stamp_trace(store, key, trace_id=trace[0],
                           parent=trace[1])
    return stamp_trace(store, key, trace_id=int(trace),
                       parent=int(trace))


def read_trace_ctx(store, idx: int, epoch: int | None = None
                   ) -> tuple[int, float, int, int] | None:
    """Daemon-side: (trace_id, client_wall_ts, parent_span, span_id)
    for slot idx, or None.  With `epoch` given (the gathered
    request's epoch), a stamp from a DIFFERENT epoch is stale: it is
    consumed (cleared, label too) and None is returned, so it can
    never corrupt a later request's record.  Legacy 3-field stamps
    read as parent=0, span=trace_id."""
    try:
        raw = store.get(trace_stamp_key(idx)).rstrip(b"\0").decode()
        parts = raw.split(":")
        tid = int(parts[0])
        ts = float(parts[1]) if len(parts) > 1 and parts[1] else 0.0
        e_stamp = int(parts[2]) if len(parts) > 2 and parts[2] else None
        parent = int(parts[3]) if len(parts) > 3 and parts[3] else 0
        span = int(parts[4]) if len(parts) > 4 and parts[4] else tid
    except (KeyError, OSError, ValueError, IndexError):
        return None
    if epoch is not None and e_stamp is not None and e_stamp != epoch:
        clear_trace_stamp(store, idx)         # stale: consume, never
        try:                                  # attribute to this row —
            key = store.key_at(idx)           # and retire the phantom
            if key is not None:               # LBL_TRACED with it
                store.label_clear(key, LBL_TRACED)
        except (KeyError, OSError):
            pass
        return None
    return tid, ts, parent, span


def read_trace_stamp(store, idx: int,
                     epoch: int | None = None) -> tuple[int, float] | None:
    """Legacy 2-field view of read_trace_ctx: (trace_id, wall_ts)."""
    ctx = read_trace_ctx(store, idx, epoch=epoch)
    return None if ctx is None else (ctx[0], ctx[1])


def clear_trace_stamp(store, idx: int) -> None:
    try:
        store.unset(trace_stamp_key(idx))
    except (KeyError, OSError):
        pass


def consume_trace_stamp(store, idx: int,
                        epoch: int | None = None
                        ) -> tuple[int, float] | None:
    """Read AND retire slot idx's trace stamp (companion key +
    LBL_TRACED on the slot's key) — the one consume sequence both
    daemons share, run while the slot still belongs to the gathered
    request (by drain end it may hold a NEW request's fresh stamp).
    Returns (trace_id, client_wall_ts) when the stamp matches `epoch`
    (or no epoch given), else None.  Never raises: tracing must never
    fail a request — a contended slot (Eagain) keeps its stamp one
    more drain."""
    stamp = read_trace_stamp(store, idx, epoch=epoch)
    try:
        clear_trace_stamp(store, idx)
        key = store.key_at(idx)
        if key is not None:
            store.label_clear(key, LBL_TRACED)
    except (KeyError, OSError):
        pass
    return stamp


# --- request deadlines ----------------------------------------------------
# A client with a latency budget stamps an ABSOLUTE wall-clock deadline
# next to its request (after set + label, before the bump — the trace
# stamp discipline): "<deadline_ts>:<slot_epoch>" in the slot-indexed
# companion key deadline_key(idx), flagged by LBL_DEADLINE on the
# request key so unstamped rows cost one bit-test, never a lookup.
# The servicing daemon fails an already-expired request fast (an error
# record / diagnostic instead of a batch slot) and consumes the stamp;
# the epoch field makes stamps self-invalidating exactly like trace
# stamps.  Search requests may alternatively carry {"deadline": ts}
# in their request JSON — the searcher honors either.
DEADLINE_STAMP_PREFIX = "__dl_"


def deadline_key(idx: int) -> str:
    return f"{DEADLINE_STAMP_PREFIX}{idx}"


def stamp_deadline(store, key: str, deadline_ts: float) -> bool:
    """Client-side: attach an absolute wall-clock deadline (seconds
    since the epoch) to the pending request on `key`.  Returns True if
    the stamp landed; never raises (a deadline must never fail the
    request it guards)."""
    try:
        idx = store.find_index(key)
        dk = deadline_key(idx)
        store.set(dk, f"{float(deadline_ts):.6f}:{store.epoch_at(idx)}")
        store.label_or(dk, LBL_DEBUG)
        store.label_or(key, LBL_DEADLINE)
        return True
    except (KeyError, OSError, ValueError):
        return False


def read_deadline(store, idx: int,
                  epoch: int | None = None) -> float | None:
    """Daemon-side: the absolute deadline for slot idx, or None.  With
    `epoch` given (the gathered request's epoch), a stamp from a
    different epoch is stale: consumed, and None returned."""
    try:
        raw = store.get(deadline_key(idx)).rstrip(b"\0").decode()
        parts = raw.split(":")
        ts = float(parts[0])
        e_stamp = int(parts[1]) if len(parts) > 1 and parts[1] else None
    except (KeyError, OSError, ValueError, IndexError):
        return None
    if epoch is not None and e_stamp is not None and e_stamp != epoch:
        clear_deadline(store, idx)            # stale: consume, never
        return None                           # bound the wrong request
    return ts


def clear_deadline(store, idx: int) -> None:
    """Retire slot idx's deadline stamp (companion key + LBL_DEADLINE
    on the slot's key).  Never raises."""
    try:
        store.unset(deadline_key(idx))
    except (KeyError, OSError):
        pass
    try:
        key = store.key_at(idx)
        if key is not None:
            store.label_clear(key, LBL_DEADLINE)
    except (KeyError, OSError):
        pass


def consume_deadline(store, idx: int,
                     epoch: int | None = None) -> float | None:
    """Read AND retire slot idx's deadline stamp — run while the slot
    still belongs to the gathered request."""
    ts = read_deadline(store, idx, epoch=epoch)
    clear_deadline(store, idx)
    return ts


# --- typed overload / expiry records --------------------------------------
# The shed contract: a saturated lane past its high-water mark fails
# overflow with THIS record instead of queueing unboundedly or
# silently dropping — clients (engine/client.py retry wrapper) honor
# the retry_after_ms hint.  Search results carry it as the __sr_ JSON
# row; the completer writes it as the slot's value (READY-flipped);
# the embedder has no value channel to spare (the slot holds the
# client's text), so its shed unblocks the client label-only and the
# counters tell the story.
ERR_OVERLOADED = "overloaded"
ERR_DEADLINE = "deadline_expired"


def overloaded_record(retry_after_ms: int) -> dict:
    return {"err": ERR_OVERLOADED,
            "retry_after_ms": int(retry_after_ms)}


def overloaded_payload(retry_after_ms: int) -> bytes:
    """The completer-lane shed value: a typed JSON body a client (or
    the shared retry wrapper) can parse for the retry hint."""
    return json.dumps(overloaded_record(retry_after_ms)).encode()


def parse_error_payload(raw: bytes | str) -> dict | None:
    """{"err": ..., ...} if `raw` is one of the typed error payloads
    above, else None (a normal completion body)."""
    if isinstance(raw, bytes):
        raw = raw.rstrip(b"\0")
        if not raw.startswith(b"{"):
            return None
        try:
            raw = raw.decode()
        except UnicodeDecodeError:
            return None
    elif not raw.startswith("{"):
        return None
    try:
        rec = json.loads(raw)
    except ValueError:
        return None
    if isinstance(rec, dict) and isinstance(rec.get("err"), str):
        return rec
    return None


DEADLINE_EXPIRED_DIAGNOSTIC = json.dumps(
    {"err": ERR_DEADLINE}).encode()


def publish_heartbeat(store, key: str, payload: dict) -> None:
    """Write a timestamped JSON stats snapshot into a debug-labeled
    key.  Telemetry must never wedge serving: a concurrently deleted
    key (KeyError) or a failed store op (OSError) is swallowed — but a
    snapshot too big for the store's max_val degrades SECTION BY
    SECTION (largest optional dict/list dropped first, marked
    truncated) so whatever telemetry fits still lands, instead of
    all-or-nothing removal the moment tracing is enabled.

    Every heartbeat carries the publisher's pid: liveness probes
    (heartbeat_live) kill-0 it, so a crashed daemon reads as dead the
    moment it dies instead of after max_age_s of heartbeat decay."""
    rec = {"ts": time.time(), "pid": os.getpid(), **payload}
    for _ in range(2 + len(payload)):
        try:
            store.set(key, json.dumps(rec))
            store.label_or(key, LBL_DEBUG)
            return
        except KeyError:
            return
        except OSError:
            sections = [k for k, v in rec.items()
                        if isinstance(v, (dict, list))]
            if not sections:
                return
            rec.pop(max(sections, key=lambda k: len(json.dumps(rec[k]))))
            rec["truncated"] = True


def bump_generation(store, heartbeat_key: str) -> int:
    """Monotonic per-lane start counter, bumped at daemon attach() and
    carried in every heartbeat: two snapshots with different
    generations bracket a restart even when the pid was recycled.
    Stored as a BIGUINT companion key (<heartbeat_key>_gen) so it
    survives the daemon that bumped it.  Never raises — a full store
    must not stop a daemon from starting (generation 0 = unknown)."""
    gk = heartbeat_key + "_gen"
    try:
        if gk not in store:
            store.set_uint(gk, 0)
        return int(store.integer_op(gk, N.IOP_INC))
    except (KeyError, OSError, ValueError):
        return 0


def pid_alive(pid: int) -> bool:
    """Same-host liveness probe: kill-0.  EPERM means alive under
    another uid; any lookup failure means gone."""
    if not pid or pid < 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True
    return True


def heartbeat_live(store, key: str, *, max_age_s: float = 15.0,
                   lane: str | None = None) -> bool:
    """THE daemon-liveness probe: a heartbeat counts as live when its
    ts is fresh AND its publisher pid still exists AND (with `lane`
    given) the supervisor has not marked the lane down.

    The pid probe is the staleness fix: a daemon that crashed one
    second after publishing used to read as live for max_age_s more
    seconds, costing every client its full submit timeout before the
    local fallback; kill-0 makes the fallback instant.  Heartbeats
    published before the pid field existed (no "pid" key) fall back to
    age-only — never treat an old-format heartbeat as dead."""
    if lane is not None and lane_down(store, lane):
        return False
    try:
        snap = json.loads(store.get(key).rstrip(b"\0"))
        ts = float(snap.get("ts", 0.0))
    except (KeyError, OSError, ValueError, AttributeError, TypeError):
        return False
    pid = snap.get("pid")
    if isinstance(pid, int) and not pid_alive(pid):
        return False
    return (time.time() - ts) < max_age_s


def lane_down(store, lane: str, *, max_age_s: float = 15.0) -> bool:
    """True when a FRESH supervisor heartbeat marks `lane` down (its
    circuit breaker is open).  Clients skip dispatch to a down lane
    instead of burning their submit timeout against a crash loop.  A
    stale or missing supervisor snapshot never vetoes a lane — an
    unsupervised deployment must behave exactly as before."""
    try:
        snap = json.loads(
            store.get(KEY_SUPERVISOR_STATS).rstrip(b"\0"))
    except (KeyError, OSError, ValueError, AttributeError):
        return False
    try:
        if (time.time() - float(snap.get("ts", 0.0))) >= max_age_s:
            return False
        info = snap.get("lanes", {}).get(lane)
        return bool(info) and info.get("state") == "down"
    except (TypeError, AttributeError):
        return False


# labels that mean "a daemon will still service (and consume the
# stamp of) this row" — a TRACED row carrying none of them is an
# orphan whose stamp landed after its request was serviced.
# DECODE_READY counts: a handed-off row is still pending decode-lane
# service, so its stamps must survive the prefill->decode gap.
_REQ_LABELS = (LBL_EMBED_REQ | LBL_INFER_REQ | LBL_SERVICING
               | LBL_SEARCH_REQ | LBL_SCRIPT_REQ | LBL_DECODE_READY)


def clear_span_stage(store, idx: int) -> None:
    """Retire slot idx's pending-span staging row.  Never raises."""
    try:
        store.unset(span_stage_key(idx))
    except (KeyError, OSError):
        pass


def _span_stage_orphaned(store, tgt: int) -> bool:
    """True when the staging row for slot `tgt` no longer belongs to
    a pending request: the slot is gone, its epoch moved past the one
    the span was staged under (a raced rewrite — the NEW occupant
    will stage its own), or no daemon will ever commit it (no request
    labels left).  Staging wire form (obs/spans.py):
    "tid:span:parent:epoch:attempts:t_queue:gap_ms:ts"."""
    try:
        raw = store.get(span_stage_key(tgt)).rstrip(b"\0").decode()
        e = int(raw.split(":")[3])
    except (KeyError, OSError, ValueError, IndexError,
            UnicodeDecodeError):
        return True                   # unreadable staging: retire
    if tgt >= store.nslots or store.key_at(tgt) is None:
        return True
    if store.epoch_at(tgt) != e:
        return True
    return not store.labels_at(tgt) & _REQ_LABELS


def shed_orphan_stamp(store, idx: int, labels: int) -> bool:
    """Retire a trace stamp whose request is no longer pending, so a
    stamp that landed AFTER its request was serviced — with no
    follow-up request ever arriving — cannot leak its __tr_<idx> slot
    and LBL_TRACED forever.  Daemons call this from their discard
    path for rows that carry TRACED or DEBUG labels; handles the
    stamped row itself, a freshly-written stamp slot (__tr_<n>)
    surfacing through the dirty mask, and an orphaned pending-span
    staging row (__sp_<n> whose request slot epoch moved or whose
    labels cleared without a span commit — the raced-rewrite leak).
    Returns True if something was shed."""
    shed = False
    if labels & LBL_TRACED and not labels & _REQ_LABELS:
        consume_trace_stamp(store, idx)
        clear_span_stage(store, idx)
        shed = True
    if labels & LBL_DEADLINE and not labels & _REQ_LABELS:
        clear_deadline(store, idx)
        shed = True
    if shed:
        return True
    if labels & LBL_DEBUG:
        try:
            key = store.key_at(idx)
        except (KeyError, OSError):
            return False
        for pfx, retire in ((TRACE_STAMP_PREFIX, consume_trace_stamp),
                            (DEADLINE_STAMP_PREFIX, clear_deadline)):
            if key and key.startswith(pfx):
                try:
                    tgt = int(key[len(pfx):])
                    tl = store.labels_at(tgt)
                except (ValueError, KeyError, OSError):
                    return False
                flag = LBL_TRACED if pfx == TRACE_STAMP_PREFIX \
                    else LBL_DEADLINE
                if tl & flag and not tl & _REQ_LABELS:
                    retire(store, tgt)
                    return True
        if key and key.startswith(SPAN_STAGE_PREFIX):
            try:
                tgt = int(key[len(SPAN_STAGE_PREFIX):])
            except ValueError:
                return False
            if _span_stage_orphaned(store, tgt):
                clear_span_stage(store, tgt)
                return True
    return False


def attach_trace_sections(payload: dict, tracer, recorder,
                          prefix: str) -> None:
    """Assemble the tracing heartbeat sections in place — ONE
    definition both daemons share, so the section contract (legacy-
    shaped spans, stage quantiles under `prefix`, recorder
    accounting, slow log) cannot diverge between them."""
    # one snapshot feeds both sections: spans keeps the LEGACY
    # aggregate shape only, quantiles carries the full histogram
    # summaries under the pinned stage names — both full would double
    # the payload for zero extra information (publish_heartbeat
    # degrades by size when max_val bites)
    snap = tracer.snapshot()
    payload["spans"] = {
        k: {f: v[f] for f in ("n", "total_ms", "max_ms") if f in v}
        for k, v in snap.items()}
    payload["quantiles"] = {k[len(prefix):]: v
                            for k, v in snap.items()
                            if k.startswith(prefix)}
    payload["recorder"] = recorder.counters()
    slow = recorder.slow_log()
    if slow:
        payload["slow_log"] = slow


def maybe_publish_trace_ring(store, key: str, recorder,
                             last_published: int) -> int:
    """Publish the flight-recorder ring iff new records arrived since
    `last_published` (an identical ring per heartbeat would be pure
    serialization waste).  Returns the new published count."""
    if recorder.recorded != last_published:
        publish_trace_ring(store, key, recorder)
    return recorder.recorded


def publish_trace_ring(store, key: str, recorder, n: int = 32) -> None:
    """Publish a flight recorder's tail into a debug-labeled key.
    Unlike publish_heartbeat's section-by-section degradation — which
    would drop this payload's ONLY section and leave `spt trace tail`
    empty exactly when there is data — an oversized ring halves its
    tail count until it fits: fewer reconstructable requests beat
    none."""
    while n >= 1:
        rec = {"ts": time.time(), "trace": recorder.tail(n)}
        try:
            store.set(key, json.dumps(rec))
            store.label_or(key, LBL_DEBUG)
            return
        except KeyError:
            return
        except OSError:
            n //= 2


CTX_EXCEEDED_DIAGNOSTIC = b"[context exceeded: input too long for model]"
