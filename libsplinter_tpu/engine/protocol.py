"""The coordination contract between clients and the inference daemons.

Mirrors the reference's label state machine and well-known keys
(splinterrc_example:83-85, splinter.h:477-491, splinference.cpp:50-89,
splainference.cpp:51-109; SURVEY.md §2.2) so a client written against the
reference's conventions finds identical behavior here.
"""
import json
import time

# --- bloom labels (bit masks) -------------------------------------------
LBL_EMBED_REQ = 0x1            # "embed me" — wakes the embedding daemon
LBL_WAITING = 0x40             # client is blocked on this key
LBL_CTX_EXCEEDED = 0x80        # input exceeded the model context window
LBL_CHUNK = 0x200              # ingest: document chunk
LBL_META = 0x400               # ingest: metadata slot
LBL_DEBUG = 0x1 << 59          # debug channel (sidecar watches this)
LBL_INFER_REQ = 0x1 << 60      # "complete me" — wakes the completion daemon
LBL_SERVICING = 0x1 << 61      # completion in progress
LBL_READY = 0x1 << 62          # completion finished

# --- bloom bit indices (for watch_label_register) -----------------------
BIT_EMBED_REQ = 0
BIT_WAITING = 6
BIT_CTX_EXCEEDED = 7
BIT_DEBUG = 59
BIT_INFER_REQ = 60

# --- signal groups -------------------------------------------------------
GROUP_EMBED = 2                # embedding daemon wake group
GROUP_INFER = 3                # completion daemon wake group
GROUP_DEBUG = 63               # sidecar debug group

# --- shard ids / priorities (cooperative advisement) --------------------
SHARD_EMBED = 0x5F10
SHARD_COMPLETE = 0x5F1A
PRIO_EMBED_LIVE = 40
PRIO_EMBED_BACKFILL = 20
PRIO_COMPLETE = 200

# --- well-known keys -----------------------------------------------------
KEY_DONE_LANE = "__lane_dw_2"  # pulsed after each committed embedding
KEY_DEBUG = "__debug"          # append-only shared debug log
KEY_SYSTEM_PROMPT = "__system_prompt"
# periodic daemon heartbeats: JSON stats snapshots, debug-labeled so
# the sidecar's group-63 watch surfaces them (the reference's only
# runtime telemetry is the __debug append channel; these are the
# structured counterpart)
KEY_EMBED_STATS = "__embedder_stats"
KEY_COMPLETE_STATS = "__completer_stats"
SEARCH_SCRATCH_PREFIX = "__sqtmp_"   # search query scratch key per pid

# context guard: reject inputs >= this fraction of the model window
CTX_GUARD_FRACTION = 0.9

# --- commit-pipeline stage contract --------------------------------------
# The wake->commit path decomposes into these stages; every stats
# surface (the embedder heartbeat's "pipeline" section, bench's
# p50_stage_means) uses these names so dashboards and before/after
# comparisons line up.  device_wait is the time the host BLOCKED on a
# device future; overlapped device time (future in flight while the
# host staged the next batch) is reported separately as overlap_ms /
# overlap_ratio, not as a stage — it costs no wake-path wall time.
PIPELINE_STAGES = ("drain", "tokenize", "dispatch", "device_wait",
                   "commit")

# latency-probe short-circuit: drains at or below this many candidate
# rows skip the windowed big-batch machinery and dispatch immediately
# on the pre-compiled small-bucket programs (Embedder.probe_batch_max
# overrides per instance)
PROBE_BATCH_MAX_DEFAULT = 8


def publish_heartbeat(store, key: str, payload: dict) -> None:
    """Write a timestamped JSON stats snapshot into a debug-labeled
    key.  Telemetry must never wedge serving: a concurrently deleted
    key (KeyError) or a failed store op (OSError) is swallowed — but a
    snapshot too big for the store's max_val degrades SECTION BY
    SECTION (largest optional dict/list dropped first, marked
    truncated) so whatever telemetry fits still lands, instead of
    all-or-nothing removal the moment tracing is enabled."""
    rec = {"ts": time.time(), **payload}
    for _ in range(2 + len(payload)):
        try:
            store.set(key, json.dumps(rec))
            store.label_or(key, LBL_DEBUG)
            return
        except KeyError:
            return
        except OSError:
            sections = [k for k, v in rec.items()
                        if isinstance(v, (dict, list))]
            if not sections:
                return
            rec.pop(max(sections, key=lambda k: len(json.dumps(rec[k]))))
            rec["truncated"] = True
CTX_EXCEEDED_DIAGNOSTIC = b"[context exceeded: input too long for model]"
