"""Resident device loops + K-deep dispatch overlap — the common
machinery that breaks the per-drain runtime dispatch floor.

BENCH_r05 attributed 62 of the 67.2 ms p50 set->vector to the per-call
XLA runtime round trip (null_dispatch_ms ~ 63 ms through the tunneled
runtime), not to this stack.  One dispatch per drain therefore floors
EVERY hot-lane latency at ~63 ms regardless of how fast the kernels
get.  Two complementary mechanisms amortize it, both defined here so
the three lane daemons share one contract:

  ResidentRing / RingResult — a **resident multi-batch device
    program**: the host pre-stages up to ring_depth same-shape batches
    into one (depth, B, S) ring, and a single dispatch runs a
    lax.while_loop over the occupied slots (the occupancy is a scalar
    OPERAND, so one compiled program serves every occupancy
    1..depth with no recompiles and no wasted compute on empty
    slots).  The whole ring's results come back in ONE transfer and
    slot views split host-side — per-drain dispatch cost amortizes to
    ~63/occupancy ms.  Output ring buffers are DONATED and recycled
    through a small pool (RingResult.materialize_host returns the
    buffer after the host copy lands), so steady-state ring serving
    allocates nothing.  The embedder's bucketed encode programs are
    the primary user (models/encoder.encode_ring_async).

  InflightWindow — **K-deep in-flight dispatch overlap** for lanes
    where one fused program is impractical (the searcher's QB-bucketed
    top-k drains, the completer's sequential paged decode chunks):
    hold up to `depth` un-awaited dispatches and resolve them in
    COMPLETION order — the host stages/dispatches work k+1..k+K while
    the device computes k, and only blocks when the window is full
    with nothing ready.  Generalizes PR 1's CommitPipeline (which now
    subclasses it); the floor amortizes to ~63/K ms per dispatch.

Fault sites (SPTPU_FAULT; docs/operations.md catalog):
  resident.ring_dispatch   before a ring program dispatch
  resident.ring_collect    before the whole-ring host fetch
"""
from __future__ import annotations

from collections import deque

import numpy as np

from ..utils.faults import fault


def pending_ready(obj) -> bool:
    """True when forcing `obj` will not block: host values are always
    ready; device futures answer is_ready(); containers are ready when
    every leaf is.  Unknown future types claim in-flight so callers
    account the force as a (possibly) blocking wait — the
    PendingEmbeddings.is_ready contract, generalized."""
    if obj is None or isinstance(obj, np.ndarray):
        return True
    if isinstance(obj, (list, tuple)):
        return all(pending_ready(o) for o in obj)
    probe = getattr(obj, "is_ready", None)
    if probe is None:
        return True                    # host value (scalar, bytes, ...)
    try:
        return bool(probe())
    except Exception:
        return False


class InflightWindow:
    """Hold up to `depth` un-awaited dispatches; resolve in COMPLETION
    order.  The skeleton every overlap consumer shares: push() enqueues
    an entry, immediately resolves whatever is already complete, and
    force-resolves the oldest only when the window overflows —
    back-pressure, never a synchronous round trip per dispatch.

    Subclasses implement _entry_ready(entry) and _resolve(entry);
    CommitPipeline (engine/embedder.py) is the original instance,
    CallbackWindow below the generic one."""

    def __init__(self, depth: int):
        self.depth = max(1, depth)
        self._q: deque = deque()
        self.dispatched = 0
        self.inflight_peak = 0       # max un-resolved depth seen

    def __len__(self) -> int:
        return len(self._q)

    def push_entry(self, entry) -> None:
        self._q.append(entry)
        self.dispatched += 1
        self.inflight_peak = max(self.inflight_peak, len(self._q))
        self.drain_ready()
        while len(self._q) > self.depth:
            self._resolve(self._q.popleft())

    def drain_ready(self) -> int:
        """Resolve every entry that has already completed (in queue
        order among the ready ones); never blocks."""
        done = 0
        if self._q:
            still: deque = deque()
            for entry in self._q:
                if self._entry_ready(entry):
                    self._resolve(entry)
                    done += 1
                else:
                    still.append(entry)
            self._q = still
        return done

    def flush(self) -> None:
        """Resolve everything: ready entries first, then block for the
        rest in dispatch order (the unavoidable tail wait — by now it
        overlapped all the host work done since dispatch)."""
        self.drain_ready()
        while self._q:
            self._resolve(self._q.popleft())

    # -- subclass surface ---------------------------------------------------

    def _entry_ready(self, entry) -> bool:
        raise NotImplementedError

    def _resolve(self, entry) -> None:
        raise NotImplementedError


class CallbackWindow(InflightWindow):
    """The generic InflightWindow: entries are (payload, pending) and a
    resolve callback consumes them in completion order.

        win = CallbackWindow(depth, resolve_fn)
        win.push(batch_meta, device_future)   # dispatch side
        ...
        win.flush()                           # drain tail

    resolve_fn(payload, pending, ready) runs exactly once per entry;
    `ready` says whether the force will block (stats attribution).
    The callback owns its own error containment — a raising resolver
    propagates, matching the caller's failure-domain design (the
    searcher wraps its resolver in the per-batch degradation ladder,
    the completer in abort_all)."""

    def __init__(self, depth: int, resolve_fn):
        super().__init__(depth)
        self._resolve_fn = resolve_fn
        self.ready_resolves = 0
        self.blocking_resolves = 0

    def push(self, payload, pending) -> None:
        self.push_entry((payload, pending))

    def _entry_ready(self, entry) -> bool:
        return pending_ready(entry[1])

    def _resolve(self, entry) -> None:
        payload, pending = entry
        ready = pending_ready(pending)
        if ready:
            self.ready_resolves += 1
        else:
            self.blocking_resolves += 1
        self._resolve_fn(payload, pending, ready)


def _wire_to_f32(out: np.ndarray) -> np.ndarray:
    """Upcast a wire-dtype host array to float32 — the one conversion
    every embedding fetch path shares (int8 is the fixed x127 scale:
    components of an L2-normalized embedding lie in [-1, 1], so no
    per-vector scale row exists to apply)."""
    if out.dtype == np.int8:
        return out.astype(np.float32) * np.float32(1.0 / 127.0)
    return out.astype(np.float32, copy=False)


class RingResult:
    """One resident ring dispatch's result: a (depth, B, ...) device
    array covering up to `depth` pre-staged batches.  The whole ring
    fetches in ONE device->host transfer on first materialize (slot
    views split host-side — a per-slot device fetch would re-pay the
    dispatch floor the ring exists to amortize), after which the
    device buffer is handed back to its donation pool via `release`
    for the next ring dispatch to consume.

    jax's async dispatch means a device-side failure surfaces HERE,
    at the fetch, not at dispatch.  A failed fetch caches its error
    (re-raised per slot — never a silent None deref), does NOT pool
    the possibly-poisoned buffer, and slots fall back through `retry`
    (a per-slot re-encode on the battle-tested per-call programs) when
    the caller provided one — so one transient device error costs a
    re-dispatch, not a failed drain."""

    __slots__ = ("_out", "_host", "_release", "_convert", "_retry",
                 "_err", "_mark", "n_valid")

    def __init__(self, out, n_valid: int, *, release=None,
                 convert=_wire_to_f32, retry=None, mark=None):
        self._out = out
        self._host: np.ndarray | None = None
        self._release = release
        self._convert = convert
        self._retry = retry           # (slot_i, n) -> (n, ...) f32
        self._err: Exception | None = None
        self._mark = mark             # devtime DispatchMark: closed at
        # the fetch — the collect point that already exists, so the
        # device window costs no new host sync
        self.n_valid = n_valid

    def is_ready(self) -> bool:
        if self._host is not None or self._err is not None:
            return True
        return pending_ready(self._out)

    def materialize_host(self) -> np.ndarray:
        """Fetch the whole ring (once), recycle the device buffer."""
        if self._host is None:
            if self._err is not None:
                raise self._err
            fault("resident.ring_collect")
            try:
                host = np.asarray(self._out)
            except Exception as ex:
                # poisoned dispatch: cache for the sibling slots and
                # drop the buffer (re-donating it could re-poison the
                # next ring); the pool re-allocates on demand
                self._err = ex
                self._out = None
                self._release = None
                raise
            self._host = host
            mark, self._mark = self._mark, None
            if mark is not None:
                mark.close()
            out, self._out = self._out, None
            rel, self._release = self._release, None
            if rel is not None:
                rel(out)              # host copy landed: re-donatable
        return self._host

    def slot(self, i: int, n: int) -> "RingSlot":
        """A PendingEmbeddings-contract view of ring slot i's first n
        rows (the rest of the slot is batch padding)."""
        return RingSlot(self, i, n)


class RingSlot:
    """One slot of a RingResult under the pending-future contract
    (is_ready / materialize / n) so per-batch consumers — the
    embedder's CommitPipeline — need not know a ring dispatch from a
    per-call one.  A ring whose fetch failed falls back to the
    parent's per-slot `retry` (when armed) before giving up."""

    __slots__ = ("_ring", "i", "n")

    def __init__(self, ring: RingResult, i: int, n: int):
        self._ring = ring
        self.i = i
        self.n = n

    def is_ready(self) -> bool:
        return self._ring.is_ready()

    def materialize(self) -> np.ndarray:
        try:
            host = self._ring.materialize_host()
        except Exception:
            if self._ring._retry is None:
                raise
            return self._ring._retry(self.i, self.n)
        return self._ring._convert(host[self.i][: self.n])
