"""Per-tenant admission control — the overload-survival policy layer.

Every drain in the serving stack (searcher `_service`, embedder
`process_rows`, completer `run_continuous` admission) faces the same
three decisions when offered load exceeds capacity:

  1. **Deadline expiry**: a request whose client deadline already
     passed can never be useful — fail it fast with an error record
     instead of letting it occupy a batch slot (serving it would burn
     device time producing an answer nobody is waiting for, and the
     queue behind it inherits the wasted wall clock).
  2. **Fairness**: when a lane is saturated, which waiting requests
     get the next drain's capacity?  Enumeration order hands the whole
     lane to whichever tenant floods fastest; weighted fair queueing
     guarantees every tenant its configured share while letting unused
     share flow to the busy ones.
  3. **Shedding**: past a configurable high-water mark the queue stops
     absorbing — overflow is failed with a typed `overloaded` record
     carrying a `retry_after_ms` hint (backpressure, never a wedge:
     PR 5's contract, now with an explicit client-visible signal
     instead of silent deferral into an unbounded backlog).

This module holds the POLICY only: `AdmissionController.plan()` takes
the drain's waiting set and capacity and partitions it into
admit / expired / shed / deferred.  The daemons keep the mechanism
(how to fail, how to defer, how to commit) — so the three lanes cannot
drift apart on what "overloaded" means, and the fairness property is
testable without spinning a daemon at all.

The fairness discipline is stride scheduling (deficit round-robin's
virtual-time formulation): each tenant carries a persistent `pass`
value advanced by 1/weight per ADMITTED request, and a saturated
drain's capacity goes to the lowest-pass requests first.  A tenant
denied this drain keeps its low pass and leads the next one, so
sustained 10:1 offered-load skew still converges to the configured
weight ratio over a few drains instead of depending on any single
drain's arrival order.  A tenant that went idle re-enters at the
current virtual time (no banked priority to monopolize a later drain).

Tenant identity and deadlines ride the wire per engine/protocol.py:
the tenant id lives in the request's bloom label word (TENANT_MASK,
bits 48-51 — daemons already read every candidate's labels, so tenant
discovery is free), the deadline in a `__dl_<idx>` companion key
flagged by LBL_DEADLINE (the LBL_TRACED discovery discipline).
"""
from __future__ import annotations

import dataclasses
import heapq
import time
from typing import Any, Sequence

# default shed hint: long enough that a retrying client skips at least
# one full drain cycle, short enough that a drained lane re-admits the
# retry promptly (clients jitter on top — engine/client.py)
DEFAULT_RETRY_AFTER_MS = 250


def prune_idle_counters(payload: dict, active: bool) -> dict:
    """Drop the all-zero QoS counters from a heartbeat payload when
    QoS is unconfigured and nothing ever tripped them: an untagged
    deployment's heartbeat must not grow (tiny stores degrade
    heartbeats by SIZE — publish_heartbeat — and three dead-zero
    fields could push a previously-fitting payload over max_val)."""
    if not active:
        for k in ("deadline_expired", "shed", "deferred"):
            if not payload.get(k):
                payload.pop(k, None)
    return payload


def parse_tenant_weights(spec: str | None) -> dict[int, float] | None:
    """Parse the daemons' --tenant-weights flag: "1:3,2:1" ->
    {1: 3.0, 2: 1.0}.  Unlisted tenants weigh 1.  A malformed spec
    raises ValueError at startup — a typo must never silently serve
    unweighted."""
    if not spec:
        return None
    out: dict[int, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        t, sep, w = part.partition(":")
        if not sep:
            raise ValueError(
                f"tenant weight {part!r}: expected TENANT:WEIGHT")
        out[int(t)] = float(w)
        if out[int(t)] <= 0:
            raise ValueError(
                f"tenant weight {part!r}: weight must be > 0")
    return out or None


def parse_tenant_quotas(spec: str | None) -> dict[int, int] | None:
    """Parse a per-tenant PAGE-quota flag (`--prefix-quota "1:64,2:8"`
    -> {1: 64, 2: 8}).  Same grammar as the weights flag, integer
    values; unlisted tenants are unbounded.  The quotas bound how
    much of the paged pool a tenant's cached prefixes may squat on
    (engine/prefix_cache.py enforces them at insert, evicting the
    tenant's own zero-ref pages first), and the per-tenant residency
    rides the heartbeat's tenant ledger section as `prefix_pages` —
    so a quota incident is visible in the same `spt metrics` series
    as the admission counters."""
    if not spec:
        return None
    out: dict[int, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        t, sep, q = part.partition(":")
        if not sep:
            raise ValueError(
                f"tenant quota {part!r}: expected TENANT:PAGES")
        out[int(t)] = int(q)
        if out[int(t)] < 0:
            raise ValueError(
                f"tenant quota {part!r}: pages must be >= 0")
    return out or None


@dataclasses.dataclass
class WaitingRow:
    """One waiting request as the admission policy sees it: an opaque
    item (slot index, request object — the daemon's business), the
    tenant that owns it, and its absolute wall-clock deadline (seconds
    since the epoch, None = no deadline)."""

    item: Any
    tenant: int = 0
    deadline: float | None = None


@dataclasses.dataclass
class AdmissionPlan:
    """One drain's admission decision.  The lists partition the input:
    admit (serve now, fairness-ordered), expired (deadline already
    passed — fail fast), shed (past high water — fail with the typed
    overloaded record), deferred (keep waiting; the next drain
    reconsiders them, and stride state makes their tenants lead it)."""

    admit: list[WaitingRow] = dataclasses.field(default_factory=list)
    expired: list[WaitingRow] = dataclasses.field(default_factory=list)
    shed: list[WaitingRow] = dataclasses.field(default_factory=list)
    deferred: list[WaitingRow] = dataclasses.field(default_factory=list)


class TenantLedger:
    """Per-tenant serving counters: admitted / shed / deadline_expired
    / served_tokens.  Rides every daemon heartbeat under a "tenants"
    section (`spt metrics` renders one labeled series per tenant) so
    an operator mid-incident can see WHICH tenant is being shed and
    whether the starved one is still making progress."""

    FIELDS = ("admitted", "shed", "deadline_expired", "served_tokens")

    def __init__(self) -> None:
        self._t: dict[int, dict[str, int]] = {}

    def bump(self, tenant: int, field: str, n: int = 1) -> None:
        row = self._t.setdefault(
            int(tenant), dict.fromkeys(self.FIELDS, 0))
        row[field] = row.get(field, 0) + n

    def snapshot(self) -> dict[str, dict[str, int]]:
        """JSON-ready: tenant ids as strings (heartbeats are JSON)."""
        return {str(t): dict(row) for t, row in sorted(self._t.items())}

    def get(self, tenant: int, field: str) -> int:
        return self._t.get(int(tenant), {}).get(field, 0)


class AdmissionController:
    """Weighted fair admission (stride scheduling) + high-water
    shedding.

    `high_water` bounds the post-admission backlog: after capacity is
    filled, at most high_water further requests stay queued; the rest
    are shed (tail of the fairness order — the flooding tenant's
    excess sheds first).  None disables shedding (deferral only, the
    pre-QoS behavior).  `capacity` <= 0 admits nothing but a wedged
    lane still expires/sheds correctly.
    """

    def __init__(self, *, weights: dict[int, float] | None = None,
                 high_water: int | None = None,
                 retry_after_ms: int = DEFAULT_RETRY_AFTER_MS):
        self.weights = dict(weights or {})
        self.high_water = high_water
        self.retry_after_ms = int(retry_after_ms)
        self._pass: dict[int, float] = {}     # tenant -> virtual time

    def weight(self, tenant: int) -> float:
        w = self.weights.get(int(tenant), 1.0)
        return w if w > 0 else 1.0

    # -- the decision ------------------------------------------------------

    def plan(self, waiting: Sequence[WaitingRow], capacity: int,
             *, now: float | None = None,
             slack_s: float = 0.0) -> AdmissionPlan:
        """slack_s is the PHASE-AWARE deadline horizon: a lane that
        knows admitted work pays an un-cancellable service phase first
        (the disaggregated prefill lane's rolling prefill wall,
        engine/disagg.py) passes that cost here, so a request whose
        deadline lands inside it fast-fails BEFORE paying prefill
        instead of expiring mid-phase.  0.0 is the exact-expiry check
        every existing caller keeps."""
        now = time.time() if now is None else now
        horizon = now + max(0.0, float(slack_s))
        plan = AdmissionPlan()
        live: list[WaitingRow] = []
        for row in waiting:
            if row.deadline is not None and row.deadline <= horizon:
                plan.expired.append(row)
            else:
                live.append(row)

        capacity = max(0, int(capacity))
        order = self._fair_order(live, capacity)
        plan.admit = order[:capacity]
        rest = order[capacity:]
        if self.high_water is not None and rest:
            keep = max(0, int(self.high_water))
            plan.deferred = rest[:keep]
            plan.shed = rest[keep:]
        else:
            plan.deferred = rest
        return plan

    def _fair_order(self, live: list[WaitingRow],
                    capacity: int) -> list[WaitingRow]:
        """Order the waiting rows by stride scheduling over persistent
        per-tenant pass values; commit pass advancement for the
        admitted prefix only (a deferred or shed request consumed no
        share, so its tenant keeps its claim).

        Pass values are stored RELATIVE to the schedule's virtual
        time: after every plan the laggard waiting tenant's position
        rebases to 0 and entries at/below it are dropped, so a tenant
        absent from the map (new, or idle since its entry was
        dropped) re-enters exactly AT the schedule position — an idle
        stretch can neither bank priority (monopolizing on return)
        nor inherit punishment for service rendered while nobody else
        was waiting."""
        queues: dict[int, list[WaitingRow]] = {}
        for row in live:
            queues.setdefault(int(row.tenant), []).append(row)
        if not queues:
            return []
        scratch = {t: max(self._pass.get(t, 0.0), 0.0)
                   for t in queues}
        if len(queues) == 1:
            (t, q), = queues.items()
            self._pass[t] = scratch[t] + (min(len(q), capacity)
                                          / self.weight(t))
            self._rebase(self._pass[t])
            return list(live)
        heap = [(p, t) for t, p in scratch.items()]
        heapq.heapify(heap)
        out: list[WaitingRow] = []
        committed = dict(scratch) if capacity == 0 else None
        while heap:
            p, t = heapq.heappop(heap)
            q = queues[t]
            out.append(q.pop(0))
            scratch[t] = p + 1.0 / self.weight(t)
            if q:
                heapq.heappush(heap, (scratch[t], t))
            if committed is None and len(out) == capacity:
                committed = dict(scratch)     # admitted prefix's cost
        if committed is None:
            committed = scratch
        self._pass.update(committed)
        self._rebase(min(committed[t] for t in queues))
        return out

    def _rebase(self, vt: float) -> None:
        """Advance the schedule's virtual time to `vt` (the laggard
        WAITING tenant's post-plan position) and renormalize: entries
        at/below it are deleted (their owners re-enter at the current
        position), survivors shift down.  Keeps the map bounded to
        tenants genuinely ahead of schedule and pass values anchored
        at 0 across a long-lived daemon."""
        if vt <= 0:
            return
        for t in list(self._pass):
            if self._pass[t] <= vt:
                del self._pass[t]
            else:
                self._pass[t] -= vt
