"""Event-driven micro-batching embedding daemon.

The TPU-native replacement for the reference's splinference sidecar
(splinference.cpp; SURVEY.md §2.2, §3.2).  Where the reference polls a
signal counter every 50 ms and decodes ONE key at a time through llama.cpp
on the CPU, this daemon:

  - blocks on the store's event bus / signal group (C-side wait, no spin);
  - drains the dirty mask per wake and gathers ALL pending candidates;
  - snapshots (text, epoch) per candidate under the seqlock read protocol;
  - pads each gather into per-bucket batches and runs one jit-compiled TPU
    encoder call per bucket;
  - pipelines the drain: encode futures are held, not forced — the host
    tokenizes/buckets/pads batch N+1 while batch N computes on-device,
    and the epoch-gated commit stage resolves futures in COMPLETION
    order (CommitPipeline), so wake->commit never pays a synchronous
    device round-trip it could have overlapped; tiny drains take a
    short-circuit lane onto pre-compiled small-bucket programs;
  - commits the whole batch of vectors with a single epoch-gated native
    call (spt_vec_commit_batch) — rows whose slot changed mid-flight are
    dropped, mirroring the reference's post-decode epoch+2 verification
    (splinference.cpp:275-287) but amortized over the batch.

Protocol fidelity (all reference behaviors preserved):
  label 0x1 wake, WAITING(0x40) clear, context-exceeded marker (zero
  vector + diagnostic value + label 0x80 + bump), --vector-training
  write-once gate, backfill sweep (SEQUENTIAL rebid + madvise), --oneshot,
  cold-start epoch baselining of keys that already carry vectors.
"""
from __future__ import annotations

import dataclasses
import logging
import os
import time
from typing import Callable, Sequence

import numpy as np

from .. import _native as N
from ..obs.devtime import DEVTIME
from ..obs.recorder import FlightRecorder
from ..obs.spans import SpanWriter
from ..store import Store
from ..utils import faults
from ..utils.faults import fault
from ..utils.trace import device_profile, tracer
from . import protocol as P
from .qos import (AdmissionController, TenantLedger, WaitingRow,
                  parse_tenant_weights, prune_idle_counters)
from .resident import InflightWindow

log = logging.getLogger("libsplinter_tpu.embedder")

# a row whose encode/commit batch failed this many times is failed
# terminally (labels cleared, client unblocked) instead of wedging the
# degradation ladder forever
ROW_STRIKE_LIMIT = 3

# An encoder takes a list of texts and returns (B, dim) float32 vectors.
EncoderFn = Callable[[Sequence[str]], np.ndarray]




@dataclasses.dataclass
class EmbedderStats:
    wakes: int = 0
    batches: int = 0
    embedded: int = 0
    raced: int = 0
    skipped_write_once: int = 0
    ctx_exceeded: int = 0
    backfilled: int = 0
    # -- failure-domain accounting (the per-batch firewall) ----------
    batch_faults: int = 0       # encode/commit batches that failed
    embed_failed: int = 0       # rows failed terminally after strikes
    drain_faults: int = 0       # run-loop cycles the firewall absorbed
    # -- multi-tenant QoS (engine/qos.py) ----------------------------
    deadline_expired: int = 0   # fast-failed: client deadline passed
    shed: int = 0               # unblocked label-only past high water
    deferred: int = 0           # held for a later drain (fairness)
    # -- commit-pipeline telemetry (the overlap is measured, not
    # asserted: bench.py's p50 stage table reads these) --------------
    futures_dispatched: int = 0
    futures_resolved: int = 0
    ready_commits: int = 0      # future already complete at commit time
    blocking_waits: int = 0     # host had to block on a device future
    inflight_peak: int = 0      # max dispatched-uncommitted depth seen
    probe_lane_hits: int = 0    # drains through the small-batch lane
    # -- resident-ring telemetry (engine/resident.py): one ring
    # dispatch services ring_occupancy batches, so the per-drain
    # dispatch floor amortizes to ~floor/occupancy -----------------
    ring_dispatches: int = 0    # resident device programs dispatched
    resident_iterations: int = 0  # batches serviced inside rings
    ring_occupancy: int = 0     # last ring's occupied slot count
    ring_occupancy_peak: int = 0
    ring_faults: int = 0        # ring dispatches degraded to per-call
    device_wait_ms: float = 0.0  # host wall time blocked in materialize
    overlap_ms: float = 0.0      # device in-flight time host spent staging
    commit_host_ms: float = 0.0  # epoch-gated commit + protocol tail

    def overlap_ratio(self) -> float:
        """Fraction of total device in-flight time the host spent doing
        useful work instead of blocking (1.0 = the device never stalled
        the host; 0.0 = every batch was a synchronous round-trip)."""
        total = self.overlap_ms + self.device_wait_ms
        return self.overlap_ms / total if total > 0 else 0.0


class CommitPipeline(InflightWindow):
    """The drain stage of the embed->commit lane — the original
    instance of the K-deep overlap pattern, now built on the shared
    InflightWindow skeleton (engine/resident.py) the searcher and the
    continuous decode lane reuse.

    Dispatched encode futures (PendingEmbeddings, or ring slot views
    of a resident multi-batch dispatch) queue here instead of being
    forced inline.  Commits resolve in COMPLETION order: any future
    that has finished is committed immediately (zero wait) while later
    batches are still being tokenized/dispatched, and the host only
    blocks on the device when the in-flight bound is hit with nothing
    ready — back-pressure, not a synchronous round-trip per batch.
    The old path forced each batch FIFO with a blocking device_get
    inside the wake handler: wake->commit paid the full device
    round-trip every time (BENCH_r05: 62.2 of the 67.2 ms p50).
    """

    def __init__(self, commit_fn, stats: EmbedderStats, depth: int,
                 *, stage_acc: dict | None = None, on_error=None):
        super().__init__(depth)
        self._commit = commit_fn      # (rows, epochs, f32 vecs) -> int
        self._stats = stats
        # per-batch failure domain: (rows, epochs, exc) -> None.  With
        # a handler armed, a batch whose materialize or commit raises
        # fails ALONE (the handler re-queues or fails its rows) and
        # the pipeline keeps resolving siblings; without one, the old
        # raise-through behavior stands.
        self._on_error = on_error
        # per-drain PIPELINE_STAGES accumulator (tracing only): the
        # resolve path adds its device_wait/commit wall here so traced
        # requests get real stage events, not re-measured estimates
        self._stage_acc = stage_acc
        self._blocked_ms = 0.0        # cumulative materialize-block time
        self.committed = 0

    def push(self, rows, epochs, pending) -> None:
        self._stats.futures_dispatched += 1
        self.push_entry((rows, epochs, pending, time.perf_counter(),
                         self._blocked_ms))
        self._stats.inflight_peak = max(self._stats.inflight_peak,
                                        self.inflight_peak)

    def _entry_ready(self, item) -> bool:
        return item[2].is_ready()

    def _resolve(self, item) -> None:
        rows, epochs, pending, t_dispatch, blocked_at_dispatch = item
        st = self._stats
        ready = pending.is_ready()
        t0 = time.perf_counter()
        # time the future flew while the host did USEFUL staging work:
        # the raw dwell minus any interval the host spent blocked in
        # OTHER futures' materialize (counting that too would let a
        # fully-stalled pipeline still report ~50% overlap)
        dwell_ms = (t0 - t_dispatch) * 1e3
        st.overlap_ms += max(
            dwell_ms - (self._blocked_ms - blocked_at_dispatch), 0.0)
        try:
            fault("embedder.encode")
            vecs = pending.materialize()
        except Exception as ex:
            self._blocked_ms += (time.perf_counter() - t0) * 1e3
            if self._on_error is None:
                raise
            self._on_error(rows, epochs, ex)
            return
        t1 = time.perf_counter()
        wait_ms = (t1 - t0) * 1e3
        st.device_wait_ms += wait_ms
        self._blocked_ms += wait_ms
        if ready:
            st.ready_commits += 1
        else:
            st.blocking_waits += 1
        try:
            self.committed += self._commit(rows, epochs, vecs)
        except Exception as ex:
            if self._on_error is None:
                raise
            self._on_error(rows, epochs, ex)
            return
        commit_ms = (time.perf_counter() - t1) * 1e3
        st.commit_host_ms += commit_ms
        st.futures_resolved += 1
        if tracer.enabled:
            # histogram records from the timings above — no extra
            # span machinery in the per-batch resolve path
            tracer.record("embed.device_wait", wait_ms)
            tracer.record("embed.commit", commit_ms)
            acc = self._stage_acc
            if acc is not None:
                acc["device_wait"] += wait_ms
                acc["commit"] += commit_ms


class Embedder:
    """The daemon object.  Drive it with run() (blocking loop), run_once()
    (single drain — the reference's --oneshot), or embed tests through a
    fake encoder_fn."""

    def __init__(self, store: Store, encoder_fn: EncoderFn | None = None,
                 *, model=None, tokenizer=None,
                 max_ctx: int = 2048,
                 vector_training: bool = False,
                 group: int = P.GROUP_EMBED,
                 batch_cap: int = 256,
                 inflight_depth: int | None = None,
                 ring_depth: int | None = None,
                 probe_batch_max: int | None = None,
                 admit_cap: int | None = None,
                 queue_high_water: int | None = None,
                 retry_after_ms: int | None = None,
                 tenant_weights: dict[int, float] | None = None,
                 replica: int = 0):
        self.store = store
        self.max_ctx = max_ctx
        self.vector_training = vector_training
        self.group = group
        self.batch_cap = batch_cap
        # elastic lanes (protocol.StripeView): replica r of a striped
        # group drains only its own slot-index stripe — the map is
        # store state, re-read at each drain, so a supervisor
        # re-stripe lands at the next drain boundary.  replica 0 with
        # no map is the classic single-process deployment.
        self.replica = int(replica)
        self.stripes = P.StripeView(store, "embedder", self.replica)
        self._hb_key = P.replica_stats_key(P.KEY_EMBED_STATS,
                                           self.replica)
        self._trace_key = P.replica_stats_key(P.KEY_EMBED_TRACE,
                                              self.replica)
        self._inflight_override = inflight_depth
        self._ring_override = ring_depth
        # drains at or below this size take the latency short-circuit
        # lane (no sort, no windowing — straight to the pre-compiled
        # small-bucket programs)
        self.probe_batch_max = (P.PROBE_BATCH_MAX_DEFAULT
                                if probe_batch_max is None
                                else probe_batch_max)
        # multi-tenant QoS (engine/qos.py): admit_cap bounds rows per
        # drain (fairness granularity — the rest stay pending and the
        # next drain re-plans with stride credit); queue_high_water
        # bounds that backlog — overflow rows are unblocked label-only
        # (the embed lane has no value channel to spare for a typed
        # record: the slot holds the client's text, so the shed signal
        # is the cleared label + zero vector + the heartbeat's shed /
        # per-tenant counters).  Deadline fast-fail is always on for
        # rows carrying a deadline stamp.
        self.admit_cap = admit_cap
        self.qos = AdmissionController(
            weights=tenant_weights, high_water=queue_high_water,
            **({"retry_after_ms": retry_after_ms}
               if retry_after_ms is not None else {}))
        self.tenants = TenantLedger()
        self._had_deferred = False
        self._row_labels: dict[int, int] = {}
        self.stats = EmbedderStats()
        # flight recorder: per-request wake->commit traces for rows
        # whose client stamped a trace id (protocol.stamp_trace);
        # published next to the heartbeat (KEY_EMBED_TRACE)
        self.recorder = FlightRecorder()
        self.spans = SpanWriter(store, "embedder")
        self._live_spans: list = []           # pending spans this drain
        self._trace_published = 0             # ring state last published
        self._stage_acc: dict | None = None   # live drain's stage sums
        self._traced_hits: list | None = None  # LBL_TRACED rows seen
        self._drain_t0: float | None = None
        self._known_epochs: dict[int, int] = {}
        # rows believed to need embedding: fed by the dirty mask (hot
        # path) and by label sweeps (cold start + periodic reconcile).
        # Raced/torn rows stay here and retry next drain — so the hot
        # path never needs the O(nslots) label scan (VERDICT r1 item 6).
        self._pending: set[int] = set()
        # failure-domain state: a failed encode/commit batch halves
        # the effective batch cap (the bucket) for subsequent drains —
        # a poison batch is bisected until the bad rows stand alone —
        # and per-row strike counts fail repeat offenders terminally
        # (keyed by slot, scoped to the request epoch: a rewrite must
        # not inherit the old text's strikes)
        self._cap_degraded: int | None = None
        self._strikes: dict[int, tuple[int, int]] = {}
        self.generation = 0          # bumped at attach (restart marker)
        self._bid = -1
        self._running = False

        if encoder_fn is not None:
            self.encoder_fn = encoder_fn
            self._tok = tokenizer
        else:
            if model is None:
                from ..models import EmbeddingModel, EncoderConfig
                model = EmbeddingModel(
                    EncoderConfig(out_dim=store.vec_dim, max_len=max_ctx))
            if tokenizer is None:
                from ..models import default_tokenizer
                tokenizer = default_tokenizer(model.cfg.vocab_size)
            self._model = model
            self._tok = tokenizer
            self.encoder_fn = self._model_encode

    # -- wiring ------------------------------------------------------------

    def attach(self) -> None:
        """Claim the shard, bind the wake label, arm/join the event bus,
        and baseline epochs of already-embedded keys (cold start)."""
        st = self.store
        try:
            self._bid = st.shard_claim(P.SHARD_EMBED, N.ADV_WILLNEED,
                                       P.PRIO_EMBED_LIVE, 30_000_000)
        except OSError:
            self._bid = -1          # bid table full: run unadvised
        st.watch_label_register(P.BIT_EMBED_REQ, self.group)
        st.bus_attach()   # adopts the bus when a crashed owner
                          # left a dead pid in the header
        self.generation = P.bump_generation(st, self._hb_key)
        # compile events ledgered from here carry this generation —
        # a restart's re-warmup is distinguishable in the ring
        DEVTIME.generation = max(DEVTIME.generation, self.generation)
        self._baseline_existing()
        # cold start: pre-existing requests enter the pending set once
        # (reference drains pre-existing WAITING keys on startup,
        # splinference.cpp:463-493); after this the hot path is fed by
        # the dirty mask alone
        self._pending.update(st.enumerate_indices(P.LBL_EMBED_REQ))

    def _baseline_existing(self) -> None:
        """Cold start: keys that already carry a non-zero vector are
        treated as up to date at their current epoch
        (reference: splinference.cpp:463-493)."""
        st = self.store
        vecs = st.vectors
        live = np.abs(vecs).max(axis=1) > 0
        for idx in np.nonzero(live)[0]:
            self._known_epochs[int(idx)] = st.epoch_at(int(idx))

    # -- encoding ----------------------------------------------------------

    def _model_encode(self, texts: Sequence[str]) -> np.ndarray:
        # tokenize first; the padding bucket comes from REAL token counts
        # (a whitespace heuristic undercounts punctuation-dense text and
        # would silently truncate it)
        if hasattr(self._tok, "encode_batch"):
            # one native GIL-releasing call for the whole micro-batch
            # (wptok.c); Unicode rows fall back internally
            ids_full, lens = self._tok.encode_batch(
                list(texts), self._model.cfg.max_len)
            return self._encode_bucketed(ids_full, lens)
        encs = [self._tok.encode(t, max_len=self._model.cfg.max_len)
                for t in texts]
        bucket = self._model.bucket_for(max(len(e) for e in encs))
        ids = np.full((len(encs), bucket), self._tok.pad_id, np.int32)
        lens = np.zeros(len(encs), np.int32)
        for i, e in enumerate(encs):
            e = e[:bucket]
            ids[i, : len(e)] = e
            lens[i] = len(e)
        return self._model.encode_ids(ids, lens)

    def _dispatch_bucketed(self, ids: np.ndarray, lens: np.ndarray):
        """Group rows by their own padding bucket and dispatch one
        encode per (bucket, <=batch_cap) group, without forcing any
        result.  Yields (row_selection, pending) lazily so the
        consumer's in-flight bound actually applies back-pressure
        between dispatches (an eager list would enqueue the whole
        window on the device before the first commit).

        Grouping matters: the reference pays each text its own length
        (serial llama.cpp decode); a naive batch pays every text the
        LONGEST text's bucket.  Grouping keeps short texts on narrow
        programs — most of the padding FLOPs come back.

        When a bucket group yields two or more FULL batches and the
        model supports the resident ring, those batches pre-stage into
        a (ring_depth, cap, bucket) ring serviced by ONE device
        dispatch (encode_ring_async: lax.while_loop over the occupied
        slots) — the ~63 ms per-dispatch runtime round trip amortizes
        to floor/occupancy.  The short tail batch rides the per-call
        path on its own (smaller, pre-compiled) program."""
        cap = self.effective_batch_cap
        depth = self.ring_depth
        ring_async = (getattr(self._model, "encode_ring_async", None)
                      if depth > 1 else None)
        bkts = self._model.buckets_for(np.asarray(lens))
        for b in np.unique(bkts):
            sel = np.nonzero(bkts == b)[0]
            chunks = [sel[lo: lo + cap]
                      for lo in range(0, len(sel), cap)]
            full = len(chunks) - (1 if len(chunks[-1]) < cap else 0)
            lo = 0
            if ring_async is not None and full >= 2:
                while full - lo >= 2:
                    group = chunks[lo: lo + min(depth, full - lo)]
                    yield from self._dispatch_ring(ids, lens, group,
                                                   int(b), cap)
                    lo += len(group)
            for ss in chunks[lo:]:
                yield ss, self._model.encode_ids_async(
                    np.ascontiguousarray(ids[ss, : int(b)]),
                    np.minimum(lens[ss], b).astype(np.int32))

    def _dispatch_ring(self, ids, lens, group, b: int, cap: int):
        """Pre-stage `group` (full cap-sized chunks of one bucket)
        into a host-fed ring and dispatch the resident program once;
        yields one RingSlot pending per chunk so the CommitPipeline
        consumes ring and per-call dispatches identically.  A ring
        dispatch that fails degrades to the per-call path for its
        chunks (the battle-tested programs; ring_faults counts it) —
        the resident optimization must never cost a drain."""
        from ..models.encoder import _batch_pad

        depth = self.ring_depth
        bpad = _batch_pad(cap)
        ids_ring = np.zeros((depth, bpad, b), np.int32)
        lens_ring = np.zeros((depth, bpad), np.int32)
        for j, ss in enumerate(group):
            ids_ring[j, : len(ss)] = ids[ss, :b]
            lens_ring[j, : len(ss)] = np.minimum(lens[ss], b)
        st = self.stats

        def retry(j: int, n: int) -> np.ndarray:
            # collect-time fallback: async dispatch surfaces device
            # failures at the ring FETCH — re-encode the one slot on
            # the per-call programs so a transient error costs a
            # re-dispatch, never a failed batch (let alone 8: without
            # this, one poisoned ring would halve the cap and strike
            # rows once PER SLOT, defeating the PR-4 bisection)
            st.ring_faults += 1
            log.warning("resident ring collect failed; re-encoding "
                        "slot %d of %d per-call", j, len(group))
            return self._model.encode_ids_async(
                np.ascontiguousarray(ids_ring[j, :n]),
                lens_ring[j, :n].copy()).materialize()

        try:
            ring = self._model.encode_ring_async(ids_ring, lens_ring,
                                                 len(group),
                                                 retry=retry)
        except Exception as ex:
            st.ring_faults += 1
            log.warning("resident ring dispatch of %d batches failed "
                        "(%s); falling back to per-call", len(group),
                        ex)
            for ss in group:
                yield ss, self._model.encode_ids_async(
                    np.ascontiguousarray(ids[ss, :b]),
                    np.minimum(lens[ss], b).astype(np.int32))
            return
        st.ring_dispatches += 1
        st.resident_iterations += len(group)
        st.ring_occupancy = len(group)
        st.ring_occupancy_peak = max(st.ring_occupancy_peak,
                                     len(group))
        for j, ss in enumerate(group):
            yield ss, ring.slot(j, len(ss))

    def _encode_bucketed(self, ids: np.ndarray, lens: np.ndarray):
        """Synchronous encode tail for the public encoder_fn surface."""
        vecs = np.zeros((len(lens), self._model.cfg.out_dim), np.float32)
        for sel, pend in self._dispatch_bucketed(ids, lens):
            vecs[sel] = pend.materialize()
        return vecs

    def _too_long(self, text: str) -> bool:
        if self._tok is None:
            return len(text.split()) >= int(self.max_ctx *
                                            P.CTX_GUARD_FRACTION)
        n = len(self._tok.encode(text))
        return n >= int(self.max_ctx * P.CTX_GUARD_FRACTION)

    # -- candidate gathering ----------------------------------------------

    def _candidates(self, indices: Sequence[int]) -> list[int]:
        st = self.store
        out = []
        self._row_labels.clear()      # per-drain QoS metadata only
        traced = self._traced_hits
        for idx in indices:
            labels = st.labels_at(idx)
            if not labels & P.LBL_EMBED_REQ:
                self._pending.discard(idx)    # done or never requested
                if labels & (P.LBL_TRACED | P.LBL_DEBUG
                             | P.LBL_DEADLINE):
                    # a stamp that landed after its request was
                    # serviced surfaces here (its own write dirtied
                    # the stamp slot) — shed it or it leaks forever
                    P.shed_orphan_stamp(st, idx, labels)
                continue
            if not self.stripes.owns(idx):
                continue              # a peer replica's stripe: stays
                                      # pending, ours after a re-stripe
            self._row_labels[idx] = labels    # tenant/deadline for QoS
            e = st.epoch_at(idx)
            if e & 1:
                self._pending.add(idx)        # writer active: next drain
                continue
            if self._known_epochs.get(idx, -1) >= e:
                self._pending.discard(idx)    # already embedded this epoch
                continue
            if labels & P.LBL_TRACED and traced is not None:
                traced.append(idx)   # stamp read deferred to _begin_trace
            out.append(idx)
        return out

    def _gather(self, rows: list[int]):
        """Snapshot (text, epoch) per row under the read protocol."""
        st = self.store
        texts, epochs, keep = [], [], []
        for idx in rows:
            e = st.epoch_at(idx)
            if e & 1:
                continue
            try:
                raw = st.get_at(idx)
            except Exception:
                continue
            if st.epoch_at(idx) != e:
                continue                      # torn: re-queued by next wake
            texts.append(raw.rstrip(b"\0").decode("utf-8", errors="replace"))
            epochs.append(e)
            keep.append(idx)
        return keep, texts, epochs

    # -- the drain ---------------------------------------------------------

    def _mark_ctx_exceeded(self, idx: int) -> None:
        st = self.store
        key = st.key_at(idx)
        if key is None:
            return
        st.vec_set_at(idx, np.zeros(st.vec_dim, np.float32))
        st.set(key, P.CTX_EXCEEDED_DIAGNOSTIC)
        st.label_or(key, P.LBL_CTX_EXCEEDED)
        st.label_clear(key, P.LBL_EMBED_REQ | P.LBL_WAITING)
        self._known_epochs[idx] = st.epoch_at(idx)
        self._pending.discard(idx)
        st.bump(key)
        self.stats.ctx_exceeded += 1

    def _ctx_flags_and_ids(self, texts):
        """Context-guard decisions for a gather, with the token ids as a
        byproduct when the real model drives encoding.

        Fused path: ONE native batch tokenization (wptok.c) yields both
        the too-long flags and the ids the encoder will consume — the
        old flow tokenized every text twice (_too_long + _model_encode).
        Rows truncated at the model window necessarily exceed the guard
        threshold, so capped lens stay decision-exact."""
        fused = (getattr(self, "_model", None) is not None
                 and self.encoder_fn == self._model_encode
                 and self._tok is not None
                 and hasattr(self._tok, "encode_batch"))
        if fused:
            thr = int(self.max_ctx * P.CTX_GUARD_FRACTION)
            if thr <= self._model.cfg.max_len:
                ids, lens = self._tok.encode_batch(
                    list(texts), self._model.cfg.max_len)
                return lens >= thr, ids, lens
        return (np.array([self._too_long(t) for t in texts], bool),
                None, None)

    # how many dispatched encode batches may be outstanding before the
    # host blocks to commit the oldest: with jax's async dispatch the
    # TPU works on batch k+1..k+depth while the host commits batch k.
    # Tunable three ways, all read live on every drain: the
    # constructor's inflight_depth, assigning .inflight_depth on an
    # instance, or the legacy class-attribute path
    # (`Embedder._INFLIGHT_DEPTH = 4`).
    _INFLIGHT_DEPTH = 2

    @property
    def inflight_depth(self) -> int:
        return (type(self)._INFLIGHT_DEPTH
                if self._inflight_override is None
                else self._inflight_override)

    @inflight_depth.setter
    def inflight_depth(self, value: int) -> None:
        self._inflight_override = value

    # resident-ring depth: how many full same-bucket batches one
    # device dispatch services (lax.while_loop over a host-fed ring,
    # engine/resident.py).  <=1 disables — every batch pays its own
    # runtime round trip, the pre-PR-7 behavior.  Same three-way
    # tunability as inflight_depth.
    _RING_DEPTH = 8

    @property
    def ring_depth(self) -> int:
        return (type(self)._RING_DEPTH
                if self._ring_override is None
                else self._ring_override)

    @ring_depth.setter
    def ring_depth(self, value: int) -> None:
        self._ring_override = value

    @property
    def effective_batch_cap(self) -> int:
        """batch_cap, halved per failed batch while the degradation
        ladder is active (restored multiplicatively after clean
        drains) — the poison-batch bisection bound."""
        if self._cap_degraded is None:
            return self.batch_cap
        return min(self._cap_degraded, self.batch_cap)

    # -- failure domains ---------------------------------------------------

    def _on_batch_error(self, rows, epochs, ex: Exception) -> None:
        """One encode/commit batch failed (XLA RESOURCE_EXHAUSTED, a
        store commit surprise, an injected fault): halve the bucket so
        the retry bisects toward the poison row, strike each row, and
        fail rows past the strike limit terminally.  Surviving rows
        stay in the pending set — the next drain retries them at the
        degraded cap; the run loop itself never sees the exception."""
        self.stats.batch_faults += 1
        cap = self._cap_degraded or min(self.batch_cap, len(rows))
        self._cap_degraded = max(1, cap // 2)
        log.warning("encode batch of %d failed (%s); batch cap "
                    "degraded to %d", len(rows), ex,
                    self._cap_degraded)
        for idx, epoch in zip(rows, epochs):
            idx, epoch = int(idx), int(epoch)
            prev_epoch, n = self._strikes.get(idx, (epoch, 0))
            if prev_epoch != epoch:
                n = 0                 # rewritten since: clean slate
            self._strikes[idx] = (epoch, n + 1)
            if n + 1 >= ROW_STRIKE_LIMIT:
                self._mark_embed_failed(idx, epoch)

    def _mark_embed_failed(self, idx: int, epoch: int) -> None:
        """Terminal per-row failure: clear the request labels and bump
        so a blocked client unblocks (it finds no vector and degrades
        client-side) instead of waiting out its timeout against a row
        that will never embed.  Epoch-gated like every other terminal
        path: a client rewrite racing the final strike must keep ITS
        request — the new epoch re-candidates the row with a clean
        slate instead of being silently dropped."""
        st = self.store
        self._strikes.pop(idx, None)
        try:
            if st.epoch_at(idx) != epoch:
                return                # rewritten mid-strike: keep it
            self.stats.embed_failed += 1
            self._pending.discard(idx)
            key = st.key_at(idx)
            if key is not None:
                st.label_clear(key, P.LBL_EMBED_REQ | P.LBL_WAITING)
                st.bump(key)
            self._known_epochs[idx] = st.epoch_at(idx)
        except (KeyError, OSError):
            pass
        log.error("row %d failed %d encode attempts; giving up",
                  idx, ROW_STRIKE_LIMIT)

    def _admission(self, rows: list[int]) -> list[int]:
        """Multi-tenant QoS over one drain's candidates: expired
        deadlines fail fast, the fairness-ordered admit set (up to
        admit_cap) proceeds, overflow past queue_high_water is shed,
        the rest stay pending with their tenants' stride credit
        intact.  With no QoS config and no stamped rows this is a
        cheap pass-through."""
        labels_of = self._row_labels
        qos_rows: list[WaitingRow] = []
        tagged = False
        for idx in rows:
            labels = labels_of.get(idx, 0)
            deadline = None
            if labels & P.LBL_DEADLINE:
                deadline = P.read_deadline(
                    self.store, idx, epoch=self.store.epoch_at(idx))
            tenant = P.read_tenant(labels)
            tagged = tagged or tenant or deadline is not None
            qos_rows.append(WaitingRow(idx, tenant, deadline))
        if not tagged and self.admit_cap is None \
                and self.qos.high_water is None:
            self._had_deferred = False
            return rows
        cap = self.admit_cap if self.admit_cap else len(rows)
        plan = self.qos.plan(qos_rows, cap)
        for row in plan.expired:
            self._fail_deadline(row.item, row.tenant)
        for row in plan.shed:
            self._shed_row(row.item, row.tenant)
        self.stats.deferred += len(plan.deferred)
        self._had_deferred = bool(plan.deferred)
        for row in plan.admit:
            if row.tenant or row.deadline is not None:
                self.tenants.bump(row.tenant, "admitted")
            if row.deadline is not None:
                P.clear_deadline(self.store, row.item)
        # deferred rows stay in the pending set — the next drain (the
        # work-conserving re-drain in run(), or the next wake)
        # reconsiders them
        self._pending.update(row.item for row in plan.deferred)
        return [row.item for row in plan.admit]

    def _reject_row(self, idx: int, status: str,
                    tenant: int = 0) -> None:
        """Shared terminal-reject tail for deadline expiry and shed:
        ZERO the vector lane first — a re-embed request's slot still
        holds the PREVIOUS text's vector, and without the scrub a
        rejected update would be indistinguishable from success (the
        client would read the stale vector as the new embedding; the
        contract is cleared label + zero vector = not embedded) —
        then unblock the row (labels cleared, bump).  The slot's text
        is untouched; a rewrite re-candidates it."""
        st = self.store
        self._pending.discard(idx)
        P.clear_deadline(st, idx)
        # a rejected request's trace context must not leak — and the
        # reject IS the request's whole service, so it gets a typed
        # span like every other lane's shed path (begin consumes the
        # stamp; an untraced row costs one label test)
        try:
            if st.labels_at(idx) & P.LBL_TRACED:
                self.spans.commit(
                    self.spans.begin(idx, st.epoch_at(idx),
                                     tenant=tenant),
                    status=status)
        except (KeyError, OSError):
            pass
        P.clear_span_stage(st, idx)
        try:
            st.vec_set_at(idx, np.zeros(st.vec_dim, np.float32))
            key = st.key_at(idx)
            if key is not None:
                st.label_clear(key, P.LBL_EMBED_REQ | P.LBL_WAITING)
                st.bump(key)
            self._known_epochs[idx] = st.epoch_at(idx)
        except (KeyError, OSError):
            pass

    def _fail_deadline(self, idx: int, tenant: int) -> None:
        """Deadline fast-fail: the client stopped waiting — unblock
        the row without spending a batch slot on a vector nobody
        reads."""
        self.stats.deadline_expired += 1
        self.tenants.bump(tenant, "deadline_expired")
        self._reject_row(idx, P.ERR_DEADLINE, tenant)

    def _shed_row(self, idx: int, tenant: int) -> None:
        """High-water shed: unblock the row label-only (the embed slot
        holds the client's text, so there is no value channel for a
        typed record — the cleared label + zero vector IS the signal,
        and the heartbeat's shed / per-tenant counters plus
        qos.retry_after_ms tell a monitoring client when to retry)."""
        self.stats.shed += 1
        self.tenants.bump(tenant, "shed")
        self._reject_row(idx, P.ERR_OVERLOADED, tenant)

    def process_rows(self, rows: list[int]) -> int:
        """Embed a set of candidate slot indices; returns committed count.

        The drain is a two-lane pipeline feeding a CommitPipeline:
        tiny drains (<= probe_batch_max rows — latency probes, single
        hot keys) short-circuit straight to tokenize->dispatch on the
        pre-compiled small-bucket programs; everything bigger runs the
        windowed big-batch lane, where the host stages window k+1
        (tokenize/bucket/pad/gather) while window k's encode runs on
        the device, and finished futures commit the moment they
        complete — the wake handler never parks on a device round-trip
        it could overlap."""
        st = self.store
        # armed BEFORE the candidate filter: it discovers traced rows
        # from the label word it reads anyway (zero extra store ops).
        # Always armed — an untraced daemon must still SHED stamps an
        # instrumented client leaves, or every stamped request leaks a
        # __tr_<idx> key + a permanent LBL_TRACED bit
        self._traced_hits = []
        rows = self._admission(self._candidates(rows))
        if not rows:
            self._traced_hits = None
            return 0
        self._pending.update(rows)            # until each row resolves
        keep, texts, epochs = self._gather(rows)
        if not keep:
            return 0
        traced = self._begin_trace(keep, epochs)

        t_start = Store.now()
        faults0 = self.stats.batch_faults
        pipe = CommitPipeline(
            lambda r, e, v: self._commit_batch(r, e, v, t_start),
            self.stats, self.inflight_depth,
            stage_acc=self._stage_acc,
            on_error=self._on_batch_error)
        if len(keep) <= self.probe_batch_max:
            self.stats.probe_lane_hits += 1
            out = self._guard_rows(keep, texts, epochs)
            if out[0]:
                self._dispatch_guarded(pipe, *out)
        else:
            self._drain_windowed(pipe, keep, texts, epochs)
        pipe.flush()
        self._end_trace(traced)
        if (self._cap_degraded is not None
                and self.stats.batch_faults == faults0):
            # clean drain under a degraded cap: restore multiplicatively
            # (the additive-increase analog of the halving decrease)
            self._cap_degraded *= 2
            if self._cap_degraded >= self.batch_cap:
                self._cap_degraded = None

        self.stats.embedded += pipe.committed
        if pipe.committed and P.KEY_DONE_LANE in st:
            st.bump(P.KEY_DONE_LANE)
        return pipe.committed

    # -- flight recording --------------------------------------------------

    def _begin_trace(self, keep: list[int],
                     epochs: list[int]) -> list | None:
        """Arm the drain's PIPELINE_STAGES accumulator and open spans
        for the LBL_TRACED rows the candidate filter flagged.  Span
        capture is ALWAYS on (bounded by head sampling — only stamped
        rows pay anything); the histogram tracer additionally arms
        the stage accumulator when SPTPU_TRACE=1.  Stamps are
        epoch-checked against the gathered request: a stale stamp (a
        request serviced before its stamp landed) is consumed, never
        attributed to this drain.  begin() consumes the stamp while
        the slot is still this request's (the consume-early
        discipline) and the span record buffers until the heartbeat-
        cadence flush."""
        hits, self._traced_hits = self._traced_hits, None
        self._live_spans = []
        if tracer.enabled:
            acc = dict.fromkeys(P.PIPELINE_STAGES, 0.0)
            # the drain stage: signal drain + candidate filter +
            # seqlock gather — everything between the wake and the
            # first tokenize (disjoint from the other stages; the
            # WHOLE drain's wall, stages nested, is embed.drain_cycle)
            if self._drain_t0 is not None:
                acc["drain"] = \
                    (time.perf_counter() - self._drain_t0) * 1e3
                self._drain_t0 = None
                tracer.record("embed.drain", acc["drain"])
            self._stage_acc = acc
        else:
            self._stage_acc = None
        traced = []
        if hits:
            kept = {idx: e for idx, e in zip(keep, epochs)}
            for idx in hits:
                if idx not in kept:
                    continue          # torn/raced: retried next drain
                span = self.spans.begin(
                    idx, kept[idx],
                    tenant=P.read_tenant(
                        self._row_labels.get(idx, 0)))
                if span is None:
                    continue          # stale stamp: already shed
                self._live_spans.append(span)
                if tracer.enabled:
                    traced.append((span.key, span.tid, span.t_queue))
        return traced

    def _end_trace(self, traced: list | None) -> None:
        """Commit the drain's spans and emit one flight-recorder
        record per traced request: the drain's stage sums as an
        ordered wake->commit event sequence, wall time measured from
        the client's stamp timestamp."""
        acc, self._stage_acc = self._stage_acc, None
        spans, self._live_spans = self._live_spans, []
        stage_map = ({s: acc[s] for s in P.PIPELINE_STAGES}
                     if acc is not None else None)
        # the drain's device window (dispatch->collect wall across all
        # its encode programs) rides the FIRST committed span —
        # drain-scoped attribution, see SpanWriter.commit
        device_ms = DEVTIME.take_lane_ms("embedder")
        for i, span in enumerate(spans):
            self.spans.commit(span, stages=stage_map,
                              device_ms=device_ms if i == 0 else None)
        if acc is None:
            return
        # e2e records for EVERY traced drain (not just stamped ones):
        # the heartbeat's e2e quantiles must sample the same
        # population as the per-stage quantiles, or comparing them is
        # comparing different workloads
        stage_sum = sum(acc.values())
        tracer.record("embed.e2e", stage_sum)
        if not spans:
            # tail-based retention: a drain past the slow threshold
            # whose requests carried no trace stamp still keeps full
            # stage detail — one synthesized `tail: true` span, and a
            # recorder entry under the same trace id so the slow log
            # resolves via `spt trace show`
            thr = self.recorder.slow_threshold_ms()
            if thr is not None and stage_sum > thr:
                tid = self.spans.tail_span(
                    "<drain>", stage_sum, stages=stage_map,
                    device_ms=device_ms if device_ms > 0 else None)
                if tid is not None:
                    self.recorder.record(
                        tid, "<drain>", stage_sum,
                        [[s, round(acc[s], 3)]
                         for s in P.PIPELINE_STAGES])
        if not traced:
            return
        now_wall = time.time()
        events = [[s, round(acc[s], 3)] for s in P.PIPELINE_STAGES]
        for key, tid, ts in traced:
            wall = (now_wall - ts) * 1e3 if ts > 0 else stage_sum
            self.recorder.record(tid, key, wall,
                                 [list(e) for e in events])

    def _drain_windowed(self, pipe: CommitPipeline, keep, texts,
                        epochs) -> None:
        # order the drain by text byte length (a cheap token-count
        # proxy): windows become nearly bucket-homogeneous, so the
        # bucket grouping fills whole batch_cap batches instead of
        # fragmenting every window into per-bucket stragglers
        order = sorted(range(len(keep)), key=lambda i: len(texts[i]))
        keep = [keep[i] for i in order]
        texts = [texts[i] for i in order]
        epochs = [epochs[i] for i in order]

        # guard + tokenize run per window (a few batch_caps): the fused
        # tokenization materializes (window, max_len) ids, which must
        # stay bounded on huge drains (backfill sweeps), while giving
        # the bucket grouping enough rows to fill homogeneous batches.
        # While this window's encodes fly, the next window tokenizes —
        # and any future that lands mid-stage commits via drain_ready.
        window = max(self.batch_cap * 4, 512)
        for lo in range(0, len(keep), window):
            ch = slice(lo, lo + window)
            out = self._guard_rows(keep[ch], texts[ch], epochs[ch])
            if out[0]:
                self._dispatch_guarded(pipe, *out)
            pipe.drain_ready()

    def _guard_rows(self, ch_rows, ch_texts, ch_eps):
        """Context-window guard (reference: splinference.cpp:226-233)
        over one gather window; violators are marked ctx-exceeded.
        Returns (ok_rows, ok_texts, ok_epochs, ok_i, ids, lens) — ids
        is None outside the fused model path."""
        t0 = time.perf_counter()
        too_long, ids, lens = self._ctx_flags_and_ids(ch_texts)
        if tracer.enabled:
            dt = (time.perf_counter() - t0) * 1e3
            tracer.record("embed.tokenize", dt)
            if self._stage_acc is not None:
                self._stage_acc["tokenize"] += dt
        ok_rows, ok_texts, ok_epochs, ok_i = [], [], [], []
        for j, (idx, text, e) in enumerate(
                zip(ch_rows, ch_texts, ch_eps)):
            if too_long[j]:
                self._mark_ctx_exceeded(idx)
            else:
                ok_rows.append(idx)
                ok_texts.append(text)
                ok_epochs.append(e)
                ok_i.append(j)
        return ok_rows, ok_texts, ok_epochs, ok_i, ids, lens

    def _dispatch_guarded(self, pipe: CommitPipeline, ok_rows, ok_texts,
                          ok_epochs, ok_i, ids, lens) -> None:
        """Dispatch one guarded window into the pipeline WITHOUT forcing
        any result (the span measures host-side dispatch; device time
        surfaces as embed.device_wait only when the host truly blocks)."""
        from ..models.encoder import PendingEmbeddings

        acc = self._stage_acc
        # pipe.push may commit ready futures inline (drain_ready):
        # that wall belongs to device_wait/commit, which _resolve
        # accrues itself — subtract it so the stage values stay
        # disjoint (the drain stages must sum to the drain, not above)
        nested0 = (acc["commit"] + acc["device_wait"]) \
            if acc is not None else 0.0
        t0 = time.perf_counter()
        if ids is not None:
            # ids already tokenized by the guard pass: group by
            # per-row bucket and dispatch async
            rows_a = np.asarray(ok_rows)
            eps_a = np.asarray(ok_epochs)
            for ss, pend in self._dispatch_bucketed(
                    ids[ok_i], lens[ok_i]):
                pipe.push([int(x) for x in rows_a[ss]],
                          [int(x) for x in eps_a[ss]], pend)
        else:
            cap = self.effective_batch_cap
            for slo in range(0, len(ok_rows), cap):
                sl = slice(slo, slo + cap)
                try:
                    # splint: ignore[SPL201] reason=the custom-encoder inline lane: encoder_fn is a user callable with no async contract (usually host numpy already) — the model path resolves through PendingEmbeddings instead
                    vecs = np.asarray(self.encoder_fn(ok_texts[sl]),
                                      np.float32)
                except Exception as ex:
                    # a raising encoder_fn fails its slice alone (the
                    # model path's materialize failures resolve inside
                    # the pipeline; this is the inline-encode analog)
                    self._on_batch_error(ok_rows[sl], ok_epochs[sl], ex)
                    continue
                pipe.push(ok_rows[sl], ok_epochs[sl],
                          PendingEmbeddings(vecs, len(vecs)))
        if tracer.enabled:
            nested = (acc["commit"] + acc["device_wait"] - nested0) \
                if acc is not None else 0.0
            dt = max((time.perf_counter() - t0) * 1e3 - nested, 0.0)
            tracer.record("embed.dispatch", dt)
            if acc is not None:
                acc["dispatch"] += dt

    def _commit_batch(self, ok_rows, ok_epochs, vecs: np.ndarray,
                      t_start: int) -> int:
        """Epoch-gated bulk vector commit + per-row protocol tail
        (labels, ctime stamp, the reference's epoch==pre+2 race check,
        splinference.cpp:275-287).  Returns the committed count."""
        fault("embedder.commit")
        st = self.store
        committed = 0
        results = st.vec_commit_batch(
            np.asarray(ok_rows, np.uint32),
            np.asarray(ok_epochs, np.uint64),
            vecs, write_once=self.vector_training)
        self.stats.batches += 1
        for idx, e, r in zip(ok_rows, ok_epochs, results):
            if r == 0:
                committed += 1
                self._strikes.pop(idx, None)  # clean commit: slate wiped
                expected = e + 2              # our commit's epoch bump
                key = st.key_at(idx)
                if key is not None:
                    st.label_clear(key, P.LBL_EMBED_REQ | P.LBL_WAITING)
                    try:
                        st.stamp(key, which=0,
                                 ticks_ago=Store.now() - t_start)
                        expected += 2         # stamp's epoch bump
                    except Exception:
                        pass
                # a content writer racing between our commit and here
                # must not be masked: only record the slot as done if
                # the epoch is exactly what OUR mutations produced
                if st.epoch_at(idx) == expected:
                    self._known_epochs[idx] = expected
                    self._pending.discard(idx)
                else:
                    self._known_epochs.pop(idx, None)
                    if key is not None:
                        try:  # restore the wake label we cleared
                            st.label_or(key, P.LBL_EMBED_REQ)
                        except KeyError:
                            pass
            elif r == -17:  # EEXIST: write-once gate
                self.stats.skipped_write_once += 1
                self._known_epochs[idx] = e
                self._pending.discard(idx)
            else:           # ESTALE: raced with a writer; retry later
                self.stats.raced += 1
        return committed

    def drain(self, *, sweep: bool = False) -> int:
        """One drain cycle.  The hot path (sweep=False) is fed ONLY by
        the dirty mask + the carried pending set — cost proportional to
        actual write traffic, independent of nslots.  sweep=True adds
        the O(nslots) label enumeration (cold start, --oneshot, and the
        periodic reconciliation that catches labels whose dirty bits a
        crashed consumer drained and lost)."""
        st = self.store
        # trace anchor: _begin_trace turns this into the per-request
        # "drain" stage (wake -> first tokenize).  The WHOLE drain's
        # wall — stages nested, empty idle sweeps included — records
        # separately as drain_cycle, so the PIPELINE_STAGES "drain"
        # histogram and the flight-recorder "drain" event measure the
        # same disjoint slice
        self._drain_t0 = time.perf_counter() if tracer.enabled else None
        with tracer.span("embed.drain_cycle"):
            fault("embedder.drain")
            self.stripes.refresh()    # a re-stripe lands HERE, at the
            bits = st.drain_dirty()   # drain boundary
            rows = set(st.dirty_to_indices(bits))
            rows.update(self._pending)
            if sweep or self.stripes.epoch or self.replica:
                # striped deployments sweep EVERY drain: drain_dirty
                # is fetch-and-clear store-global, so a peer replica's
                # drain eats the dirty bits for rows in OUR stripes —
                # without the label walk those rows would wait out the
                # 10s reconcile cadence (the searcher pays the same
                # enumeration every drain)
                rows.update(st.enumerate_indices(P.LBL_EMBED_REQ))
            if self._bid >= 0:
                try:
                    st.shard_rebid(self._bid)
                    st.madvise(self._bid, N.ADV_WILLNEED, timeout_ms=0)
                except OSError:
                    pass
            if not rows:
                self._had_deferred = False    # nothing pending: the
                return 0                      # redrain loop must end
            # device profile only around real work: a busy daemon runs
            # many empty sweep drains per second — capturing those
            # would pile up trace dirs with nothing in them
            with device_profile("drain"):
                return self.process_rows(sorted(rows))

    def run_once(self) -> int:
        """One full drain cycle (--oneshot): dirty mask + label sweep.
        Buffered span records flush here (oneshot = drain to a
        consistent observable state); the run loop flushes them on
        the heartbeat cadence instead."""
        n = self.drain(sweep=True)
        self.spans.flush()
        return n

    def publish_stats(self) -> None:
        """Heartbeat: JSON stats snapshot into the debug-labeled
        __embedder_stats key (observability counterpart of the
        reference's __debug channel; the sidecar's group-63 watch
        surfaces every update)."""
        self.spans.flush()            # heartbeat cadence, off the
        payload = {**dataclasses.asdict(self.stats),  # wake path
                   "spans_obs": self.spans.counters(),
                   "overlap_ratio": round(self.stats.overlap_ratio(), 4),
                   "generation": self.generation,
                   "pending": len(self._pending)}
        if self.replica or self.stripes.epoch:
            payload["replica"] = self.replica
            payload["stripe"] = self.stripes.snapshot()
        # dispatch-overlap gauges ride their own SECTION so a tiny
        # store's max_val drops them (publish_heartbeat's size
        # degradation) instead of losing the whole heartbeat; `spt
        # metrics` renders them flat (sptpu_embedder_ring_depth etc.).
        # Saturation of the overlap window is visible when
        # ring_occupancy pins at ring_depth / inflight_peak pins at
        # inflight_depth.
        payload["dispatch"] = {
            "inflight_depth": self.inflight_depth,
            "ring_depth": self.ring_depth,
            **{k: payload.pop(k)
               for k in ("ring_dispatches", "resident_iterations",
                         "ring_occupancy", "ring_occupancy_peak",
                         "ring_faults")}}
        if self.admit_cap or self.qos.high_water is not None:
            payload["qos"] = {
                "admit_cap": self.admit_cap or 0,
                "queue_high_water": self.qos.high_water
                if self.qos.high_water is not None else -1,
                "retry_after_ms": self.qos.retry_after_ms}
        tenants = self.tenants.snapshot()
        if tenants:
            # per-tenant admitted/shed/deadline_expired counters —
            # `spt metrics` renders one labeled series per tenant
            payload["tenants"] = tenants
        prune_idle_counters(
            payload, bool(self.admit_cap
                          or self.qos.high_water is not None
                          or tenants))
        if faults.armed():
            payload["faults"] = faults.stats()
        model = getattr(self, "_model", None)
        if model is not None and hasattr(model, "compile_count"):
            payload["compile_count"] = model.compile_count()
        # device-time & compile attribution: runtime-cause compile
        # count (must stay 0 after warmup) + per-program device
        # quantiles; the buffered ledger lands in the __compile_<i>
        # ring on the same cadence
        payload["compile_events"] = DEVTIME.compile_events("embedder")
        devtime = DEVTIME.heartbeat_section("embedder")
        if devtime:
            payload["devtime"] = devtime
        DEVTIME.flush(self.store)
        for k in ("device_wait_ms", "overlap_ms", "commit_host_ms"):
            payload[k] = round(payload[k], 3)
        if tracer.enabled:
            # histogram-sourced per-stage quantiles under the
            # PIPELINE_STAGES names — what bench.py's stage table and
            # `spt metrics` consume (true percentiles, never means)
            P.attach_trace_sections(payload, tracer, self.recorder,
                                    "embed.")
        P.publish_heartbeat(self.store, self._hb_key, payload)
        if tracer.enabled:
            # the flight-recorder ring rides its own key so `spt trace
            # tail` reconstructs individual requests cross-process
            self._trace_published = P.maybe_publish_trace_ring(
                self.store, self._trace_key, self.recorder,
                self._trace_published)

    def run(self, *, idle_timeout_ms: int = 100,
            stop_after: float | None = None,
            sweep_interval_s: float = 10.0) -> None:
        """The daemon loop: block on the signal group, drain, repeat.
        Each periodic sweep also publishes the stats heartbeat."""
        self._running = True
        last = self.store.signal_count(self.group)
        deadline = (time.monotonic() + stop_after) if stop_after else None
        next_sweep = time.monotonic() + sweep_interval_s
        next_retire_check = 0.0
        # first heartbeat NOW, not a sweep interval away: it is the
        # attach-complete signal the supervisor's scale-up promotion
        # (and every liveness probe) waits on
        self.publish_stats()
        while self._running:
            got = self.store.signal_wait(self.group, last,
                                         timeout_ms=idle_timeout_ms)
            now = time.monotonic()
            do_sweep = now >= next_sweep
            if do_sweep:
                next_sweep = now + sweep_interval_s
            # loop-level exception firewall: per-batch failures are
            # absorbed inside process_rows (_on_batch_error); anything
            # reaching here is a gather/store-level surprise — log and
            # keep serving, the run loop never unwinds
            try:
                if got is not None:
                    last = got
                    self.stats.wakes += 1
                    self.drain(sweep=do_sweep)
                    # work-conserving under admit_cap: deferred rows
                    # stay in the pending set — re-drain immediately
                    # in fair slices instead of waiting for the next
                    # wake or the sweep cadence
                    redrains = 0
                    while self._had_deferred and self._running \
                            and redrains < 256:
                        redrains += 1
                        self.drain()
                elif do_sweep:
                    # periodic reconciliation only — an idle daemon
                    # must not walk the whole label lane on every idle
                    # timeout.  A restarted daemon's first sweep also
                    # reclaims requests a crashed predecessor stranded
                    # (label bit set, no inflight owner).
                    self.drain(sweep=True)
                if do_sweep:
                    self.publish_stats()
                if self.replica and now >= next_retire_check:
                    # scale-down drain (own 1s cadence — the sweep
                    # interval is slower than the supervisor's drain
                    # deadline): the supervisor closed our stripes;
                    # the drains above finished any in-flight work,
                    # so exit cleanly and let it reap us
                    next_retire_check = now + 1.0
                    if self.stripes.poll_retired():
                        log.info("replica %d destriped — retiring",
                                 self.replica)
                        self.publish_stats()
                        break
            except Exception:
                self.stats.drain_faults += 1
                log.exception("run loop cycle failed; continuing")
            if deadline and now > deadline:
                break

    def stop(self) -> None:
        self._running = False

    # -- backfill ----------------------------------------------------------

    def backfill(self) -> int:
        """Sweep: embed every VARTEXT key whose vector is all zeros
        (reference --backfill-text-keys, splinference.cpp:289-325).
        Re-bids SEQUENTIAL at backfill priority for the sweep."""
        st = self.store
        bid = -1
        try:
            bid = st.shard_claim(P.SHARD_EMBED, N.ADV_SEQUENTIAL,
                                 P.PRIO_EMBED_BACKFILL, 30_000_000)
            st.madvise(bid, N.ADV_SEQUENTIAL, timeout_ms=0)
        except OSError:
            pass
        vecs = st.vectors
        zero = np.abs(vecs).max(axis=1) == 0
        rows = []
        for idx in np.nonzero(zero)[0]:
            idx = int(idx)
            if st.epoch_at(idx) == 0:
                continue                      # empty slot
            if not st.flags_at(idx) & N.T_VARTEXT:
                continue
            self._known_epochs.pop(idx, None)
            key = st.key_at(idx)
            if key is not None:
                st.label_or(key, P.LBL_EMBED_REQ)
            rows.append(idx)
        n = self.process_rows(rows)
        self.stats.backfilled += n
        if bid >= 0:
            st.shard_release(bid)
        return n


def main(argv: list[str] | None = None) -> int:
    """CLI entry: python -m libsplinter_tpu.engine.embedder --store NAME"""
    import argparse

    ap = argparse.ArgumentParser(
        description="splinter-tpu embedding daemon (micro-batched TPU "
                    "encoder over the store's event bus)")
    ap.add_argument("--store", required=True)
    ap.add_argument("--persistent", action="store_true")
    ap.add_argument("--oneshot", action="store_true")
    ap.add_argument("--backfill-text-keys", action="store_true")
    ap.add_argument("--vector-training", action="store_true",
                    help="write-once vectors: never overwrite an existing "
                         "non-zero embedding")
    ap.add_argument("--max-ctx", type=int, default=None,
                    help="context window override (default: the "
                         "checkpoint's trained window, or 2048 for "
                         "seeded-random weights)")
    ap.add_argument("--batch-cap", type=int, default=256,
                    help="rows per encode batch (padding bucket "
                         "grouping happens under this cap)")
    ap.add_argument("--ring-depth", type=int, default=None,
                    help="resident device loop: service up to this "
                         "many full same-bucket batches per device "
                         "dispatch (lax.while_loop over a host-fed "
                         "ring; default 8, <=1 disables — every "
                         "batch then pays its own ~63 ms runtime "
                         "round trip)")
    ap.add_argument("--inflight-depth", type=int, default=None,
                    help="K-deep dispatch overlap: un-awaited encode "
                         "futures held before the host blocks on the "
                         "oldest (default 2)")
    ap.add_argument("--idle-timeout-ms", type=int, default=100)
    ap.add_argument("--replica", type=int, default=0,
                    help="striped replica index (elastic lanes): "
                         "drain only the slot-index stripes the "
                         "lane's stripe map assigns this replica; "
                         "heartbeat publishes replica-suffixed "
                         "(__embedder_stats.rN).  The supervisor "
                         "passes this — replica 0 is the classic "
                         "single-process deployment")
    ap.add_argument("--admit-cap", type=int, default=None,
                    help="multi-tenant QoS: max rows embedded per "
                         "drain (fairness granularity; backlog stays "
                         "pending with stride credit; default: "
                         "unlimited)")
    ap.add_argument("--queue-high-water", type=int, default=None,
                    help="multi-tenant QoS: max deferred backlog — "
                         "overflow rows are unblocked label-only "
                         "(shed; the heartbeat counters carry the "
                         "evidence; default: never shed)")
    ap.add_argument("--retry-after-ms", type=int, default=None,
                    help="retry hint published in the qos heartbeat "
                         "section when shedding")
    ap.add_argument("--tenant-weights", default=None,
                    help="per-tenant fair-share weights, "
                         "TENANT:W[,TENANT:W...] (unlisted weigh 1)")
    ap.add_argument("--warmup", action="store_true",
                    help="pre-compile the (1, bucket) and (batch_cap, "
                         "bucket) encoder programs before serving "
                         "(.xla_cache persists them across restarts)")
    ap.add_argument("--weights",
                    help="encoder checkpoint: .safetensors (HF naming) or "
                         ".gguf (llama.cpp naming; a GGUF's embedded "
                         "tokenizer is used automatically)")
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO)
    if os.environ.get("SPTPU_FORCE_CPU") == "1":
        import jax
        jax.config.update("jax_platforms", "cpu")
    from ..utils.jaxplatform import enable_compile_cache
    enable_compile_cache()
    store = Store.open(args.store, persistent=args.persistent)
    model = tokenizer = None
    max_ctx = args.max_ctx or 2048
    if args.weights:
        from ..models import EmbeddingModel, EncoderConfig
        if args.weights.endswith(".gguf"):
            from ..models.gguf import (GgufFile, encoder_config_from_gguf,
                                       load_tokenizer)
            overrides = {"max_len": args.max_ctx} if args.max_ctx else {}
            with GgufFile(args.weights) as gf:  # parse the container once
                cfg = encoder_config_from_gguf(gf, out_dim=store.vec_dim,
                                               **overrides)
                tokenizer = load_tokenizer(gf)
        else:
            cfg = EncoderConfig(out_dim=store.vec_dim, max_len=max_ctx)
            log.warning(
                "--weights %s has no tokenizer metadata; falling back to "
                "the hashed-vocab tokenizer, which will NOT match a real "
                "checkpoint's vocabulary — use the model's .gguf export, "
                "or wire a vocab.txt WordPiece tokenizer in code",
                args.weights)
        max_ctx = cfg.max_len       # guards track the model's real window
        model = EmbeddingModel(cfg, weights=args.weights)
    emb = Embedder(store, model=model, tokenizer=tokenizer,
                   max_ctx=max_ctx,
                   batch_cap=args.batch_cap,
                   ring_depth=args.ring_depth,
                   inflight_depth=args.inflight_depth,
                   vector_training=args.vector_training,
                   admit_cap=args.admit_cap,
                   queue_high_water=args.queue_high_water,
                   retry_after_ms=args.retry_after_ms,
                   tenant_weights=parse_tenant_weights(
                       args.tenant_weights),
                   replica=args.replica)
    emb.attach()
    if args.warmup:
        t0 = time.monotonic()
        # probe-lane pad sizes (powers of two up to probe_batch_max)
        # compile too, or the first latency probe of each size pays a
        # fresh XLA compile on the wake path
        probe_pads = []
        b = 1
        while b <= emb.probe_batch_max:
            probe_pads.append(b)
            b *= 2
        emb._model.warmup(
            batch_sizes=tuple(dict.fromkeys(probe_pads
                                            + [emb.batch_cap])))
        # the resident ring program too: a big drain's first ring
        # dispatch must not pay a fresh while_loop compile on the
        # wake path (occupancy is an operand — one probe per bucket
        # covers every occupancy)
        emb._model.warmup_ring(emb.ring_depth, emb.batch_cap)
        log.info("warmup compiled in %.1fs", time.monotonic() - t0)
    if args.backfill_text_keys:
        n = emb.backfill()
        log.info("backfill embedded %d keys", n)
    if args.oneshot:
        n = emb.run_once()
        log.info("oneshot embedded %d keys", n)
        return 0
    try:
        emb.run(idle_timeout_ms=args.idle_timeout_ms)
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
