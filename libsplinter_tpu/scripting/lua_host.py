"""The `splinter` Lua module: store bindings for the scripting host.

Same host-function surface as the reference's embedded Lua
(splinter_cli_cmd_lua.c:365-386): get, get_tandem, set, set_tandem, math,
watch, unwatch, label, unset, bump, sleep, get_embedding, set_embedding —
plus epoch/list/poll which scripts kept reimplementing via watch loops.

Value conventions match the reference host:
  - get returns a string, or a Lua integer for BIGUINT slots, or nil;
  - set accepts strings or numbers (non-negative integers are stored as
    decimal text then auto-promoted to BIGUINT so splinter.math works on
    them; negatives and floats stay text — BIGUINT is unsigned);
  - embeddings cross the boundary as 1-based Lua arrays of numbers;
  - errors return nil (+ message where useful) rather than raising, so
    scripts can `or`-chain defaults, e.g. `bus.get(k) or 0`.
"""
from __future__ import annotations

import time

from .. import _native as N
from .microlua import LuaRuntime, LuaTable, _wrap_i64

_IOPS = {
    "and": N.IOP_AND, "or": N.IOP_OR, "xor": N.IOP_XOR, "not": N.IOP_NOT,
    "inc": N.IOP_INC, "dec": N.IOP_DEC, "add": N.IOP_ADD, "sub": N.IOP_SUB,
}


def make_splinter_module(store, budget=None) -> LuaTable:
    """Build the `splinter` table over a libsplinter_tpu.store.Store.

    `budget` (scripting.sandbox.ScriptBudget) clamps the blocking
    verbs — today `sleep`, which used to honor any float a script
    passed (`sleep(1e9)` wedged the host for 31 years): with a budget
    it sleeps at most `max_sleep_s` and never past the session's
    remaining deadline.  The CLI host and the pipeline lane both pass
    one, so their sandbox semantics cannot drift."""

    def _get(key):
        if key is None:
            return None
        key = str(key)
        try:
            if store.get_type(key) & N.T_BIGUINT:
                return store.get_uint(key)
            raw = store.get(key)
        except (OSError, KeyError, ValueError):
            return None
        if raw is None:
            return None
        try:
            return raw.decode("utf-8")
        except UnicodeDecodeError:
            return raw.decode("latin-1")

    def _set(key, value):
        if key is None or value is None:
            return None
        key = str(key)
        try:
            if isinstance(value, bool):
                store.set(key, b"1" if value else b"0")
            elif isinstance(value, int) and 0 <= value < 2**64:
                # uint64-range numbers become BIGUINT so splinter.math
                # works right away; negatives and >=2^64 stay text
                # (promotion would fail or wrap after the write)
                store.set(key, str(value).encode())
                store.set_type(key, N.T_BIGUINT)
            elif isinstance(value, int):
                store.set(key, str(value).encode())
            elif isinstance(value, float):
                store.set(key, repr(value).encode())
            else:
                store.set(key, str(value).encode())
        except (OSError, KeyError) as e:
            return (None, str(e))
        return 0

    def _unset(key):
        try:
            store.unset(str(key))
            return 0
        except (OSError, KeyError):
            return None

    def _get_tandem(base, order):
        try:
            raw = store.tandem_get(str(base), int(order))
        except (OSError, KeyError):
            return None
        return None if raw is None else raw.decode("utf-8", "replace")

    def _set_tandem(base, order, value):
        try:
            store.tandem_set_at(str(base), int(order), str(value))
            return 0
        except (OSError, KeyError):
            return None

    def _math(key, op, operand=0):
        op = str(op).lower()
        if op not in _IOPS:
            return (None, f"unknown op '{op}'")
        try:
            return store.integer_op(str(key), _IOPS[op], int(operand))
        except (OSError, KeyError) as e:
            return (None, str(e))

    def _watch(key, group):
        try:
            store.watch_register(str(key), int(group))
            return 0
        except (OSError, KeyError):
            return None

    def _unwatch(key, group):
        try:
            store.watch_unregister(str(key), int(group))
            return 0
        except (OSError, KeyError):
            return None

    def _label(key, mask, clear=None):
        try:
            if clear:
                store.label_clear(str(key), int(mask))
            else:
                store.label_or(str(key), int(mask))
            return 0
        except (OSError, KeyError):
            return None

    def _labels(key):
        """Read a key's bloom label mask (nil on a missing key) — the
        counterpart scripts need now that 5.4 bitwise operators make
        mask tests (m & BIT ~= 0) expressible in-script.  Wrapped to
        the interpreter's signed-i64 convention so a mask with bit 63
        set compares equal to the in-script `1 << 63` constant."""
        try:
            return _wrap_i64(store.labels(str(key)))
        except (OSError, KeyError):
            return None

    def _bump(key):
        try:
            store.bump(str(key))
            return 0
        except (OSError, KeyError):
            return None

    def _sleep(seconds):
        s = float(seconds)
        if budget is not None:
            s = budget.clamp_sleep(s)
        time.sleep(s)
        return 0

    def _get_embedding(key):
        try:
            vec = store.vec_get(str(key))
        except (OSError, KeyError):
            return None
        if vec is None:
            return None
        return LuaTable.from_list([float(x) for x in vec])

    def _set_embedding(key, tbl):
        if not isinstance(tbl, LuaTable):
            return None
        vals = [float(v) for v in tbl.to_list()]
        try:
            store.vec_set(str(key), vals)
            return 0
        except (OSError, KeyError, ValueError) as e:
            return (None, str(e))

    def _epoch(key):
        try:
            return store.epoch(str(key))
        except (OSError, KeyError):
            return None

    def _list():
        return LuaTable.from_list(store.list())

    def _poll(key, timeout_ms):
        try:
            return 0 if store.poll(str(key), int(timeout_ms)) else None
        except (OSError, KeyError):
            return None

    def _signal_count(group):
        return store.signal_count(int(group))

    return LuaTable({
        "get": _get,
        "set": _set,
        "unset": _unset,
        "get_tandem": _get_tandem,
        "set_tandem": _set_tandem,
        "math": _math,
        "watch": _watch,
        "unwatch": _unwatch,
        "label": _label,
        "labels": _labels,
        "bump": _bump,
        "sleep": _sleep,
        "get_embedding": _get_embedding,
        "set_embedding": _set_embedding,
        "epoch": _epoch,
        "list": _list,
        "poll": _poll,
        "signal_count": _signal_count,
    })


def make_runtime(store, output=None, budget=None) -> LuaRuntime:
    """LuaRuntime with the splinter module registered (require-able and
    predeclared as the global `splinter`).  With `budget` given the
    blocking verbs are clamped (the sandboxed hosts go further — see
    scripting.sandbox.make_sandboxed_runtime)."""
    rt = LuaRuntime(output=output)
    rt.register_module("splinter", make_splinter_module(store,
                                                        budget=budget))
    return rt
