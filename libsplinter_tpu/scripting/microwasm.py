"""microwasm — a minimal WebAssembly (MVP) interpreter for the CLI host.

The reference embeds a WasmEdge VM with splinter.get/set host functions and
the SIMD proposal enabled (splinter_cli_cmd_wasm.c:85-143).  This image has
no wasm runtime, so the host executes binary modules with a from-scratch
interpreter covering the MVP core:

  sections    type, import, function, table, memory, global, export, start,
              elem, code, data (+ custom, skipped)
  control     block, loop, if/else, br, br_if, br_table, return, call,
              call_indirect
  parametric  drop, select
  variables   local.get/set/tee, global.get/set
  memory      all i32/i64/f32/f64 loads & stores (incl. 8/16/32 partial
              widths), memory.size, memory.grow; bulk memory
              (memory.copy/fill/init, data.drop, passive data segments
              — modern clang --target=wasm32 emits these by default)
  tables      funcref table 0 end to end: the full elem-segment flag
              matrix (active/passive/declared, index- or
              expr-encoded), table.get/set, table.init/copy/grow/
              size/fill + elem.drop, and the funcref ops
              ref.null/ref.is_null/ref.func (null = -1 in the
              unityped interpreter)
  misc        the 0xFC saturating float->int truncation matrix
              (i32/i64.trunc_sat_f32/f64_s/u)
  numeric     full i32/i64 ALU (clz..rotr), f32/f64 arithmetic & compares,
              the conversion/reinterpret matrix, sign-extension ops
  simd        the fixed-width SIMD proposal's v128 core (the reference
              enables the proposal in WasmEdge,
              splinter_cli_cmd_wasm.c:85-143): loads/stores incl. lane +
              splat + extend variants, const/shuffle/swizzle, splats,
              lane extract/replace, ALL lane comparisons, bitwise +
              bitselect + any/all_true + bitmask, integer lane
              add/sub/mul/abs/neg/min/max/shifts/saturating/avgr/dot/
              narrow/extend, float lane
              arith/sqrt/rounding/min/max/pmin/pmax, and the
              int<->float conversion matrix

  multi-value  functions and block signatures returning/carrying
              multiple values (type-index blocktypes; branches to a
              loop carry its params back to the top)

Out of scope (raise WasmError): threads, externref / multiple tables,
and the SIMD tail that exists for codec inner loops (q15mulr,
extadd_pairwise, extmul, relaxed-simd).
Scripts that heavy-compute belong in the JAX tier; wasm here is a
portable *protocol* client, like the reference's.

Host functions are supplied as a dict {("module","name"): python_callable};
callables receive (Instance, *args) so they can touch linear memory.
"""
from __future__ import annotations

import math
import struct
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np


class WasmError(Exception):
    pass


class Trap(WasmError):
    pass


MAGIC = b"\x00asm\x01\x00\x00\x00"
PAGE = 65536

I32, I64, F32, F64, V128 = 0x7F, 0x7E, 0x7D, 0x7C, 0x7B
_VALNAMES = {I32: "i32", I64: "i64", F32: "f32", F64: "f64",
             V128: "v128"}


# -------------------------------------------------------------- byte reader

class _Reader:
    __slots__ = ("b", "p")

    def __init__(self, b: bytes, p: int = 0):
        self.b = b
        self.p = p

    def u8(self) -> int:
        v = self.b[self.p]
        self.p += 1
        return v

    def bytes_(self, n: int) -> bytes:
        v = self.b[self.p:self.p + n]
        if len(v) < n:
            raise WasmError("truncated module")
        self.p += n
        return v

    def uleb(self) -> int:
        out = shift = 0
        while True:
            byte = self.u8()
            out |= (byte & 0x7F) << shift
            if not byte & 0x80:
                return out
            shift += 7

    def sleb(self, bits: int) -> int:
        out = shift = 0
        while True:
            byte = self.u8()
            out |= (byte & 0x7F) << shift
            shift += 7
            if not byte & 0x80:
                if shift < bits and (byte & 0x40):
                    out |= -(1 << shift)
                return out

    def f32(self) -> float:
        return struct.unpack("<f", self.bytes_(4))[0]

    def f64(self) -> float:
        return struct.unpack("<d", self.bytes_(8))[0]

    def name(self) -> str:
        return self.bytes_(self.uleb()).decode("utf-8")

    def eof(self) -> bool:
        return self.p >= len(self.b)


# ------------------------------------------------------------- module model

@dataclass
class FuncType:
    params: tuple
    results: tuple


@dataclass
class Function:
    type: FuncType
    locals: list
    body: list           # decoded instruction list
    name: str = "?"


@dataclass
class Module:
    types: list = field(default_factory=list)
    imports: list = field(default_factory=list)   # (mod, name, kind, extra)
    funcs: list = field(default_factory=list)     # local funcs
    n_imported_funcs: int = 0
    table_min: int = 0
    table_max: Optional[int] = None
    # every elem segment in index order, for table.init/elem.drop:
    # ("active", offset, [funcidx]) | ("passive", None, [funcidx]) |
    # ("declared", None, [funcidx]) — active ones are applied to the
    # table then implicitly dropped at instantiation, declared ones
    # exist only to forward-declare ref.func targets and start dropped
    # (bulk-memory/reference-types spec).  null refs are -1.
    elemsegs: list = field(default_factory=list)
    mem_min: int = 0
    mem_max: Optional[int] = None
    globals: list = field(default_factory=list)   # (valtype, mutable, init)
    exports: dict = field(default_factory=dict)   # name -> (kind, idx)
    start: Optional[int] = None
    data: list = field(default_factory=list)      # active: (offset, bytes)
    # every data segment in index order, for memory.init/data.drop:
    # ("active"|"passive", bytes) — active ones are implicitly dropped
    # after instantiation (bulk-memory spec)
    datasegs: list = field(default_factory=list)


def _decode_valtype(r: _Reader) -> int:
    t = r.u8()
    if t not in _VALNAMES:
        raise WasmError(f"unsupported value type 0x{t:02x}")
    return t


def _decode_blocktype(r: _Reader, types=None) -> tuple:
    """(params, results) valtype tuples.  Three encodings (wasm 1.1 /
    multi-value): 0x40 = empty, a single valtype byte = one result, or
    a non-negative s33 = index into the type section (full signature,
    params enter the block on the stack)."""
    t = r.b[r.p]
    if t == 0x40:
        r.p += 1
        return ((), ())
    if t in _VALNAMES:
        r.p += 1
        return ((), (t,))
    if t >= 0x80 or t < 0x40:      # non-negative s33 -> type index
        idx = r.uleb()
        if types is None or idx >= len(types):
            raise WasmError(f"blocktype type index {idx} out of range")
        ft = types[idx]
        return (ft.params, ft.results)
    raise WasmError(f"bad blocktype 0x{t:02x}")


# ------------------------------------------------------------- SIMD tables
# v128 values travel the stack as 16-byte `bytes`; lane math runs on numpy
# views.  Tables key the fixed-width SIMD proposal's sub-opcodes (0xFD
# prefix) to (dtype, operation) pairs so the executor stays a dispatch.

_SD = {0: "<i1", 1: "<i2", 2: "<i4", 3: "<i8"}   # signed by log2 width
_UD = {0: "<u1", 1: "<u2", 2: "<u4", 3: "<u8"}

_SIMD_CMP: dict[int, tuple[str, str]] = {}
for _b, _w in ((35, 0), (45, 1), (55, 2)):
    for _j, _dt in enumerate((_SD[_w], _SD[_w], _SD[_w], _UD[_w],
                              _SD[_w], _UD[_w], _SD[_w], _UD[_w],
                              _SD[_w], _UD[_w])):
        _SIMD_CMP[_b + _j] = (_dt, ("eq", "ne", "lt", "lt", "gt", "gt",
                                    "le", "le", "ge", "ge")[_j])
for _b, _dt in ((65, "<f4"), (71, "<f8")):
    for _j, _nm in enumerate(("eq", "ne", "lt", "gt", "le", "ge")):
        _SIMD_CMP[_b + _j] = (_dt, _nm)
for _j, _nm in enumerate(("eq", "ne", "lt", "gt", "le", "ge")):
    _SIMD_CMP[214 + _j] = ("<i8", _nm)

_SIMD_IBIN = {  # wrap-around + saturating + min/max/avgr integer binops
    110: ("<u1", "add"), 113: ("<u1", "sub"),
    142: ("<u2", "add"), 145: ("<u2", "sub"), 149: ("<u2", "mul"),
    174: ("<u4", "add"), 177: ("<u4", "sub"), 181: ("<u4", "mul"),
    206: ("<u8", "add"), 209: ("<u8", "sub"), 213: ("<u8", "mul"),
    111: ("<i1", "add_sat"), 112: ("<u1", "add_sat"),
    114: ("<i1", "sub_sat"), 115: ("<u1", "sub_sat"),
    143: ("<i2", "add_sat"), 144: ("<u2", "add_sat"),
    146: ("<i2", "sub_sat"), 147: ("<u2", "sub_sat"),
    118: ("<i1", "min"), 119: ("<u1", "min"),
    120: ("<i1", "max"), 121: ("<u1", "max"),
    150: ("<i2", "min"), 151: ("<u2", "min"),
    152: ("<i2", "max"), 153: ("<u2", "max"),
    182: ("<i4", "min"), 183: ("<u4", "min"),
    184: ("<i4", "max"), 185: ("<u4", "max"),
    123: ("<u1", "avgr"), 155: ("<u2", "avgr"),
}
_SIMD_IUN = {
    96: ("<i1", "abs"), 97: ("<u1", "neg"), 98: ("<u1", "popcnt"),
    128: ("<i2", "abs"), 129: ("<u2", "neg"),
    160: ("<i4", "abs"), 161: ("<u4", "neg"),
    192: ("<i8", "abs"), 193: ("<u8", "neg"),
}
_SIMD_ALLTRUE = {99: "<u1", 131: "<u2", 163: "<u4", 195: "<u8"}
_SIMD_BITMASK = {100: "<i1", 132: "<i2", 164: "<i4", 196: "<i8"}
_SIMD_SHIFT = {
    107: ("<u1", "shl"), 108: ("<i1", "shr"), 109: ("<u1", "shr"),
    139: ("<u2", "shl"), 140: ("<i2", "shr"), 141: ("<u2", "shr"),
    171: ("<u4", "shl"), 172: ("<i4", "shr"), 173: ("<u4", "shr"),
    203: ("<u8", "shl"), 204: ("<i8", "shr"), 205: ("<u8", "shr"),
}
_SIMD_FUN = {
    103: ("<f4", "ceil"), 104: ("<f4", "floor"), 105: ("<f4", "trunc"),
    106: ("<f4", "nearest"), 116: ("<f8", "ceil"), 117: ("<f8", "floor"),
    122: ("<f8", "trunc"), 148: ("<f8", "nearest"),
    224: ("<f4", "abs"), 225: ("<f4", "neg"), 227: ("<f4", "sqrt"),
    236: ("<f8", "abs"), 237: ("<f8", "neg"), 239: ("<f8", "sqrt"),
}
_SIMD_FBIN = {}
for _b, _dt in ((228, "<f4"), (240, "<f8")):
    for _j, _nm in enumerate(("add", "sub", "mul", "div",
                              "min", "max", "pmin", "pmax")):
        _SIMD_FBIN[_b + _j] = (_dt, _nm)

_SIMD_NARROW = {101: ("<i2", "<i1"), 102: ("<i2", "<u1"),
                133: ("<i4", "<i2"), 134: ("<i4", "<u2")}
_SIMD_EXTEND = {}
for _b, _src, _dst in ((135, "<i1", "<i2"), (167, "<i2", "<i4"),
                       (199, "<i4", "<i8")):
    _usrc = "<u" + _src[2]
    _udst = "<u" + _dst[2]
    _SIMD_EXTEND[_b] = (_src, _dst, "low")
    _SIMD_EXTEND[_b + 1] = (_src, _dst, "high")
    _SIMD_EXTEND[_b + 2] = (_usrc, _udst, "low")
    _SIMD_EXTEND[_b + 3] = (_usrc, _udst, "high")

# lane counts for extract/replace immediates (decode-time validation)
_SIMD_LANE_N = {21: 16, 22: 16, 23: 16, 24: 8, 25: 8, 26: 8,
                27: 4, 28: 4, 29: 2, 30: 2, 31: 4, 32: 4, 33: 2, 34: 2}

_SIMD_SUPPORTED = (
    set(range(14, 21)) | set(_SIMD_CMP) | set(range(77, 84))
    | set(_SIMD_IBIN) | set(_SIMD_IUN) | set(_SIMD_ALLTRUE)
    | set(_SIMD_BITMASK) | set(_SIMD_SHIFT) | set(_SIMD_FUN)
    | set(_SIMD_FBIN) | set(_SIMD_NARROW) | set(_SIMD_EXTEND)
    | {94, 95, 186} | set(range(248, 256))
)


# opcode name tables keep the decoder readable; executor dispatches on int.

def _decode_expr(r: _Reader, types=None) -> list:
    """Decode instructions until the matching 0x0B end (depth balanced)."""
    out = []
    depth = 0
    while True:
        op = r.u8()
        if op in (0x02, 0x03, 0x04):            # block, loop, if
            out.append((op, _decode_blocktype(r, types)))
            depth += 1
        elif op == 0x05:                        # else
            out.append((op,))
        elif op == 0x0B:                        # end
            out.append((op,))
            if depth == 0:
                return out
            depth -= 1
        elif op in (0x0C, 0x0D):                # br, br_if
            out.append((op, r.uleb()))
        elif op == 0x0E:                        # br_table
            n = r.uleb()
            targets = [r.uleb() for _ in range(n)]
            out.append((op, targets, r.uleb()))
        elif op == 0x0F:                        # return
            out.append((op,))
        elif op == 0x10:                        # call
            out.append((op, r.uleb()))
        elif op == 0x11:                        # call_indirect
            out.append((op, r.uleb(), r.uleb()))
        elif op in (0x00, 0x01):                # unreachable, nop
            out.append((op,))
        elif op in (0x1A, 0x1B):                # drop, select
            out.append((op,))
        elif op in (0x20, 0x21, 0x22, 0x23, 0x24):  # local/global access
            out.append((op, r.uleb()))
        elif op in (0x25, 0x26):                # table.get/set (table 0)
            if r.uleb() != 0:
                raise WasmError("only table 0 supported")
            out.append((op,))
        elif op == 0xD0:                        # ref.null t -> -1
            r.u8()
            out.append((op,))
        elif op == 0xD1:                        # ref.is_null
            out.append((op,))
        elif op == 0xD2:                        # ref.func f
            out.append((op, r.uleb()))
        elif 0x28 <= op <= 0x3E:                # loads & stores
            align, offset = r.uleb(), r.uleb()
            out.append((op, align, offset))
        elif op in (0x3F, 0x40):                # memory.size, memory.grow
            r.uleb()                            # reserved 0x00
            out.append((op,))
        elif op == 0x41:
            # canonical value representation is unsigned (ALU ops wrap)
            out.append((op, r.sleb(32) & 0xFFFFFFFF))
        elif op == 0x42:
            out.append((op, r.sleb(64) & 0xFFFFFFFFFFFFFFFF))
        elif op == 0x43:
            out.append((op, r.f32()))
        elif op == 0x44:
            out.append((op, r.f64()))
        elif 0x45 <= op <= 0xC4:                # numeric ops, no immediates
            out.append((op,))
        elif op == 0xFC:                        # misc prefix (bulk memory
            sub = r.uleb()                      # + saturating truncation)
            if sub <= 7:                        # ixx.trunc_sat_fyy_s/u
                out.append((0xFC00 | sub,))
            elif sub == 8:                      # memory.init dataidx mem
                seg = r.uleb()
                if r.u8() != 0:
                    raise WasmError("memory.init: only memory 0")
                out.append((0xFC08, seg))
            elif sub == 9:                      # data.drop dataidx
                out.append((0xFC09, r.uleb()))
            elif sub == 10:                     # memory.copy mem mem
                if r.u8() != 0 or r.u8() != 0:
                    raise WasmError("memory.copy: only memory 0")
                out.append((0xFC0A,))
            elif sub == 11:                     # memory.fill mem
                if r.u8() != 0:
                    raise WasmError("memory.fill: only memory 0")
                out.append((0xFC0B,))
            elif sub == 12:                     # table.init elem table
                seg = r.uleb()
                if r.uleb() != 0:
                    raise WasmError("table.init: only table 0")
                out.append((0xFC0C, seg))
            elif sub == 13:                     # elem.drop elemidx
                out.append((0xFC0D, r.uleb()))
            elif sub == 14:                     # table.copy table table
                if r.uleb() != 0 or r.uleb() != 0:
                    raise WasmError("table.copy: only table 0")
                out.append((0xFC0E,))
            elif sub in (15, 16, 17):           # table.grow/size/fill
                if r.uleb() != 0:
                    raise WasmError("table.*: only table 0")
                out.append((0xFC00 | sub,))
            else:
                raise WasmError(f"unsupported 0xFC opcode {sub}")
        elif op == 0xFD:                        # SIMD prefix
            sub = r.uleb()
            # ops are re-keyed as 0xFD00|sub so the executor still
            # dispatches on one int
            if sub <= 11 or sub in (92, 93):    # loads/store: memarg
                align, offset = r.uleb(), r.uleb()
                out.append((0xFD00 | sub, align, offset))
            elif 84 <= sub <= 91:               # lane load/store: +lane
                align, offset = r.uleb(), r.uleb()
                lane = r.u8()
                if lane >= 16 >> ((sub - 84) & 3):
                    raise WasmError(f"lane {lane} out of range for "
                                    f"SIMD op 0xfd {sub}")
                out.append((0xFD00 | sub, align, offset, lane))
            elif sub in (12, 13):               # const / shuffle: 16 bytes
                imm = bytes(r.b[r.p:r.p + 16])
                if len(imm) != 16:
                    raise WasmError("truncated v128 immediate")
                r.p += 16
                if sub == 13 and any(i >= 32 for i in imm):
                    raise WasmError("shuffle lane index >= 32")
                out.append((0xFD00 | sub, imm))
            elif 21 <= sub <= 34:               # lane ops: lane index
                lane = r.u8()
                if lane >= _SIMD_LANE_N[sub]:
                    raise WasmError(f"lane {lane} out of range for "
                                    f"SIMD op 0xfd {sub}")
                out.append((0xFD00 | sub, lane))
            elif sub in _SIMD_SUPPORTED:
                out.append((0xFD00 | sub,))
            else:
                raise WasmError(f"unsupported SIMD opcode 0xfd {sub} "
                                "(q15mulr/extadd/extmul/relaxed tail is "
                                "out of scope; see module docstring)")
        else:
            raise WasmError(f"unsupported opcode 0x{op:02x}")


def decode_module(data: bytes) -> Module:
    if not data.startswith(MAGIC):
        raise WasmError("bad magic (not a wasm binary, or not version 1)")
    r = _Reader(data, len(MAGIC))
    m = Module()
    func_type_idx: list[int] = []
    bodies: list[tuple] = []

    while not r.eof():
        sec = r.u8()
        size = r.uleb()
        body = _Reader(r.bytes_(size))
        if sec == 1:                                     # type
            for _ in range(body.uleb()):
                if body.u8() != 0x60:
                    raise WasmError("bad functype tag")
                params = tuple(_decode_valtype(body)
                               for _ in range(body.uleb()))
                results = tuple(_decode_valtype(body)
                                for _ in range(body.uleb()))
                m.types.append(FuncType(params, results))
        elif sec == 2:                                   # import
            for _ in range(body.uleb()):
                mod, name = body.name(), body.name()
                kind = body.u8()
                if kind == 0x00:                         # func
                    ti = body.uleb()
                    m.imports.append((mod, name, "func", ti))
                    m.n_imported_funcs += 1
                elif kind == 0x02:                       # memory import
                    flags = body.u8()
                    mn = body.uleb()
                    mx = body.uleb() if flags & 1 else None
                    m.imports.append((mod, name, "memory", (mn, mx)))
                    m.mem_min = max(m.mem_min, mn)
                else:
                    raise WasmError(
                        f"unsupported import kind {kind} for {mod}.{name}")
        elif sec == 3:                                   # function
            func_type_idx = [body.uleb() for _ in range(body.uleb())]
        elif sec == 4:                                   # table
            for _ in range(body.uleb()):
                if body.u8() != 0x70:
                    raise WasmError("only funcref tables supported")
                flags = body.u8()
                m.table_min = body.uleb()
                if flags & 1:
                    m.table_max = body.uleb()
        elif sec == 5:                                   # memory
            for _ in range(body.uleb()):
                flags = body.u8()
                m.mem_min = body.uleb()
                if flags & 1:
                    m.mem_max = body.uleb()
        elif sec == 6:                                   # global
            for _ in range(body.uleb()):
                vt = _decode_valtype(body)
                mut = body.u8()
                init = _decode_expr(body)
                m.globals.append((vt, bool(mut), init))
        elif sec == 7:                                   # export
            for _ in range(body.uleb()):
                name = body.name()
                kind, idx = body.u8(), body.uleb()
                m.exports[name] = (("func", "table", "memory",
                                    "global")[kind], idx)
        elif sec == 8:                                   # start
            m.start = body.uleb()
        elif sec == 9:                                   # elem
            # full flag matrix (spec 5.5.12): bit0 passive/declared,
            # bit1 explicit-table-or-declared, bit2 expr-encoded refs
            def _ref_expr(r: _Reader) -> int:
                op = r.u8()
                if op == 0xD2:                  # ref.func f
                    v = r.uleb()
                elif op == 0xD0:                # ref.null t
                    r.u8()
                    v = -1
                else:
                    raise WasmError(f"unsupported elem expr op {op:#x}")
                if r.u8() != 0x0B:
                    raise WasmError("elem expr: expected end")
                return v

            for _ in range(body.uleb()):
                flags = body.uleb()
                if flags > 7:
                    raise WasmError(f"bad elem segment flags {flags}")
                off = None
                if flags & 1 == 0:                       # active
                    if flags & 2:                        # explicit table
                        if body.uleb() != 0:
                            raise WasmError("only table 0 supported")
                    off = _const_expr_value(_decode_expr(body))
                if flags & 3 != 0:
                    # elemkind (0x00 = funcref) or reftype (0x70)
                    if body.u8() not in (0x00, 0x70):
                        raise WasmError("only funcref elem segments")
                refs = [(_ref_expr(body) if flags & 4 else body.uleb())
                        for _ in range(body.uleb())]
                mode = ("active" if flags & 1 == 0
                        else "declared" if flags & 3 == 3 else "passive")
                m.elemsegs.append((mode, off, refs))
        elif sec == 10:                                  # code
            for _ in range(body.uleb()):
                sz = body.uleb()
                fr = _Reader(body.bytes_(sz))
                locals_: list[int] = []
                for _ in range(fr.uleb()):
                    count, vt = fr.uleb(), _decode_valtype(fr)
                    locals_.extend([vt] * count)
                bodies.append((locals_, _decode_expr(fr, m.types)))
        elif sec == 11:                                  # data
            for _ in range(body.uleb()):
                flags = body.uleb()
                if flags == 1:                           # passive
                    payload = body.bytes_(body.uleb())
                    m.datasegs.append(("passive", payload))
                    continue
                if flags == 2 and body.uleb() != 0:      # explicit memidx
                    raise WasmError("only memory-0 data segments")
                elif flags not in (0, 2):
                    raise WasmError(f"bad data segment flags {flags}")
                off_expr = _decode_expr(body)
                payload = body.bytes_(body.uleb())
                m.data.append((_const_expr_value(off_expr), payload))
                m.datasegs.append(("active", payload))
        # custom (0), datacount (12) and unknown sections are skipped
        # (the decoder doesn't need the datacount hint: code is decoded
        # after the full module is read)

    if len(func_type_idx) != len(bodies):
        raise WasmError("function/code section mismatch")
    for ti, (locals_, code) in zip(func_type_idx, bodies):
        m.funcs.append(Function(m.types[ti], locals_, code))
    for name, (kind, idx) in m.exports.items():
        if kind == "func" and idx >= m.n_imported_funcs:
            m.funcs[idx - m.n_imported_funcs].name = name
    return m


def _const_expr_value(expr: list) -> int:
    if len(expr) >= 1 and expr[0][0] in (0x41, 0x42):
        return expr[0][1]
    raise WasmError("unsupported constant expression")


# ---------------------------------------------------------------- execution

def _wrap32(v: int) -> int:
    return v & 0xFFFFFFFF


def _wrap64(v: int) -> int:
    return v & 0xFFFFFFFFFFFFFFFF


def _sign32(v: int) -> int:
    v &= 0xFFFFFFFF
    return v - 0x100000000 if v & 0x80000000 else v


def _sign64(v: int) -> int:
    v &= 0xFFFFFFFFFFFFFFFF
    return v - (1 << 64) if v & (1 << 63) else v


def _trunc_sat(sub: int, v: float) -> int:
    """0xFC 0..7: saturating float->int truncation (NaN -> 0, out of
    range clamps — never traps).  Result in canonical unsigned form."""
    signed = (sub & 1) == 0      # 0,2,4,6 = _s; 1,3,5,7 = _u
    bits = 64 if sub >= 4 else 32
    if math.isnan(v):
        return 0
    lo, hi = ((-(1 << (bits - 1)), (1 << (bits - 1)) - 1) if signed
              else (0, (1 << bits) - 1))
    if v <= lo:
        t = lo
    elif v >= hi:
        t = hi
    else:
        t = math.trunc(v)
    return _wrap32(t) if bits == 32 else _wrap64(t)


def _trunc(v: float, lo: int, hi: int, name: str) -> int:
    if math.isnan(v) or math.isinf(v):
        raise Trap(f"invalid conversion to integer ({name})")
    t = math.trunc(v)
    if t < lo or t > hi:
        raise Trap(f"integer overflow in {name}")
    return t


def _f32(v: float) -> float:
    return struct.unpack("<f", struct.pack("<f", v))[0]


class _Label:
    __slots__ = ("arity", "stack_h", "cont", "is_loop")

    def __init__(self, arity, stack_h, cont, is_loop):
        self.arity = arity
        self.stack_h = stack_h
        self.cont = cont          # instruction index to jump to on br
        self.is_loop = is_loop


class Instance:
    """An instantiated module: memory, globals, and callable exports."""

    MAX_STEPS = 200_000_000

    def __init__(self, module: Module,
                 host: dict[tuple[str, str], Callable]):
        self.m = module
        self.mem = bytearray(module.mem_min * PAGE)
        self.mem_max = module.mem_max
        self.globals: list[Any] = []
        for vt, _mut, init in module.globals:
            self.globals.append(_const_expr_value(init)
                                if init[0][0] in (0x41, 0x42)
                                else (init[0][1] if init[0][0] in
                                      (0x43, 0x44, 0xFD0C) else 0))
        self.host: list[Optional[Callable]] = []
        self.host_types: list[FuncType] = []
        for mod, name, kind, extra in module.imports:
            if kind == "func":
                fn = host.get((mod, name))
                if fn is None:
                    raise WasmError(f"unresolved import {mod}.{name}")
                self.host.append(fn)
                self.host_types.append(module.types[extra])
        for off, payload in module.data:
            end = off + len(payload)
            if end > len(self.mem):
                raise WasmError("data segment out of bounds")
            self.mem[off:end] = payload
        # runtime segment store for memory.init/data.drop: passive
        # segments keep their bytes until dropped; active segments are
        # implicitly dropped at instantiation (bulk-memory spec)
        self.datasegs: list[Optional[bytes]] = [
            payload if mode == "passive" else None
            for mode, payload in module.datasegs]
        # runtime funcref table (-1 = null) + elem segment store with
        # the same lifecycle as datasegs: active applied then dropped,
        # declared born dropped, passive live until elem.drop
        self.table: list[int] = [-1] * module.table_min
        self.elemsegs: list[Optional[list[int]]] = []
        for mode, off, refs in module.elemsegs:
            if mode == "active":
                if off + len(refs) > len(self.table):
                    raise WasmError("elem segment out of bounds")
                self.table[off: off + len(refs)] = refs
                self.elemsegs.append(None)
            else:
                self.elemsegs.append(list(refs)
                                     if mode == "passive" else None)
        self.steps = 0
        if module.start is not None:
            self._call_function(module.start, [])

    # -- public API ------------------------------------------------------
    @property
    def exports(self) -> list[str]:
        return [n for n, (k, _) in self.m.exports.items() if k == "func"]

    def invoke(self, name: str, args: list) -> list:
        if name not in self.m.exports or self.m.exports[name][0] != "func":
            raise WasmError(f"no exported function '{name}'")
        self.steps = 0
        return self._call_function(self.m.exports[name][1], list(args))

    # memory helpers for host functions
    def mem_read(self, ptr: int, n: int) -> bytes:
        if ptr < 0 or ptr + n > len(self.mem):
            raise Trap("host memory read out of bounds")
        return bytes(self.mem[ptr:ptr + n])

    def mem_write(self, ptr: int, data: bytes) -> None:
        if ptr < 0 or ptr + len(data) > len(self.mem):
            raise Trap("host memory write out of bounds")
        self.mem[ptr:ptr + len(data)] = data

    def mem_read_cstr(self, ptr: int, maxlen: int = 1 << 20) -> bytes:
        end = self.mem.find(b"\0", ptr, min(ptr + maxlen, len(self.mem)))
        if end < 0:
            raise Trap("unterminated string in wasm memory")
        return bytes(self.mem[ptr:end])

    # -- function invocation ---------------------------------------------
    def _call_function(self, idx: int, args: list) -> list:
        n_imp = self.m.n_imported_funcs
        if idx < n_imp:
            ft = self.host_types[idx]
            res = self.host[idx](self, *args)
            if res is None:
                return []
            if isinstance(res, tuple):
                return list(res)
            return [res] if ft.results else []
        fn = self.m.funcs[idx - n_imp]
        locals_: list[Any] = [
            _wrap32(a) if t == I32 else (_wrap64(a) if t == I64 else a)
            for a, t in zip(args, fn.type.params)]
        for vt in fn.locals:
            locals_.append(b"\x00" * 16 if vt == V128
                           else 0.0 if vt in (F32, F64) else 0)
        return self._exec(fn, locals_)

    # -- the interpreter loop --------------------------------------------
    def _exec(self, fn: Function, locals_: list) -> list:
        code = fn.body
        stack: list[Any] = []
        labels: list[_Label] = [
            _Label(len(fn.type.results), 0, len(code) - 1, False)]
        pc = 0
        mem = self.mem

        def grow_check() -> None:
            self.steps += 1
            if self.steps > self.MAX_STEPS:
                raise Trap("execution budget exceeded (runaway loop?)")

        def find_matching(from_pc: int) -> tuple[int, int]:
            """For block/loop/if at from_pc: (else_pc|-1, end_pc)."""
            depth = 0
            else_pc = -1
            i = from_pc + 1
            while i < len(code):
                op2 = code[i][0]
                if op2 in (0x02, 0x03, 0x04):
                    depth += 1
                elif op2 == 0x05 and depth == 0:
                    else_pc = i
                elif op2 == 0x0B:
                    if depth == 0:
                        return else_pc, i
                    depth -= 1
                i += 1
            raise WasmError("unbalanced block")

        def do_branch(n: int) -> int:
            # br n targets the n-th enclosing label: a loop branch re-enters
            # (its label survives), a block branch exits (label popped too)
            lbl = labels[-1 - n]
            keep = stack[len(stack) - lbl.arity:] if lbl.arity else []
            del stack[lbl.stack_h:]
            stack.extend(keep)
            if lbl.is_loop:
                del labels[len(labels) - n:]
            else:
                del labels[len(labels) - n - 1:]
            return lbl.cont

        while pc < len(code):
            ins = code[pc]
            op = ins[0]
            grow_check()

            if op == 0x0B:                       # end
                if len(labels) > 1:
                    labels.pop()
                pc += 1
                continue
            if op == 0x01:                       # nop
                pc += 1
                continue
            if op == 0x00:
                raise Trap("unreachable executed")
            if op == 0x02:                       # block
                _else, end = find_matching(pc)
                bt_params, bt_results = ins[1]
                labels.append(_Label(len(bt_results),
                                     len(stack) - len(bt_params),
                                     end + 1, False))
                pc += 1
                continue
            if op == 0x03:                       # loop
                # cont = first instruction INSIDE: a br re-enters the body
                # without re-executing the loop opcode (label is kept live
                # by do_branch, so it is pushed exactly once).  A branch
                # to a loop carries the loop's PARAMS back to the top.
                bt_params, _bt_results = ins[1]
                labels.append(_Label(len(bt_params),
                                     len(stack) - len(bt_params),
                                     pc + 1, True))
                pc += 1
                continue
            if op == 0x04:                       # if
                else_pc, end = find_matching(pc)
                cond = stack.pop()
                bt_params, bt_results = ins[1]
                labels.append(_Label(len(bt_results),
                                     len(stack) - len(bt_params),
                                     end + 1, False))
                if cond:
                    pc += 1
                else:
                    pc = (else_pc + 1) if else_pc >= 0 else end
                continue
            if op == 0x05:                       # else (end of then-arm)
                pc = labels[-1].cont             # jump past end
                labels.pop()
                continue
            if op == 0x0C:                       # br
                pc = do_branch(ins[1])
                continue
            if op == 0x0D:                       # br_if
                if stack.pop():
                    pc = do_branch(ins[1])
                else:
                    pc += 1
                continue
            if op == 0x0E:                       # br_table
                i = stack.pop()
                targets, default = ins[1], ins[2]
                n = targets[i] if 0 <= i < len(targets) else default
                pc = do_branch(n)
                continue
            if op == 0x0F:                       # return
                arity = len(fn.type.results)
                return stack[len(stack) - arity:] if arity else []
            if op == 0x10:                       # call
                callee_idx = ins[1]
                ft = (self.host_types[callee_idx]
                      if callee_idx < self.m.n_imported_funcs
                      else self.m.funcs[
                          callee_idx - self.m.n_imported_funcs].type)
                argn = len(ft.params)
                args = stack[len(stack) - argn:] if argn else []
                del stack[len(stack) - argn:]
                stack.extend(self._call_function(callee_idx, args))
                pc += 1
                continue
            if op == 0x11:                       # call_indirect
                ti = ins[1]
                elem_i = stack.pop()
                target = self.table[elem_i] \
                    if 0 <= elem_i < len(self.table) else -1
                if target < 0:
                    raise Trap("undefined table element")
                ft = self.m.types[ti]
                argn = len(ft.params)
                args = stack[len(stack) - argn:] if argn else []
                del stack[len(stack) - argn:]
                stack.extend(self._call_function(target, args))
                pc += 1
                continue
            if op == 0xD0:                       # ref.null -> -1
                stack.append(-1)
            elif op == 0xD1:                     # ref.is_null
                stack.append(1 if stack.pop() < 0 else 0)
            elif op == 0xD2:                     # ref.func f
                stack.append(ins[1])
            elif op == 0x25:                     # table.get
                i = _wrap32(stack.pop())
                if i >= len(self.table):
                    raise Trap("out of bounds table.get")
                stack.append(self.table[i])
            elif op == 0x26:                     # table.set
                v = stack.pop()
                i = _wrap32(stack.pop())
                if i >= len(self.table):
                    raise Trap("out of bounds table.set")
                self.table[i] = v
            elif op == 0x1A:                     # drop
                stack.pop()
            elif op == 0x1B:                     # select
                c = stack.pop()
                b = stack.pop()
                a = stack.pop()
                stack.append(a if c else b)
            elif op == 0x20:
                stack.append(locals_[ins[1]])
            elif op == 0x21:
                locals_[ins[1]] = stack.pop()
            elif op == 0x22:
                locals_[ins[1]] = stack[-1]
            elif op == 0x23:
                stack.append(self.globals[ins[1]])
            elif op == 0x24:
                self.globals[ins[1]] = stack.pop()
            elif 0x28 <= op <= 0x35:             # loads
                addr = _wrap32(stack.pop()) + ins[2]
                stack.append(self._load(op, addr))
            elif 0x36 <= op <= 0x3E:             # stores
                val = stack.pop()
                addr = _wrap32(stack.pop()) + ins[2]
                self._store(op, addr, val)
            elif op == 0x3F:                     # memory.size
                stack.append(len(mem) // PAGE)
            elif op == 0x40:                     # memory.grow
                delta = _wrap32(stack.pop())
                old = len(self.mem) // PAGE
                new = old + delta
                # wasm32 hard ceiling (65536 pages = 4 GiB) applies even
                # with no declared max; failure pushes -1, never raises
                cap = self.mem_max if self.mem_max is not None else 65536
                if new > min(cap, 65536):
                    stack.append(_wrap32(-1))
                else:
                    try:
                        self.mem.extend(b"\0" * (delta * PAGE))
                    except MemoryError:
                        stack.append(_wrap32(-1))
                    else:
                        mem = self.mem
                        stack.append(old)
            elif op in (0x41, 0x42, 0x43, 0x44):  # consts
                stack.append(ins[1])
            elif 0xFC00 <= op <= 0xFC07:         # ixx.trunc_sat_fyy_s/u
                stack.append(_trunc_sat(op & 7, stack.pop()))
            elif op == 0xFC08:                   # memory.init
                n = _wrap32(stack.pop())
                s = _wrap32(stack.pop())
                d = _wrap32(stack.pop())
                seg = self.datasegs[ins[1]]
                src = seg if seg is not None else b""   # dropped = empty
                if s + n > len(src) or d + n > len(mem):
                    raise Trap("out of bounds memory.init")
                mem[d:d + n] = src[s:s + n]
            elif op == 0xFC09:                   # data.drop
                self.datasegs[ins[1]] = None
            elif op == 0xFC0A:                   # memory.copy (memmove)
                n = _wrap32(stack.pop())
                s = _wrap32(stack.pop())
                d = _wrap32(stack.pop())
                if s + n > len(mem) or d + n > len(mem):
                    raise Trap("out of bounds memory.copy")
                mem[d:d + n] = bytes(mem[s:s + n])
            elif op == 0xFC0B:                   # memory.fill
                n = _wrap32(stack.pop())
                v = _wrap32(stack.pop()) & 0xFF
                d = _wrap32(stack.pop())
                if d + n > len(mem):
                    raise Trap("out of bounds memory.fill")
                mem[d:d + n] = bytes([v]) * n
            elif op == 0xFC0C:                   # table.init
                n = _wrap32(stack.pop())
                s = _wrap32(stack.pop())
                d = _wrap32(stack.pop())
                seg = self.elemsegs[ins[1]] \
                    if ins[1] < len(self.elemsegs) else None
                src = seg if seg is not None else []
                if s + n > len(src) or d + n > len(self.table):
                    raise Trap("out of bounds table.init")
                self.table[d:d + n] = src[s:s + n]
            elif op == 0xFC0D:                   # elem.drop
                if ins[1] < len(self.elemsegs):
                    self.elemsegs[ins[1]] = None
            elif op == 0xFC0E:                   # table.copy (memmove)
                n = _wrap32(stack.pop())
                s = _wrap32(stack.pop())
                d = _wrap32(stack.pop())
                if s + n > len(self.table) or d + n > len(self.table):
                    raise Trap("out of bounds table.copy")
                self.table[d:d + n] = self.table[s:s + n]
            elif op == 0xFC0F:                   # table.grow
                n = _wrap32(stack.pop())
                v = stack.pop()
                old = len(self.table)
                # like memory.grow's 4 GiB page ceiling: an untrusted
                # module must not be able to allocate unbounded host
                # memory through a no-max table — failure is the
                # spec's -1, never a host MemoryError
                cap = self.m.table_max if self.m.table_max is not None \
                    else 1 << 20
                if old + n > cap:
                    stack.append(_wrap32(-1))
                else:
                    try:
                        self.table.extend([v] * n)
                        stack.append(old)
                    except MemoryError:
                        stack.append(_wrap32(-1))
            elif op == 0xFC10:                   # table.size
                stack.append(len(self.table))
            elif op == 0xFC11:                   # table.fill
                n = _wrap32(stack.pop())
                v = stack.pop()
                i = _wrap32(stack.pop())
                if i + n > len(self.table):
                    raise Trap("out of bounds table.fill")
                self.table[i:i + n] = [v] * n
            elif op >= 0xFD00:                   # SIMD (pops/pushes itself)
                self._simd(ins, stack)
            else:
                stack.append(self._numeric(op, stack))
                # _numeric pops its own operands and returns the result
            pc += 1

        arity = len(fn.type.results)
        return stack[len(stack) - arity:] if arity else []

    # -- memory ----------------------------------------------------------
    _LOADS = {
        0x28: ("<i", 4, False), 0x29: ("<q", 8, False),
        0x2A: ("<f", 4, False), 0x2B: ("<d", 8, False),
        0x2C: ("<b", 1, False), 0x2D: ("<B", 1, False),
        0x2E: ("<h", 2, False), 0x2F: ("<H", 2, False),
        0x30: ("<b", 1, True), 0x31: ("<B", 1, True),
        0x32: ("<h", 2, True), 0x33: ("<H", 2, True),
        0x34: ("<i", 4, True), 0x35: ("<I", 4, True),
    }

    def _load(self, op: int, addr: int):
        fmtc, n, to64 = self._LOADS[op]
        if addr + n > len(self.mem):
            raise Trap("out-of-bounds memory access")
        v = struct.unpack_from(fmtc, self.mem, addr)[0]
        if op in (0x28,):
            return _wrap32(v)
        if op in (0x29,):
            return _wrap64(v)
        if to64:
            return _wrap64(v) if fmtc in ("<i", "<b", "<h") else v
        if fmtc in ("<b", "<h"):
            return _wrap32(v)
        return v

    _STORES = {
        0x36: ("<I", 4), 0x37: ("<Q", 8), 0x38: ("<f", 4), 0x39: ("<d", 8),
        0x3A: ("<B", 1), 0x3B: ("<H", 2), 0x3C: ("<B", 1), 0x3D: ("<H", 2),
        0x3E: ("<I", 4),
    }

    def _store(self, op: int, addr: int, val) -> None:
        fmtc, n = self._STORES[op]
        if addr + n > len(self.mem):
            raise Trap("out-of-bounds memory access")
        if fmtc == "<B":
            val = int(val) & 0xFF
        elif fmtc == "<H":
            val = int(val) & 0xFFFF
        elif fmtc == "<I":
            val = int(val) & 0xFFFFFFFF
        elif fmtc == "<Q":
            val = int(val) & 0xFFFFFFFFFFFFFFFF
        struct.pack_into(fmtc, self.mem, addr, val)

    # -- numeric ops ------------------------------------------------------
    def _numeric(self, op: int, stack: list):
        # i32 compares / ALU --------------------------------------------
        if op == 0x45:                            # i32.eqz
            return int(stack.pop() == 0)
        if 0x46 <= op <= 0x4F:
            b, a = stack.pop(), stack.pop()
            sa, sb = _sign32(a), _sign32(b)
            ua, ub = _wrap32(a), _wrap32(b)
            return int({
                0x46: ua == ub, 0x47: ua != ub,
                0x48: sa < sb, 0x49: ua < ub,
                0x4A: sa > sb, 0x4B: ua > ub,
                0x4C: sa <= sb, 0x4D: ua <= ub,
                0x4E: sa >= sb, 0x4F: ua >= ub,
            }[op])
        if op == 0x50:                            # i64.eqz
            return int(stack.pop() == 0)
        if 0x51 <= op <= 0x5A:
            b, a = stack.pop(), stack.pop()
            sa, sb = _sign64(a), _sign64(b)
            ua, ub = _wrap64(a), _wrap64(b)
            return int({
                0x51: ua == ub, 0x52: ua != ub,
                0x53: sa < sb, 0x54: ua < ub,
                0x55: sa > sb, 0x56: ua > ub,
                0x57: sa <= sb, 0x58: ua <= ub,
                0x59: sa >= sb, 0x5A: ua >= ub,
            }[op])
        if 0x5B <= op <= 0x60:                    # f32 compares
            b, a = stack.pop(), stack.pop()
            return int({0x5B: a == b, 0x5C: a != b, 0x5D: a < b,
                        0x5E: a > b, 0x5F: a <= b, 0x60: a >= b}[op])
        if 0x61 <= op <= 0x66:                    # f64 compares
            b, a = stack.pop(), stack.pop()
            return int({0x61: a == b, 0x62: a != b, 0x63: a < b,
                        0x64: a > b, 0x65: a <= b, 0x66: a >= b}[op])

        if op == 0x67:                            # i32.clz
            v = _wrap32(stack.pop())
            return 32 if v == 0 else 32 - v.bit_length()
        if op == 0x68:                            # i32.ctz
            v = _wrap32(stack.pop())
            return 32 if v == 0 else (v & -v).bit_length() - 1
        if op == 0x69:                            # i32.popcnt
            return bin(_wrap32(stack.pop())).count("1")
        if 0x6A <= op <= 0x78:                    # i32 binary ALU
            b, a = stack.pop(), stack.pop()
            ua, ub = _wrap32(a), _wrap32(b)
            sa, sb = _sign32(a), _sign32(b)
            if op == 0x6A:
                return _wrap32(ua + ub)
            if op == 0x6B:
                return _wrap32(ua - ub)
            if op == 0x6C:
                return _wrap32(ua * ub)
            if op == 0x6D:                        # div_s
                if ub == 0:
                    raise Trap("integer divide by zero")
                q = abs(sa) // abs(sb)
                q = -q if (sa < 0) != (sb < 0) else q
                if q == 0x80000000:
                    raise Trap("integer overflow")
                return _wrap32(q)
            if op == 0x6E:
                if ub == 0:
                    raise Trap("integer divide by zero")
                return ua // ub
            if op == 0x6F:                        # rem_s
                if ub == 0:
                    raise Trap("integer divide by zero")
                r = abs(sa) % abs(sb)
                return _wrap32(-r if sa < 0 else r)
            if op == 0x70:
                if ub == 0:
                    raise Trap("integer divide by zero")
                return ua % ub
            if op == 0x71:
                return ua & ub
            if op == 0x72:
                return ua | ub
            if op == 0x73:
                return ua ^ ub
            if op == 0x74:
                return _wrap32(ua << (ub % 32))
            if op == 0x75:
                return _wrap32(sa >> (ub % 32))
            if op == 0x76:
                return ua >> (ub % 32)
            if op == 0x77:                        # rotl
                k = ub % 32
                return _wrap32((ua << k) | (ua >> (32 - k))) if k else ua
            if op == 0x78:                        # rotr
                k = ub % 32
                return _wrap32((ua >> k) | (ua << (32 - k))) if k else ua

        if op == 0x79:                            # i64.clz
            v = _wrap64(stack.pop())
            return 64 if v == 0 else 64 - v.bit_length()
        if op == 0x7A:
            v = _wrap64(stack.pop())
            return 64 if v == 0 else (v & -v).bit_length() - 1
        if op == 0x7B:
            return bin(_wrap64(stack.pop())).count("1")
        if 0x7C <= op <= 0x8A:                    # i64 binary ALU
            b, a = stack.pop(), stack.pop()
            ua, ub = _wrap64(a), _wrap64(b)
            sa, sb = _sign64(a), _sign64(b)
            if op == 0x7C:
                return _wrap64(ua + ub)
            if op == 0x7D:
                return _wrap64(ua - ub)
            if op == 0x7E:
                return _wrap64(ua * ub)
            if op == 0x7F:
                if ub == 0:
                    raise Trap("integer divide by zero")
                q = abs(sa) // abs(sb)
                q = -q if (sa < 0) != (sb < 0) else q
                if q == 1 << 63:
                    raise Trap("integer overflow")
                return _wrap64(q)
            if op == 0x80:
                if ub == 0:
                    raise Trap("integer divide by zero")
                return ua // ub
            if op == 0x81:
                if ub == 0:
                    raise Trap("integer divide by zero")
                r = abs(sa) % abs(sb)
                return _wrap64(-r if sa < 0 else r)
            if op == 0x82:
                if ub == 0:
                    raise Trap("integer divide by zero")
                return ua % ub
            if op == 0x83:
                return ua & ub
            if op == 0x84:
                return ua | ub
            if op == 0x85:
                return ua ^ ub
            if op == 0x86:
                return _wrap64(ua << (ub % 64))
            if op == 0x87:
                return _wrap64(sa >> (ub % 64))
            if op == 0x88:
                return ua >> (ub % 64)
            if op == 0x89:
                k = ub % 64
                return _wrap64((ua << k) | (ua >> (64 - k))) if k else ua
            if op == 0x8A:
                k = ub % 64
                return _wrap64((ua >> k) | (ua << (64 - k))) if k else ua

        # f32/f64 unary & binary ----------------------------------------
        if 0x8B <= op <= 0x91:                    # f32 unary
            a = stack.pop()
            return _f32({0x8B: abs(a), 0x8C: -a,
                         0x8D: float(math.ceil(a)),
                         0x8E: float(math.floor(a)),
                         0x8F: float(math.trunc(a)),
                         0x90: float(round(a)),
                         0x91: math.sqrt(a) if a >= 0 else math.nan}[op])
        if 0x92 <= op <= 0x98:                    # f32 binary
            b, a = stack.pop(), stack.pop()
            return _f32({0x92: a + b, 0x93: a - b, 0x94: a * b,
                         0x95: (a / b) if b != 0 else
                         (math.inf if a > 0 else
                          (-math.inf if a < 0 else math.nan)),
                         0x96: min(a, b), 0x97: max(a, b),
                         0x98: math.copysign(abs(a), b)}[op])
        if 0x99 <= op <= 0x9F:                    # f64 unary
            a = stack.pop()
            return {0x99: abs(a), 0x9A: -a,
                    0x9B: float(math.ceil(a)),
                    0x9C: float(math.floor(a)),
                    0x9D: float(math.trunc(a)),
                    0x9E: float(round(a)),
                    0x9F: math.sqrt(a) if a >= 0 else math.nan}[op]
        if 0xA0 <= op <= 0xA6:                    # f64 binary
            b, a = stack.pop(), stack.pop()
            return {0xA0: a + b, 0xA1: a - b, 0xA2: a * b,
                    0xA3: (a / b) if b != 0 else
                    (math.inf if a > 0 else
                     (-math.inf if a < 0 else math.nan)),
                    0xA4: min(a, b), 0xA5: max(a, b),
                    0xA6: math.copysign(abs(a), b)}[op]

        # conversions ----------------------------------------------------
        if op == 0xA7:                            # i32.wrap_i64
            return _wrap32(stack.pop())
        if op in (0xA8, 0xAA):                    # i32.trunc_f32/f64_s
            return _wrap32(_trunc(stack.pop(), -(1 << 31), (1 << 31) - 1,
                                  "i32.trunc_s"))
        if op in (0xA9, 0xAB):                    # i32.trunc_f32/f64_u
            return _trunc(stack.pop(), 0, (1 << 32) - 1, "i32.trunc_u")
        if op == 0xAC:                            # i64.extend_i32_s
            return _wrap64(_sign32(stack.pop()))
        if op == 0xAD:                            # i64.extend_i32_u
            return _wrap32(stack.pop())
        if op in (0xAE, 0xB0):                    # i64.trunc_f32/f64_s
            return _wrap64(_trunc(stack.pop(), -(1 << 63), (1 << 63) - 1,
                                  "i64.trunc_s"))
        if op in (0xAF, 0xB1):                    # i64.trunc_f32/f64_u
            return _trunc(stack.pop(), 0, (1 << 64) - 1, "i64.trunc_u")
        if op in (0xB2, 0xB4):                    # f32.convert_i32/i64_s
            return _f32(float(_sign32(stack.pop()) if op == 0xB2
                              else _sign64(stack.pop())))
        if op in (0xB3, 0xB5):                    # f32.convert_u
            return _f32(float(_wrap32(stack.pop()) if op == 0xB3
                              else _wrap64(stack.pop())))
        if op == 0xB6:                            # f32.demote_f64
            return _f32(stack.pop())
        if op in (0xB7, 0xB9):                    # f64.convert_i32/i64_s
            return float(_sign32(stack.pop()) if op == 0xB7
                         else _sign64(stack.pop()))
        if op in (0xB8, 0xBA):                    # f64.convert_u
            return float(_wrap32(stack.pop()) if op == 0xB8
                         else _wrap64(stack.pop()))
        if op == 0xBB:                            # f64.promote_f32
            return float(stack.pop())
        if op == 0xBC:                            # i32.reinterpret_f32
            return struct.unpack("<I", struct.pack("<f", stack.pop()))[0]
        if op == 0xBD:                            # i64.reinterpret_f64
            return struct.unpack("<Q", struct.pack("<d", stack.pop()))[0]
        if op == 0xBE:                            # f32.reinterpret_i32
            return struct.unpack("<f", struct.pack("<I",
                                                   _wrap32(stack.pop())))[0]
        if op == 0xBF:                            # f64.reinterpret_i64
            return struct.unpack("<d", struct.pack("<Q",
                                                   _wrap64(stack.pop())))[0]
        if op == 0xC0:                            # i32.extend8_s
            return _wrap32(struct.unpack(
                "<b", struct.pack("<B", _wrap32(stack.pop()) & 0xFF))[0])
        if op == 0xC1:                            # i32.extend16_s
            return _wrap32(struct.unpack(
                "<h", struct.pack("<H", _wrap32(stack.pop()) & 0xFFFF))[0])
        if op == 0xC2:                            # i64.extend8_s
            return _wrap64(struct.unpack(
                "<b", struct.pack("<B", _wrap64(stack.pop()) & 0xFF))[0])
        if op == 0xC3:                            # i64.extend16_s
            return _wrap64(struct.unpack(
                "<h", struct.pack("<H", _wrap64(stack.pop()) & 0xFFFF))[0])
        if op == 0xC4:                            # i64.extend32_s
            return _wrap64(_sign32(stack.pop()))

        raise WasmError(f"unsupported numeric opcode 0x{op:02x}")

    # -- SIMD (v128) -------------------------------------------------------

    def _simd(self, ins: tuple, stack: list) -> None:
        """Execute one 0xFD-prefixed op.  v128 values are 16-byte bytes
        on the stack; lane math runs on numpy views of them."""
        sub = ins[0] - 0xFD00
        mem = self.mem

        def ld(addr: int, n: int) -> bytes:
            if addr < 0 or addr + n > len(mem):
                raise Trap("out-of-bounds memory access")
            return bytes(mem[addr:addr + n])

        def stv(addr: int, data: bytes) -> None:
            if addr < 0 or addr + len(data) > len(mem):
                raise Trap("out-of-bounds memory access")
            mem[addr:addr + len(data)] = data

        # ---- memory ------------------------------------------------------
        if sub == 0:                              # v128.load
            stack.append(ld(_wrap32(stack.pop()) + ins[2], 16))
        elif 1 <= sub <= 6:                       # load-extend 8 bytes
            src, dst = (("<i1", "<i2"), ("<u1", "<u2"),
                        ("<i2", "<i4"), ("<u2", "<u4"),
                        ("<i4", "<i8"), ("<u4", "<u8"))[sub - 1]
            raw = ld(_wrap32(stack.pop()) + ins[2], 8)
            stack.append(np.frombuffer(raw, src).astype(dst).tobytes())
        elif 7 <= sub <= 10:                      # loadN_splat
            n = 1 << (sub - 7)
            stack.append(ld(_wrap32(stack.pop()) + ins[2], n) * (16 // n))
        elif sub == 11:                           # v128.store
            v = stack.pop()
            stv(_wrap32(stack.pop()) + ins[2], v)
        elif sub in (92, 93):                     # load32_zero/load64_zero
            n = 4 if sub == 92 else 8
            stack.append(ld(_wrap32(stack.pop()) + ins[2], n)
                         + b"\x00" * (16 - n))
        elif 84 <= sub <= 87:                     # loadN_lane
            n = 1 << (sub - 84)
            lane = ins[3]
            v = bytearray(stack.pop())
            v[lane * n:(lane + 1) * n] = ld(
                _wrap32(stack.pop()) + ins[2], n)
            stack.append(bytes(v))
        elif 88 <= sub <= 91:                     # storeN_lane
            n = 1 << (sub - 88)
            lane = ins[3]
            v = stack.pop()
            stv(_wrap32(stack.pop()) + ins[2],
                v[lane * n:(lane + 1) * n])
        # ---- const / lane shuffles --------------------------------------
        elif sub == 12:                           # v128.const
            stack.append(ins[1])
        elif sub == 13:                           # i8x16.shuffle
            b2 = stack.pop()
            a = stack.pop()
            both = a + b2
            stack.append(bytes(both[i] for i in ins[1]))
        elif sub == 14:                           # i8x16.swizzle
            s = stack.pop()
            a = stack.pop()
            stack.append(bytes(a[i] if i < 16 else 0 for i in s))
        elif 15 <= sub <= 18:                     # int splats
            dt, mask, n = (("<u1", 0xFF, 16), ("<u2", 0xFFFF, 8),
                           ("<u4", 0xFFFFFFFF, 4),
                           ("<u8", (1 << 64) - 1, 2))[sub - 15]
            stack.append(np.full(n, int(stack.pop()) & mask,
                                 dt).tobytes())
        elif sub in (19, 20):                     # float splats
            dt, n = ("<f4", 4) if sub == 19 else ("<f8", 2)
            stack.append(np.full(n, float(stack.pop()), dt).tobytes())
        elif 21 <= sub <= 34:                     # extract/replace lane
            self._simd_lane(sub, ins[1], stack)
        # ---- comparisons / bitwise --------------------------------------
        elif sub in _SIMD_CMP:
            dt, nm = _SIMD_CMP[sub]
            b_ = np.frombuffer(stack.pop(), dt)
            a_ = np.frombuffer(stack.pop(), dt)
            cond = {"eq": a_ == b_, "ne": a_ != b_, "lt": a_ < b_,
                    "gt": a_ > b_, "le": a_ <= b_, "ge": a_ >= b_}[nm]
            lanes = "<i" + (dt[2] if dt[1] != "f"
                            else ("4" if dt == "<f4" else "8"))
            stack.append(np.where(cond, -1, 0).astype(lanes).tobytes())
        elif sub == 77:                           # v128.not
            x = int.from_bytes(stack.pop(), "little")
            stack.append((~x & ((1 << 128) - 1)).to_bytes(16, "little"))
        elif 78 <= sub <= 81:                     # and/andnot/or/xor
            b_ = int.from_bytes(stack.pop(), "little")
            a_ = int.from_bytes(stack.pop(), "little")
            full = (1 << 128) - 1
            r = {78: a_ & b_, 79: a_ & (~b_ & full), 80: a_ | b_,
                 81: a_ ^ b_}[sub]
            stack.append(r.to_bytes(16, "little"))
        elif sub == 82:                           # bitselect
            c = int.from_bytes(stack.pop(), "little")
            b_ = int.from_bytes(stack.pop(), "little")
            a_ = int.from_bytes(stack.pop(), "little")
            stack.append(((a_ & c) | (b_ & ~c & ((1 << 128) - 1)))
                         .to_bytes(16, "little"))
        elif sub == 83:                           # v128.any_true
            stack.append(int(stack.pop() != b"\x00" * 16))
        # ---- integer lane math ------------------------------------------
        elif sub in _SIMD_IBIN:
            dt, nm = _SIMD_IBIN[sub]
            b_ = np.frombuffer(stack.pop(), dt)
            a_ = np.frombuffer(stack.pop(), dt)
            if nm in ("add", "sub", "mul"):
                with np.errstate(over="ignore"):
                    r = {"add": a_ + b_, "sub": a_ - b_,
                         "mul": a_ * b_}[nm]
            elif nm in ("add_sat", "sub_sat"):
                wide = np.int32 if dt[2] in "12" else np.int64
                info = np.iinfo(dt[1:])
                w = (a_.astype(wide) + b_.astype(wide)) if nm[0] == "a" \
                    else (a_.astype(wide) - b_.astype(wide))
                r = np.clip(w, info.min, info.max).astype(dt)
            elif nm == "avgr":
                r = ((a_.astype(np.uint32) + b_.astype(np.uint32) + 1)
                     // 2).astype(dt)
            else:                                 # min / max
                r = (np.minimum if nm == "min" else np.maximum)(a_, b_)
            stack.append(r.astype(dt).tobytes())
        elif sub in _SIMD_IUN:
            dt, nm = _SIMD_IUN[sub]
            a_ = np.frombuffer(stack.pop(), dt)
            if nm == "abs":
                with np.errstate(over="ignore"):
                    r = np.abs(a_)                # INT_MIN wraps (spec)
            elif nm == "neg":
                with np.errstate(over="ignore"):
                    r = (0 - a_).astype(dt)
            else:                                 # popcnt (u8 lanes)
                r = np.unpackbits(a_).reshape(16, 8).sum(1).astype(dt)
            stack.append(r.astype(dt).tobytes())
        elif sub in _SIMD_ALLTRUE:
            a_ = np.frombuffer(stack.pop(), _SIMD_ALLTRUE[sub])
            stack.append(int(bool((a_ != 0).all())))
        elif sub in _SIMD_BITMASK:
            a_ = np.frombuffer(stack.pop(), _SIMD_BITMASK[sub])
            stack.append(int(sum(1 << i for i, t
                                 in enumerate(a_ < 0) if t)))
        elif sub in _SIMD_SHIFT:
            dt, nm = _SIMD_SHIFT[sub]
            bits = int(dt[2]) * 8
            k = _wrap32(stack.pop()) % bits
            a_ = np.frombuffer(stack.pop(), dt)
            with np.errstate(over="ignore"):
                r = (a_ << k) if nm == "shl" else (a_ >> k)
            stack.append(r.astype(dt).tobytes())
        elif sub == 186:                          # i32x4.dot_i16x8_s
            b_ = np.frombuffer(stack.pop(), "<i2").astype(np.int32)
            a_ = np.frombuffer(stack.pop(), "<i2").astype(np.int32)
            stack.append((a_ * b_).reshape(4, 2).sum(1)
                         .astype("<i4").tobytes())
        elif sub in _SIMD_NARROW:
            src, dst = _SIMD_NARROW[sub]
            info = np.iinfo(dst[1:])
            b_ = np.frombuffer(stack.pop(), src)
            a_ = np.frombuffer(stack.pop(), src)
            r = np.clip(np.concatenate([a_, b_]), info.min, info.max)
            stack.append(r.astype(dst).tobytes())
        elif sub in _SIMD_EXTEND:
            src, dst, half = _SIMD_EXTEND[sub]
            a_ = np.frombuffer(stack.pop(), src)
            n = len(a_) // 2
            part = a_[:n] if half == "low" else a_[n:]
            stack.append(part.astype(dst).tobytes())
        # ---- float lane math --------------------------------------------
        elif sub in _SIMD_FUN:
            dt, nm = _SIMD_FUN[sub]
            a_ = np.frombuffer(stack.pop(), dt)
            with np.errstate(invalid="ignore"):
                r = {"ceil": np.ceil, "floor": np.floor,
                     "trunc": np.trunc, "nearest": np.rint,
                     "abs": np.abs, "neg": np.negative,
                     "sqrt": np.sqrt}[nm](a_)
            stack.append(r.astype(dt).tobytes())
        elif sub in _SIMD_FBIN:
            dt, nm = _SIMD_FBIN[sub]
            b_ = np.frombuffer(stack.pop(), dt)
            a_ = np.frombuffer(stack.pop(), dt)
            with np.errstate(invalid="ignore", divide="ignore"):
                if nm == "pmin":
                    r = np.where(b_ < a_, b_, a_)
                elif nm == "pmax":
                    r = np.where(a_ < b_, b_, a_)
                else:
                    r = {"add": a_ + b_, "sub": a_ - b_, "mul": a_ * b_,
                         "div": a_ / b_, "min": np.minimum(a_, b_),
                         "max": np.maximum(a_, b_)}[nm]
            stack.append(r.astype(dt).tobytes())
        # ---- conversions ------------------------------------------------
        elif sub == 94:                           # f32x4.demote_f64x2_zero
            a_ = np.frombuffer(stack.pop(), "<f8").astype("<f4")
            stack.append(a_.tobytes() + b"\x00" * 8)
        elif sub == 95:                           # f64x2.promote_low_f32x4
            a_ = np.frombuffer(stack.pop(), "<f4")[:2].astype("<f8")
            stack.append(a_.tobytes())
        elif sub in (248, 249, 252, 253):         # trunc_sat variants
            src = "<f4" if sub in (248, 249) else "<f8"
            signed = sub in (248, 252)
            a_ = np.frombuffer(stack.pop(), src).astype(np.float64)
            a_ = np.where(np.isnan(a_), 0.0, a_)
            lo, hi = ((-2**31, 2**31 - 1) if signed else (0, 2**32 - 1))
            r = np.clip(np.trunc(a_), lo, hi)
            r = r.astype("<i4" if signed else "<u4")
            if sub in (252, 253):                 # _zero: 2 lanes + zeros
                stack.append(r.tobytes() + b"\x00" * 8)
            else:
                stack.append(r.tobytes())
        elif sub in (250, 251):                   # f32x4.convert_i32x4
            dt = "<i4" if sub == 250 else "<u4"
            a_ = np.frombuffer(stack.pop(), dt).astype("<f4")
            stack.append(a_.tobytes())
        elif sub in (254, 255):                   # f64x2.convert_low_i32x4
            dt = "<i4" if sub == 254 else "<u4"
            a_ = np.frombuffer(stack.pop(), dt)[:2].astype("<f8")
            stack.append(a_.tobytes())
        else:                                     # pragma: no cover
            raise WasmError(f"unsupported SIMD opcode 0xfd {sub}")

    def _simd_lane(self, sub: int, lane: int, stack: list) -> None:
        """extract_lane / replace_lane family (subs 21-34)."""
        spec = {
            21: ("<i1", "xs"), 22: ("<u1", "xu"), 23: ("<u1", "r"),
            24: ("<i2", "xs"), 25: ("<u2", "xu"), 26: ("<u2", "r"),
            27: ("<i4", "xs"), 28: ("<u4", "r"),
            29: ("<i8", "xs64"), 30: ("<u8", "r"),
            31: ("<f4", "xf"), 32: ("<f4", "rf"),
            33: ("<f8", "xf"), 34: ("<f8", "rf"),
        }[sub]
        dt, kind = spec
        if kind.startswith("x"):                  # extract
            v = np.frombuffer(stack.pop(), dt)
            x = v[lane]
            if kind == "xs":
                stack.append(_wrap32(int(x)))
            elif kind == "xu":
                stack.append(int(x))
            elif kind == "xs64":
                stack.append(_wrap64(int(x)))
            else:
                stack.append(float(x))
        else:                                     # replace
            x = stack.pop()
            v = np.frombuffer(stack.pop(), dt).copy()
            if kind == "rf":
                v[lane] = float(x)
            else:
                mask = (1 << (int(dt[2]) * 8)) - 1
                v[lane] = int(x) & mask
            stack.append(v.tobytes())


def instantiate(data: bytes,
                host: Optional[dict[tuple[str, str], Callable]] = None
                ) -> Instance:
    return Instance(decode_module(data), host or {})
